"""Continuous-time logSNR-parameterised variance-preserving DDPM.

This is the single home of the diffusion math that the reference duplicates
in three near-identical copies (``/root/reference/train.py:30-177``,
``lightning/diff3d.py:131-238``, ``sampling.py:59-127``).  Everything is a
pure function over explicit ``jax.random`` keys, jit/scan/pjit-friendly.

Layout note: images are channels-last ``[B, H, W, 3]`` (TPU-native NHWC);
the reference uses NCHW.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# A denoiser: (batch dict, cond_mask [B] bool) -> eps_hat [B, H, W, 3].
# Dropout/other rngs are expected to be bound by the caller (closure over
# model.apply with its `rngs=`).
DenoiseFn = Callable[[dict, jnp.ndarray], jnp.ndarray]

# Reverse-process update rules understood by `sample_loop` / `Sampler`:
# "ancestral" is the paper's stochastic DDPM step, "ddim" the deterministic
# eta=0 update (Song et al., DDIM) over the same x0-prediction.
SAMPLER_KINDS = ("ancestral", "ddim")


def logsnr_schedule_cosine(t: jnp.ndarray, *, logsnr_min: float = -20.0,
                           logsnr_max: float = 20.0) -> jnp.ndarray:
    """Cosine schedule in SNR space: ``logsnr(t) = -2 log(tan(a t + b))``.

    Parity: reference ``train.py:30-34``.  ``t`` in [0, 1] maps to logsnr in
    [logsnr_max, logsnr_min] (monotonically decreasing).
    """
    b = np.arctan(np.exp(-0.5 * logsnr_max))
    a = np.arctan(np.exp(-0.5 * logsnr_min)) - b
    return -2.0 * jnp.log(jnp.tan(a * t + b))


def alpha_sigma(logsnr: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """VP coefficients ``alpha = sqrt(sigmoid(logsnr))``,
    ``sigma = sqrt(sigmoid(-logsnr))`` (reference ``train.py:54-55``)."""
    return (jnp.sqrt(jax.nn.sigmoid(logsnr)),
            jnp.sqrt(jax.nn.sigmoid(-logsnr)))


def q_sample(z: jnp.ndarray, logsnr: jnp.ndarray,
             noise: jnp.ndarray) -> jnp.ndarray:
    """Forward process ``z_t = alpha z + sigma eps`` (reference
    ``train.py:50-60``).  ``logsnr`` is ``[B]``, images ``[B, H, W, C]``."""
    alpha, sigma = alpha_sigma(logsnr)
    return alpha[:, None, None, None] * z + sigma[:, None, None, None] * noise


def make_model_batch(x: jnp.ndarray, z: jnp.ndarray, logsnr: jnp.ndarray,
                     R: jnp.ndarray, t: jnp.ndarray, K: jnp.ndarray,
                     *, logsnr_max: float = 20.0) -> dict:
    """Pack the model input dict (parity with ``xt2batch``,
    ``train.py:36-46``): the conditioning frame gets the schedule's max
    logSNR (= clean, ``logsnr_schedule_cosine(0)``) stacked with the target
    frame's logsnr into ``[B, 2]``."""
    cond_logsnr = jnp.full_like(logsnr, logsnr_max)
    return {
        "x": x,
        "z": z,
        "logsnr": jnp.stack([cond_logsnr, logsnr], axis=1),
        "R": R,
        "t": t,
        "K": K,
    }


def p_losses(denoise_fn: DenoiseFn, imgs: jnp.ndarray, R: jnp.ndarray,
             T: jnp.ndarray, K: jnp.ndarray, rng: jax.Array, *,
             cond_prob: float = 0.1, loss_type: str = "l2",
             logsnr_min: float = -20.0, logsnr_max: float = 20.0
             ) -> jnp.ndarray:
    """epsilon-prediction loss with classifier-free-guidance dropout.

    Parity: reference ``train.py:80-114`` (and its per-step logsnr draw at
    ``train.py:272``).  ``imgs`` is ``[B, 2, H, W, 3]`` — frame 0 is the
    source view ``x``, frame 1 the target view ``z``.  With probability
    ``cond_prob`` a batch element is trained unconditionally: its
    conditioning frame is replaced by pure N(0,1) noise and ``cond_mask`` is
    False (the "max noise level" CFG variant, ``lightning/diff3d.py:13-16``).
    """
    B = imgs.shape[0]
    x, z = imgs[:, 0], imgs[:, 1]

    k_t, k_noise, k_mask, k_xnoise = jax.random.split(rng, 4)
    logsnr = logsnr_schedule_cosine(
        jax.random.uniform(k_t, (B,)), logsnr_min=logsnr_min,
        logsnr_max=logsnr_max)
    noise = jax.random.normal(k_noise, z.shape, z.dtype)
    z_noisy = q_sample(z, logsnr, noise)

    cond_mask = jax.random.uniform(k_mask, (B,)) > cond_prob
    x_cond = jnp.where(cond_mask[:, None, None, None], x,
                       jax.random.normal(k_xnoise, x.shape, x.dtype))
    batch = make_model_batch(x_cond, z_noisy, logsnr, R, T, K,
                             logsnr_max=logsnr_max)
    eps_hat = denoise_fn(batch, cond_mask)

    if loss_type == "l1":
        return jnp.mean(jnp.abs(noise - eps_hat))
    if loss_type == "l2":
        return jnp.mean(jnp.square(noise - eps_hat))
    if loss_type == "huber":
        # torch smooth_l1 with beta=1 (reference train.py:109).
        d = jnp.abs(noise - eps_hat)
        return jnp.mean(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5))
    raise NotImplementedError(loss_type)


def p_mean_variance(eps_cond: jnp.ndarray, eps_uncond: jnp.ndarray,
                    z: jnp.ndarray, logsnr: jnp.ndarray,
                    logsnr_next: jnp.ndarray, w: jnp.ndarray, *,
                    clip_x0: bool = True
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ancestral step in logSNR form (reference ``train.py:131-166``).

    ``c = -expm1(logsnr - logsnr_next)``; CFG combine
    ``eps = (1+w) eps_cond - w eps_uncond``; ``z0 = (z - sigma eps)/alpha``
    clamped to [-1, 1]; posterior mean
    ``alpha_next (z (1-c)/alpha + c z0)``, variance
    ``sigmoid(-logsnr_next) * c``.
    ``w`` is ``[B]`` (the guidance sweep IS the batch axis, sampling.py:158).
    """
    c = -jnp.expm1(logsnr - logsnr_next)
    alpha, sigma = alpha_sigma(logsnr)
    alpha_next, _ = alpha_sigma(logsnr_next)
    sq_sigma_next = jax.nn.sigmoid(-logsnr_next)

    w = w[:, None, None, None]
    eps = (1.0 + w) * eps_cond - w * eps_uncond
    z_start = (z - sigma * eps) / alpha
    if clip_x0:
        z_start = jnp.clip(z_start, -1.0, 1.0)
    mean = alpha_next * (z * (1.0 - c) / alpha + c * z_start)
    return mean, sq_sigma_next * c


def ddim_step(eps_cond: jnp.ndarray, eps_uncond: jnp.ndarray,
              z: jnp.ndarray, logsnr: jnp.ndarray,
              logsnr_next: jnp.ndarray, w: jnp.ndarray, *,
              clip_x0: bool = True) -> jnp.ndarray:
    """One deterministic DDIM step (eta = 0) in logSNR form.

    Shares the CFG combine and clipped x0-prediction with
    :func:`p_mean_variance`; after clipping, eps is RE-derived from the
    clipped x0 (``eps = (z - alpha x0)/sigma``) so the update stays on the
    manifold implied by the clamp, then
    ``z_next = alpha_next x0 + sigma_next eps``.  At logsnr_next ==
    logsnr_max (t = 0) sigma_next ~ 0 and this returns x0 — no special
    final-step guard is needed.
    """
    alpha, sigma = alpha_sigma(logsnr)
    alpha_next, sigma_next = alpha_sigma(logsnr_next)

    w = w[:, None, None, None]
    eps = (1.0 + w) * eps_cond - w * eps_uncond
    z_start = (z - sigma * eps) / alpha
    if clip_x0:
        z_start = jnp.clip(z_start, -1.0, 1.0)
        eps = (z - alpha * z_start) / sigma
    return alpha_next * z_start + sigma_next * eps


class ScheduleError(ValueError):
    """A sampling-schedule parameter is off the valid grid — ``steps``
    not a divisor of the dense schedule, or ``start_t`` not one of the
    grid's time points."""


def schedule_start_index(steps: int, start_t: float, *,
                         timesteps: int) -> int:
    """Index of ``start_t`` in the ``[steps + 1]`` grid of
    :func:`sample_schedule_ts` (grid points ``t_i = 1 - i/steps``).

    Truncated (draft-seeded) refinement must START on a grid point:
    entering between points would evaluate logsnrs no full run ever
    visits and silently break the exact-subset property the parity
    oracle depends on.  Raises :class:`ScheduleError` for off-grid
    ``start_t``, or one leaving no reverse steps (``start_t <= 0``).
    """
    start_t = float(start_t)
    idx = round((1.0 - start_t) * steps)
    if (not 0 <= idx < steps
            or abs((1.0 - idx / steps) - start_t) > 1e-6):
        pts = [round(1.0 - i / steps, 6) for i in range(steps)]
        raise ScheduleError(
            f"start_t={start_t} is not a grid point of the {steps}-step "
            f"schedule (timesteps={timesteps}): valid start points are "
            f"{pts} (start_t=1.0 runs the whole grid; 0.0 would leave "
            "no reverse steps)")
    return idx


def sample_schedule_ts(steps: int | None, *, timesteps: int,
                       start_t: float | None = None) -> jnp.ndarray:
    """The time grid for a ``k``-step sampling run (``[k + 1]`` entries,
    or the tail of them when ``start_t`` truncates the schedule).

    ``steps`` must divide ``timesteps`` (the dense grid size, 256 in the
    paper configs): the result is the stride-``timesteps // steps`` subset
    of ``linspace(1, 0, timesteps + 1)``, so every k-step logsnr grid is an
    EXACT index subset of the dense grid and ``steps == timesteps`` (stride
    1) reproduces the dense grid bit-for-bit — the ancestral parity oracle
    relies on that.  ``steps=None`` means the full grid.

    ``start_t`` (cascade refinement) truncates the grid to ``[start_t, 0]``:
    the caller renoises an upsampled draft to ``start_t`` via the forward
    process and runs only the remaining reverse steps.  It must be one of
    the grid's own time points (:func:`schedule_start_index`);
    ``start_t=1.0`` is the untruncated grid, so the truncated path degrades
    exactly to the full schedule.
    """
    if steps is None:
        steps = timesteps
    steps = int(steps)
    if steps < 1 or timesteps % steps:
        divisors = [d for d in range(1, timesteps + 1) if timesteps % d == 0]
        raise ScheduleError(
            f"steps={steps} must be a positive divisor of the dense "
            f"schedule (timesteps={timesteps}); valid step counts are "
            f"{divisors}")
    ts = jnp.linspace(1.0, 0.0, timesteps + 1)[::timesteps // steps]
    if start_t is not None:
        ts = ts[schedule_start_index(steps, start_t, timesteps=timesteps):]
    return ts


class SampleState(NamedTuple):
    img: jnp.ndarray   # current z_t, [B, H, W, 3]
    rng: jax.Array


def sample_loop(denoise_fn: DenoiseFn, *, record_imgs: jnp.ndarray,
                record_R: jnp.ndarray, record_T: jnp.ndarray,
                record_len: jnp.ndarray, target_R: jnp.ndarray,
                target_T: jnp.ndarray, K: jnp.ndarray, w: jnp.ndarray,
                rng: jax.Array, timesteps: int = 256,
                logsnr_min: float = -20.0, logsnr_max: float = 20.0,
                clip_x0: bool = True, steps: int | None = None,
                sampler_kind: str = "ancestral",
                start_t: float | None = None,
                draft: jnp.ndarray | None = None,
                hoist_cond: bool = True) -> jnp.ndarray:
    """Full reverse-diffusion for one novel view, as a single ``lax.scan``.

    Stochastic conditioning (reference ``sampling.py:129-155``): at every
    step a conditioning view is drawn uniformly from the first
    ``record_len`` entries of a fixed-size record buffer.  The reference's
    cond+uncond double forward (``sampling.py:97-99``) is folded into ONE
    batched model call of size 2B so the scan body stays static.

    Args:
      record_imgs: ``[N, B, H, W, 3]`` record buffer (autoregressive
        history; entry b is the image generated with guidance ``w[b]``).
      record_R / record_T: ``[N, 3, 3]`` / ``[N, 3]`` poses of the record.
      record_len: scalar int — number of valid entries.
      target_R / target_T: pose of the view being synthesised.
      K: ``[3, 3]`` shared intrinsics.
      w: ``[B]`` guidance weights (one image per weight).
      steps: schedule subset size (see :func:`sample_schedule_ts`);
        ``None`` runs the full ``timesteps`` grid.
      sampler_kind: one of :data:`SAMPLER_KINDS`.
      start_t / draft: truncated refinement — renoise the ``[B, H, W, 3]``
        draft to grid point ``start_t`` and run only the remaining steps
        (see :func:`sample_loop_prepare`).
    Returns:
      ``[B, H, W, 3]`` generated view.
    """
    if sampler_kind not in SAMPLER_KINDS:
        raise ValueError(
            f"sampler_kind={sampler_kind!r} not in {SAMPLER_KINDS}")
    state, xs = sample_loop_prepare(
        record_len=record_len, rng=rng, timesteps=timesteps,
        shape=(w.shape[0],) + record_imgs.shape[-3:],
        logsnr_min=logsnr_min, logsnr_max=logsnr_max, steps=steps,
        start_t=start_t, draft=draft)
    state = sample_loop_scan(
        denoise_fn, state, xs, record_imgs=record_imgs, record_R=record_R,
        record_T=record_T, target_R=target_R, target_T=target_T, K=K,
        w=w, logsnr_max=logsnr_max, clip_x0=clip_x0,
        deterministic=(sampler_kind == "ddim"), hoist_cond=hoist_cond)
    return state.img


def sample_view(denoise_fn: DenoiseFn, *, record_imgs: jnp.ndarray,
                record_R: jnp.ndarray, record_T: jnp.ndarray,
                record_len: jnp.ndarray, K: jnp.ndarray, w: jnp.ndarray,
                rng: jax.Array, timesteps: int = 256,
                logsnr_min: float = -20.0, logsnr_max: float = 20.0,
                clip_x0: bool = True, steps: int | None = None,
                sampler_kind: str = "ancestral",
                start_t: float | None = None,
                draft: jnp.ndarray | None = None):
    """One autoregressive view step over a DEVICE-RESIDENT record.

    The record-carry contract (the sampler's host loop never touches the
    buffers between views):

      * ``record_R`` / ``record_T`` are pre-filled with the poses of ALL
        views up front — safe because the stochastic-conditioning draw
        (:func:`sample_loop_prepare`) only reads indices ``<
        record_len``, so entry ``record_len`` doubles as the target pose
        of the view being synthesised.
      * the generated view is written back at index ``record_len`` via
        ``lax.dynamic_update_slice`` (donate ``record_imgs`` when
        jitting: the update is then in place on device).
      * ``rng`` is the per-object carry; it is split here exactly like
        the legacy host loop's ``rng, k = jax.random.split(rng)``, so
        the per-view key stream is bit-identical to the pre-resident
        sampler (the serving parity tests pin this).

    Returns ``(out, record_imgs, record_len + 1, rng)`` with ``out``
    ``[B, H, W, 3]`` — a pure carry update; the host feeds the returned
    buffers straight into the next call.
    """
    rng, k = jax.random.split(rng)
    out = sample_loop(
        denoise_fn, record_imgs=record_imgs, record_R=record_R,
        record_T=record_T, record_len=record_len,
        target_R=record_R[record_len], target_T=record_T[record_len],
        K=K, w=w, rng=k, timesteps=timesteps, logsnr_min=logsnr_min,
        logsnr_max=logsnr_max, clip_x0=clip_x0, steps=steps,
        sampler_kind=sampler_kind, start_t=start_t, draft=draft)
    out2, record_imgs, record_len = sample_view_commit(
        record_imgs, record_len, out)
    return out2, record_imgs, record_len, rng


def sample_view_commit(record_imgs: jnp.ndarray, record_len: jnp.ndarray,
                       img: jnp.ndarray):
    """Append ``img`` to the record at index ``record_len`` (the
    device-resident tail of :func:`sample_view`, split out so chunked
    callers can commit after their last :func:`sample_loop_scan` chunk).
    Returns ``(img, record_imgs, record_len + 1)``."""
    start = (record_len,) + (0,) * (record_imgs.ndim - 1)
    record_imgs = jax.lax.dynamic_update_slice(
        record_imgs, img[None].astype(record_imgs.dtype), start)
    return img, record_imgs, record_len + 1


def sample_loop_prepare(*, record_len: jnp.ndarray, rng: jax.Array,
                        timesteps: int, shape, logsnr_min: float,
                        logsnr_max: float, steps: int | None = None,
                        start_t: float | None = None,
                        draft: jnp.ndarray | None = None):
    """Initial carry + per-step scan inputs for :func:`sample_loop_scan`.

    Splitting preparation from the scan lets a caller CHUNK the reverse
    diffusion across several device executions (``Sampler(scan_chunks=k)``)
    with a bit-identical RNG stream: ``scan(step, s0, xs)`` equals folding
    ``sample_loop_scan`` over consecutive slices of ``xs`` because every
    per-step key derives from the carried rng.  (Needed where a single
    ~2-minute device execution trips an RPC deadline — e.g. the full-width
    128^2 sampler over this dev tunnel; direct-attached chips keep
    chunks=1.)  ``shape`` is ``(B, H, W, 3)``.

    ``steps`` (default: ``timesteps``) subsets the dense grid via
    :func:`sample_schedule_ts`.  All random draws — init image and the
    stochastic-conditioning indices — stay on the SAME carried key stream
    regardless of ``steps``; at ``steps == timesteps`` every array here is
    bit-identical to the historical full-grid path, which is what keeps
    the 256-step ancestral sampler usable as a parity oracle.

    ``start_t`` + ``draft`` (cascade refinement): the grid is truncated to
    ``[start_t, 0]`` and the init image becomes the ``[B, H, W, 3]`` draft
    renoised to ``start_t`` via the forward process (:func:`q_sample`)
    using the SAME ``k_init`` draw the untruncated path spends on pure
    noise — the key stream is schedule-independent either way.  At
    ``start_t = 1.0`` the VP prior is exactly ``N(0, 1)``, so the draft is
    ignored and the init is the untruncated path's noise bit-for-bit: a
    stride-1-from-t=max cascade run equals the ancestral dense oracle.
    """
    ts = sample_schedule_ts(steps, timesteps=timesteps, start_t=start_t)
    n_steps = ts.shape[0] - 1
    logsnrs = logsnr_schedule_cosine(ts[:-1], logsnr_min=logsnr_min,
                                     logsnr_max=logsnr_max)
    logsnr_nexts = logsnr_schedule_cosine(ts[1:], logsnr_min=logsnr_min,
                                          logsnr_max=logsnr_max)
    rng, k_init, k_idx = jax.random.split(rng, 3)
    noise = jax.random.normal(k_init, shape)
    if draft is None or start_t is None or float(start_t) >= 1.0:
        init_img = noise
    else:
        logsnr_start = logsnr_schedule_cosine(
            jnp.asarray(start_t), logsnr_min=logsnr_min,
            logsnr_max=logsnr_max)
        init_img = q_sample(draft.astype(noise.dtype),
                            jnp.full((shape[0],), logsnr_start), noise)
    # Pre-sampled stochastic-conditioning indices (reference
    # `random.choice(record)`, sampling.py:138) — computed up front so the
    # scan body is trace-static.
    cond_idx = jax.random.randint(k_idx, (n_steps,), 0, record_len)
    return SampleState(init_img, rng), (logsnrs, logsnr_nexts, cond_idx)


def sample_loop_scan(denoise_fn: DenoiseFn, state: SampleState, xs, *,
                     record_imgs: jnp.ndarray, record_R: jnp.ndarray,
                     record_T: jnp.ndarray, target_R: jnp.ndarray,
                     target_T: jnp.ndarray, K: jnp.ndarray, w: jnp.ndarray,
                     logsnr_max: float, clip_x0: bool,
                     deterministic: bool = False,
                     hoist_cond: bool = True) -> SampleState:
    """``lax.scan`` the reverse steps in ``xs`` from ``state`` (a full
    run, or one chunk of it — see :func:`sample_loop_prepare`).

    ``deterministic`` selects the DDIM (eta=0) update instead of the
    ancestral one.  Both branches split the carried rng identically
    (``rng, k_x, k_noise``) so the uncond-frame draws and the downstream
    key stream are shared between samplers at matched seeds — the DDIM
    path simply never consumes ``k_noise``.

    ``hoist_cond`` precomputes the intrinsics-only conditioning stage
    (``pinhole_rays_cam``: the K_inv @ pixel-grid contraction, constant
    across the trajectory's steps) once before the scan and feeds it to
    the model as ``batch['cam_dirs']`` — certified loop-invariant by
    ``equiv.verify_hoist`` and bit-exact vs the unhoisted body (the
    rngcheck stream manifests are byte-identical either way).  False
    keeps the in-loop computation (the equivalence oracle).
    """
    B = w.shape[0]

    Kb = jnp.broadcast_to(K[None], (B, 3, 3))
    w_mask_2b = jnp.concatenate(
        [jnp.ones((B,), bool), jnp.zeros((B,), bool)])

    cam_dirs = None
    if hoist_cond:
        from diff3d_tpu.geometry import pinhole_rays_cam

        H, W = record_imgs.shape[-3:-1]
        K2 = jnp.concatenate([Kb, Kb])                 # [2B, 3, 3]
        cam_dirs = pinhole_rays_cam(
            K2[:, None].astype(jnp.float32), H, W)     # [2B, 1, H, W, 3]

    def step(state: SampleState, xs):
        logsnr, logsnr_next, idx, = xs
        rng, k_x, k_noise = jax.random.split(state.rng, 3)

        cond_img = record_imgs[idx]                     # [B, H, W, 3]
        R = jnp.stack([record_R[idx], target_R])        # [2, 3, 3]
        T = jnp.stack([record_T[idx], target_T])        # [2, 3]
        Rb = jnp.broadcast_to(R[None], (B, 2, 3, 3))
        Tb = jnp.broadcast_to(T[None], (B, 2, 3))

        # Fold CFG cond + uncond passes into one 2B model call.
        x_uncond = jax.random.normal(k_x, cond_img.shape, cond_img.dtype)
        logsnr_b = jnp.full((2 * B,), logsnr)
        batch = make_model_batch(
            jnp.concatenate([cond_img, x_uncond]),
            jnp.concatenate([state.img, state.img]),
            logsnr_b,
            jnp.concatenate([Rb, Rb]),
            jnp.concatenate([Tb, Tb]),
            jnp.concatenate([Kb, Kb]),
            logsnr_max=logsnr_max)
        if cam_dirs is not None:
            batch = dict(batch, cam_dirs=cam_dirs)     # scan constant
        eps = denoise_fn(batch, w_mask_2b)
        eps_cond, eps_uncond = eps[:B], eps[B:]

        if deterministic:
            img = ddim_step(
                eps_cond, eps_uncond, state.img, logsnr, logsnr_next,
                w.astype(state.img.dtype), clip_x0=clip_x0)
        else:
            mean, var = p_mean_variance(
                eps_cond, eps_uncond, state.img, logsnr, logsnr_next,
                w.astype(state.img.dtype), clip_x0=clip_x0)
            noise = jax.random.normal(
                k_noise, state.img.shape, state.img.dtype)
            # Reference guard `if logsnr_next == 0: return mean`
            # (train.py:125-126) — kept for parity even though the
            # schedule's min logsnr is -20, so it never fires there.
            img = jnp.where(logsnr_next == 0.0, mean,
                            mean + jnp.sqrt(var) * noise)
        return SampleState(img, rng), None

    state, _ = jax.lax.scan(step, state, xs)
    return state
