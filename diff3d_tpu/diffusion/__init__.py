from diff3d_tpu.diffusion.core import (
    alpha_sigma,
    logsnr_schedule_cosine,
    make_model_batch,
    p_losses,
    p_mean_variance,
    q_sample,
    sample_loop,
    sample_loop_prepare,
    sample_loop_scan,
    sample_view,
    sample_view_commit,
)

__all__ = [
    "alpha_sigma",
    "logsnr_schedule_cosine",
    "make_model_batch",
    "p_losses",
    "p_mean_variance",
    "q_sample",
    "sample_loop",
    "sample_loop_prepare",
    "sample_loop_scan",
    "sample_view",
    "sample_view_commit",
]
