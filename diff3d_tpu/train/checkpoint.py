"""Orbax-backed checkpoint/resume.

Semantics parity with the reference (``/root/reference/train.py:244-251,
287-298``): periodic saves of ``{model, optim, step}`` (here: the whole
:class:`TrainState` pytree including the EMA the reference lacked), restore
resumes model + optimizer + step exactly, writes gated on the primary
process.  TPU-native upgrades: async array writes, step-indexed directories
with retention, sharded-array-aware restore (each host reads only its
shards back).

Save modes:
  * ``"full"`` (default) — the whole TrainState; exact resume.
  * ``"ema_bf16"`` — ``{step, ema_params}`` with params cast to bfloat16:
    ~1/16 the bytes of the full state (no Adam moments, no raw params,
    half-width floats).  Built for constrained device->host links (this
    image's dev tunnel moves ~1.6 MB/s; a full-width srn64 TrainState is
    ~1.9 GB = impractical, its bf16 EMA is ~240 MB = minutes).  Restoring
    gives eval-grade weights and a *warm restart* (optimizer moments are
    re-zeroed), not an exact resume.

The directory carries a ``ckpt_format.json`` marker so readers
(``eval_cli``, ``Trainer(transfer=True)``) auto-detect the mode; an
unmarked directory is ``"full"`` (all checkpoints written before the
marker existed were full).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from diff3d_tpu.parallel.multihost import is_primary
from diff3d_tpu.train.state import TrainState

_MARKER = "ckpt_format.json"
MODES = ("full", "ema_bf16")


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int | None = None,
                 mode: str | None = None):
        """``mode=None`` (readers, resume-without-flag) follows the
        directory's ``ckpt_format.json`` marker, defaulting to "full" on
        an unmarked directory.  An explicit mode must AGREE with an
        existing marker — silently overriding in either direction would
        either mislabel full checkpoints or quietly discard the user's
        exact-resume request."""
        if mode is not None and mode not in MODES:
            raise ValueError(f"mode={mode!r} not in {MODES}")
        self._dir = os.path.abspath(directory)
        marker = os.path.join(self._dir, _MARKER)
        if os.path.exists(marker):
            with open(marker) as f:
                marked = json.load(f)["mode"]
            if marked not in MODES:
                raise ValueError(
                    f"{marker} declares unknown mode {marked!r}")
            if mode is not None and mode != marked:
                raise ValueError(
                    f"{self._dir} is marked mode={marked!r} but "
                    f"mode={mode!r} was requested — use a fresh "
                    "checkpoint directory to change modes")
            self.mode = marked
        else:
            self.mode = mode or "full"
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=save_interval_steps or 1,
            create=True,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)
        if not os.path.exists(marker) and self.mode != "full":
            # Never mislabel existing data: an unmarked directory that
            # already holds checkpoints holds FULL TrainStates (every
            # writer of non-full data writes the marker first), and
            # stamping it ema_bf16 would wedge restores of those steps.
            if self._mgr.latest_step() is not None:
                raise ValueError(
                    f"{self._dir} already contains full checkpoints; "
                    f"refusing to relabel the directory mode={self.mode!r} "
                    "— use a fresh checkpoint directory")
            if is_primary():
                os.makedirs(self._dir, exist_ok=True)
                with open(marker, "w") as f:
                    json.dump({"mode": self.mode}, f)

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        step = int(jax.device_get(state.step))
        if self.mode == "ema_bf16":
            payload = {
                "step": state.step,
                "ema_params": jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16), state.ema_params),
            }
        else:
            payload = state
        return self._mgr.save(step, args=ocp.args.StandardSave(payload),
                              force=force)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: TrainState,
                step: int | None = None) -> Optional[TrainState]:
        """Restore into the shardings/dtypes of ``abstract_state`` (build it
        with ``jax.eval_shape`` + the mesh's sharding rules).  Returns None
        when no checkpoint exists (fresh run, like the reference's
        ``--transfer`` being absent).

        Only valid for ``mode="full"`` directories — an ``ema_bf16``
        directory has no optimizer state to restore; use
        :meth:`restore_ema` (raises ValueError otherwise, rather than
        silently handing back a half-initialized state).
        """
        if self.mode != "full":
            raise ValueError(
                f"restore() on a mode={self.mode!r} checkpoint dir; use "
                "restore_ema() and rebuild the optimizer state")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))

    def restore_ema(self, abstract_params,
                    step: int | None = None) -> Optional[Tuple[int, object]]:
        """Restore ``(step, ema_params)`` from an ``ema_bf16`` directory.

        ``abstract_params`` is the params pytree of ShapeDtypeStructs (its
        dtypes are the *target* dtypes — bf16-stored arrays are upcast on
        the way in).  Raises ValueError on a ``full`` directory: restoring
        only the EMA leaf there would need the whole abstract TrainState
        anyway, so callers branch on :attr:`mode` (see
        ``cli/_common.py:load_eval_params`` for the mode-agnostic wrapper).
        """
        if self.mode == "full":
            raise ValueError(
                "restore_ema() from a full checkpoint needs the whole "
                "abstract TrainState; call restore() and read .ema_params")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        abstract_bf16 = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16,
                                           sharding=s.sharding),
            abstract_params)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                {"step": jax.ShapeDtypeStruct((), jnp.int32),
                 "ema_params": abstract_bf16}))
        ema = jax.tree.map(
            lambda x, s: x.astype(s.dtype), restored["ema_params"],
            abstract_params)
        return int(restored["step"]), ema

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
