"""Orbax-backed checkpoint/resume.

Semantics parity with the reference (``/root/reference/train.py:244-251,
287-298``): periodic saves of ``{model, optim, step}`` (here: the whole
:class:`TrainState` pytree including the EMA the reference lacked), restore
resumes model + optimizer + step exactly, writes gated on the primary
process.  TPU-native upgrades: async array writes, step-indexed directories
with retention, sharded-array-aware restore (each host reads only its
shards back).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from diff3d_tpu.train.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int | None = None):
        self._dir = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=save_interval_steps or 1,
            create=True,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        step = int(jax.device_get(state.step))
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: TrainState,
                step: int | None = None) -> Optional[TrainState]:
        """Restore into the shardings/dtypes of ``abstract_state`` (build it
        with ``jax.eval_shape`` + the mesh's sharding rules).  Returns None
        when no checkpoint exists (fresh run, like the reference's
        ``--transfer`` being absent)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
