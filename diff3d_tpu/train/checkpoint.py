"""Orbax-backed checkpoint/resume.

Semantics parity with the reference (``/root/reference/train.py:244-251,
287-298``): periodic saves of ``{model, optim, step}`` (here: the whole
:class:`TrainState` pytree including the EMA the reference lacked), restore
resumes model + optimizer + step exactly, writes gated on the primary
process.  TPU-native upgrades: async array writes, step-indexed directories
with retention, sharded-array-aware restore (each host reads only its
shards back).

Save modes:
  * ``"full"`` (default) — the whole TrainState; exact resume.
  * ``"ema_bf16"`` — ``{step, ema_params}`` with params cast to bfloat16:
    ~1/16 the bytes of the full state (no Adam moments, no raw params,
    half-width floats).  Built for constrained device->host links (this
    image's dev tunnel moves ~1.6 MB/s; a full-width srn64 TrainState is
    ~1.9 GB = impractical, its bf16 EMA is ~240 MB = minutes).  Restoring
    gives eval-grade weights and a *warm restart* (optimizer moments are
    re-zeroed), not an exact resume.
  * ``"full_sliced"`` — the whole TrainState streamed leaf-by-leaf as N
    sequential small device->host fetches + ``.npy`` writes with
    per-leaf retry, committed atomically (write to ``<step>.tmp``,
    rename).  Same EXACT-resume semantics as ``full`` (params, EMA,
    Adam moments, step), built for links where one monolithic save is a
    20-minute single point of failure: a transient fault costs one
    leaf's retry, not the whole save, and no single RPC ever moves more
    than the largest parameter (a few MB).  Single-host writer (each
    leaf is fully fetched); pods should keep Orbax ``full``.

The directory carries a ``ckpt_format.json`` marker so readers
(``eval_cli``, ``Trainer(transfer=True)``) auto-detect the mode; an
unmarked directory is ``"full"`` (all checkpoints written before the
marker existed were full).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import shutil
import threading
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from diff3d_tpu.parallel.multihost import is_primary
from diff3d_tpu.runtime.retry import RetryPolicy, is_transient_io_error
from diff3d_tpu.train.state import TrainState

log = logging.getLogger(__name__)

_MARKER = "ckpt_format.json"
_SLICED_MANIFEST = "sliced_manifest.json"
MODES = ("full", "ema_bf16", "full_sliced")


class CheckpointMismatchError(ValueError):
    """A checkpoint/target disagreement caught by restore preflight.

    Raised *before* any ``device_put`` when the on-disk manifest and the
    target abstract state disagree on tree structure, a leaf's shape or
    a leaf's dtype — naming the offending leaf, expected vs found, and
    the checkpoint step, instead of letting the mismatch surface as a
    raw XLA error deep inside resharding.  Subclasses ``ValueError`` so
    callers that caught the old untyped errors keep working.

    Note: a *topology* (mesh) difference is NOT an error — resharding a
    checkpoint into a different mesh is the elasticity loop's normal
    resume path (see :attr:`CheckpointManager.last_restore_reshard`).
    Only value-changing mismatches (shape/dtype/structure) are refused.
    """

    def __init__(self, msg: str, *, leaf: str | None = None,
                 expected=None, found=None, step: int | None = None):
        super().__init__(msg)
        self.leaf = leaf
        self.expected = expected
        self.found = found
        self.step = step

#: Per-leaf device->host fetch retry for sliced saves.  Any exception is
#: retried (matching the historical behavior: a transient link fault
#: costs one leaf's retry, not the whole save); the delays mirror the
#: old hand-rolled 5s/10s schedule.
_DEFAULT_FETCH_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=5.0, max_delay_s=10.0, growth=2.0,
    jitter=0.0, classify=lambda exc: True)

#: Commit retry for the async writer: exponential backoff + jitter over
#: filesystem faults.  The commit rebuilds its tmp dir from the host
#: snapshot on every attempt, so a half-written tmp tree from a failed
#: attempt is simply clobbered.
_DEFAULT_WRITE_RETRY = RetryPolicy(
    max_attempts=4, base_delay_s=0.5, max_delay_s=8.0, growth=2.0,
    jitter=0.25, classify=is_transient_io_error)


@dataclasses.dataclass
class _SlicedSnapshot:
    """A fully host-resident copy of one TrainState, ready to write.

    Built on the *training* thread (device->host fetches must not race
    the train step's donated buffers); consumed by the writer thread,
    which touches only these numpy arrays and the filesystem.
    """

    step: int
    arrays: List[np.ndarray]     # bf16 already re-viewed as uint16
    manifest: dict


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int | None = None,
                 mode: str | None = None,
                 async_writes: bool = False,
                 max_inflight_saves: int = 2,
                 write_retry: RetryPolicy | None = None,
                 fetch_retry: RetryPolicy | None = None,
                 fault_hook: Callable[[str], None] | None = None):
        """``mode=None`` (readers, resume-without-flag) follows the
        directory's ``ckpt_format.json`` marker, defaulting to "full" on
        an unmarked directory.  An explicit mode must AGREE with an
        existing marker — silently overriding in either direction would
        either mislabel full checkpoints or quietly discard the user's
        exact-resume request.

        ``async_writes`` applies to ``full_sliced`` only (the Orbax
        modes are already async): :meth:`save` snapshots device->host on
        the calling thread, then a background writer commits the files
        with retry/backoff.  At most ``max_inflight_saves`` snapshots are
        queued — beyond that :meth:`save` blocks (backpressure, bounding
        host RAM at ``max_inflight_saves`` extra TrainState copies).  A
        write failure that survives ``write_retry`` surfaces at the next
        :meth:`save` or at the :meth:`wait_until_finished` durability
        barrier, never silently.  The written directory layout is
        byte-identical to a sync save — restore is shared and the sync
        path (``async_writes=False``) stays available as the parity
        oracle.

        ``fault_hook`` is a testing seam (see
        :mod:`diff3d_tpu.testing.faults`): called with a site name
        (``"snapshot"``, ``"write"``, ``"commit"``) at each sliced-save
        IO point so chaos tests can inject failures deterministically.
        """
        if mode is not None and mode not in MODES:
            raise ValueError(f"mode={mode!r} not in {MODES}")
        self._dir = os.path.abspath(directory)
        marker = os.path.join(self._dir, _MARKER)
        if os.path.exists(marker):
            with open(marker) as f:
                marked = json.load(f)["mode"]
            if marked not in MODES:
                raise ValueError(
                    f"{marker} declares unknown mode {marked!r}")
            if mode is not None and mode != marked:
                raise ValueError(
                    f"{self._dir} is marked mode={marked!r} but "
                    f"mode={mode!r} was requested — use a fresh "
                    "checkpoint directory to change modes")
            self.mode = marked
        else:
            self.mode = mode or "full"
        self._keep = keep
        #: Optional ``MeshEnv.topology_summary()`` dict; when set, sliced
        #: manifests record the mesh the state was sharded over at save
        #: time, and restore logs a first-class reshard when the target
        #: topology differs (writer-thread-free: set once at bring-up).
        self.mesh_info: dict | None = None
        #: After a restore whose save-time mesh differs from the current
        #: one: ``{"step", "from", "to"}`` (None otherwise).  The
        #: elasticity supervisor reads this to log/metric the reshard.
        self.last_restore_reshard: dict | None = None
        self._fire = fault_hook or (lambda site: None)
        self._fetch_retry = fetch_retry or _DEFAULT_FETCH_RETRY
        self._write_retry = write_retry or _DEFAULT_WRITE_RETRY
        self._async = bool(async_writes) and self.mode == "full_sliced"
        self._async_lock = threading.Lock()
        self._async_error: BaseException | None = None  # guarded-by: self._async_lock
        self._pending_steps: set[int] = set()  # guarded-by: self._async_lock
        self._queue: queue.Queue = queue.Queue()
        self._inflight_sem = threading.Semaphore(max(1, max_inflight_saves))
        self._writer: threading.Thread | None = None
        if self.mode == "full_sliced":
            # No Orbax involvement: saves are plain per-leaf .npy files
            # under <dir>/<step>/ with an atomic-rename commit.  The
            # writer fully fetches every leaf, which needs all shards
            # addressable and exactly one writer — single-host only
            # (pods keep Orbax 'full', whose per-host shard IO is the
            # point).
            if jax.process_count() > 1:
                raise ValueError(
                    "ckpt mode 'full_sliced' is single-host only "
                    f"(process_count={jax.process_count()}); use 'full'")
            self._mgr = None
            # Orbax handles interval gating for the managed modes; the
            # sliced writer applies the same semantics itself in save().
            self._save_interval = save_interval_steps or 1
            if is_primary():
                os.makedirs(self._dir, exist_ok=True)
        else:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps or 1,
                create=True,
                enable_async_checkpointing=True,
            )
            self._mgr = ocp.CheckpointManager(self._dir, options=options)
        if not os.path.exists(marker) and self.mode != "full":
            # Never mislabel existing data: an unmarked directory that
            # already holds checkpoints holds FULL TrainStates (every
            # writer of non-full data writes the marker first), and
            # stamping it ema_bf16/full_sliced would wedge restores of
            # those steps.
            existing = (self._sliced_steps() if self._mgr is None
                        else ([self._mgr.latest_step()]
                              if self._mgr.latest_step() is not None
                              else []))
            has_orbax_dirs = any(
                d.isdigit() and not os.path.exists(
                    os.path.join(self._dir, d, _SLICED_MANIFEST))
                for d in (os.listdir(self._dir)
                          if os.path.isdir(self._dir) else []))
            if existing or (self._mgr is None and has_orbax_dirs):
                raise ValueError(
                    f"{self._dir} already contains full checkpoints; "
                    f"refusing to relabel the directory mode={self.mode!r} "
                    "— use a fresh checkpoint directory")
            if is_primary():
                os.makedirs(self._dir, exist_ok=True)
                with open(marker, "w") as f:
                    json.dump({"mode": self.mode}, f)

    # ---- full_sliced internals -------------------------------------

    def _sliced_steps(self):
        if not os.path.isdir(self._dir):
            return []
        return sorted(
            int(d) for d in os.listdir(self._dir)
            if d.isdigit() and os.path.exists(
                os.path.join(self._dir, d, _SLICED_MANIFEST)))

    def _snapshot_sliced(self, state: TrainState) -> _SlicedSnapshot:
        """Device->host copy of every leaf, on the calling thread.

        Must run on the training thread: the train step donates its
        input state, so fetching from a background thread would race
        buffer donation.  Holds one full host copy of the state (the
        price of decoupling the writer from the training loop).
        """
        self._fire("snapshot")
        step = int(jax.device_get(state.step))
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        leaves = [leaf for _, leaf in flat]
        arrays: List[np.ndarray] = []
        manifest = {
            "step": step,
            "leaves": [],
            # Leaf paths make preflight mismatches nameable ("params.
            # conv1.kernel expects ..."), and the save-time mesh makes a
            # cross-topology restore a recognised reshard, not a guess.
            "paths": [jax.tree_util.keystr(p) for p, _ in flat],
        }
        if self.mesh_info is not None:
            manifest["mesh"] = self.mesh_info
        for i, leaf in enumerate(leaves):
            def _fetch(leaf=leaf):
                # MUST be an owned copy: device_get may return a
                # zero-copy VIEW of the live device buffer (CPU
                # backend), and the training loop DONATES the state to
                # the next step — an async writer serializing that view
                # would read freed/reused memory.
                return np.array(jax.device_get(leaf), copy=True)
            arr = self._fetch_retry.call(
                _fetch, describe=f"sliced save: leaf {i} fetch")
            dtype = str(arr.dtype)       # ml_dtypes name, e.g. 'bfloat16'
            if dtype == "bfloat16":      # np.save can't round-trip bf16
                arr = arr.view(np.uint16)
            arrays.append(arr)
            manifest["leaves"].append(
                {"dtype": dtype, "shape": list(arr.shape)})
        return _SlicedSnapshot(step=step, arrays=arrays, manifest=manifest)

    def _commit_sliced(self, snap: _SlicedSnapshot) -> None:
        """Write one snapshot to disk and atomically publish it.

        Pure filesystem work over host arrays — safe on any thread, and
        safe to retry: each attempt rebuilds the tmp dir from scratch,
        so a half-written tree from a failed attempt is clobbered and
        readers only ever see the atomic ``os.replace`` result.
        """
        final = os.path.join(self._dir, str(snap.step))
        if os.path.exists(final):
            return
        tmp = final + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for i, arr in enumerate(snap.arrays):
            self._fire("write")
            np.save(os.path.join(tmp, f"leaf_{i:05d}.npy"), arr)
        with open(os.path.join(tmp, _SLICED_MANIFEST), "w") as f:
            json.dump(snap.manifest, f)
        self._fire("commit")
        os.replace(tmp, final)           # commit: readers never see partial
        if self._keep and self._keep > 0:   # keep<=0 means keep-all
            for old in self._sliced_steps()[: -self._keep]:
                shutil.rmtree(os.path.join(self._dir, str(old)),
                              ignore_errors=True)

    def _writer_loop(self) -> None:
        while True:
            snap = self._queue.get()
            if snap is None:
                self._queue.task_done()
                return
            try:
                self._write_retry.call(
                    lambda: self._commit_sliced(snap),
                    describe=f"async ckpt commit (step {snap.step})")
            except BaseException as e:
                # Surfaced at the next save() or wait_until_finished():
                # a durability failure must reach the training loop, not
                # die with this thread.
                log.exception(
                    "async checkpoint commit failed permanently (step %d)",
                    snap.step)
                with self._async_lock:
                    self._async_error = e
            finally:
                with self._async_lock:
                    self._pending_steps.discard(snap.step)
                self._inflight_sem.release()
                self._queue.task_done()

    def _raise_deferred_error(self) -> None:
        with self._async_lock:
            err, self._async_error = self._async_error, None
        if err is not None:
            raise err

    def _save_sliced(self, state: TrainState, force: bool = False) -> bool:
        # A previously failed async save surfaces here, before new work:
        # durable checkpointing being broken must halt the run, not pass
        # silently while checkpoints quietly stop landing.
        self._raise_deferred_error()
        step = int(jax.device_get(state.step))
        if not force and step % self._save_interval:
            return False       # same gating Orbax applies in managed modes
        with self._async_lock:
            pending = step in self._pending_steps
        if pending or os.path.exists(os.path.join(self._dir, str(step))):
            return False
        snap = self._snapshot_sliced(state)
        if not self._async:
            self._commit_sliced(snap)    # sync parity oracle
            return True
        if self._writer is None:
            self._writer = threading.Thread(
                target=self._writer_loop, name="diff3d-ckpt-writer",
                daemon=True)
            self._writer.start()
        with self._async_lock:
            self._pending_steps.add(step)
        self._inflight_sem.acquire()     # backpressure: bounded in-flight
        self._queue.put(snap)
        return True

    def _restore_sliced(self, abstract_state: TrainState,
                        step: int | None) -> Optional[TrainState]:
        steps = self._sliced_steps()
        if step is not None and step not in steps:
            # An explicitly requested step that isn't there (never saved,
            # or pruned by retention) is a caller error worth naming —
            # not a raw FileNotFoundError from the manifest open below.
            raise ValueError(
                f"sliced checkpoint step {step} not found in {self._dir}; "
                f"available steps: {steps or 'none'}")
        step = step if step is not None else (steps[-1] if steps else None)
        if step is None:
            return None
        d = os.path.join(self._dir, str(step))
        with open(os.path.join(d, _SLICED_MANIFEST)) as f:
            manifest = json.load(f)
        abs_flat, treedef = jax.tree_util.tree_flatten_with_path(
            abstract_state)
        abs_leaves = [leaf for _, leaf in abs_flat]
        abs_paths = [jax.tree_util.keystr(p) for p, _ in abs_flat]
        # Older manifests (pre-elasticity) carry no paths: name leaves by
        # the target's paths, which are positionally correct whenever the
        # leaf count matches at all.
        paths = manifest.get("paths") or abs_paths
        if len(abs_leaves) != len(manifest["leaves"]):
            raise CheckpointMismatchError(
                f"sliced checkpoint at {d} (step {step}) has "
                f"{len(manifest['leaves'])} leaves; the target state has "
                f"{len(abs_leaves)} — model/optimizer config mismatch",
                expected=len(abs_leaves), found=len(manifest["leaves"]),
                step=step)
        # Preflight the WHOLE manifest before touching any device: a
        # mismatch at leaf 400 must not surface after 399 device_puts.
        for i, (sds, meta) in enumerate(zip(abs_leaves,
                                            manifest["leaves"])):
            name = paths[i] if i < len(paths) else f"leaf {i}"
            if tuple(meta["shape"]) != tuple(sds.shape):
                raise CheckpointMismatchError(
                    f"sliced checkpoint at {d} (step {step}): leaf "
                    f"{name!r} has shape {tuple(meta['shape'])}, target "
                    f"expects {tuple(sds.shape)} — model/optimizer "
                    "config mismatch",
                    leaf=name, expected=tuple(sds.shape),
                    found=tuple(meta["shape"]), step=step)
            if meta["dtype"] != str(sds.dtype):
                # A dtype mismatch is a config mismatch (e.g. restoring a
                # float32 run into a bf16-param config): silently casting
                # would hand back numerically different weights.
                raise CheckpointMismatchError(
                    f"sliced checkpoint at {d} (step {step}): leaf "
                    f"{name!r} was saved as {meta['dtype']}, target "
                    f"expects {sds.dtype} — model/optimizer config "
                    "mismatch",
                    leaf=name, expected=str(sds.dtype),
                    found=meta["dtype"], step=step)
        saved_mesh = manifest.get("mesh")
        self.last_restore_reshard = None
        if saved_mesh is not None and self.mesh_info is not None \
                and saved_mesh != self.mesh_info:
            # First-class reshard: the slices below are device_put into
            # the TARGET topology's shardings — restoring an 8-device
            # checkpoint onto 4 devices (or vice versa) is the elasticity
            # loop's normal resume, not an error.
            self.last_restore_reshard = {
                "step": step, "from": saved_mesh, "to": self.mesh_info}
            log.info("resharding checkpoint step %d: saved on %s -> "
                     "restoring into %s", step, saved_mesh, self.mesh_info)
        out = []
        for i, (sds, meta) in enumerate(zip(abs_leaves,
                                            manifest["leaves"])):
            arr = np.load(os.path.join(d, f"leaf_{i:05d}.npy"))
            if meta["dtype"] == "bfloat16":
                arr = jnp.asarray(arr.view(np.uint16)).view(jnp.bfloat16)
            else:
                arr = jnp.asarray(arr)
            # jnp.asarray may zero-copy ALIAS the freshly-loaded numpy
            # buffer (CPU backend, alignment permitting).  Restored
            # leaves feed a donating jit, and donation frees through the
            # XLA allocator — freeing an aliased numpy buffer corrupts
            # the heap.  jnp.copy lands the leaf in an XLA-owned buffer.
            arr = jnp.copy(arr)
            sharding = getattr(sds, "sharding", None)
            out.append(jax.device_put(arr, sharding)
                       if sharding is not None else arr)
        return jax.tree_util.tree_unflatten(treedef, out)

    # ---- public API ------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        if self.mode == "full_sliced":
            return self._save_sliced(state, force=force)
        step = int(jax.device_get(state.step))
        if self.mode == "ema_bf16":
            payload = {
                "step": state.step,
                "ema_params": jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16), state.ema_params),
            }
        else:
            payload = state
        return self._mgr.save(step, args=ocp.args.StandardSave(payload),
                              force=force)

    def latest_step(self) -> Optional[int]:
        if self.mode == "full_sliced":
            steps = self._sliced_steps()
            return steps[-1] if steps else None
        return self._mgr.latest_step()

    def restore(self, abstract_state: TrainState,
                step: int | None = None) -> Optional[TrainState]:
        """Restore into the shardings/dtypes of ``abstract_state`` (build it
        with ``jax.eval_shape`` + the mesh's sharding rules).  Returns None
        when no checkpoint exists (fresh run, like the reference's
        ``--transfer`` being absent).

        Only valid for exact-resume directories (``full`` /
        ``full_sliced``) — an ``ema_bf16`` directory has no optimizer
        state to restore; use :meth:`restore_ema` (raises ValueError
        otherwise, rather than silently handing back a half-initialized
        state).
        """
        if self.mode == "full_sliced":
            return self._restore_sliced(abstract_state, step)
        if self.mode != "full":
            raise ValueError(
                f"restore() on a mode={self.mode!r} checkpoint dir; use "
                "restore_ema() and rebuild the optimizer state")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))

    def restore_ema(self, abstract_params,
                    step: int | None = None) -> Optional[Tuple[int, object]]:
        """Restore ``(step, ema_params)`` from an ``ema_bf16`` directory.

        ``abstract_params`` is the params pytree of ShapeDtypeStructs (its
        dtypes are the *target* dtypes — bf16-stored arrays are upcast on
        the way in).  Raises ValueError on a ``full`` directory: restoring
        only the EMA leaf there would need the whole abstract TrainState
        anyway, so callers branch on :attr:`mode` (see
        ``cli/_common.py:load_eval_params`` for the mode-agnostic wrapper).
        """
        if self.mode in ("full", "full_sliced"):
            raise ValueError(
                "restore_ema() from a full checkpoint needs the whole "
                "abstract TrainState; call restore() and read .ema_params")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        abstract_bf16 = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16,
                                           sharding=s.sharding),
            abstract_params)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                {"step": jax.ShapeDtypeStruct((), jnp.int32),
                 "ema_params": abstract_bf16}))
        ema = jax.tree.map(
            lambda x, s: x.astype(s.dtype), restored["ema_params"],
            abstract_params)
        return int(restored["step"]), ema

    def wait_until_finished(self) -> None:
        """Durability barrier: returns only once every accepted save is
        committed on disk, raising any deferred write failure.

        The preemption path depends on this contract — "saved then
        exited" must mean the checkpoint actually landed, for async
        saves exactly as for sync ones.
        """
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            return
        if self._writer is not None:
            self._queue.join()
        self._raise_deferred_error()

    def wait(self) -> None:
        self.wait_until_finished()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
            return
        if self._writer is not None:
            self._queue.put(None)        # sentinel: drain then exit
            self._writer.join(timeout=60.0)
            if self._writer.is_alive():  # pragma: no cover - stuck disk
                log.error("checkpoint writer did not exit within 60s")
            self._writer = None
