"""Orbax-backed checkpoint/resume.

Semantics parity with the reference (``/root/reference/train.py:244-251,
287-298``): periodic saves of ``{model, optim, step}`` (here: the whole
:class:`TrainState` pytree including the EMA the reference lacked), restore
resumes model + optimizer + step exactly, writes gated on the primary
process.  TPU-native upgrades: async array writes, step-indexed directories
with retention, sharded-array-aware restore (each host reads only its
shards back).

Save modes:
  * ``"full"`` (default) — the whole TrainState; exact resume.
  * ``"ema_bf16"`` — ``{step, ema_params}`` with params cast to bfloat16:
    ~1/16 the bytes of the full state (no Adam moments, no raw params,
    half-width floats).  Built for constrained device->host links (this
    image's dev tunnel moves ~1.6 MB/s; a full-width srn64 TrainState is
    ~1.9 GB = impractical, its bf16 EMA is ~240 MB = minutes).  Restoring
    gives eval-grade weights and a *warm restart* (optimizer moments are
    re-zeroed), not an exact resume.
  * ``"full_sliced"`` — the whole TrainState streamed leaf-by-leaf as N
    sequential small device->host fetches + ``.npy`` writes with
    per-leaf retry, committed atomically (write to ``<step>.tmp``,
    rename).  Same EXACT-resume semantics as ``full`` (params, EMA,
    Adam moments, step), built for links where one monolithic save is a
    20-minute single point of failure: a transient fault costs one
    leaf's retry, not the whole save, and no single RPC ever moves more
    than the largest parameter (a few MB).  Single-host writer (each
    leaf is fully fetched); pods should keep Orbax ``full``.

The directory carries a ``ckpt_format.json`` marker so readers
(``eval_cli``, ``Trainer(transfer=True)``) auto-detect the mode; an
unmarked directory is ``"full"`` (all checkpoints written before the
marker existed were full).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from diff3d_tpu.parallel.multihost import is_primary
from diff3d_tpu.train.state import TrainState

log = logging.getLogger(__name__)

_MARKER = "ckpt_format.json"
_SLICED_MANIFEST = "sliced_manifest.json"
MODES = ("full", "ema_bf16", "full_sliced")


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int | None = None,
                 mode: str | None = None):
        """``mode=None`` (readers, resume-without-flag) follows the
        directory's ``ckpt_format.json`` marker, defaulting to "full" on
        an unmarked directory.  An explicit mode must AGREE with an
        existing marker — silently overriding in either direction would
        either mislabel full checkpoints or quietly discard the user's
        exact-resume request."""
        if mode is not None and mode not in MODES:
            raise ValueError(f"mode={mode!r} not in {MODES}")
        self._dir = os.path.abspath(directory)
        marker = os.path.join(self._dir, _MARKER)
        if os.path.exists(marker):
            with open(marker) as f:
                marked = json.load(f)["mode"]
            if marked not in MODES:
                raise ValueError(
                    f"{marker} declares unknown mode {marked!r}")
            if mode is not None and mode != marked:
                raise ValueError(
                    f"{self._dir} is marked mode={marked!r} but "
                    f"mode={mode!r} was requested — use a fresh "
                    "checkpoint directory to change modes")
            self.mode = marked
        else:
            self.mode = mode or "full"
        self._keep = keep
        if self.mode == "full_sliced":
            # No Orbax involvement: saves are plain per-leaf .npy files
            # under <dir>/<step>/ with an atomic-rename commit.  The
            # writer fully fetches every leaf, which needs all shards
            # addressable and exactly one writer — single-host only
            # (pods keep Orbax 'full', whose per-host shard IO is the
            # point).
            if jax.process_count() > 1:
                raise ValueError(
                    "ckpt mode 'full_sliced' is single-host only "
                    f"(process_count={jax.process_count()}); use 'full'")
            self._mgr = None
            # Orbax handles interval gating for the managed modes; the
            # sliced writer applies the same semantics itself in save().
            self._save_interval = save_interval_steps or 1
            if is_primary():
                os.makedirs(self._dir, exist_ok=True)
        else:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                save_interval_steps=save_interval_steps or 1,
                create=True,
                enable_async_checkpointing=True,
            )
            self._mgr = ocp.CheckpointManager(self._dir, options=options)
        if not os.path.exists(marker) and self.mode != "full":
            # Never mislabel existing data: an unmarked directory that
            # already holds checkpoints holds FULL TrainStates (every
            # writer of non-full data writes the marker first), and
            # stamping it ema_bf16/full_sliced would wedge restores of
            # those steps.
            existing = (self._sliced_steps() if self._mgr is None
                        else ([self._mgr.latest_step()]
                              if self._mgr.latest_step() is not None
                              else []))
            has_orbax_dirs = any(
                d.isdigit() and not os.path.exists(
                    os.path.join(self._dir, d, _SLICED_MANIFEST))
                for d in (os.listdir(self._dir)
                          if os.path.isdir(self._dir) else []))
            if existing or (self._mgr is None and has_orbax_dirs):
                raise ValueError(
                    f"{self._dir} already contains full checkpoints; "
                    f"refusing to relabel the directory mode={self.mode!r} "
                    "— use a fresh checkpoint directory")
            if is_primary():
                os.makedirs(self._dir, exist_ok=True)
                with open(marker, "w") as f:
                    json.dump({"mode": self.mode}, f)

    # ---- full_sliced internals -------------------------------------

    def _sliced_steps(self):
        if not os.path.isdir(self._dir):
            return []
        return sorted(
            int(d) for d in os.listdir(self._dir)
            if d.isdigit() and os.path.exists(
                os.path.join(self._dir, d, _SLICED_MANIFEST)))

    def _save_sliced(self, state: TrainState, force: bool = False) -> bool:
        step = int(jax.device_get(state.step))
        if not force and step % self._save_interval:
            return False       # same gating Orbax applies in managed modes
        final = os.path.join(self._dir, str(step))
        if os.path.exists(final):
            return False
        tmp = final + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        leaves, _ = jax.tree_util.tree_flatten(state)
        manifest = {"step": step, "leaves": []}
        for i, leaf in enumerate(leaves):
            for attempt in range(3):
                try:
                    arr = np.asarray(jax.device_get(leaf))
                    break
                except Exception as e:   # transient link fault: one leaf
                    if attempt == 2:     # retries, not the whole save
                        raise
                    log.warning(
                        "sliced save: leaf %d fetch failed (%s); retrying",
                        i, str(e).splitlines()[0][:120])
                    time.sleep(5.0 * (attempt + 1))
            dtype = str(arr.dtype)       # ml_dtypes name, e.g. 'bfloat16'
            if dtype == "bfloat16":      # np.save can't round-trip bf16
                arr = arr.view(np.uint16)
            np.save(os.path.join(tmp, f"leaf_{i:05d}.npy"), arr)
            manifest["leaves"].append(
                {"dtype": dtype, "shape": list(arr.shape)})
        with open(os.path.join(tmp, _SLICED_MANIFEST), "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, final)           # commit: readers never see partial
        if self._keep and self._keep > 0:   # keep<=0 means keep-all
            for old in self._sliced_steps()[: -self._keep]:
                shutil.rmtree(os.path.join(self._dir, str(old)),
                              ignore_errors=True)
        return True

    def _restore_sliced(self, abstract_state: TrainState,
                        step: int | None) -> Optional[TrainState]:
        steps = self._sliced_steps()
        if step is not None and step not in steps:
            # An explicitly requested step that isn't there (never saved,
            # or pruned by retention) is a caller error worth naming —
            # not a raw FileNotFoundError from the manifest open below.
            raise ValueError(
                f"sliced checkpoint step {step} not found in {self._dir}; "
                f"available steps: {steps or 'none'}")
        step = step if step is not None else (steps[-1] if steps else None)
        if step is None:
            return None
        d = os.path.join(self._dir, str(step))
        with open(os.path.join(d, _SLICED_MANIFEST)) as f:
            manifest = json.load(f)
        abs_leaves, treedef = jax.tree_util.tree_flatten(abstract_state)
        if len(abs_leaves) != len(manifest["leaves"]):
            raise ValueError(
                f"sliced checkpoint at {d} has {len(manifest['leaves'])} "
                f"leaves; the target state has {len(abs_leaves)} — "
                "model/optimizer config mismatch")
        out = []
        for i, (sds, meta) in enumerate(zip(abs_leaves,
                                            manifest["leaves"])):
            if tuple(meta["shape"]) != tuple(sds.shape):
                raise ValueError(
                    f"sliced checkpoint at {d}: leaf {i} has shape "
                    f"{tuple(meta['shape'])}, target expects "
                    f"{tuple(sds.shape)} — model/optimizer config "
                    "mismatch")
            if meta["dtype"] != str(sds.dtype):
                # A dtype mismatch is a config mismatch (e.g. restoring a
                # float32 run into a bf16-param config): silently casting
                # would hand back numerically different weights.
                raise ValueError(
                    f"sliced checkpoint at {d}: leaf {i} was saved as "
                    f"{meta['dtype']}, target expects {sds.dtype} — "
                    "model/optimizer config mismatch")
            arr = np.load(os.path.join(d, f"leaf_{i:05d}.npy"))
            if meta["dtype"] == "bfloat16":
                arr = jnp.asarray(arr.view(np.uint16)).view(jnp.bfloat16)
            else:
                arr = jnp.asarray(arr)
            sharding = getattr(sds, "sharding", None)
            out.append(jax.device_put(arr, sharding)
                       if sharding is not None else arr)
        return jax.tree_util.tree_unflatten(treedef, out)

    # ---- public API ------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False) -> bool:
        if self.mode == "full_sliced":
            return self._save_sliced(state, force=force)
        step = int(jax.device_get(state.step))
        if self.mode == "ema_bf16":
            payload = {
                "step": state.step,
                "ema_params": jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16), state.ema_params),
            }
        else:
            payload = state
        return self._mgr.save(step, args=ocp.args.StandardSave(payload),
                              force=force)

    def latest_step(self) -> Optional[int]:
        if self.mode == "full_sliced":
            steps = self._sliced_steps()
            return steps[-1] if steps else None
        return self._mgr.latest_step()

    def restore(self, abstract_state: TrainState,
                step: int | None = None) -> Optional[TrainState]:
        """Restore into the shardings/dtypes of ``abstract_state`` (build it
        with ``jax.eval_shape`` + the mesh's sharding rules).  Returns None
        when no checkpoint exists (fresh run, like the reference's
        ``--transfer`` being absent).

        Only valid for exact-resume directories (``full`` /
        ``full_sliced``) — an ``ema_bf16`` directory has no optimizer
        state to restore; use :meth:`restore_ema` (raises ValueError
        otherwise, rather than silently handing back a half-initialized
        state).
        """
        if self.mode == "full_sliced":
            return self._restore_sliced(abstract_state, step)
        if self.mode != "full":
            raise ValueError(
                f"restore() on a mode={self.mode!r} checkpoint dir; use "
                "restore_ema() and rebuild the optimizer state")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))

    def restore_ema(self, abstract_params,
                    step: int | None = None) -> Optional[Tuple[int, object]]:
        """Restore ``(step, ema_params)`` from an ``ema_bf16`` directory.

        ``abstract_params`` is the params pytree of ShapeDtypeStructs (its
        dtypes are the *target* dtypes — bf16-stored arrays are upcast on
        the way in).  Raises ValueError on a ``full`` directory: restoring
        only the EMA leaf there would need the whole abstract TrainState
        anyway, so callers branch on :attr:`mode` (see
        ``cli/_common.py:load_eval_params`` for the mode-agnostic wrapper).
        """
        if self.mode in ("full", "full_sliced"):
            raise ValueError(
                "restore_ema() from a full checkpoint needs the whole "
                "abstract TrainState; call restore() and read .ema_params")
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        abstract_bf16 = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16,
                                           sharding=s.sharding),
            abstract_params)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                {"step": jax.ShapeDtypeStruct((), jnp.int32),
                 "ema_params": abstract_bf16}))
        ema = jax.tree.map(
            lambda x, s: x.astype(s.dtype), restored["ema_params"],
            abstract_params)
        return int(restored["step"]), ema

    def wait(self) -> None:
        if self._mgr is not None:       # sliced saves are synchronous
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
