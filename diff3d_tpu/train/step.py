"""The single compiled train step.

Replaces the reference hot loop (``/root/reference/train.py:264-293``):
loss -> backward -> Adam -> (checkpoint cadence) with per-step
``dist.barrier()``s and host-side RNG.  Here the entire step — logsnr draw,
q_sample, CFG dropout, forward, grad, all-reduce, Adam update, EMA — is ONE
jitted function over global arrays sharded by the mesh layer.  XLA inserts
the gradient collectives (the DDP all-reduce equivalent) from the sharding
specs; donation reuses the old state's buffers.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from diff3d_tpu.config import Config
from diff3d_tpu.data.images import dequantize
from diff3d_tpu.diffusion import p_losses
from diff3d_tpu.parallel import MeshEnv
from diff3d_tpu.train.state import (TrainState, ema_decay_per_step,
                                    make_optimizer, warmup_schedule)

TrainStepFn = Callable[[TrainState, Dict[str, jnp.ndarray], jax.Array],
                       Tuple[TrainState, Dict[str, jnp.ndarray]]]


def make_train_step(model, cfg: Config, env: MeshEnv | None = None,
                    donate: bool = True) -> TrainStepFn:
    """Build ``(state, batch, rng) -> (state, metrics)``, jit-compiled with
    explicit shardings when a mesh is given.

    ``batch``: ``imgs [B,2,H,W,3]``, ``R [B,2,3,3]``, ``T [B,2,3]``,
    ``K [B,3,3]`` — global shapes, batch axis sharded over the data axis.
    ``rng`` is folded with the step counter so every step draws fresh
    noise/logsnr/CFG masks deterministically from one seed (the reference
    uses unseeded host RNG, ``train.py:272``).
    """
    tx = make_optimizer(cfg.train)
    sched = warmup_schedule(cfg.train)
    ema_decay = ema_decay_per_step(cfg.train)
    dcfg = cfg.diffusion

    accum = max(1, cfg.train.accum_steps)
    # GSPMD context parallelism: constrain activations' spatial axis onto
    # the model axis so XLA compiles conv halo exchanges / GN reductions /
    # attention KV gathers (MeshConfig.context_parallel).
    constrain = (env.activation_constraint()
                 if env is not None and cfg.mesh.context_parallel else None)

    def loss_and_grad(params, batch, rng):
        rng, k_drop = jax.random.split(rng)

        def loss_fn(params):
            def denoise(model_batch, cond_mask):
                return model.apply({"params": params}, model_batch,
                                   cond_mask=cond_mask, deterministic=False,
                                   rngs={"dropout": k_drop},
                                   constrain=constrain)
            # Loader batches arrive as uint8 (data/images.py); the cast
            # to [-1, 1] f32 happens here on device, fused by XLA.
            return p_losses(
                denoise, dequantize(batch["imgs"]), batch["R"], batch["T"],
                batch["K"], rng, cond_prob=dcfg.cond_prob,
                loss_type=dcfg.loss_type, logsnr_min=dcfg.logsnr_min,
                logsnr_max=dcfg.logsnr_max)

        return jax.value_and_grad(loss_fn)(params)

    def step_fn(state: TrainState, batch: Dict[str, jnp.ndarray],
                rng: jax.Array) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        rng = jax.random.fold_in(rng, state.step)

        if accum == 1:
            loss, grads = loss_and_grad(state.params, batch, rng)
        else:
            # Scan over `accum` microbatches; only one microbatch's
            # activations are live at a time, grads averaged.
            micro = jax.tree.map(
                lambda x: x.reshape(accum, x.shape[0] // accum,
                                    *x.shape[1:]), batch)

            def body(carry, inp):
                i, mb = inp
                l, g = loss_and_grad(state.params, mb,
                                     jax.random.fold_in(rng, i))
                loss_acc, grads_acc = carry
                return (loss_acc + l,
                        jax.tree.map(jnp.add, grads_acc, g)), None

            init = (jnp.zeros(()),
                    jax.tree.map(jnp.zeros_like, state.params))
            (loss, grads), _ = jax.lax.scan(
                body, init, (jnp.arange(accum), micro))
            loss = loss / accum
            grads = jax.tree.map(lambda g: g / accum, grads)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ema_params = jax.tree.map(
            lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
            state.ema_params, params)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state, ema_params=ema_params)
        metrics = {
            "loss": loss,
            "lr": sched(state.step),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    if env is None:
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    batch_sh = env.batch()
    rep = env.replicated()

    jitted = None  # built on first call (shardings come from the pytrees)

    def _jitted(state, batch):
        nonlocal jitted
        if jitted is None:
            st_sh = env.state_shardings(state)
            batch_shardings = jax.tree.map(lambda _: batch_sh, batch)
            jitted = jax.jit(
                step_fn,
                in_shardings=(st_sh, batch_shardings, rep),
                out_shardings=(st_sh, rep),
                donate_argnums=(0,) if donate else ())
        return jitted

    def sharded_step(state, batch, rng):
        return _jitted(state, batch)(state, batch, rng)

    # The sharded path jits lazily inside this closure; expose the same
    # ``.lower`` the env=None jit has so analysis tooling (shardcheck,
    # flops_report) can lower the REAL sharded program on abstract args
    # (ShapeDtypeStructs work — the sharding pytrees only map leaves).
    sharded_step.lower = (
        lambda state, batch, rng: _jitted(state, batch).lower(
            state, batch, rng))
    return sharded_step
