from diff3d_tpu.train.state import (TrainState, create_train_state,
                                    ema_decay_per_step, make_optimizer,
                                    warmup_schedule)
from diff3d_tpu.train.step import make_train_step
from diff3d_tpu.train.distill import (distill, distill_schedule,
                                      make_distill_step)
from diff3d_tpu.train.checkpoint import CheckpointManager
from diff3d_tpu.train.trainer import Trainer

__all__ = [
    "TrainState", "create_train_state", "make_optimizer", "warmup_schedule",
    "ema_decay_per_step", "make_train_step", "CheckpointManager", "Trainer",
    "distill", "distill_schedule", "make_distill_step",
]
