"""Progressive distillation of the few-step DDIM sampler.

Salimans & Ho (2022), adapted to the pose-conditional 3DiM denoiser: a
student with a ``k``-step deterministic schedule is trained so that ONE
student DDIM step matches TWO consecutive teacher DDIM steps (each of
size ``1/(2k)``) from the same ``z_t``.  Halving rounds
``256 -> 128 -> ... -> 16`` compound into a 16x cheaper sampler whose
updates stay on the dense grid's logsnr subsets
(:func:`diff3d_tpu.diffusion.sample_schedule_ts`), so the distilled
checkpoints drop straight into ``Sampler(sampler_kind="ddim", steps=k)``
and the serving schedule registry.

Distillation is conditional-only (``cond_mask=True``, guidance ``w=0``):
the student inherits CFG behaviour from its epsilon-parameterisation, and
sampling-time guidance still works because the uncond branch rides the
same network.  The loss is the truncated-SNR x-space loss from the paper:
``max(SNR(t), 1) * ||x_tilde - x_hat||^2`` — at high noise the
epsilon->x map is ill-conditioned, so weighting in x-space keeps the
low-SNR tail from dominating.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, Iterator, List, Tuple

import jax
import jax.numpy as jnp
import optax

from diff3d_tpu.config import Config
from diff3d_tpu.data.images import dequantize
from diff3d_tpu.diffusion import (alpha_sigma, ddim_step,
                                  logsnr_schedule_cosine, make_model_batch,
                                  q_sample)
from diff3d_tpu.parallel import MeshEnv
from diff3d_tpu.train.state import (TrainState, create_train_state,
                                    ema_decay_per_step, make_optimizer,
                                    warmup_schedule)

log = logging.getLogger(__name__)

DistillStepFn = Callable[
    [TrainState, dict, Dict[str, jnp.ndarray], jax.Array, jnp.ndarray],
    Tuple[TrainState, Dict[str, jnp.ndarray]]]


def make_distill_step(model, cfg: Config, env: MeshEnv | None = None,
                      donate: bool = True) -> DistillStepFn:
    """Build ``(state, teacher_params, batch, rng, student_steps) ->
    (state, metrics)`` for the halving rounds: the student's
    ``student_steps``-step schedule against a teacher running
    ``2 * student_steps`` DDIM steps.

    ``student_steps`` is a TRACED scalar, not baked in: every round of
    the 256 -> ... -> 16 ladder reuses ONE compiled step (the graph is
    identical across rounds; only the signal-time grid constant changes),
    so the driver pays a single compile instead of one per halving.
    Validity (``2 * student_steps`` divides the dense grid) is the
    driver's to check — see :func:`distill_schedule`.

    ``batch`` has the trainer's shape contract (``imgs [B,2,H,W,3]``
    uint8, ``R``, ``T``, ``K``); ``teacher_params`` is an argument (not a
    closure) so successive rounds reuse nothing stale and shard like the
    student's params.  ``rng`` is folded with the step counter as in
    :func:`diff3d_tpu.train.step.make_train_step`.
    """
    dcfg = cfg.diffusion
    tx = make_optimizer(cfg.train)
    sched = warmup_schedule(cfg.train)
    ema_decay = ema_decay_per_step(cfg.train)
    constrain = (env.activation_constraint()
                 if env is not None and cfg.mesh.context_parallel else None)

    def logsnr_of(t):
        return logsnr_schedule_cosine(t, logsnr_min=dcfg.logsnr_min,
                                      logsnr_max=dcfg.logsnr_max)

    # rng-lineage: keys(rng) passthrough(rng) stream(teacher/student
    # split: rng is rebound via fold_in(step) before any draw — the
    # caller's key survives the call — then split once into k_i
    # (signal-time randint) and k_noise (q_sample normal); teacher
    # half-steps are deterministic and draw nothing)
    def step_fn(state: TrainState, teacher_params,
                batch: Dict[str, jnp.ndarray], rng: jax.Array,
                student_steps: jnp.ndarray
                ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        rng = jax.random.fold_in(rng, state.step)
        k_i, k_noise = jax.random.split(rng)

        imgs = dequantize(batch["imgs"])
        x, z = imgs[:, 0], imgs[:, 1]
        B = z.shape[0]
        cond_mask = jnp.ones((B,), bool)
        w0 = jnp.zeros((B,), z.dtype)

        # Student signal times t = i/k, i ~ U{1..k}; the teacher crosses
        # the same interval in two half-steps t -> t - 1/(2k) -> t - 1/k.
        i = jax.random.randint(k_i, (B,), 1, student_steps + 1)
        t = i.astype(z.dtype) / student_steps
        logsnr_t = logsnr_of(t)
        logsnr_mid = logsnr_of(t - 0.5 / student_steps)
        logsnr_next = logsnr_of(t - 1.0 / student_steps)
        lt = logsnr_t[:, None, None, None]
        lm = logsnr_mid[:, None, None, None]
        ln = logsnr_next[:, None, None, None]

        noise = jax.random.normal(k_noise, z.shape, z.dtype)
        z_t = q_sample(z, logsnr_t, noise)

        def denoise(params, z_in, logsnr):
            mb = make_model_batch(x, z_in, logsnr, batch["R"], batch["T"],
                                  batch["K"], logsnr_max=dcfg.logsnr_max)
            return model.apply({"params": params}, mb, cond_mask=cond_mask,
                               deterministic=True, constrain=constrain)

        # Two teacher DDIM steps; passing eps twice makes the CFG combine
        # with w=0 the plain conditional prediction.
        eps1 = denoise(teacher_params, z_t, logsnr_t)
        z_mid = ddim_step(eps1, eps1, z_t, lt, lm, w0)
        eps2 = denoise(teacher_params, z_mid, logsnr_mid)
        z_next = ddim_step(eps2, eps2, z_mid, lm, ln, w0)

        # The x0 the student must predict so that ITS one DDIM step lands
        # on z_next (paper eq. 8): x~ = (z_next - (s_n/s_t) z_t)
        #                               / (a_n - (s_n/s_t) a_t).
        alpha_t, sigma_t = alpha_sigma(lt)
        alpha_n, sigma_n = alpha_sigma(ln)
        ratio = sigma_n / sigma_t
        x_target = jax.lax.stop_gradient(
            (z_next - ratio * z_t) / (alpha_n - ratio * alpha_t))

        def loss_fn(params):
            eps_hat = denoise(params, z_t, logsnr_t)
            x_hat = (z_t - sigma_t * eps_hat) / alpha_t
            per = jnp.mean(jnp.square(x_target - x_hat), axis=(1, 2, 3))
            wgt = jnp.maximum(jnp.exp(logsnr_t), 1.0)   # truncated SNR
            return jnp.mean(wgt * per)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ema_params = jax.tree.map(
            lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
            state.ema_params, params)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state, ema_params=ema_params)
        metrics = {
            "distill_loss": loss,
            "lr": sched(state.step),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    if env is None:
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    batch_sh = env.batch()
    rep = env.replicated()
    jitted = None

    def _jitted(state, teacher_params, batch):
        nonlocal jitted
        if jitted is None:
            st_sh = env.state_shardings(state)
            jitted = jax.jit(
                step_fn,
                in_shardings=(st_sh, env.params(teacher_params),
                              jax.tree.map(lambda _: batch_sh, batch),
                              rep, rep),
                out_shardings=(st_sh, rep),
                donate_argnums=(0,) if donate else ())
        return jitted

    def sharded_step(state, teacher_params, batch, rng, student_steps):
        return _jitted(state, teacher_params, batch)(
            state, teacher_params, batch, rng, student_steps)

    # Same ``.lower`` surface as the env=None jit, for shardcheck —
    # abstract (ShapeDtypeStruct) pytrees are fine, the sharding specs
    # only map over leaves.
    sharded_step.lower = (
        lambda state, teacher_params, batch, rng, student_steps:
        _jitted(state, teacher_params, batch).lower(
            state, teacher_params, batch, rng, student_steps))
    return sharded_step


def distill_schedule(timesteps: int, start_steps: int,
                     final_steps: int) -> List[int]:
    """The per-round student step counts ``[start/2, start/4, ...,
    final]``; validates the halving chain stays on dense-grid divisors."""
    start_steps, final_steps = int(start_steps), int(final_steps)
    if start_steps < 2 or timesteps % start_steps:
        raise ValueError(
            f"start_steps={start_steps} must divide timesteps={timesteps}")
    if final_steps < 1 or start_steps % final_steps:
        raise ValueError(
            f"final_steps={final_steps} must divide "
            f"start_steps={start_steps}")
    rounds = []
    k = start_steps // 2
    while k >= final_steps:
        rounds.append(k)
        k //= 2
    if not rounds or rounds[-1] != final_steps:
        raise ValueError(
            f"start_steps={start_steps} cannot halve down to "
            f"final_steps={final_steps} (need a power-of-two ratio)")
    return rounds


def distill(model, cfg: Config, teacher_params,
            batches: Iterator[Dict[str, jnp.ndarray]], rng: jax.Array, *,
            start_steps: int | None = None, final_steps: int = 16,
            round_steps: int = 2000, workdir: str | None = None,
            keep: int = 2, env: MeshEnv | None = None,
            log_every: int = 100):
    """Run the halving rounds; returns ``(params, history)``.

    Per round: the student initialises from the current teacher
    (:func:`diff3d_tpu.convert.progressive.init_student_from_teacher` —
    a fresh copy, so donation in the step never aliases the teacher),
    trains ``round_steps`` steps, then its EMA becomes the next round's
    teacher.  With ``workdir`` each round lands in
    ``<workdir>/steps_<k>/`` through the async ``full_sliced``
    checkpoint path (constrained-link safe), force-saved and awaited
    before the next round starts so a preempted run restarts from the
    last finished round.

    ``batches`` is any iterator yielding trainer-contract batches; it is
    drained across rounds (``rounds * round_steps`` draws).
    """
    from diff3d_tpu.convert.progressive import init_student_from_teacher
    from diff3d_tpu.train.checkpoint import CheckpointManager

    rounds = distill_schedule(cfg.diffusion.timesteps,
                              cfg.diffusion.timesteps
                              if start_steps is None else start_steps,
                              final_steps)
    teacher = teacher_params
    history = []
    step_fn = make_distill_step(model, cfg, env=env)   # shared: one compile
    for k in rounds:
        k_arr = jnp.asarray(k, jnp.int32)
        state = create_train_state(init_student_from_teacher(teacher),
                                   cfg.train)
        metrics = {}
        for n in range(round_steps):
            state, metrics = step_fn(state, teacher, next(batches), rng,
                                     k_arr)
            if log_every and (n + 1) % log_every == 0:
                log.info("distill %d-step round: %d/%d loss=%.5f", k,
                         n + 1, round_steps,
                         float(metrics["distill_loss"]))
        entry = {"student_steps": k, "round_steps": round_steps,
                 "final_loss": float(metrics["distill_loss"])}
        if workdir is not None:
            ckpt_dir = os.path.join(workdir, f"steps_{k}")
            mgr = CheckpointManager(ckpt_dir, keep=keep,
                                    mode="full_sliced", async_writes=True)
            mgr.save(state, force=True)
            mgr.wait_until_finished()
            entry["checkpoint"] = ckpt_dir
        history.append(entry)
        teacher = state.ema_params
    return teacher, history
