"""Train state: params + Adam state + EMA, one pytree.

Reference counterparts: ``Adam(lr=1e-4, betas=(0.9, 0.99))``
(``/root/reference/train.py:235``), linear lr warmup
(``train.py:169-177``, intended over the first 10M examples per the paper
config quoted at ``lightning/diff3d.py:11-20``), and the EMA with 500K-
example half-life that the reference *documents but never implements*
(``lightning/diff3d.py:19-20``; SURVEY.md §2.3) — implemented here.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax

from diff3d_tpu.config import TrainConfig


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray            # scalar int32
    params: Any
    opt_state: Any
    ema_params: Any


def warmup_schedule(cfg: TrainConfig) -> optax.Schedule:
    """Linear warmup to ``cfg.lr`` over ``warmup_examples`` examples
    (= ``warmup_examples / global_batch`` steps), then constant.

    Matches the reference's ``(step+1)/last_step`` ramp
    (``train.py:172-175``) so step 0 already takes a non-zero lr.  (The
    reference's raw-DDP path computes ``last_step = num_epochs /
    batch_size`` by mistake, disabling warmup — ``train.py:267``, SURVEY.md
    §2.7; this implements the documented 10M-example intent.)"""
    warmup_steps = max(1, cfg.warmup_examples // cfg.global_batch)

    def schedule(step):
        frac = jnp.clip((step + 1.0) / warmup_steps, 0.0, 1.0)
        return cfg.lr * frac

    return schedule


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    tx = optax.adam(learning_rate=warmup_schedule(cfg),
                    b1=cfg.betas[0], b2=cfg.betas[1])
    if cfg.grad_clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip), tx)
    return tx


def ema_decay_per_step(cfg: TrainConfig) -> float:
    """Per-step decay for an EMA with half-life ``ema_halflife_examples``:
    ``0.5 ** (global_batch / halflife)``."""
    if cfg.ema_halflife_examples <= 0:
        return 0.0
    return float(0.5 ** (cfg.global_batch / cfg.ema_halflife_examples))


def advance_schedule(opt_state, step: int):
    """Return ``opt_state`` with every ``ScaleByScheduleState.count`` set to
    ``step``, leaving Adam's own count (bias correction for the fresh zero
    moments) at 0.  Needed when seeding a state from a converted checkpoint:
    the lr schedule's position lives in optax's internal count, not in
    ``TrainState.step``, so without this a converted step-100K checkpoint
    would silently re-run the whole lr warmup."""
    import jax.numpy as jnp

    def fix(s):
        if isinstance(s, optax.ScaleByScheduleState):
            return optax.ScaleByScheduleState(
                count=jnp.asarray(step, jnp.int32))
        return s

    # tree.map with is_leaf recurses through EVERY container (tuples,
    # namedtuple wrappers like MultiSteps/masked states), stopping at the
    # schedule states themselves.
    return jax.tree.map(
        fix, opt_state,
        is_leaf=lambda s: isinstance(s, optax.ScaleByScheduleState))


def create_train_state(params, cfg: TrainConfig) -> TrainState:
    tx = make_optimizer(cfg)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        ema_params=jax.tree.map(jnp.copy, params),
    )
