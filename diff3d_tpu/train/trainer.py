"""Trainer: wires data, mesh, compiled step, checkpoints, and metrics.

Capability parity with both reference trainers (raw-DDP ``train.py:200-303``
and Lightning ``lightning/train.py`` + ``lightning/diff3d.py:77-127``),
minus their defects (SURVEY.md §2.7): the data path is correctly sharded
per host, gradients actually all-reduce (compiled from shardings), warmup
follows the documented 10M-example intent, checkpoints never reference
undefined state, and there are no per-step host barriers.

Observability the reference lacks: JSONL metrics (loss / lr / grad-norm /
steps-per-sec / examples-per-sec), optional ``jax.profiler`` traces.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import threading
import time
from typing import Callable, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.config import Config
from diff3d_tpu.diffusion import p_losses
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import MeshEnv, make_mesh
from diff3d_tpu.parallel.multihost import is_primary
from diff3d_tpu.runtime.retry import (RetryBudget, RetryPolicy,
                                      is_transient_backend_error)
from diff3d_tpu.train.checkpoint import CheckpointManager
from diff3d_tpu.train.state import TrainState, create_train_state
from diff3d_tpu.train.step import make_train_step

log = logging.getLogger(__name__)

#: Retry around each compiled-step dispatch.  Only errors the shared
#: classifier calls transient (UNAVAILABLE, connection resets, ...) are
#: retried — those surface at dispatch, before the donated input buffers
#: are consumed.  A real execution failure is non-retryable and
#: propagates to the emergency-checkpoint path.
_STEP_RETRY = RetryPolicy(max_attempts=3, base_delay_s=5.0,
                          max_delay_s=30.0,
                          classify=is_transient_backend_error)


def init_params(model: XUNet, cfg: Config, rng: jax.Array):
    """Initialise params with a dummy batch (shapes only).  Compiled —
    eager flax init dispatches thousands of tiny device ops, which is
    minutes over a tunneled TPU."""
    H, W = cfg.model.H, cfg.model.W
    batch = {
        "x": jnp.zeros((1, H, W, 3)),
        "z": jnp.zeros((1, H, W, 3)),
        "logsnr": jnp.zeros((1, 2)),
        "R": jnp.broadcast_to(jnp.eye(3), (1, 2, 3, 3)),
        "t": jnp.zeros((1, 2, 3)),
        "K": jnp.broadcast_to(jnp.eye(3), (1, 3, 3)),
    }
    return jax.jit(
        lambda r: model.init({"params": r}, batch,
                             cond_mask=jnp.ones((1,), bool))
    )(rng)["params"]


class Trainer:
    def __init__(self, cfg: Config, loader: Optional[Iterator] = None,
                 env: Optional[MeshEnv] = None,
                 workdir: str = ".", transfer: bool = False):
        """``loader`` may be attached after construction (``self.loader``) —
        a resuming caller needs the restored step (``int(self.state.step)``)
        to build a loader that seeks the data stream to the right batch."""
        cfg.validate()
        self.cfg = cfg
        self.loader = loader
        self.env = env or make_mesh(cfg.mesh)
        self.workdir = workdir
        self.model = XUNet(cfg.model)
        self.rng = jax.random.PRNGKey(cfg.train.seed)

        params = init_params(self.model, cfg, self.rng)
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(params))
        log.info("XUNet: %.1fM params", n_params / 1e6)
        state = create_train_state(params, cfg.train)

        # Place the fresh state according to the mesh policy before any
        # compile, so fsdp never materialises a replicated copy.
        self.state = jax.device_put(state, self._state_shardings(state))

        self.ckpt = CheckpointManager(
            os.path.join(workdir, cfg.train.checkpoint_dir),
            keep=cfg.train.keep_checkpoints,
            mode=cfg.train.ckpt_mode,
            async_writes=cfg.train.ckpt_async)
        # Stamp the mesh topology BEFORE any restore: sliced manifests
        # record the save-time mesh, and a restore into a different
        # topology is then recognised (and logged) as a first-class
        # reshard — the elasticity re-mesh contract (DESIGN.md §16).
        self.ckpt.mesh_info = self.env.topology_summary()
        if transfer:
            if self.ckpt.mode == "ema_bf16":
                # Warm restart: EMA-only checkpoints carry no optimizer
                # moments, so params and EMA both start from the restored
                # EMA and Adam re-accumulates; the lr schedule is advanced
                # to the restored step so warmup does not re-run.
                abstract = self._abstract_state()
                got = self.ckpt.restore_ema(abstract.params)
                if got is not None:
                    step, ema = got
                    from diff3d_tpu.train.state import advance_schedule
                    ema = jax.device_put(
                        ema, self._state_shardings(self.state).params)
                    self.state = self.state.replace(
                        step=jnp.asarray(step, jnp.int32),
                        params=ema,
                        # DISTINCT buffers: the train step donates the
                        # state, and donating the same buffer via two
                        # leaves fails at execute time.
                        ema_params=jax.tree.map(jnp.copy, ema),
                        opt_state=advance_schedule(self.state.opt_state,
                                                   step))
                    log.info("warm-restarted (ema_bf16) at step %d", step)
            else:
                restored = self.ckpt.restore(self._abstract_state())
                if restored is not None:
                    # Re-place on the mesh policy: restore() hands back
                    # single-device arrays (full_sliced leaves may even
                    # alias the loader's host buffers), and the donating
                    # sharded step must only ever see jax-owned buffers
                    # laid out like the fresh-state path above.
                    self.state = jax.device_put(
                        restored, self._state_shardings(restored))
                    log.info("resumed at step %d", int(self.state.step))

        self.step_fn = make_train_step(self.model, cfg, self.env)
        self._metrics_path = os.path.join(workdir, "metrics.jsonl")
        self._preempted = threading.Event()
        self.preempt_observed_step: Optional[int] = None
        self._preempt_uninstall = None   # cached by install_preemption_handler
        self._in_handler = False         # re-entrancy guard (main thread only)
        self._eval_fn = None
        self.val_loader: Optional[Iterator] = None

    def install_preemption_handler(
            self, signals=(signal.SIGTERM, signal.SIGINT)):
        """Catch preemption signals and finish gracefully: the training
        loop checkpoints the current state, waits on the checkpoint
        durability barrier, and returns instead of dying mid-step.
        SIGTERM is what TPU maintenance / spot reclamation sends;
        SIGINT makes a Ctrl-C'd interactive run exit just as cleanly.
        Restart with ``transfer=True`` to resume.  (The reference's only
        recovery story is rerunning with ``--transfer`` from the last
        50-step save — ``train.py:238-251``.)

        Returns an ``uninstall()`` callable that restores the previous
        handlers — installation is no longer forever, so tests and
        embedding processes (e.g. a notebook driving several trainers)
        can scope the handler to one training run.

        Idempotent and re-entrant (the elasticity loop installs and
        uninstalls every re-mesh cycle): a second ``install`` returns
        the existing uninstaller instead of chaining the handler onto
        itself, a second ``uninstall()`` is a no-op, and a signal
        arriving while the handler is already running only sets the stop
        flag — it does not recursively re-chain the previous handler.
        """
        if self._preempt_uninstall is not None:
            # Already installed: handing out a fresh chain here would
            # make the handler its own `prev` and recurse on delivery.
            return self._preempt_uninstall

        prev = {}

        def handler(signum, frame):
            log.warning("signal %d: checkpointing and stopping", signum)
            self._preempted.set()
            if self._in_handler:
                # Signal-during-signal (repeated SIGTERM from an
                # impatient scheduler): the flag is set, the chained
                # notifier already ran — re-chaining would recurse.
                return
            self._in_handler = True
            try:
                # Chain whatever handler was installed before us — on
                # pods, jax.distributed.initialize registers the
                # preemption-sync notifier on SIGTERM, and clobbering it
                # would leave reached_preemption_sync_point permanently
                # False.  The default SIGINT handler is deliberately NOT
                # chained: it raises KeyboardInterrupt, which would turn
                # this graceful stop into the emergency-checkpoint crash
                # path.
                p = prev.get(signum)
                if callable(p) and p is not signal.default_int_handler:
                    p(signum, frame)
            finally:
                self._in_handler = False

        for s in signals:
            prev[s] = signal.getsignal(s)
            signal.signal(s, handler)

        def uninstall():
            if self._preempt_uninstall is not uninstall:
                return                   # already uninstalled: no-op
            self._preempt_uninstall = None
            for s, p in prev.items():
                # Only restore what we still own — if someone installed
                # their own handler after us, clobbering it here would
                # repeat the exact bug this handle exists to fix.
                if signal.getsignal(s) is handler:
                    signal.signal(s, p if p is not None else signal.SIG_DFL)

        self._preempt_uninstall = uninstall
        return uninstall

    def _stop_requested(self, step: int) -> bool:
        """Multi-host-safe preemption check.  A process-local flag alone
        would deadlock a pod: hosts observing SIGTERM at different step
        boundaries would split between a collective checkpoint save and a
        collective train step.  On multi-process runs the decision goes
        through the coordination service's preemption-sync protocol (any
        host's notice propagates to all — our signal handler chains JAX's
        notifier — and all hosts agree on the same stop step); the local
        flag feeds single-process runs and tests."""
        if jax.process_count() > 1:
            try:
                from jax.experimental import multihost_utils

                return multihost_utils.reached_preemption_sync_point(step)
            except Exception:
                # No preemption-sync manager in this runtime: the local
                # flag is the only signal left.  Hosts may observe it at
                # different steps — a hang risk, but strictly better than
                # ignoring the preemption and losing the state entirely.
                return self._preempted.is_set()
        return self._preempted.is_set()

    def _eval_step(self, state: TrainState, batch, rng):
        """Validation loss (EMA params, no dropout, no CFG randomness
        beyond the rng given) — compiled on first use with the same
        global shardings as the train step, so multi-host runs evaluate
        ONE globally-assembled val batch (each host contributes its
        shard) rather than racing host-local batches through a shared
        computation."""
        from diff3d_tpu.parallel.multihost import shard_host_local
        batch = shard_host_local(batch, self.env.batch())
        if self._eval_fn is None:
            dcfg = self.cfg.diffusion

            def eval_fn(params, batch, rng):
                def denoise(model_batch, cond_mask):
                    return self.model.apply({"params": params}, model_batch,
                                            cond_mask=cond_mask)
                from diff3d_tpu.data.images import dequantize
                return p_losses(
                    denoise, dequantize(batch["imgs"]), batch["R"],
                    batch["T"], batch["K"], rng, cond_prob=dcfg.cond_prob,
                    loss_type=dcfg.loss_type, logsnr_min=dcfg.logsnr_min,
                    logsnr_max=dcfg.logsnr_max)

            self._eval_fn = jax.jit(
                eval_fn,
                in_shardings=(self.env.params(state.ema_params),
                              jax.tree.map(lambda _: self.env.batch(),
                                           batch),
                              self.env.replicated()),
                out_shardings=self.env.replicated())
        return self._eval_fn(state.ema_params, batch, rng)

    def _state_shardings(self, state: TrainState) -> TrainState:
        return self.env.state_shardings(state)

    def _abstract_state(self) -> TrainState:
        abstract = jax.eval_shape(
            lambda s: s, jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.state))
        sh = self._state_shardings(abstract)
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, sh)

    def _log(self, record: dict) -> None:
        if not is_primary():
            return
        with open(self._metrics_path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def train(self, max_steps: Optional[int] = None,
              profile_steps: Optional[tuple] = None) -> TrainState:
        """Run the training loop.

        ``profile_steps=(start, stop)`` captures a ``jax.profiler`` device
        trace of those steps into ``<workdir>/profile`` (start after the
        first step so the compile isn't traced).

        Failure handling the reference lacks (SURVEY.md §5.3): a non-finite
        loss halts with a checkpoint-preserving ``FloatingPointError``
        instead of silently training on garbage, and any exception inside
        the loop triggers a best-effort emergency checkpoint so ``transfer=
        True`` (the reference's ``--transfer``) resumes at the last step.
        """
        if self.loader is None:
            raise ValueError("attach a loader before train()")
        cfg = self.cfg.train
        max_steps = max_steps if max_steps is not None else cfg.max_steps
        t0 = time.monotonic()
        # Host-side step mirror: avoids a device sync per iteration (the
        # jitted step runs async; we only block at log boundaries).
        step = int(self.state.step)
        window_start, window_t = step, t0
        profiling = False

        try:
            while step < max_steps:
                if profile_steps and step == profile_steps[0]:
                    jax.profiler.start_trace(
                        os.path.join(self.workdir, "profile"))
                    profiling = True

                batch = next(self.loader)
                batch = {"imgs": batch["imgs"], "R": batch["R"],
                         "T": batch["T"], "K": batch["K"]}
                # Transient backend faults at dispatch (UNAVAILABLE,
                # reset connections) get the shared retry policy; real
                # step failures are non-retryable and fall through to
                # the emergency checkpoint below.
                self.state, metrics = _STEP_RETRY.call(
                    lambda: self.step_fn(self.state, batch, self.rng),
                    describe=f"train step {step + 1}")
                step += 1

                if profiling and step >= profile_steps[1]:
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling = False

                if ((cfg.log_every > 0 and step % cfg.log_every == 0)
                        or step >= max_steps):
                    jax.block_until_ready(metrics["loss"])
                    now = time.monotonic()
                    dt = max(now - window_t, 1e-9)
                    sps = (step - window_start) / dt
                    window_start, window_t = step, now
                    loss = float(metrics["loss"])
                    rec = {
                        "step": step,
                        "loss": loss,
                        "lr": float(metrics["lr"]),
                        "grad_norm": float(metrics["grad_norm"]),
                        "steps_per_sec": sps,
                        "examples_per_sec": sps * cfg.global_batch,
                        "wall_s": now - t0,
                    }
                    self._log(rec)
                    log.info("step %d loss %.4f (%.2f steps/s)",
                             step, rec["loss"], sps)
                    if not np.isfinite(loss):
                        raise FloatingPointError(
                            f"non-finite loss {loss} at step {step}; "
                            "last finite checkpoint preserved")

                saved_this_step = False
                # ckpt_every <= 0 disables periodic saves (the final-step
                # and preemption saves still run) instead of crashing on
                # a modulo-by-zero
                if ((cfg.ckpt_every > 0 and step % cfg.ckpt_every == 0)
                        or step >= max_steps):
                    # Never persist a poisoned state: ckpt cadence need not
                    # align with log cadence, so check this step's health
                    # here too.  grad_norm covers the finite-loss /
                    # non-finite-gradient case (the loss is computed from
                    # pre-update params, so it can look fine while the
                    # just-updated params are already NaN).
                    loss = float(metrics["loss"])
                    gnorm = float(metrics["grad_norm"])
                    if not (np.isfinite(loss) and np.isfinite(gnorm)):
                        raise FloatingPointError(
                            f"non-finite loss {loss} / grad_norm {gnorm} "
                            f"at step {step}; last finite checkpoint "
                            "preserved")
                    saved_this_step = self.ckpt.save(self.state)

                if (self.val_loader is not None and cfg.eval_every
                        and (step % cfg.eval_every == 0
                             or step >= max_steps)):
                    vb = next(self.val_loader)
                    # Distinct stream tag: the train step already consumes
                    # fold_in(rng, step) (step.py), so fold an eval-only
                    # constant on top to decorrelate val noise draws from
                    # that step's train draws.
                    eval_rng = jax.random.fold_in(
                        jax.random.fold_in(self.rng, step), 0xE7A1)
                    vloss = float(self._eval_step(
                        self.state,
                        {"imgs": vb["imgs"], "R": vb["R"], "T": vb["T"],
                         "K": vb["K"]},
                        eval_rng))
                    self._log({"step": step, "val_loss": vloss})
                    log.info("step %d val_loss %.4f", step, vloss)

                if self._stop_requested(step):
                    # Graceful preemption: persist the exact step and stop.
                    # Skip the save if the ckpt_every branch above already
                    # wrote this step — force=True would delete and rewrite
                    # the finished checkpoint, reopening the loss window a
                    # mid-rewrite SIGKILL was supposed to be protected from.
                    self.preempt_observed_step = step
                    log.warning("preemption flag observed at step %d",
                                step)
                    if not saved_this_step:
                        # The periodic branches carry the NaN guard; with
                        # log/ckpt cadences disabled nothing has checked
                        # this step, and the preemption save must uphold
                        # "never persist a poisoned state" on its own.
                        loss = float(metrics["loss"])
                        gnorm = float(metrics["grad_norm"])
                        if not (np.isfinite(loss) and np.isfinite(gnorm)):
                            raise FloatingPointError(
                                f"non-finite loss {loss} / grad_norm "
                                f"{gnorm} at preemption (step {step}); "
                                "last finite checkpoint preserved")
                        self.ckpt.save(self.state, force=True)
                    # Durability barrier: "saved then stopped" must mean
                    # the bytes are committed before the process exits —
                    # async saves make this wait load-bearing.
                    self.ckpt.wait_until_finished()
                    log.warning("preempted at step %d; state saved", step)
                    break
        except FloatingPointError:
            raise
        except BaseException:
            # Preemption / OOM / data error: keep the last good state so a
            # restart with transfer=True loses at most ckpt_every steps.
            try:
                self.ckpt.save(self.state, force=True)
                self.ckpt.wait_until_finished()
            except Exception:  # pragma: no cover - best effort
                log.exception("emergency checkpoint failed")
            raise
        finally:
            if profiling:  # pragma: no cover - only on mid-window exit
                jax.profiler.stop_trace()

        self.ckpt.wait()
        return self.state


# ---- elasticity -----------------------------------------------------

#: Typed elasticity states (DESIGN.md §16).  They flow into the train
#: log and ``metrics.jsonl`` as ``{"elastic": <state>, ...}`` records so
#: a long elastic run is auditable after the fact: every disruption, the
#: topology it re-meshed to, and the step it resumed from.
ELASTIC_RUNNING = "RUNNING"
ELASTIC_REMESHING = "REMESHING"
ELASTIC_RESUMED = "RESUMED"
ELASTIC_GAVE_UP = "GAVE_UP"


@dataclasses.dataclass(frozen=True)
class ElasticEvent:
    """One elasticity state transition."""

    state: str          # one of the ELASTIC_* constants
    cycle: int          # 1-based re-mesh cycle this event belongs to
    step: int           # trainer step at the transition
    n_devices: int      # device count of the cycle's mesh (0 = unknown)
    reason: str = ""    # disruption cause / reshard description
    wall_s: float = 0.0

    def record(self) -> dict:
        return {"elastic": self.state, "cycle": self.cycle,
                "step": self.step, "n_devices": self.n_devices,
                "reason": self.reason, "wall_s": round(self.wall_s, 3)}


class ElasticityGaveUp(RuntimeError):
    """The supervisor exhausted its no-progress failure budget.

    Carries the full event history so the operator (or the chaos
    harness) sees every cycle's disposition, not just the last error.
    """

    def __init__(self, msg: str, events: List[ElasticEvent]):
        super().__init__(msg)
        self.events = list(events)


class ElasticSupervisor:
    """Re-mesh-and-resume loop around :meth:`Trainer.train`.

    The dynamic half of fault tolerance (ROADMAP item 5; PR 3 landed the
    static half): on a preemption (SIGTERM observed by the trainer's
    handler) or a transient backend fault (failed collective, reset
    transport), the supervisor tears the live cycle down, re-initialises
    the distributed runtime for the surviving host set, rebuilds the
    mesh/shardings for the new topology, restores the latest durable
    checkpoint — resharded into the new mesh by the ``full_sliced``
    restore path — and resumes the input pipeline deterministically
    (``make_loader(step, env)`` re-derives each host's shard of the
    global stream from the restored step; see the loader's elasticity
    determinism rule).

    Give-up policy: ``retry.max_attempts`` consecutive cycles *without
    forward progress* (the durable step never advanced) exhaust the
    :class:`~diff3d_tpu.runtime.retry.RetryBudget` and raise
    :class:`ElasticityGaveUp`; any cycle that advanced the step refills
    the budget — a run preempted hourly for a week should never die.

    Seams (all injectable, so chaos tests script real topology changes
    on a single host):

    * ``make_loader(step, env)`` — build the cycle's input iterator,
      seeked to ``step`` and partitioned for ``env``'s topology;
    * ``topology_fn()`` — devices for the next mesh (None = all);
    * ``reinit_fn()`` — distributed-runtime re-dial (default re-dials
      only on real multi-process jobs via
      :func:`~diff3d_tpu.parallel.multihost.reinitialize_distributed`);
    * ``fault_hook(site)`` — fired at ``"elastic.cycle"`` each bring-up
      (a :class:`~diff3d_tpu.testing.faults.FaultInjector` seam).
    """

    def __init__(self, cfg: Config,
                 make_loader: Callable[[int, MeshEnv], Iterator],
                 workdir: str = ".",
                 topology_fn: Optional[Callable[[], list]] = None,
                 reinit_fn: Optional[Callable[[], object]] = None,
                 retry: Optional[RetryPolicy] = None,
                 fault_hook: Optional[Callable[[str], None]] = None):
        self.cfg = cfg
        self.make_loader = make_loader
        self.workdir = workdir
        self.topology_fn = topology_fn
        self.reinit_fn = reinit_fn
        self.retry = retry or RetryPolicy(
            max_attempts=8, base_delay_s=2.0, max_delay_s=60.0,
            classify=is_transient_backend_error)
        self._budget = RetryBudget(self.retry.max_attempts)
        self._fire = fault_hook or (lambda site: None)
        self._metrics_path = os.path.join(workdir, "metrics.jsonl")
        self._lock = threading.Lock()
        self._events: List[ElasticEvent] = []  # guarded-by: self._lock
        self.trainer: Optional[Trainer] = None

    @property
    def events(self) -> List[ElasticEvent]:
        with self._lock:
            return list(self._events)

    def _emit(self, ev: ElasticEvent) -> None:
        with self._lock:
            self._events.append(ev)
        # File IO strictly after the lock is released (LC303): the event
        # list is shared with readers, the metrics file is not.
        log.warning("elastic %s: cycle %d step %d on %d devices%s",
                    ev.state, ev.cycle, ev.step, ev.n_devices,
                    f" ({ev.reason})" if ev.reason else "")
        if is_primary():
            with open(self._metrics_path, "a") as f:
                f.write(json.dumps(ev.record()) + "\n")

    def _give_up(self, cycle: int, step: int, n_dev: int, reason: str,
                 t0: float) -> None:
        self._emit(ElasticEvent(ELASTIC_GAVE_UP, cycle, step, n_dev,
                                reason, time.monotonic() - t0))
        raise ElasticityGaveUp(
            f"elasticity budget exhausted: {self._budget.spent} "
            f"consecutive no-progress cycles (last: {reason})", self.events)

    def run(self, max_steps: Optional[int] = None) -> TrainState:
        """Train to ``max_steps``, surviving preemptions and transient
        backend faults by re-meshing; returns the final state."""
        max_steps = (max_steps if max_steps is not None
                     else self.cfg.train.max_steps)
        t0 = time.monotonic()
        rng = random.Random(self.retry.seed)
        cycle = 0
        while True:
            cycle += 1
            trainer = None
            loader = None
            uninstall = None
            step0 = -1
            n_dev = 0
            try:
                self._fire("elastic.cycle")
                if self.reinit_fn is not None:
                    self.reinit_fn()
                elif jax.process_count() > 1:  # pragma: no cover - pods
                    from diff3d_tpu.parallel.multihost import \
                        reinitialize_distributed
                    reinitialize_distributed()
                devices = (self.topology_fn()
                           if self.topology_fn is not None else None)
                env = make_mesh(self.cfg.mesh, devices=devices)
                n_dev = int(env.mesh.size)
                trainer = Trainer(self.cfg, env=env, workdir=self.workdir,
                                  transfer=True)
                self.trainer = trainer
                step0 = int(trainer.state.step)
                reshard = trainer.ckpt.last_restore_reshard
                reason = ""
                if reshard is not None:
                    reason = (f"resharded step {reshard['step']}: "
                              f"{reshard['from']['n_devices']} -> "
                              f"{reshard['to']['n_devices']} devices")
                loader = self.make_loader(step0, env)
                trainer.loader = loader
                self._emit(ElasticEvent(
                    ELASTIC_RESUMED if cycle > 1 else ELASTIC_RUNNING,
                    cycle, step0, n_dev, reason, time.monotonic() - t0))
                uninstall = trainer.install_preemption_handler()
                state = trainer.train(max_steps)
                step = int(state.step)
                if step >= max_steps:
                    return state
                # train() returned early: graceful preemption.  Progress
                # refills the budget; a sigterm storm pinning us to the
                # same step eventually exhausts it.
                if step > step0:
                    self._budget.reset()
                elif not self._budget.spend():
                    self._give_up(cycle, step, n_dev,
                                  "preempted without progress", t0)
                self._emit(ElasticEvent(
                    ELASTIC_REMESHING, cycle, step, n_dev, "preemption",
                    time.monotonic() - t0))
            except (FloatingPointError, ElasticityGaveUp):
                raise   # poisoned state / exhausted budget: not elastic
            except Exception as exc:
                if not is_transient_backend_error(exc):
                    raise
                fail_step = step0
                if trainer is not None:
                    try:
                        fail_step = int(trainer.state.step)
                    except Exception:  # pragma: no cover - dead backend
                        pass
                if trainer is not None and fail_step > step0 >= 0:
                    self._budget.reset()
                elif not self._budget.spend():
                    self._give_up(cycle, max(fail_step, 0), n_dev,
                                  f"{type(exc).__name__}: {exc}", t0)
                self._emit(ElasticEvent(
                    ELASTIC_REMESHING, cycle, max(fail_step, 0), n_dev,
                    f"{type(exc).__name__}: {exc}", time.monotonic() - t0))
                self.retry.sleep(self.retry.delay_for(
                    max(1, self._budget.spent), rng))
            finally:
                if uninstall is not None:
                    uninstall()
                if loader is not None and hasattr(loader, "close"):
                    try:
                        loader.close()
                    except Exception:  # pragma: no cover - best effort
                        log.exception("loader close failed during re-mesh")
                if trainer is not None:
                    try:
                        trainer.ckpt.close()
                    except Exception:  # pragma: no cover - best effort
                        log.exception("ckpt close failed during re-mesh")
