"""Trainer: wires data, mesh, compiled step, checkpoints, and metrics.

Capability parity with both reference trainers (raw-DDP ``train.py:200-303``
and Lightning ``lightning/train.py`` + ``lightning/diff3d.py:77-127``),
minus their defects (SURVEY.md §2.7): the data path is correctly sharded
per host, gradients actually all-reduce (compiled from shardings), warmup
follows the documented 10M-example intent, checkpoints never reference
undefined state, and there are no per-step host barriers.

Observability the reference lacks: JSONL metrics (loss / lr / grad-norm /
steps-per-sec / examples-per-sec), optional ``jax.profiler`` traces.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.config import Config
from diff3d_tpu.diffusion import p_losses
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import MeshEnv, make_mesh
from diff3d_tpu.parallel.multihost import is_primary
from diff3d_tpu.runtime.retry import (RetryPolicy,
                                      is_transient_backend_error)
from diff3d_tpu.train.checkpoint import CheckpointManager
from diff3d_tpu.train.state import TrainState, create_train_state
from diff3d_tpu.train.step import make_train_step

log = logging.getLogger(__name__)

#: Retry around each compiled-step dispatch.  Only errors the shared
#: classifier calls transient (UNAVAILABLE, connection resets, ...) are
#: retried — those surface at dispatch, before the donated input buffers
#: are consumed.  A real execution failure is non-retryable and
#: propagates to the emergency-checkpoint path.
_STEP_RETRY = RetryPolicy(max_attempts=3, base_delay_s=5.0,
                          max_delay_s=30.0,
                          classify=is_transient_backend_error)


def init_params(model: XUNet, cfg: Config, rng: jax.Array):
    """Initialise params with a dummy batch (shapes only).  Compiled —
    eager flax init dispatches thousands of tiny device ops, which is
    minutes over a tunneled TPU."""
    H, W = cfg.model.H, cfg.model.W
    batch = {
        "x": jnp.zeros((1, H, W, 3)),
        "z": jnp.zeros((1, H, W, 3)),
        "logsnr": jnp.zeros((1, 2)),
        "R": jnp.broadcast_to(jnp.eye(3), (1, 2, 3, 3)),
        "t": jnp.zeros((1, 2, 3)),
        "K": jnp.broadcast_to(jnp.eye(3), (1, 3, 3)),
    }
    return jax.jit(
        lambda r: model.init({"params": r}, batch,
                             cond_mask=jnp.ones((1,), bool))
    )(rng)["params"]


class Trainer:
    def __init__(self, cfg: Config, loader: Optional[Iterator] = None,
                 env: Optional[MeshEnv] = None,
                 workdir: str = ".", transfer: bool = False):
        """``loader`` may be attached after construction (``self.loader``) —
        a resuming caller needs the restored step (``int(self.state.step)``)
        to build a loader that seeks the data stream to the right batch."""
        cfg.validate()
        self.cfg = cfg
        self.loader = loader
        self.env = env or make_mesh(cfg.mesh)
        self.workdir = workdir
        self.model = XUNet(cfg.model)
        self.rng = jax.random.PRNGKey(cfg.train.seed)

        params = init_params(self.model, cfg, self.rng)
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(params))
        log.info("XUNet: %.1fM params", n_params / 1e6)
        state = create_train_state(params, cfg.train)

        # Place the fresh state according to the mesh policy before any
        # compile, so fsdp never materialises a replicated copy.
        self.state = jax.device_put(state, self._state_shardings(state))

        self.ckpt = CheckpointManager(
            os.path.join(workdir, cfg.train.checkpoint_dir),
            keep=cfg.train.keep_checkpoints,
            mode=cfg.train.ckpt_mode,
            async_writes=cfg.train.ckpt_async)
        if transfer:
            if self.ckpt.mode == "ema_bf16":
                # Warm restart: EMA-only checkpoints carry no optimizer
                # moments, so params and EMA both start from the restored
                # EMA and Adam re-accumulates; the lr schedule is advanced
                # to the restored step so warmup does not re-run.
                abstract = self._abstract_state()
                got = self.ckpt.restore_ema(abstract.params)
                if got is not None:
                    step, ema = got
                    from diff3d_tpu.train.state import advance_schedule
                    ema = jax.device_put(
                        ema, self._state_shardings(self.state).params)
                    self.state = self.state.replace(
                        step=jnp.asarray(step, jnp.int32),
                        params=ema,
                        # DISTINCT buffers: the train step donates the
                        # state, and donating the same buffer via two
                        # leaves fails at execute time.
                        ema_params=jax.tree.map(jnp.copy, ema),
                        opt_state=advance_schedule(self.state.opt_state,
                                                   step))
                    log.info("warm-restarted (ema_bf16) at step %d", step)
            else:
                restored = self.ckpt.restore(self._abstract_state())
                if restored is not None:
                    # Re-place on the mesh policy: restore() hands back
                    # single-device arrays (full_sliced leaves may even
                    # alias the loader's host buffers), and the donating
                    # sharded step must only ever see jax-owned buffers
                    # laid out like the fresh-state path above.
                    self.state = jax.device_put(
                        restored, self._state_shardings(restored))
                    log.info("resumed at step %d", int(self.state.step))

        self.step_fn = make_train_step(self.model, cfg, self.env)
        self._metrics_path = os.path.join(workdir, "metrics.jsonl")
        self._preempted = threading.Event()
        self.preempt_observed_step: Optional[int] = None
        self._eval_fn = None
        self.val_loader: Optional[Iterator] = None

    def install_preemption_handler(
            self, signals=(signal.SIGTERM, signal.SIGINT)):
        """Catch preemption signals and finish gracefully: the training
        loop checkpoints the current state, waits on the checkpoint
        durability barrier, and returns instead of dying mid-step.
        SIGTERM is what TPU maintenance / spot reclamation sends;
        SIGINT makes a Ctrl-C'd interactive run exit just as cleanly.
        Restart with ``transfer=True`` to resume.  (The reference's only
        recovery story is rerunning with ``--transfer`` from the last
        50-step save — ``train.py:238-251``.)

        Returns an ``uninstall()`` callable that restores the previous
        handlers — installation is no longer forever, so tests and
        embedding processes (e.g. a notebook driving several trainers)
        can scope the handler to one training run.
        """

        prev = {}

        def handler(signum, frame):
            log.warning("signal %d: checkpointing and stopping", signum)
            self._preempted.set()
            # Chain whatever handler was installed before us — on pods,
            # jax.distributed.initialize registers the preemption-sync
            # notifier on SIGTERM, and clobbering it would leave
            # reached_preemption_sync_point permanently False.  The
            # default SIGINT handler is deliberately NOT chained: it
            # raises KeyboardInterrupt, which would turn this graceful
            # stop into the emergency-checkpoint crash path.
            p = prev.get(signum)
            if callable(p) and p is not signal.default_int_handler:
                p(signum, frame)

        for s in signals:
            prev[s] = signal.getsignal(s)
            signal.signal(s, handler)

        def uninstall():
            for s, p in prev.items():
                # Only restore what we still own — if someone installed
                # their own handler after us, clobbering it here would
                # repeat the exact bug this handle exists to fix.
                if signal.getsignal(s) is handler:
                    signal.signal(s, p if p is not None else signal.SIG_DFL)

        return uninstall

    def _stop_requested(self, step: int) -> bool:
        """Multi-host-safe preemption check.  A process-local flag alone
        would deadlock a pod: hosts observing SIGTERM at different step
        boundaries would split between a collective checkpoint save and a
        collective train step.  On multi-process runs the decision goes
        through the coordination service's preemption-sync protocol (any
        host's notice propagates to all — our signal handler chains JAX's
        notifier — and all hosts agree on the same stop step); the local
        flag feeds single-process runs and tests."""
        if jax.process_count() > 1:
            try:
                from jax.experimental import multihost_utils

                return multihost_utils.reached_preemption_sync_point(step)
            except Exception:
                # No preemption-sync manager in this runtime: the local
                # flag is the only signal left.  Hosts may observe it at
                # different steps — a hang risk, but strictly better than
                # ignoring the preemption and losing the state entirely.
                return self._preempted.is_set()
        return self._preempted.is_set()

    def _eval_step(self, state: TrainState, batch, rng):
        """Validation loss (EMA params, no dropout, no CFG randomness
        beyond the rng given) — compiled on first use with the same
        global shardings as the train step, so multi-host runs evaluate
        ONE globally-assembled val batch (each host contributes its
        shard) rather than racing host-local batches through a shared
        computation."""
        from diff3d_tpu.parallel.multihost import shard_host_local
        batch = shard_host_local(batch, self.env.batch())
        if self._eval_fn is None:
            dcfg = self.cfg.diffusion

            def eval_fn(params, batch, rng):
                def denoise(model_batch, cond_mask):
                    return self.model.apply({"params": params}, model_batch,
                                            cond_mask=cond_mask)
                from diff3d_tpu.data.images import dequantize
                return p_losses(
                    denoise, dequantize(batch["imgs"]), batch["R"],
                    batch["T"], batch["K"], rng, cond_prob=dcfg.cond_prob,
                    loss_type=dcfg.loss_type, logsnr_min=dcfg.logsnr_min,
                    logsnr_max=dcfg.logsnr_max)

            self._eval_fn = jax.jit(
                eval_fn,
                in_shardings=(self.env.params(state.ema_params),
                              jax.tree.map(lambda _: self.env.batch(),
                                           batch),
                              self.env.replicated()),
                out_shardings=self.env.replicated())
        return self._eval_fn(state.ema_params, batch, rng)

    def _state_shardings(self, state: TrainState) -> TrainState:
        return self.env.state_shardings(state)

    def _abstract_state(self) -> TrainState:
        abstract = jax.eval_shape(
            lambda s: s, jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.state))
        sh = self._state_shardings(abstract)
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, sh)

    def _log(self, record: dict) -> None:
        if not is_primary():
            return
        with open(self._metrics_path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def train(self, max_steps: Optional[int] = None,
              profile_steps: Optional[tuple] = None) -> TrainState:
        """Run the training loop.

        ``profile_steps=(start, stop)`` captures a ``jax.profiler`` device
        trace of those steps into ``<workdir>/profile`` (start after the
        first step so the compile isn't traced).

        Failure handling the reference lacks (SURVEY.md §5.3): a non-finite
        loss halts with a checkpoint-preserving ``FloatingPointError``
        instead of silently training on garbage, and any exception inside
        the loop triggers a best-effort emergency checkpoint so ``transfer=
        True`` (the reference's ``--transfer``) resumes at the last step.
        """
        if self.loader is None:
            raise ValueError("attach a loader before train()")
        cfg = self.cfg.train
        max_steps = max_steps if max_steps is not None else cfg.max_steps
        t0 = time.monotonic()
        # Host-side step mirror: avoids a device sync per iteration (the
        # jitted step runs async; we only block at log boundaries).
        step = int(self.state.step)
        window_start, window_t = step, t0
        profiling = False

        try:
            while step < max_steps:
                if profile_steps and step == profile_steps[0]:
                    jax.profiler.start_trace(
                        os.path.join(self.workdir, "profile"))
                    profiling = True

                batch = next(self.loader)
                batch = {"imgs": batch["imgs"], "R": batch["R"],
                         "T": batch["T"], "K": batch["K"]}
                # Transient backend faults at dispatch (UNAVAILABLE,
                # reset connections) get the shared retry policy; real
                # step failures are non-retryable and fall through to
                # the emergency checkpoint below.
                self.state, metrics = _STEP_RETRY.call(
                    lambda: self.step_fn(self.state, batch, self.rng),
                    describe=f"train step {step + 1}")
                step += 1

                if profiling and step >= profile_steps[1]:
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling = False

                if ((cfg.log_every > 0 and step % cfg.log_every == 0)
                        or step >= max_steps):
                    jax.block_until_ready(metrics["loss"])
                    now = time.monotonic()
                    dt = max(now - window_t, 1e-9)
                    sps = (step - window_start) / dt
                    window_start, window_t = step, now
                    loss = float(metrics["loss"])
                    rec = {
                        "step": step,
                        "loss": loss,
                        "lr": float(metrics["lr"]),
                        "grad_norm": float(metrics["grad_norm"]),
                        "steps_per_sec": sps,
                        "examples_per_sec": sps * cfg.global_batch,
                        "wall_s": now - t0,
                    }
                    self._log(rec)
                    log.info("step %d loss %.4f (%.2f steps/s)",
                             step, rec["loss"], sps)
                    if not np.isfinite(loss):
                        raise FloatingPointError(
                            f"non-finite loss {loss} at step {step}; "
                            "last finite checkpoint preserved")

                saved_this_step = False
                # ckpt_every <= 0 disables periodic saves (the final-step
                # and preemption saves still run) instead of crashing on
                # a modulo-by-zero
                if ((cfg.ckpt_every > 0 and step % cfg.ckpt_every == 0)
                        or step >= max_steps):
                    # Never persist a poisoned state: ckpt cadence need not
                    # align with log cadence, so check this step's health
                    # here too.  grad_norm covers the finite-loss /
                    # non-finite-gradient case (the loss is computed from
                    # pre-update params, so it can look fine while the
                    # just-updated params are already NaN).
                    loss = float(metrics["loss"])
                    gnorm = float(metrics["grad_norm"])
                    if not (np.isfinite(loss) and np.isfinite(gnorm)):
                        raise FloatingPointError(
                            f"non-finite loss {loss} / grad_norm {gnorm} "
                            f"at step {step}; last finite checkpoint "
                            "preserved")
                    saved_this_step = self.ckpt.save(self.state)

                if (self.val_loader is not None and cfg.eval_every
                        and (step % cfg.eval_every == 0
                             or step >= max_steps)):
                    vb = next(self.val_loader)
                    # Distinct stream tag: the train step already consumes
                    # fold_in(rng, step) (step.py), so fold an eval-only
                    # constant on top to decorrelate val noise draws from
                    # that step's train draws.
                    eval_rng = jax.random.fold_in(
                        jax.random.fold_in(self.rng, step), 0xE7A1)
                    vloss = float(self._eval_step(
                        self.state,
                        {"imgs": vb["imgs"], "R": vb["R"], "T": vb["T"],
                         "K": vb["K"]},
                        eval_rng))
                    self._log({"step": step, "val_loss": vloss})
                    log.info("step %d val_loss %.4f", step, vloss)

                if self._stop_requested(step):
                    # Graceful preemption: persist the exact step and stop.
                    # Skip the save if the ckpt_every branch above already
                    # wrote this step — force=True would delete and rewrite
                    # the finished checkpoint, reopening the loss window a
                    # mid-rewrite SIGKILL was supposed to be protected from.
                    self.preempt_observed_step = step
                    log.warning("preemption flag observed at step %d",
                                step)
                    if not saved_this_step:
                        # The periodic branches carry the NaN guard; with
                        # log/ckpt cadences disabled nothing has checked
                        # this step, and the preemption save must uphold
                        # "never persist a poisoned state" on its own.
                        loss = float(metrics["loss"])
                        gnorm = float(metrics["grad_norm"])
                        if not (np.isfinite(loss) and np.isfinite(gnorm)):
                            raise FloatingPointError(
                                f"non-finite loss {loss} / grad_norm "
                                f"{gnorm} at preemption (step {step}); "
                                "last finite checkpoint preserved")
                        self.ckpt.save(self.state, force=True)
                    # Durability barrier: "saved then stopped" must mean
                    # the bytes are committed before the process exits —
                    # async saves make this wait load-bearing.
                    self.ckpt.wait_until_finished()
                    log.warning("preempted at step %d; state saved", step)
                    break
        except FloatingPointError:
            raise
        except BaseException:
            # Preemption / OOM / data error: keep the last good state so a
            # restart with transfer=True loses at most ckpt_every steps.
            try:
                self.ckpt.save(self.state, force=True)
                self.ckpt.wait_until_finished()
            except Exception:  # pragma: no cover - best effort
                log.exception("emergency checkpoint failed")
            raise
        finally:
            if profiling:  # pragma: no cover - only on mid-window exit
                jax.profiler.stop_trace()

        self.ckpt.wait()
        return self.state
