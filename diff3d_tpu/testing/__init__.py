"""Test-support utilities shipped with the package.

:mod:`diff3d_tpu.testing.faults` is the deterministic fault-injection
harness behind the chaos suite (``pytest -m chaos``) and
``tools/chaos_serving.py``.  It lives in the package (not ``tests/``)
so the soak tool and downstream users can inject faults against a real
engine without importing test code.
"""

from diff3d_tpu.testing.faults import (FaultInjected, FaultInjector,
                                       FaultSpec, wrap_sampler)

__all__ = ["FaultInjected", "FaultInjector", "FaultSpec", "wrap_sampler"]
