"""Deterministic, seedable fault injection for chaos testing.

A :class:`FaultInjector` owns a set of named *sites* — instrumentation
points such as ``"engine.step"`` or the checkpoint writer's ``"commit"``
— and a list of :class:`FaultSpec` rules per site.  Production code
never imports this module; instead it exposes small hooks (the
checkpoint manager's ``fault_hook``, the sampler proxy returned by
:func:`wrap_sampler`, or a plain :meth:`FaultInjector.wrap` around any
callable) that call :meth:`FaultInjector.fire` with a site name.

Determinism: call counts are tracked per site under a lock, and
probabilistic specs draw from a per-site ``random.Random`` stream seeded
from ``(seed, site)``.  As long as the per-site call *order* is
deterministic (it is in the chaos tests: one engine loop, one writer
thread), the injected fault schedule replays exactly.

Fault kinds:

* ``"error"``   — raise (default :class:`FaultInjected`, a typed
  retryable error, so injected faults flow through the same
  classification as real transient faults).
* ``"slow"``    — sleep ``delay_s`` before proceeding (drives watchdog
  stuck-step detection; a sleep past the watchdog budget is the
  "wedged replica" fault).
* ``"sigterm"`` — deliver a real ``SIGTERM`` to this process's main
  thread (drives the trainer's preemption path end-to-end).
* ``"kill"``    — invoke the kill hook registered for the site
  (:meth:`FaultInjector.set_kill_hook`) and then raise, aborting the
  dispatch that fired it.  This is replica death for the fleet router:
  :func:`arm_replica` instruments a fleet replica so every view-step
  dispatch fires ``replica.<name>.step`` and registers
  ``Replica.kill`` as that site's kill hook — a ``kill`` spec then
  takes the replica down mid-run, in-flight work and all.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import random
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from diff3d_tpu.runtime.retry import RetryableError

log = logging.getLogger(__name__)


class FaultInjected(RetryableError):
    """An injected fault.  Retryable by type, like the real transients
    it stands in for."""


@dataclasses.dataclass
class FaultSpec:
    """One rule deciding when a site's calls fault.

    A call triggers the spec if its 1-based per-site call number is
    ``<= first_n``, is listed in ``at_calls``, or wins a Bernoulli draw
    with probability ``prob`` from the site's seeded stream.
    ``max_fires`` caps total firings of this spec.
    """

    kind: str = "error"              # "error" | "slow" | "sigterm" | "kill"
    first_n: int = 0
    at_calls: Tuple[int, ...] = ()
    prob: float = 0.0
    delay_s: float = 0.0
    exc: Optional[Callable[[], BaseException]] = None
    max_fires: Optional[int] = None
    fires: int = 0                            # bookkeeping, not config

    def __post_init__(self):
        if self.kind not in ("error", "slow", "sigterm", "kill"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")


class FaultInjector:
    """Registry of fault specs plus the per-site counters that drive them."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._specs: Dict[str, List[FaultSpec]] = collections.defaultdict(list)
        self._rngs: Dict[str, random.Random] = {}
        # Per-site kill hooks ("kill" specs invoke them); see
        # set_kill_hook / arm_replica.
        self._kill_hooks: Dict[str, Callable[[], None]] = (
            {})  # guarded-by: self._lock
        self.calls: collections.Counter = collections.Counter()
        self.fired: collections.Counter = collections.Counter()

    def add(self, site: str, *, kind: str = "error", first_n: int = 0,
            at_calls: Tuple[int, ...] = (), prob: float = 0.0,
            delay_s: float = 0.0,
            exc: Optional[Callable[[], BaseException]] = None,
            max_fires: Optional[int] = None) -> FaultSpec:
        spec = FaultSpec(kind=kind, first_n=first_n, at_calls=tuple(at_calls),
                         prob=prob, delay_s=delay_s, exc=exc,
                         max_fires=max_fires)
        with self._lock:
            self._specs[site].append(spec)
        return spec

    def clear(self, site: Optional[str] = None) -> None:
        """Drop all specs (for ``site``, or everywhere).  Counters survive
        so tests can still assert how many calls happened."""
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    def set_kill_hook(self, site: str,
                      hook: Callable[[], None]) -> None:
        """Register the destructive action a ``"kill"`` spec at ``site``
        performs (e.g. ``Replica.kill``).  The hook runs on the thread
        that fired the site — for a replica that is its own engine
        loop, which is exactly what real mid-dispatch death looks
        like."""
        with self._lock:
            self._kill_hooks[site] = hook

    def _kill_hook_for(self, site: str) -> Optional[Callable[[], None]]:
        with self._lock:
            return self._kill_hooks.get(site)

    def _rng_for(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def fire(self, site: str) -> None:
        """Record one call at ``site`` and apply any triggered faults.

        Raising specs raise; slow specs sleep; sigterm specs deliver the
        signal.  Multiple triggered specs apply in registration order
        (so a ``slow`` + ``error`` pair sleeps, then raises).
        """
        to_apply: List[FaultSpec] = []
        with self._lock:
            self.calls[site] += 1
            n = self.calls[site]
            rng = self._rng_for(site)
            for spec in self._specs.get(site, ()):
                if spec.max_fires is not None and spec.fires >= spec.max_fires:
                    continue
                hit = (n <= spec.first_n or n in spec.at_calls
                       or (spec.prob > 0.0 and rng.random() < spec.prob))
                if hit:
                    spec.fires += 1
                    self.fired[site] += 1
                    to_apply.append(spec)
        for spec in to_apply:
            if spec.kind == "slow":
                log.info("fault[%s]: sleeping %.2fs (call %d)", site, spec.delay_s, n)
                time.sleep(spec.delay_s)
            elif spec.kind == "kill":
                hook = self._kill_hook_for(site)
                if hook is None:
                    raise RuntimeError(
                        f"kill spec fired at {site!r} but no kill hook "
                        "is registered (set_kill_hook / arm_replica)")
                log.info("fault[%s]: invoking kill hook (call %d)",
                         site, n)
                hook()
                # Abort the dispatch that fired us: the killed target's
                # in-flight work is already rejected; letting this call
                # run to completion would resurrect it.
                raise FaultInjected(
                    f"killed at {site} (call {n})")
            elif spec.kind == "sigterm":
                log.info("fault[%s]: delivering SIGTERM (call %d)", site, n)
                # Target the main thread explicitly.  os.kill() lets the
                # kernel pick any thread that doesn't block SIGTERM —
                # including runtime worker threads (XLA dispatch,
                # TensorStore I/O), and interrupting one of those
                # mid-operation can abort the whole process instead of
                # driving the Python-level handler.  pthread_kill still
                # exercises the real installed handler; it only makes the
                # delivery point deterministic.
                signal.pthread_kill(
                    threading.main_thread().ident, signal.SIGTERM)
            else:
                exc = (spec.exc() if spec.exc is not None
                       else FaultInjected(f"injected fault at {site} (call {n})"))
                log.info("fault[%s]: raising %r (call %d)", site, exc, n)
                raise exc

    def wrap(self, site: str, fn: Callable) -> Callable:
        """Return ``fn`` instrumented to :meth:`fire` at ``site`` first."""

        def wrapped(*args, **kwargs):
            self.fire(site)
            return fn(*args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


def wrap_iter(it, injector: FaultInjector, site: str):
    """Instrument an iterator so every ``__next__`` fires ``site`` first.

    The chaos harness's kill seam for input pipelines: wrapping a
    trainer's loader makes each batch fetch a fault site, so a
    ``kind="sigterm"`` spec at a given call number delivers preemption
    at an exact step boundary (and across elasticity cycles the per-site
    call counter keeps counting, so one schedule spans re-meshes).
    ``close()`` passes through when the inner iterator has one.
    """

    class _FaultyIter:
        def __iter__(self):
            return self

        def __next__(self):
            injector.fire(site)
            return next(it)

        def close(self):
            close = getattr(it, "close", None)
            if close is not None:
                close()

    return _FaultyIter()


class _FaultySampler:
    """Proxy delegating everything to a real sampler, with ``step_many``
    instrumented.  Attribute reads (``w``, ``lane_multiple``, ...) pass
    straight through so the engine and program cache see the real
    sampler's contract."""

    def __init__(self, inner, injector: FaultInjector, site: str):
        self._inner = inner
        self._injector = injector
        self._site = site

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step_many(self, *args, **kwargs):
        self._injector.fire(self._site)
        return self._inner.step_many(*args, **kwargs)


def wrap_sampler(sampler, injector: FaultInjector, site: str = "engine.step"):
    """Wrap a sampler so every ``step_many`` dispatch fires ``site``."""
    return _FaultySampler(sampler, injector, site)


def replica_site(name: str) -> str:
    """The named fault site of one fleet replica's view-step dispatch."""
    return f"replica.{name}.step"


def arm_replica(replica, injector: FaultInjector) -> str:
    """Instrument one fleet replica for chaos and return its site name.

    Every view-step dispatch of ``replica`` (any schedule — the hook
    sits on its ProgramCache, below the per-schedule samplers) fires
    ``replica.<name>.step``; specs registered there then mean:

    * ``kind="slow", delay_s=...`` — a slow replica (past the watchdog
      budget: a wedged one);
    * ``kind="error"``             — a faulting replica (degrades);
    * ``kind="kill"``              — replica death mid-dispatch:
      ``Replica.kill`` runs, in-flight and queued requests resolve with
      typed retryable errors, and the replica reports ``dead``.

    Post-hoc instrumentation (no build-time sampler wrapping), so one
    fleet can arm each replica under its own name even when the
    replicas share a sampler object.
    """
    site = replica_site(replica.name)
    programs = replica.engine.programs
    programs.step_many = injector.wrap(site, programs.step_many)
    injector.set_kill_hook(site, replica.kill)
    return site
