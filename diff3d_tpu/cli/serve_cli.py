"""Long-running novel-view inference service.

Loads a checkpoint and serves ``POST /synthesize`` — concurrent requests
are microbatched into shared compiled scans (``diff3d_tpu/serving``), so
the chip stays occupied under live load instead of running one request's
underfilled guidance sweep at a time.

Usage:
    python -m diff3d_tpu.cli.serve_cli --model ./checkpoints \
        [--config srn64] [--port 8080] [--max_batch 8] [--max_wait_ms 50]

    # smoke-serve random-init params (no checkpoint; CPU-friendly):
    python -m diff3d_tpu.cli.serve_cli --init random --config test

Endpoints: ``POST /synthesize``, ``GET /result/<id>``, ``GET /healthz``,
``GET /metrics`` (text; ``?format=json`` for the structured snapshot).
With ``--cascade``, ``POST /cascade`` serves progressive previews: draft
frames stream first, refined frames replace them (DESIGN.md §20).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from diff3d_tpu.cli._common import (add_model_width_args,
                                    apply_model_width_overrides,
                                    build_abstract_state,
                                    load_eval_params)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default=None,
                   help="checkpoint directory (Orbax root); omit with "
                        "--init random")
    p.add_argument("--init", choices=["checkpoint", "random"],
                   default="checkpoint",
                   help="'random' serves freshly initialised params — "
                        "for smoke tests and load benches, no --model "
                        "needed")
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--host", default=None,
                   help="bind address (default: config, 127.0.0.1)")
    p.add_argument("--port", type=int, default=None,
                   help="bind port (default: config, 8080; 0 = ephemeral)")
    p.add_argument("--max_batch", type=int, default=None,
                   help="device-batch lane ceiling per shape bucket")
    p.add_argument("--max_wait_ms", type=float, default=None,
                   help="microbatch flush deadline after the first "
                        "request of a bucket arrives")
    p.add_argument("--max_queue", type=int, default=None,
                   help="bounded queue size; beyond it submissions get "
                        "HTTP 429")
    p.add_argument("--timeout_s", type=float, default=None,
                   help="default per-request deadline")
    p.add_argument("--watchdog_s", type=float, default=None,
                   help="watchdog deadline per device step: past it the "
                        "engine rejects the stuck batch with a retryable "
                        "error and degrades instead of hanging futures "
                        "(0 disables)")
    p.add_argument("--drain_s", type=float, default=10.0,
                   help="on SIGTERM/SIGINT, stop admitting work and wait "
                        "up to this long for in-flight requests to finish "
                        "before stopping (0 = immediate stop)")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps per view (reference: 256) — the "
                        "DENSE training grid; see --sampler_steps for the "
                        "few-step sampling subset")
    p.add_argument("--sampler", choices=["ancestral", "ddim"],
                   default="ancestral",
                   help="default reverse-process update: 'ancestral' "
                        "(paper's stochastic sampler) or 'ddim' "
                        "(deterministic eta=0)")
    p.add_argument("--sampler_steps", type=int, default=None,
                   help="few-step schedule for the default sampler: "
                        "reverse steps per view, a divisor of the dense "
                        "grid (e.g. 16 with 256 timesteps); default = "
                        "full grid")
    p.add_argument("--schedules", default=None,
                   help="extra compiled schedules to serve beyond the "
                        "default, as 'kind:steps,...' (e.g. "
                        "'ddim:16,ancestral:256'); requests naming any "
                        "other schedule get a typed 503 with this list. "
                        "With --replicas N, prefix an entry with 'i@' to "
                        "give it to replica i only (e.g. "
                        "'0@ddim:8,ancestral:256' = distilled-student "
                        "schedule on replica 0, ancestral everywhere) — "
                        "the router places requests on a replica that "
                        "compiled their schedule")
    p.add_argument("--replicas", type=int, default=None,
                   help="in-process engine replicas behind the fleet "
                        "router front door (default: config, 1 = plain "
                        "single-engine service).  Sessions "
                        "(payload 'session_id') pin to a replica; "
                        "adds GET /fleet and router counters to "
                        "GET /metrics")
    p.add_argument("--workers", default=None,
                   help="front pre-started worker processes "
                        "(diff3d_tpu.cli.worker_cli) as remote replicas: "
                        "'host:port,host:port'.  Mixes with --replicas: "
                        "N in-process replicas plus the listed workers "
                        "form one fleet (sessions pin across both kinds"
                        "); with --workers alone no local engine is "
                        "built, so this process needs no devices")
    p.add_argument("--scan_chunks", type=int, default=1,
                   help="split each view's diffusion scan into this many "
                        "device executions (must divide the per-view "
                        "step count)")
    p.add_argument("--cascade", default=None, metavar="PLAN",
                   help="serve progressive-preview cascades "
                        "(POST /cascade): 'draft=RES:kind:steps,"
                        "refine=RES:kind:steps@tSTART', e.g. "
                        "'draft=64:ddim:8,refine=128:ancestral:64@t0.4'"
                        " — the draft streams first at RES, then a "
                        "truncated refine pass (from t=START) replaces "
                        "each frame in place; refine RES must equal the "
                        "config's image size")
    p.add_argument("--mesh", action="store_true",
                   help="shard serving over a device mesh (cfg.mesh): "
                        "the request batch's object axis rides the data "
                        "axis, params follow the configured "
                        "replicated/fsdp policy; lane counts round up to "
                        "the data-axis size")
    p.add_argument("--pallas", action="store_true",
                   help="route the GroupNorm->FiLM/SiLU epilogues through "
                        "the fused Pallas kernels (ops/pallas_film.py; "
                        "interpret mode off-TPU).  Equivalent to "
                        "model.kernels='pallas'")
    p.add_argument("--raw_params", action="store_true",
                   help="serve raw params instead of EMA")
    p.add_argument("--warmup", action="store_true",
                   help="pre-compile the single-lane program for the "
                        "max_views bucket before accepting traffic")
    add_model_width_args(p)
    return p


def build_service(args):
    """Config + params + sampler(s) -> ServingService (not started), or
    a FleetService when --replicas > 1."""
    import dataclasses

    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler, record_capacity
    from diff3d_tpu.serving import FleetService, ServingService
    from diff3d_tpu.serving.fleet import build_fleet

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    if args.steps:
        cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                               timesteps=args.steps))
    cfg = apply_model_width_overrides(cfg, args)
    if args.pallas:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, kernels="pallas"))
    over = {k: getattr(args, k) for k in
            ("host", "port", "max_batch", "max_queue")
            if getattr(args, k) is not None}
    if args.replicas:            # 0 = remote-only fleet, keep cfg valid
        over["replicas"] = args.replicas
    if args.max_wait_ms is not None:
        over["max_wait_ms"] = args.max_wait_ms
    if args.timeout_s is not None:
        over["default_timeout_s"] = args.timeout_s
    if args.watchdog_s is not None:
        over["watchdog_timeout_s"] = args.watchdog_s
    if over:
        cfg = dataclasses.replace(
            cfg, serving=dataclasses.replace(cfg.serving, **over))
    cfg.validate()

    worker_addrs = []
    if getattr(args, "workers", None):
        for spec in args.workers.split(","):
            spec = spec.strip()
            if not spec:
                continue
            host, _, port_s = spec.rpartition(":")
            try:
                worker_addrs.append((host or "127.0.0.1", int(port_s)))
            except ValueError:
                raise SystemExit(
                    f"--workers entry {spec!r}: expected 'host:port'")
    # Local in-process replicas: with --workers present, default to a
    # pure-remote fleet unless --replicas asks for locals too.
    n_local = args.replicas if args.replicas is not None else (
        0 if worker_addrs else cfg.serving.replicas)
    if n_local == 0 and not worker_addrs:
        raise SystemExit("--replicas 0 needs --workers")

    def _remotes():
        from diff3d_tpu.serving.transport import (RemoteReplica,
                                                  TransportError)

        reps = []
        for host, port in worker_addrs:
            try:
                reps.append(RemoteReplica(
                    host, port,
                    heartbeat_interval_s=cfg.serving.heartbeat_interval_s,
                    heartbeat_timeout_s=cfg.serving.heartbeat_timeout_s,
                    max_frame_bytes=cfg.serving.max_frame_bytes))
            except TransportError as e:
                raise SystemExit(
                    f"--workers {host}:{port}: worker unreachable "
                    f"({e}) — start it first with "
                    f"'python -m diff3d_tpu.cli.worker_cli'")
        return reps

    if n_local == 0:
        # Remote-only front door: no local engine, no devices touched.
        logging.info("fronting %d remote workers, no local replicas",
                     len(worker_addrs))
        return FleetService(_remotes(), cfg)

    model = XUNet(cfg.model)
    if args.init == "random":
        from diff3d_tpu.train.trainer import init_params

        params = init_params(model, cfg, jax.random.PRNGKey(0))
        step, version = 0, "random-init"
    else:
        if not args.model:
            raise SystemExit("--model is required unless --init random")
        try:
            step, params = load_eval_params(args.model,
                                            build_abstract_state(cfg),
                                            args.raw_params)
        except ValueError as e:
            raise SystemExit(str(e))
        version = f"{args.model}@step{step}"
    logging.info("serving %s params (step %d)", version, step)

    mesh_env = None
    if getattr(args, "mesh", False):
        from diff3d_tpu.parallel import make_mesh

        mesh_env = make_mesh(cfg.mesh)
        logging.info("serving on mesh %s (lane multiple %d)",
                     dict(mesh_env.mesh.shape), mesh_env.data_size)
    sampler = Sampler(model, params, cfg, scan_chunks=args.scan_chunks,
                      mesh=mesh_env, sampler_kind=args.sampler,
                      steps=args.sampler_steps)
    cascade = None
    if args.cascade:
        from diff3d_tpu.cascade import CascadePlan, CascadeSampler

        try:
            plan = CascadePlan.parse(args.cascade)
        except ValueError as e:
            raise SystemExit(f"--cascade: {e}")
        if plan.refine.resolution != cfg.model.H:
            raise SystemExit(
                f"--cascade: refine resolution {plan.refine.resolution} "
                f"must equal the config's image size {cfg.model.H} "
                f"(--config {args.config})")
        cascade = CascadeSampler(model, params, cfg, plan, mesh=mesh_env)
        logging.info("cascade plan %s (draft %d^2 -> refine %d^2 from "
                     "t=%.2f)", plan.spec(), plan.draft.resolution,
                     plan.refine.resolution, plan.refine.start_t)
    n_replicas = n_local
    extra_samplers = {}
    per_replica_extra = {}
    made = {}                  # one Sampler per distinct extra schedule

    def _sampler_for(sched):
        if sched not in made:
            made[sched] = Sampler(
                model, params, cfg, scan_chunks=args.scan_chunks,
                mesh=mesh_env, sampler_kind=sched[0], steps=sched[1])
        return made[sched]

    if args.schedules:
        for spec in args.schedules.split(","):
            spec = spec.strip()
            target, at, rest = spec.partition("@")
            idx = None
            if at:
                try:
                    idx = int(target)
                except ValueError:
                    raise SystemExit(
                        f"--schedules entry {spec!r}: replica prefix "
                        "must be an integer index ('i@kind:steps')")
                if not 0 <= idx < n_replicas:
                    raise SystemExit(
                        f"--schedules entry {spec!r}: replica index "
                        f"{idx} outside --replicas {n_replicas}")
            else:
                rest = spec
            kind, _, steps_s = rest.partition(":")
            try:
                sched = (kind, int(steps_s))
            except ValueError:
                raise SystemExit(
                    f"--schedules entry {spec!r}: expected "
                    "'[i@]kind:steps'")
            if sched == (sampler.sampler_kind, sampler.steps):
                continue                    # already the default sampler
            if idx is None:
                extra_samplers[sched] = _sampler_for(sched)
            else:
                per_replica_extra.setdefault(idx, {})[sched] = (
                    _sampler_for(sched))
    if worker_addrs:
        # Mixed fleet: local in-process replicas + remote workers
        # behind one router (sessions pin across both kinds).
        local = build_fleet(
            sampler, cfg, n_replicas,
            extra_samplers=extra_samplers or None,
            per_replica_extra=per_replica_extra or None,
            params_version=version, cascade=cascade)
        service = FleetService(local + _remotes(), cfg)
    elif n_replicas > 1:
        service = FleetService.build(
            sampler, cfg, n=n_replicas,
            extra_samplers=extra_samplers or None,
            per_replica_extra=per_replica_extra or None,
            params_version=version, cascade=cascade)
    else:
        if per_replica_extra:
            raise SystemExit(
                "per-replica 'i@kind:steps' schedules require "
                "--replicas > 1")
        service = ServingService(sampler, cfg, params_version=version,
                                 extra_samplers=extra_samplers or None,
                                 cascade=cascade)
    if args.warmup:
        from diff3d_tpu.serving import Bucket

        cap = record_capacity(cfg.serving.max_views)
        # Remote replicas warm their own programs at worker boot; only
        # local engines can be warmed from this process.
        engines = ([service.engine] if hasattr(service, "engine")
                   else [rep.engine for rep in service.replicas
                         if hasattr(rep, "engine")])
        for eng in engines:
            for s in eng.samplers.values():
                bucket = Bucket(cfg.model.H, cfg.model.W, cap,
                                s.steps, s.sampler_kind)
                secs = eng.programs.warmup(bucket, s.lane_multiple,
                                           s.w.shape[0])
                logging.info("warmed bucket %s in %.1fs",
                             tuple(bucket), secs)
            if eng.cascade is not None:
                for phase, s in (("draft", eng.cascade.draft),
                                 ("refine", eng.cascade.refine)):
                    bucket = Bucket(s.cfg.model.H, s.cfg.model.W, cap,
                                    s.steps, s.sampler_kind, phase)
                    secs = eng.programs.warmup(bucket, s.lane_multiple,
                                               s.w.shape[0])
                    logging.info("warmed cascade %s bucket %s in %.1fs",
                                 phase, tuple(bucket), secs)
    return service


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    service = build_service(args)
    service.start(serve_http=True)
    fleet = " , GET /fleet" if hasattr(service, "fleet_snapshot") else ""
    logging.info("listening on http://%s:%d (POST /synthesize, "
                 "GET /healthz, GET /metrics%s)",
                 service.cfg.serving.host, service.port, fleet)

    done = threading.Event()

    def _sig(signum, frame):
        logging.info("signal %d: shutting down", signum)
        done.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        done.wait()
    finally:
        service.stop(drain_s=args.drain_s)
        logging.info("stopped")


if __name__ == "__main__":
    main()
