"""Shared CLI plumbing.

The three checkpoint-consuming CLIs (train resume, sample, eval) must
rebuild the exact ``ModelConfig`` a checkpoint was trained with; the
width knobs that change the parameter tree's shape live here so a new
knob lands in every CLI at once.
"""

from __future__ import annotations

import argparse
import dataclasses

_WIDTH_KEYS = ("ch", "emb_ch", "num_res_blocks")


def add_model_width_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--ch", type=int, default=None,
                   help="base channel width — must match the trained "
                        "checkpoint (reference: 128 at 64^2, "
                        "xunet.py:229; smaller widths train/checkpoint "
                        "faster on slow dev links)")
    p.add_argument("--emb_ch", type=int, default=None,
                   help="conditioning embedding width (reference: 1024)")
    p.add_argument("--num_res_blocks", type=int, default=None,
                   help="res blocks per UNet level (reference: 3)")
    p.add_argument("--imgsize", type=int, default=None,
                   help="square image resolution H=W — overrides the "
                        "--config preset (must match the trained "
                        "checkpoint; must be divisible by 2^(levels-1))")


def apply_model_width_overrides(cfg, args):
    """Returns ``cfg`` with any of --ch/--emb_ch/--num_res_blocks applied,
    plus --imgsize (H=W resolution override)."""
    over = {k: getattr(args, k) for k in _WIDTH_KEYS
            if getattr(args, k) is not None}
    if getattr(args, "imgsize", None) is not None:
        over["H"] = over["W"] = args.imgsize
    if not over:
        return cfg
    return dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, **over))


def build_abstract_state(cfg):
    """Abstract TrainState template (ShapeDtypeStructs, nothing
    materialised) for ``XUNet(cfg.model)`` — the restore target every
    checkpoint-consuming CLI needs.  ``jax.eval_shape`` means no params,
    moments, or EMA are ever allocated just to describe the tree."""
    import jax

    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train import create_train_state
    from diff3d_tpu.train.trainer import init_params

    model = XUNet(cfg.model)
    return jax.eval_shape(lambda: create_train_state(
        init_params(model, cfg, jax.random.PRNGKey(0)), cfg.train))


def load_eval_params(model_dir: str, state, raw_params: bool):
    """Load ``(step, params)`` for inference from a checkpoint directory of
    either save mode (full TrainState or ema_bf16 — see
    ``train/checkpoint.py``).  ``state`` is a template TrainState —
    abstract (:func:`build_abstract_state`) or concrete; ``raw_params``
    picks the non-EMA weights, which only full checkpoints carry."""
    import jax

    from diff3d_tpu.train import CheckpointManager

    mgr = CheckpointManager(model_dir)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    if mgr.mode == "ema_bf16":
        if raw_params:
            # Typed error at the library layer; the CLIs present argparse
            # problems as SystemExit themselves (ADVICE r4 — train_cli's
            # --init_from path also lands here, and a library misuse
            # should not look like a clean CLI exit).
            raise ValueError(
                f"{model_dir} is an ema_bf16 checkpoint: it has no raw "
                "params to score (--raw_params unavailable)")
        got = mgr.restore_ema(abstract.params)
        if got is None:
            raise FileNotFoundError(f"no checkpoint under {model_dir}")
        return got
    restored = mgr.restore(abstract)
    if restored is None:
        raise FileNotFoundError(f"no checkpoint under {model_dir}")
    params = restored.params if raw_params else restored.ema_params
    return int(restored.step), params
