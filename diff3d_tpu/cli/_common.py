"""Shared CLI plumbing.

The three checkpoint-consuming CLIs (train resume, sample, eval) must
rebuild the exact ``ModelConfig`` a checkpoint was trained with; the
width knobs that change the parameter tree's shape live here so a new
knob lands in every CLI at once.
"""

from __future__ import annotations

import argparse
import dataclasses

_WIDTH_KEYS = ("ch", "emb_ch", "num_res_blocks")


def add_model_width_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--ch", type=int, default=None,
                   help="base channel width — must match the trained "
                        "checkpoint (reference: 128 at 64^2, "
                        "xunet.py:229; smaller widths train/checkpoint "
                        "faster on slow dev links)")
    p.add_argument("--emb_ch", type=int, default=None,
                   help="conditioning embedding width (reference: 1024)")
    p.add_argument("--num_res_blocks", type=int, default=None,
                   help="res blocks per UNet level (reference: 3)")


def apply_model_width_overrides(cfg, args):
    """Returns ``cfg`` with any of --ch/--emb_ch/--num_res_blocks applied."""
    over = {k: getattr(args, k) for k in _WIDTH_KEYS
            if getattr(args, k) is not None}
    if not over:
        return cfg
    return dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, **over))
