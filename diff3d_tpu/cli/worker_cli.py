"""Fleet worker process: one replica pinned to a device slice.

Boots a single serving replica in THIS process, pinned to a disjoint
subset of the host's devices, and serves the framed socket protocol
(``serving/transport.py``) that ``serve_cli --workers`` fronts.  N
workers on one host split the device set instead of sharing it — on
the CPU test backend the 8 virtual devices split 2×4::

    python -m diff3d_tpu.cli.worker_cli --config test --init random \
        --devices 0-3 --port 0 --name w0 --host_device_count 8
    python -m diff3d_tpu.cli.worker_cli --config test --init random \
        --devices 4-7 --port 0 --name w1 --host_device_count 8

With ``--port 0`` the worker binds an ephemeral port and prints one
JSON ready line to stdout (``{"ready": true, "port": ..., "name":
..., "http_port": ...}``) so a supervisor can harvest the address.

``--hbm_budget_bytes`` arms the admission gate: requests whose
resident-records + program-peak arithmetic (the ``runs/memcheck/``
pins, see ``--memcheck_dir``) exceeds the slice budget are rejected at
the door with a typed ``ReplicaOverBudget``.  ``--compile_cache DIR``
points jax's persistent compilation cache at a shared directory so
sibling workers and blue/green restarts skip cold compiles.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading

from diff3d_tpu.cli._common import (add_model_width_args,
                                    apply_model_width_overrides,
                                    build_abstract_state,
                                    load_eval_params)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default=None,
                   help="checkpoint directory; omit with --init random")
    p.add_argument("--init", choices=["checkpoint", "random"],
                   default="checkpoint")
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--name", default=None,
                   help="replica name (fleet-wide identity; default "
                        "'w<pid>')")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for the socket transport")
    p.add_argument("--port", type=int, default=0,
                   help="transport port (0 = ephemeral; the bound port "
                        "is printed on the JSON ready line)")
    p.add_argument("--http_port", type=int, default=None,
                   help="also serve the worker's own HTTP surface "
                        "(/healthz /metrics /stats) on this port "
                        "(0 = ephemeral)")
    p.add_argument("--devices", required=True,
                   help="device slice this replica owns: '0-3' "
                        "(inclusive range) or '0,2,4' (list); disjoint "
                        "across workers on one host")
    p.add_argument("--host_device_count", type=int, default=None,
                   help="force this many virtual host devices "
                        "(XLA_FLAGS, CPU backend) — set it identically "
                        "on every worker sharing a host so slices mean "
                        "the same thing")
    p.add_argument("--sampler", choices=["ancestral", "ddim"],
                   default="ancestral")
    p.add_argument("--sampler_steps", type=int, default=None,
                   help="reverse steps per view for the default sampler "
                        "(default: the config's dense grid)")
    p.add_argument("--schedules", default=None,
                   help="extra compiled schedules beyond the default, "
                        "'kind:steps,...' — same grammar as serve_cli "
                        "--schedules (no 'i@' prefix: one worker is one "
                        "replica)")
    p.add_argument("--scan_chunks", type=int, default=1)
    p.add_argument("--hbm_budget_bytes", type=int, default=0,
                   help="slice HBM budget for admission control "
                        "(0 disables): resident records + program peak "
                        "past it -> typed ReplicaOverBudget 503")
    p.add_argument("--memcheck_dir", default=None,
                   help="memcheck manifest dir with the program peak "
                        "pins (default: runs/memcheck)")
    p.add_argument("--compile_cache", default=None,
                   help="persistent XLA compile-cache dir shared "
                        "across workers/restarts")
    p.add_argument("--shallow", action="store_true",
                   help="with --config test: shallow 2-level UNet")
    p.add_argument("--max_views", type=int, default=None)
    p.add_argument("--timeout_s", type=float, default=None)
    p.add_argument("--raw_params", action="store_true")
    add_model_width_args(p)
    return p


def parse_schedules(spec: str):
    scheds = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, _, steps_s = entry.partition(":")
        try:
            scheds.append((kind, int(steps_s)))
        except ValueError:
            raise SystemExit(
                f"--schedules entry {entry!r}: expected 'kind:steps'")
    return scheds


def build_worker(args):
    """Config + params -> Worker (not started)."""
    import dataclasses

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.analysis import membudgets
    from diff3d_tpu.serving.worker import boot_worker, device_slice

    if args.config == "test":
        cfg = config_lib.test_config(
            imgsize=args.imgsize or 16,
            ch=args.ch or 8,
            shallow=args.shallow)
    else:
        cfg = {"srn64": config_lib.srn64_config,
               "srn128": config_lib.srn128_config}[args.config]()
        cfg = apply_model_width_overrides(cfg, args)
    over = {}
    if args.max_views is not None:
        over["max_views"] = args.max_views
    if args.timeout_s is not None:
        over["default_timeout_s"] = args.timeout_s
    if over:
        cfg = dataclasses.replace(
            cfg, serving=dataclasses.replace(cfg.serving, **over))
    cfg.validate()

    params, version = None, "random-init"
    if args.init == "checkpoint":
        if not args.model:
            raise SystemExit("--model is required unless --init random")
        try:
            step, params = load_eval_params(args.model,
                                            build_abstract_state(cfg),
                                            args.raw_params)
        except ValueError as e:
            raise SystemExit(str(e))
        version = f"{args.model}@step{step}"

    name = args.name or f"w{os.getpid()}"
    return boot_worker(
        cfg,
        name=name,
        devices=device_slice(args.devices),
        sampler_kind=args.sampler,
        steps=args.sampler_steps,
        extra_schedules=(parse_schedules(args.schedules)
                         if args.schedules else None),
        params=params,
        params_version=version,
        host=args.host,
        port=args.port,
        hbm_budget_bytes=args.hbm_budget_bytes,
        memcheck_dir=(args.memcheck_dir
                      or membudgets.DEFAULT_MANIFEST_DIR),
        compile_cache=args.compile_cache,
        scan_chunks=args.scan_chunks)


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    # Must precede the first jax import anywhere in-process: the CPU
    # backend reads XLA_FLAGS once, at client init.
    if args.host_device_count:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.host_device_count}").strip()
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    worker = build_worker(args)
    worker.start(http_port=args.http_port)
    # Machine-readable ready line: supervisors (serve_cli --workers,
    # chaos_router --remote, the tests) harvest the ephemeral port.
    print(json.dumps({"ready": True, "name": worker.replica.name,
                      "port": worker.port,
                      "http_port": worker.http_port}), flush=True)
    logging.info("worker %s: transport on %s:%d",
                 worker.replica.name, args.host, worker.port)

    done = threading.Event()

    def _sig(signum, frame):
        logging.info("signal %d: shutting down", signum)
        done.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        done.wait()
    finally:
        worker.stop()
        logging.info("stopped")


if __name__ == "__main__":
    main()
