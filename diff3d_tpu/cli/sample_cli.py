"""Novel-view sampling entry point.

Flag parity with the reference sampler (``/root/reference/sampling.py:
19-23``): ``--model`` is the checkpoint to load, ``--target`` the SRN
object directory whose views are synthesised autoregressively.  Output
layout matches ``sampling/{step}/{gt,0..7}.png`` (``sampling.py:179-182``).

Usage:
    python -m diff3d_tpu.cli.sample_cli --model ./checkpoints \
        --target ./data/SRN/cars_test/<object-id> [--out ./sampling]
"""

from __future__ import annotations

import argparse
import logging
import os

from diff3d_tpu.cli._common import (add_model_width_args,
                                    apply_model_width_overrides,
                                    build_abstract_state,
                                    load_eval_params)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True,
                   help="checkpoint directory (Orbax root)")
    p.add_argument("--target", required=True,
                   help="SRN object dir with rgb/ pose/ intrinsics/")
    p.add_argument("--out", default="sampling")
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps (reference: 256)")
    p.add_argument("--max_views", type=int, default=None)
    p.add_argument("--scan_chunks", type=int, default=1,
                   help="split each view's diffusion scan into this many "
                        "device executions (must divide --steps; "
                        "bit-identical to 1 — raise where one long "
                        "execution trips an RPC deadline, e.g. "
                        "full-width 128^2 over a tunneled chip)")
    p.add_argument("--raw_params", action="store_true",
                   help="sample with raw params instead of EMA")
    p.add_argument("--seed", type=int, default=0)
    add_model_width_args(p)
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    import dataclasses

    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.data.srn import load_object_views
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    if args.steps:
        cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                               timesteps=args.steps))
    cfg = apply_model_width_overrides(cfg, args)

    model = XUNet(cfg.model)
    try:
        step, params = load_eval_params(args.model,
                                        build_abstract_state(cfg),
                                        args.raw_params)
    except ValueError as e:   # e.g. --raw_params on an ema_bf16 checkpoint
        raise SystemExit(str(e))
    logging.info("loaded step-%d checkpoint from %s", step, args.model)

    # Load every view of the target object dir (reference sampling.py:26-48).
    views = load_object_views(os.path.normpath(args.target), cfg.model.H)

    sampler = Sampler(model, params, cfg,
                      scan_chunks=args.scan_chunks)
    sampler.synthesize(views, jax.random.PRNGKey(args.seed),
                       out_dir=args.out, max_views=args.max_views)
    logging.info("wrote %s", args.out)


if __name__ == "__main__":
    main()
