"""Evaluation entry point: PSNR / SSIM / FID of synthesised novel views.

The reference has NO evaluation code (``SURVEY.md`` §5.5) despite FID/PSNR
being the paper's headline metrics; this closes that gap.  For each of the
first ``--objects`` val-split objects, the trained model synthesises every
view autoregressively from view 0 (the reference sampler's protocol,
``/root/reference/sampling.py:158-184``), and the generated views are
scored against ground truth:

  * PSNR / SSIM per view at the sampler's guidance weight ``--w_index``
    (default 1, i.e. w=1 in the reference's 0..7 sweep), averaged.
  * FID between the pooled generated views and the pooled GT views.
    With ``--feature_weights <local VGG16 state dict>`` the real
    VGG16-fc2 extractor is used and the number is reported as ``fid``;
    without it the seeded random-projection fallback is used and the
    number is reported as ``fid_randfeat`` — the key always says which
    extractor produced the value (``evaluation/features.py``).

Writes one JSON line to stdout and (optionally) ``--out`` JSONL.

Usage:
    python -m diff3d_tpu.cli.eval_cli --model ./checkpoints \
        --val_data ./data/SRN/cars_train [--objects 8]
"""

from __future__ import annotations

import argparse
import json
import logging

from diff3d_tpu.cli._common import (add_model_width_args,
                                    apply_model_width_overrides,
                                    build_abstract_state,
                                    load_eval_params)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True,
                   help="checkpoint directory (Orbax root)")
    p.add_argument("--val_data", default=None,
                   help="SRN split dir (val objects are drawn from the "
                        "same 90/10 split the trainer used)")
    p.add_argument("--synthetic_scenes", action="store_true",
                   help="evaluate on ray-traced sphere scenes instead of "
                        "--val_data (default seed 1 = the held-out set "
                        "train_cli --synthetic_scenes validates on)")
    p.add_argument("--scenes_seed", type=int, default=1,
                   help="scene generator seed for --synthetic_scenes "
                        "(0 = the training scenes, 1 = held-out)")
    p.add_argument("--scene_objects", type=int, default=None,
                   help="the --scene_objects count the model was TRAINED "
                        "with; with --scenes_seed 0 ('the training "
                        "scenes'), --objects beyond it were never seen in "
                        "training and would skew a train-vs-heldout "
                        "comparison, so that combination errors out")
    p.add_argument("--object_batch", type=int, default=None,
                   help="objects synthesised concurrently as one batched "
                        "program (objects are independent; batching fills "
                        "the chip — per-object scores match --object_batch "
                        "1 to float tolerance).  Default: 8 at <=64^2, 2 "
                        "above (the batched model call and the record "
                        "buffer both scale with it; lower if OOM)")
    add_model_width_args(p)
    p.add_argument("--picklefile", default=None)
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--objects", type=int, default=8,
                   help="number of val objects to evaluate")
    p.add_argument("--max_views", type=int, default=None,
                   help="cap views per object (full object if omitted)")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps (reference: 256)")
    p.add_argument("--scan_chunks", type=int, default=1,
                   help="split each view's diffusion scan into this many "
                        "device executions (must divide --steps; "
                        "bit-identical to 1 — raise where one long "
                        "execution trips an RPC deadline, e.g. "
                        "full-width 128^2 over a tunneled chip)")
    p.add_argument("--w_index", type=int, default=1,
                   help="guidance-sweep index scored for PSNR/SSIM/FID")
    p.add_argument("--feature_weights", default=None,
                   help="local VGG16 state-dict file (.pth/.pt/.npz, "
                        "torchvision key names) for real-feature FID; "
                        "omitted -> random-feature fallback, reported as "
                        "fid_randfeat")
    p.add_argument("--raw_params", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None, help="append JSONL here")
    p.add_argument("--save_dir", default=None,
                   help="dump gt/generated view PNGs here "
                        "(<obj>/view{V}_{gt,gen}.png)")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    # Dataset-choice errors fire BEFORE model init + checkpoint restore
    # (minutes on a slow device link).
    if args.synthetic_scenes and args.val_data:
        raise SystemExit(
            "--synthetic_scenes and --val_data are mutually exclusive")
    if not (args.synthetic_scenes or args.val_data):
        raise SystemExit("pass --val_data or --synthetic_scenes")
    if (args.synthetic_scenes and args.scenes_seed == 0
            and args.scene_objects is not None
            and args.objects > args.scene_objects):
        raise SystemExit(
            f"--scenes_seed 0 scores training scenes, but --objects "
            f"{args.objects} exceeds the trained --scene_objects "
            f"{args.scene_objects}: objects beyond the trained count were "
            "never seen in training and would be mislabeled as 'train' "
            "scores — lower --objects or drop --scene_objects")
    if args.object_batch is not None and args.object_batch < 1:
        raise SystemExit("--object_batch must be >= 1")

    import dataclasses

    import jax
    import numpy as np

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.data.srn import SRNDataset
    from diff3d_tpu.evaluation import (fid_from_stats, gaussian_stats, psnr,
                                       ssim)
    from diff3d_tpu.evaluation.features import resolve_feature_fn
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    if args.steps:
        cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                               timesteps=args.steps))
    cfg = apply_model_width_overrides(cfg, args)

    # Fail fast on a bad --feature_weights path/file BEFORE the expensive
    # sampling loop; jit once here so the gt and gen stats passes share
    # one compiled executable.
    feature_fn, fid_key = resolve_feature_fn(args.feature_weights)
    feature_fn = jax.jit(feature_fn)

    model = XUNet(cfg.model)
    step, params = load_eval_params(args.model, build_abstract_state(cfg),
                                    args.raw_params)

    if args.synthetic_scenes:
        from diff3d_tpu.data import SyntheticScenesDataset

        ds = SyntheticScenesDataset(num_objects=max(8, args.objects),
                                    imgsize=cfg.model.H,
                                    seed=args.scenes_seed)
    else:
        ds = SRNDataset("val", args.val_data, args.picklefile,
                        imgsize=cfg.model.H,
                        split_seed=cfg.data.split_seed,
                        train_fraction=cfg.data.train_fraction)
    sampler = Sampler(model, params, cfg,
                      scan_chunks=args.scan_chunks)

    if args.object_batch is None:
        # The batched model call (N*2B examples) and the [N, capacity, B,
        # H, W, 3] record buffer both scale with N; at 128^2 a full-width
        # no-max_views eval would OOM at N=8, so the default stays shy
        # there and the flag overrides.
        args.object_batch = 8 if cfg.model.H <= 64 else 2
        logging.info("object_batch auto -> %d (H=%d)", args.object_batch,
                     cfg.model.H)

    # Per-object keys are split off in object order BEFORE batching, so
    # the scores are invariant to --object_batch (same key -> same
    # per-object stream; see Sampler.synthesize_many).
    rng = jax.random.PRNGKey(args.seed)
    objs = list(ds.ids[: args.objects])
    obj_views, obj_keys = [], []
    for obj in objs:
        obj_views.append(ds.all_views(obj))
        rng, k = jax.random.split(rng)
        obj_keys.append(k)

    def n_views_of(v) -> int:
        n = int(v["imgs"].shape[0])
        return min(n, args.max_views) if args.max_views else n

    per_object = []
    psnrs, base_psnrs, ssims, gen_views, gt_views = [], [], [], [], []
    per_w_psnrs = None
    i = 0
    while i < len(objs):
        # chunk of <= object_batch consecutive objects with equal view
        # counts (synthesize_many truncates to the batch minimum)
        j, nv = i + 1, n_views_of(obj_views[i])
        while (j < len(objs) and j - i < args.object_batch
               and n_views_of(obj_views[j]) == nv):
            j += 1
        outs = sampler.synthesize_many(obj_views[i:j], obj_keys[i:j],
                                       max_views=args.max_views)
        for obj, views, out in zip(objs[i:j], obj_views[i:j], outs):
            if out.shape[0] == 0:
                continue
            gen = out[:, args.w_index]                 # [V-1, H, W, 3]
            gt = views["imgs"][1: 1 + gen.shape[0]]
            # the guidance sweep is the batch axis — score every w while
            # the samples are in hand (picking w after the fact is free);
            # the headline psnr list reuses this object's w_index column
            obj_w_psnrs = [np.asarray(psnr(out[:, wi], gt)).tolist()
                           for wi in range(out.shape[1])]
            if per_w_psnrs is None:
                per_w_psnrs = [[] for _ in range(out.shape[1])]
            for wi, vals in enumerate(obj_w_psnrs):
                per_w_psnrs[wi].extend(vals)
            obj_psnrs = obj_w_psnrs[args.w_index]
            obj_ssims = np.asarray(ssim(gen, gt)).tolist()
            # copy-view-0 baseline: the score of ignoring the pose
            # entirely and repeating the conditioning view — synthesis
            # must beat this
            copy0 = np.broadcast_to(views["imgs"][:1], gt.shape)
            obj_base = np.asarray(psnr(copy0, gt)).tolist()
            psnrs.extend(obj_psnrs)
            ssims.extend(obj_ssims)
            base_psnrs.extend(obj_base)
            gen_views.append(gen)
            gt_views.append(gt)
            per_object.append({
                "id": str(obj),
                "views": len(obj_psnrs),
                "psnr": round(float(np.mean(obj_psnrs)), 3),
                "psnr_std": round(float(np.std(obj_psnrs)), 3),
                "psnr_copy_view0": round(float(np.mean(obj_base)), 3),
                "ssim": round(float(np.mean(obj_ssims)), 4),
            })
            if args.save_dir:
                import os

                from PIL import Image

                from diff3d_tpu.sampling.runtime import to_uint8

                d = os.path.join(args.save_dir, str(obj))
                os.makedirs(d, exist_ok=True)
                Image.fromarray(to_uint8(views["imgs"][0])).save(
                    os.path.join(d, "view0_cond.png"))
                for v in range(gen.shape[0]):
                    Image.fromarray(to_uint8(gt[v])).save(
                        os.path.join(d, f"view{v + 1}_gt.png"))
                    Image.fromarray(to_uint8(gen[v])).save(
                        os.path.join(d, f"view{v + 1}_gen.png"))
            logging.info("object %s: psnr %.2f (copy-view-0 %.2f)", obj,
                         per_object[-1]["psnr"],
                         per_object[-1]["psnr_copy_view0"])
        i = j

    if not gen_views:
        raise SystemExit(
            "no views generated: every object had < 2 usable views "
            "(check --max_views / the dataset)")
    if fid_key == "fid_randfeat":
        logging.warning(
            "FID below uses the seeded random-projection fallback — "
            "reported as 'fid_randfeat', NOT comparable to paper FID. "
            "Pass --feature_weights <local VGG16 state dict> for "
            "real-feature FID.")

    fid = fid_from_stats(gaussian_stats(gt_views, feature_fn),
                         gaussian_stats(gen_views, feature_fn))
    # Per-object dispersion: the quality claim is "synthesis beats the
    # copy-view-0 baseline by more than the per-object spread", so the
    # margin's mean/std across objects is first-class output.
    margins = [o["psnr"] - o["psnr_copy_view0"] for o in per_object]
    obj_means = [o["psnr"] for o in per_object]
    record = {
        "checkpoint_step": step,
        "objects": len(gen_views),
        "views": len(psnrs),
        "psnr": round(float(np.mean(psnrs)), 3),
        "psnr_copy_view0_baseline": round(float(np.mean(base_psnrs)), 3),
        "psnr_obj_mean": round(float(np.mean(obj_means)), 3),
        "psnr_obj_std": round(float(np.std(obj_means)), 3),
        "psnr_margin_mean": round(float(np.mean(margins)), 3),
        "psnr_margin_std": round(float(np.std(margins)), 3),
        "objects_above_baseline": int(sum(m > 0 for m in margins)),
        "psnr_per_w": [round(float(np.mean(p)), 3) for p in per_w_psnrs],
        "ssim": round(float(np.mean(ssims)), 4),
        fid_key: round(float(fid), 3),
        "w_index": args.w_index,
        "timesteps": cfg.diffusion.timesteps,
        "per_object": per_object,
    }
    print(json.dumps(record))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(record) + "\n")


if __name__ == "__main__":
    main()
