"""Evaluation entry point: PSNR / SSIM / FID of synthesised novel views.

The reference has NO evaluation code (``SURVEY.md`` §5.5) despite FID/PSNR
being the paper's headline metrics; this closes that gap.  For each of the
first ``--objects`` val-split objects, the trained model synthesises every
view autoregressively from view 0 (the reference sampler's protocol,
``/root/reference/sampling.py:158-184``), and the generated views are
scored against ground truth:

  * PSNR / SSIM per view at the sampler's guidance weight ``--w_index``
    (default 1, i.e. w=1 in the reference's 0..7 sweep), averaged.
  * FID between the pooled generated views and the pooled GT views.
    With ``--feature_weights <local VGG16 state dict>`` the real
    VGG16-fc2 extractor is used and the number is reported as ``fid``;
    without it the seeded random-projection fallback is used and the
    number is reported as ``fid_randfeat`` — the key always says which
    extractor produced the value (``evaluation/features.py``).

Evaluation is OUTAGE-PROOF: synthesis and scoring are separate phases.
Each object's generated views are written to ``--resume_dir`` (default
``<out>.objdir``) the moment its batch finishes; re-running the same
command skips already-synthesised objects and proceeds straight to
scoring, so a link failure N objects in costs nothing but the partial
batch.  Scoring always recomputes every metric from the on-disk records,
so the final JSON is identical whether the run completed in one pass or
five.

``--w_select K`` adds validation-selected guidance: K EXTRA objects
(drawn after the eval set — disjoint from it) are synthesised, the
guidance weight with the best mean PSNR on them is chosen, and the eval
set is additionally scored at that weight (``*_w_selected`` fields).
The fixed ``--w_index`` headline is unchanged; selection never sees an
eval object.  This is the methodologically clean version of the
reference's w=0..7 sweep (``/root/reference/sampling.py:158``), whose
point is that the best w is data-dependent.

Writes one JSON line to stdout and (optionally) ``--out`` JSONL.

Usage:
    python -m diff3d_tpu.cli.eval_cli --model ./checkpoints \
        --val_data ./data/SRN/cars_train [--objects 8]
"""

from __future__ import annotations

import argparse
import json
import logging
import os

from diff3d_tpu.cli._common import (add_model_width_args,
                                    apply_model_width_overrides,
                                    build_abstract_state,
                                    load_eval_params)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True,
                   help="checkpoint directory (Orbax root)")
    p.add_argument("--val_data", default=None,
                   help="SRN split dir (val objects are drawn from the "
                        "same 90/10 split the trainer used)")
    p.add_argument("--synthetic_scenes", action="store_true",
                   help="evaluate on ray-traced sphere scenes instead of "
                        "--val_data (default seed 1 = the held-out set "
                        "train_cli --synthetic_scenes validates on)")
    p.add_argument("--scenes_seed", type=int, default=1,
                   help="scene generator seed for --synthetic_scenes "
                        "(0 = the training scenes, 1 = held-out)")
    p.add_argument("--scene_objects", type=int, default=None,
                   help="the --scene_objects count the model was TRAINED "
                        "with; with --scenes_seed 0 ('the training "
                        "scenes'), --objects beyond it were never seen in "
                        "training and would skew a train-vs-heldout "
                        "comparison, so that combination errors out")
    p.add_argument("--object_batch", type=int, default=None,
                   help="objects synthesised concurrently as one batched "
                        "program (objects are independent; batching fills "
                        "the chip — per-object scores match --object_batch "
                        "1 to float tolerance).  Default: 8 at <=64^2, 2 "
                        "above (the batched model call and the record "
                        "buffer both scale with it; lower if OOM)")
    p.add_argument("--mesh", action="store_true",
                   help="shard synthesis over a device mesh (cfg.mesh): "
                        "the object batch rides the data axis, params "
                        "follow the configured replicated/fsdp policy; "
                        "--object_batch rounds up to the data-axis size")
    add_model_width_args(p)
    p.add_argument("--picklefile", default=None)
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--objects", type=int, default=8,
                   help="number of val objects to evaluate")
    p.add_argument("--max_views", type=int, default=None,
                   help="cap views per object (full object if omitted)")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps (reference: 256) — the DENSE "
                        "training grid; see --sampler_steps for the "
                        "few-step sampling subset")
    p.add_argument("--sampler", choices=["ancestral", "ddim"],
                   default="ancestral",
                   help="reverse-process update: 'ancestral' (paper's "
                        "stochastic sampler) or 'ddim' (deterministic "
                        "eta=0, enables few-step sampling)")
    p.add_argument("--sampler_steps", type=int, default=None,
                   help="few-step schedule: reverse steps per view, a "
                        "divisor of the dense grid (e.g. 16 with 256 "
                        "timesteps); default = full grid")
    p.add_argument("--parity_objects", type=int, default=0,
                   help="ALSO synthesise this many eval objects with the "
                        "full-grid ancestral oracle at matched seeds and "
                        "report PSNR/SSIM of the evaluated sampler "
                        "against it (sampler_parity in the output JSON) — "
                        "quantifies few-step quality degradation")
    p.add_argument("--scan_chunks", type=int, default=1,
                   help="split each view's diffusion scan into this many "
                        "device executions (must divide --steps; "
                        "bit-identical to 1 — raise where one long "
                        "execution trips an RPC deadline, e.g. "
                        "full-width 128^2 over a tunneled chip)")
    p.add_argument("--w_index", type=int, default=1,
                   help="guidance-sweep index scored for PSNR/SSIM/FID")
    p.add_argument("--w_select", type=int, default=0,
                   help="ALSO score at a validation-selected guidance "
                        "weight: synthesise this many extra selection "
                        "objects (disjoint from the eval set, drawn after "
                        "it), pick the w with the best mean PSNR on them, "
                        "and report *_w_selected fields at that w")
    p.add_argument("--feature_weights", default=None,
                   help="local VGG16 state-dict file (.pth/.pt/.npz, "
                        "torchvision key names) for real-feature FID; "
                        "omitted -> random-feature fallback, reported as "
                        "fid_randfeat")
    p.add_argument("--raw_params", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None, help="append final JSONL here")
    p.add_argument("--resume_dir", default=None,
                   help="per-object synthesis records live here (one .npz "
                        "per object, written as each object completes); "
                        "re-running skips objects already present.  "
                        "Default: <--out>.objdir when --out is given, "
                        "else a fresh temp dir (no resumability)")
    p.add_argument("--save_dir", default=None,
                   help="dump gt/generated view PNGs here "
                        "(<obj>/view{V}_{gt,gen}.png)")
    p.add_argument("--orbit", type=int, default=0,
                   help="ALSO render an N-frame orbit turntable per "
                        "--orbit_objects eval object (radius/elevation "
                        "derived from its GT poses) and report the "
                        "multi-view reprojection-consistency metric "
                        "(orbit_consistency in the output JSON); with "
                        "--save_dir the frames land in "
                        "<obj>/orbit/frame_%%03d.png + a contact sheet")
    p.add_argument("--orbit_objects", type=int, default=1,
                   help="eval objects to render orbits for (first K)")
    return p


def _record_path(resume_dir: str, obj, step: int) -> str:
    # checkpoint step is part of the NAME, not the settings stamp: after
    # more training, the same longitudinal eval command simply finds no
    # records for the new step and re-synthesises (stale-step records are
    # ignored, not a fatal protocol conflict) — while a dataset/model/
    # seed/timesteps mismatch against a same-step record stays a hard
    # error, since silently mixing those corrupts the aggregate.
    return os.path.join(resume_dir, f"obj_s{step}_{obj}.npz")


def _save_object_record(resume_dir: str, obj, gen, meta: dict) -> None:
    """Atomically persist one object's generated views (all guidance
    weights, float16 — ~2.4 MB at 128^2) plus the synthesis settings
    they were produced under."""
    import numpy as np

    path = _record_path(resume_dir, obj, meta["checkpoint_step"])
    tmp = path + ".tmp"
    np.savez_compressed(tmp, gen=gen.astype(np.float16),
                        meta=json.dumps(meta))
    # np.savez appends .npz to names it doesn't recognise
    if os.path.exists(tmp + ".npz"):
        tmp += ".npz"
    os.replace(tmp, path)


def _load_object_record(resume_dir: str, obj, expect_meta: dict):
    """Return (gen float32, True) if a valid record exists, else
    (None, False).  A record whose synthesis settings don't match the
    current flags is a hard error — silently mixing protocols would
    corrupt the aggregate."""
    import numpy as np

    path = _record_path(resume_dir, obj, expect_meta["checkpoint_step"])
    if not os.path.exists(path):
        return None, False
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        gen = z["gen"]                   # float16, cast per-use
    if meta != expect_meta:
        raise SystemExit(
            f"resume record {path} was synthesised under different "
            f"settings ({meta} != {expect_meta}); clear --resume_dir or "
            "point it elsewhere")
    return gen, True


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    # Dataset-choice errors fire BEFORE model init + checkpoint restore
    # (minutes on a slow device link).
    if args.synthetic_scenes and args.val_data:
        raise SystemExit(
            "--synthetic_scenes and --val_data are mutually exclusive")
    if not (args.synthetic_scenes or args.val_data):
        raise SystemExit("pass --val_data or --synthetic_scenes")
    if (args.synthetic_scenes and args.scenes_seed == 0
            and args.scene_objects is not None
            and args.objects + args.w_select > args.scene_objects):
        raise SystemExit(
            f"--scenes_seed 0 scores training scenes, but --objects "
            f"{args.objects} + --w_select {args.w_select} exceeds the "
            f"trained --scene_objects {args.scene_objects}: objects "
            "beyond the trained count were never seen in training and "
            "would be mislabeled as 'train' scores — lower --objects or "
            "drop --scene_objects")
    if args.object_batch is not None and args.object_batch < 1:
        raise SystemExit("--object_batch must be >= 1")

    import dataclasses

    import jax
    import numpy as np

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.data.srn import SRNDataset
    from diff3d_tpu.evaluation import (fid_from_stats, gaussian_stats, psnr,
                                       ssim)
    from diff3d_tpu.evaluation.features import resolve_feature_fn
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    if args.steps:
        cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                               timesteps=args.steps))
    cfg = apply_model_width_overrides(cfg, args)

    # Fail fast on a bad --feature_weights path/file BEFORE the expensive
    # sampling loop; jit once here so the gt and gen stats passes share
    # one compiled executable.
    feature_fn, fid_key = resolve_feature_fn(args.feature_weights)
    feature_fn = jax.jit(feature_fn)

    model = XUNet(cfg.model)
    try:
        step, params = load_eval_params(args.model,
                                        build_abstract_state(cfg),
                                        args.raw_params)
    except ValueError as e:   # e.g. --raw_params on an ema_bf16 checkpoint
        raise SystemExit(str(e))

    n_dataset_objs = max(8, args.objects + args.w_select)
    if args.synthetic_scenes:
        from diff3d_tpu.data import SyntheticScenesDataset

        ds = SyntheticScenesDataset(num_objects=n_dataset_objs,
                                    imgsize=cfg.model.H,
                                    seed=args.scenes_seed)
    else:
        ds = SRNDataset("val", args.val_data, args.picklefile,
                        imgsize=cfg.model.H,
                        split_seed=cfg.data.split_seed,
                        train_fraction=cfg.data.train_fraction)
    mesh_env = None
    if args.mesh:
        from diff3d_tpu.parallel import make_mesh

        mesh_env = make_mesh(cfg.mesh)
        logging.info("sampling on mesh %s (object axis over '%s', "
                     "params %s)", dict(mesh_env.mesh.shape),
                     cfg.mesh.data_axis, cfg.mesh.param_sharding)
    sampler = Sampler(model, params, cfg,
                      scan_chunks=args.scan_chunks, mesh=mesh_env,
                      sampler_kind=args.sampler, steps=args.sampler_steps)

    if args.object_batch is None:
        # The batched model call (N*2B examples) and the [N, capacity, B,
        # H, W, 3] record buffer both scale with N; at 128^2 a full-width
        # no-max_views eval would OOM at N=8, so the default stays shy
        # there and the flag overrides.
        args.object_batch = 8 if cfg.model.H <= 64 else 2
        logging.info("object_batch auto -> %d (H=%d)", args.object_batch,
                     cfg.model.H)
    if args.object_batch % sampler.lane_multiple:
        # synthesize_many pads internally, but a non-multiple batch wastes
        # the padding lanes' FLOPs every chunk — round the batch itself.
        args.object_batch = (-(-args.object_batch // sampler.lane_multiple)
                             * sampler.lane_multiple)
        logging.info("object_batch rounded -> %d (mesh data-axis size %d)",
                     args.object_batch, sampler.lane_multiple)

    ephemeral_resume_dir = None
    if args.resume_dir is None:
        if args.out:
            args.resume_dir = args.out + ".objdir"
        else:
            import tempfile

            # no --out, no --resume_dir: records still go through disk
            # (one scoring path) but the dir is ours to delete at exit —
            # otherwise every throwaway eval leaks MBs of npz into /tmp
            args.resume_dir = tempfile.mkdtemp(prefix="diff3d_eval_")
            ephemeral_resume_dir = args.resume_dir
    os.makedirs(args.resume_dir, exist_ok=True)
    if ephemeral_resume_dir is not None:
        import atexit
        import shutil

        atexit.register(shutil.rmtree, ephemeral_resume_dir,
                        ignore_errors=True)

    # Per-object keys are split off in object order BEFORE batching —
    # eval objects first, then the w_select selection objects — so the
    # scores are invariant to --object_batch AND to resume boundaries
    # (same key -> same per-object stream; see Sampler.synthesize_many),
    # and adding --w_select never perturbs an eval object's stream.
    rng = jax.random.PRNGKey(args.seed)
    if len(ds.ids) < args.objects + args.w_select:
        raise SystemExit(
            f"dataset has {len(ds.ids)} val objects; --objects "
            f"{args.objects} + --w_select {args.w_select} requested")
    eval_objs = list(ds.ids[: args.objects])
    sel_objs = list(ds.ids[args.objects: args.objects + args.w_select])
    all_objs = eval_objs + sel_objs
    obj_views, obj_keys = {}, {}
    for obj in all_objs:
        obj_views[obj] = ds.all_views(obj)
        rng, k = jax.random.split(rng)
        obj_keys[obj] = k

    def n_views_of(v) -> int:
        n = int(v["imgs"].shape[0])
        return min(n, args.max_views) if args.max_views else n

    # Synthesis settings stamp: a resume record is valid only if it was
    # produced by an identical sampling protocol — including the model
    # directory and the DATASET identity (without it, a seed-0 and a
    # seed-1 eval sharing an --out would silently score each other's
    # generations against the wrong ground truth).
    dataset_id = (f"scenes:{args.scenes_seed}" if args.synthetic_scenes
                  else f"srn:{os.path.abspath(args.val_data)}")
    expect_meta = {
        "model": os.path.abspath(args.model),
        "dataset": dataset_id,
        "checkpoint_step": int(step),
        "timesteps": int(cfg.diffusion.timesteps),
        # The schedule changes every generated pixel: stale records from a
        # different sampler/step count must hard-error, not silently mix.
        "sampler": sampler.sampler_kind,
        "sampler_steps": int(sampler.steps),
        "seed": int(args.seed),
        "max_views": args.max_views,
        "H": int(cfg.model.H),
        # The guidance sweep is the record's B axis: a changed sweep must
        # invalidate stale records, or psnr_per_w / --w_index silently
        # mis-index into generations made under different weights.
        "guidance_weights": [float(w) for w in
                             cfg.diffusion.guidance_weights],
    }

    # ---- Phase 1: synthesis (resumable; each object lands on disk the
    # moment its batch completes) -------------------------------------
    gens = {}
    todo = []
    for obj in all_objs:
        gen, ok = _load_object_record(args.resume_dir, obj, expect_meta)
        if ok:
            gens[obj] = gen
        else:
            todo.append(obj)
    if gens:
        logging.info("resume: %d/%d objects already synthesised in %s",
                     len(gens), len(all_objs), args.resume_dir)

    progress_path = os.path.join(args.resume_dir, "progress.jsonl")
    i = 0
    while i < len(todo):
        # chunk of <= object_batch consecutive objects with equal view
        # counts (synthesize_many truncates to the batch minimum)
        j = i + 1
        nv = n_views_of(obj_views[todo[i]])
        while (j < len(todo) and j - i < args.object_batch
               and n_views_of(obj_views[todo[j]]) == nv):
            j += 1
        batch = todo[i:j]
        outs = sampler.synthesize_many([obj_views[o] for o in batch],
                                       [obj_keys[o] for o in batch],
                                       max_views=args.max_views)
        for obj, out in zip(batch, outs):
            # float16 in memory AND on disk: a fresh pass and a resumed
            # pass (which reads the float16 record back) score the SAME
            # pixels, and the resident full-sweep arrays cost half the
            # bytes (scoring casts one w column at a time to float32)
            gens[obj] = np.asarray(out, np.float16)
            _save_object_record(args.resume_dir, obj, gens[obj],
                                expect_meta)
            with open(progress_path, "a") as f:
                f.write(json.dumps({"object": str(obj),
                                    "views": int(out.shape[0])}) + "\n")
            logging.info("synthesised object %s (%d views) -> %s", obj,
                         out.shape[0],
                         _record_path(args.resume_dir, obj,
                                      expect_meta["checkpoint_step"]))
        i = j

    # ---- Phase 2: scoring (pure recomputation from the records; a
    # resumed run and a single-pass run produce the same JSON) ---------
    def score_object(obj):
        """Per-view PSNR at every w + copy baseline for one object.
        ``out`` stays float16 ([V-1, B, H, W, 3]); metric passes cast one
        w column at a time so the resident footprint is halved."""
        out = gens[obj]
        if out.shape[0] == 0:
            return None
        views = obj_views[obj]
        gt = views["imgs"][1: 1 + out.shape[0]]
        w_psnrs = [np.asarray(psnr(out[:, wi].astype(np.float32),
                                   gt)).tolist()
                   for wi in range(out.shape[1])]
        copy0 = np.broadcast_to(views["imgs"][:1], gt.shape)
        base = np.asarray(psnr(copy0, gt)).tolist()
        return {"out": out, "gt": gt, "w_psnrs": w_psnrs, "base": base}

    scored = {obj: score_object(obj) for obj in all_objs}
    eval_scored = [(o, scored[o]) for o in eval_objs if scored[o]]
    if not eval_scored:
        raise SystemExit(
            "no views generated: every object had < 2 usable views "
            "(check --max_views / the dataset)")

    # Guidance-weight selection on the DISJOINT selection objects: best
    # pooled mean PSNR across their views.  The copy baseline is
    # w-independent, so argmax-PSNR == argmax-margin.
    w_selected = None
    if args.w_select:
        sel_scored = [scored[o] for o in sel_objs if scored[o]]
        if not sel_scored:
            raise SystemExit("--w_select objects produced no views")
        n_w = len(sel_scored[0]["w_psnrs"])
        sel_per_w = [float(np.mean([v for s in sel_scored
                                    for v in s["w_psnrs"][wi]]))
                     for wi in range(n_w)]
        w_selected = int(np.argmax(sel_per_w))
        logging.info("w_select: per-w PSNR on %d selection objects: %s "
                     "-> w_selected=%d", len(sel_scored),
                     [round(v, 3) for v in sel_per_w], w_selected)

    # GT features never vary with w: one stats pass shared by every
    # aggregate() call (fixed-w headline AND w_selected).
    gt_stats = gaussian_stats([s["gt"] for _, s in eval_scored],
                              feature_fn)
    agg_cache = {}

    def aggregate(w_index):
        """Headline + per-object stats of the EVAL set at one w (cached:
        when selection picks the same w as the fixed headline, the
        second call is free instead of re-running SSIM + FID)."""
        if w_index in agg_cache:
            return agg_cache[w_index]
        per_object, psnrs, base_psnrs, ssims = [], [], [], []
        gen_views = []
        for obj, s in eval_scored:
            obj_psnrs = s["w_psnrs"][w_index]
            gen = s["out"][:, w_index].astype(np.float32)
            obj_ssims = np.asarray(ssim(gen, s["gt"])).tolist()
            psnrs.extend(obj_psnrs)
            ssims.extend(obj_ssims)
            base_psnrs.extend(s["base"])
            gen_views.append(gen)
            per_object.append({
                "id": str(obj),
                "views": len(obj_psnrs),
                "psnr": round(float(np.mean(obj_psnrs)), 3),
                "psnr_std": round(float(np.std(obj_psnrs)), 3),
                "psnr_copy_view0": round(float(np.mean(s["base"])), 3),
                "ssim": round(float(np.mean(obj_ssims)), 4),
            })
        fid = fid_from_stats(gt_stats,
                             gaussian_stats(gen_views, feature_fn))
        margins = [o["psnr"] - o["psnr_copy_view0"] for o in per_object]
        obj_means = [o["psnr"] for o in per_object]
        agg_cache[w_index] = {
            "objects": len(per_object),
            "views": len(psnrs),
            "psnr": round(float(np.mean(psnrs)), 3),
            "psnr_copy_view0_baseline": round(float(np.mean(base_psnrs)),
                                              3),
            "psnr_obj_mean": round(float(np.mean(obj_means)), 3),
            "psnr_obj_std": round(float(np.std(obj_means)), 3),
            "psnr_margin_mean": round(float(np.mean(margins)), 3),
            "psnr_margin_std": round(float(np.std(margins)), 3),
            "objects_above_baseline": int(sum(m > 0 for m in margins)),
            "ssim": round(float(np.mean(ssims)), 4),
            fid_key: round(float(fid), 3),
            "per_object": per_object,
        }
        return agg_cache[w_index]

    if fid_key == "fid_randfeat":
        logging.warning(
            "FID below uses the seeded random-projection fallback — "
            "reported as 'fid_randfeat', NOT comparable to paper FID. "
            "Pass --feature_weights <local VGG16 state dict> for "
            "real-feature FID.")

    # Per-w pooled PSNR over the eval set (the reference's 0..7 sweep
    # readout) — selection objects are excluded from every eval metric.
    n_w = len(eval_scored[0][1]["w_psnrs"])
    per_w_psnrs = [
        round(float(np.mean([v for _, s in eval_scored
                             for v in s["w_psnrs"][wi]])), 3)
        for wi in range(n_w)]

    record = {"checkpoint_step": step, **aggregate(args.w_index),
              "psnr_per_w": per_w_psnrs, "w_index": args.w_index,
              "timesteps": cfg.diffusion.timesteps,
              "sampler": sampler.sampler_kind,
              "sampler_steps": int(sampler.steps)}

    # Matched-seed parity vs the full-grid ancestral oracle: same
    # per-object keys, so the generations differ ONLY by the reverse
    # schedule — the quality cost of few-step sampling, isolated.
    if args.parity_objects:
        from diff3d_tpu.evaluation import matched_seed_parity

        par_objs = eval_objs[: args.parity_objects]
        oracle = Sampler(model, params, cfg,
                         scan_chunks=args.scan_chunks, mesh=mesh_env)
        oracle_outs = [oracle.synthesize(obj_views[o], obj_keys[o],
                                         max_views=args.max_views)
                       for o in par_objs]
        record["sampler_parity"] = {
            "oracle": f"ancestral:{cfg.diffusion.timesteps}",
            "sampler": f"{sampler.sampler_kind}:{sampler.steps}",
            "objects": len(par_objs),
            **matched_seed_parity([gens[o] for o in par_objs],
                                  oracle_outs, w_index=args.w_index),
        }
    if w_selected is not None:
        sel_agg = aggregate(w_selected)
        record["w_selected"] = w_selected
        record["w_select_objects"] = [str(o) for o in sel_objs]
        for key in ("psnr", "psnr_margin_mean", "psnr_margin_std",
                    "objects_above_baseline", "ssim", fid_key):
            record[f"{key}_w_selected"] = sel_agg[key]
        record["per_object_w_selected"] = sel_agg["per_object"]

    # Orbit turntables + 3D-consistency readout: the trajectory-service
    # workload, scored offline.  Radius/elevation come from each
    # object's own GT poses so the orbit stays on the data manifold the
    # model was trained on; frames are synthesised autoregressively
    # (same record contract as serving's TrajectoryRequest), then scored
    # with the plane-homography reprojection metric.
    if args.orbit:
        from diff3d_tpu.evaluation import reprojection_consistency
        from diff3d_tpu.trajectory import orbit_path, trajectory_views

        if args.orbit < 2:
            raise SystemExit("--orbit needs >= 2 frames to score "
                             "consistency")
        per_orbit = []
        for obj in eval_objs[: args.orbit_objects]:
            views = obj_views[obj]
            T_gt = np.asarray(views["T"], np.float64)
            radii = np.linalg.norm(T_gt, axis=-1)
            radius = float(radii.mean())
            elevation = float(np.rad2deg(np.arcsin(
                np.clip(T_gt[:, 2] / np.maximum(radii, 1e-9),
                        -1.0, 1.0)).mean()))
            path_R, path_T = orbit_path(args.orbit, radius=radius,
                                        elevation_deg=elevation)
            tviews = trajectory_views(views["imgs"][0], views["R"][0],
                                      views["T"][0], views["K"],
                                      path_R, path_T)
            # synthesize sizes the record from imgs.shape[0]: tile the
            # conditioning image across the path (only imgs[0] is read).
            tviews["imgs"] = np.broadcast_to(
                tviews["imgs"][:1], (args.orbit + 1,) +
                tviews["imgs"].shape[1:])
            rng, k = jax.random.split(rng)
            frames = sampler.synthesize(tviews, k)  # [N, B, H, W, 3]
            gen = frames[:, args.w_index].astype(np.float32)
            score = reprojection_consistency(gen, path_R, path_T,
                                             views["K"])
            entry = {"id": str(obj), "radius": round(radius, 3),
                     "elevation_deg": round(elevation, 2),
                     "consistency_l1": score["consistency_l1"],
                     "consistency_psnr": score["consistency_psnr"],
                     "valid_frac": round(score["valid_frac"], 4)}
            if args.save_dir:
                from diff3d_tpu.utils import save_frame_sequence

                art = save_frame_sequence(
                    os.path.join(args.save_dir, str(obj), "orbit"), gen)
                entry["frames_dir"] = art["dir"]
                logging.info("orbit frames for %s -> %s", obj,
                             art["dir"])
            per_orbit.append(entry)
        l1s = [o["consistency_l1"] for o in per_orbit
               if o["consistency_l1"] is not None]
        ps = [o["consistency_psnr"] for o in per_orbit
              if o["consistency_psnr"] is not None]
        record["orbit_consistency"] = {
            "frames": args.orbit,
            "objects": len(per_orbit),
            "w_index": args.w_index,
            "consistency_l1": (round(float(np.mean(l1s)), 5)
                               if l1s else None),
            "consistency_psnr": (round(float(np.mean(ps)), 3)
                                 if ps else None),
            "per_object": per_orbit,
        }

    if args.save_dir:
        from PIL import Image

        from diff3d_tpu.sampling.runtime import to_uint8

        for obj, s in eval_scored:
            gen = s["out"][:, args.w_index]
            d = os.path.join(args.save_dir, str(obj))
            os.makedirs(d, exist_ok=True)
            Image.fromarray(
                to_uint8(obj_views[obj]["imgs"][0])).save(
                    os.path.join(d, "view0_cond.png"))
            for v in range(gen.shape[0]):
                Image.fromarray(to_uint8(s["gt"][v])).save(
                    os.path.join(d, f"view{v + 1}_gt.png"))
                Image.fromarray(to_uint8(gen[v])).save(
                    os.path.join(d, f"view{v + 1}_gen.png"))

    print(json.dumps(record))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(record) + "\n")


if __name__ == "__main__":
    main()
