"""Convert a reference PyTorch checkpoint into this framework's format.

Takes the reference's ``.pt`` files (``{'model': state_dict, 'optim': ...,
'step': ...}`` — ``/root/reference/train.py:287-298``, incl. the published
pretrained weights) and writes an Orbax checkpoint that ``train_cli
--transfer``, ``sample_cli`` and ``eval_cli`` load directly.  The optimizer
state is NOT converted (torch Adam moments don't map onto optax's tree);
the step counter is preserved so schedules resume at the right point, and
the EMA is seeded from the converted weights (the reference never
implemented its documented EMA, SURVEY.md §2.3).

Usage:
    python -m diff3d_tpu.cli.convert_cli --torch_ckpt latest.pt \
        --out ./checkpoints [--config srn64]
"""

from __future__ import annotations

import argparse
import logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--torch_ckpt", required=True, help="reference .pt file")
    p.add_argument("--out", required=True,
                   help="Orbax checkpoint root to write")
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--step", type=int, default=None,
                   help="override the step recorded in the checkpoint")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    import jax
    import jax.numpy as jnp

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.convert import load_torch_checkpoint
    from diff3d_tpu.train import CheckpointManager, create_train_state
    from diff3d_tpu.train.state import advance_schedule

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()

    params, ckpt_step = load_torch_checkpoint(args.torch_ckpt, cfg.model)
    step = args.step if args.step is not None else ckpt_step

    params = jax.tree.map(jnp.asarray, params)

    # Fail fast on config/checkpoint mismatch (e.g. a 64px .pt converted
    # with --config srn128): compare against the model's expected tree
    # BEFORE writing a checkpoint that would only blow up at restore time.
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train.trainer import init_params as _init_params
    expected = jax.eval_shape(
        lambda: _init_params(XUNet(cfg.model), cfg, jax.random.PRNGKey(0)))
    exp_flat = dict(jax.tree_util.tree_flatten_with_path(expected)[0])
    got_flat = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    missing = exp_flat.keys() - got_flat.keys()
    extra = got_flat.keys() - exp_flat.keys()
    bad = [jax.tree_util.keystr(k) for k in exp_flat.keys() & got_flat.keys()
           if exp_flat[k].shape != got_flat[k].shape]
    if missing or extra or bad:
        raise SystemExit(
            f"checkpoint does not match --config {args.config}: "
            f"missing={sorted(map(jax.tree_util.keystr, missing))[:5]} "
            f"extra={sorted(map(jax.tree_util.keystr, extra))[:5]} "
            f"shape-mismatch={sorted(bad)[:5]}")
    state = create_train_state(params, cfg.train)
    # The lr schedule's position is optax's internal count, not
    # TrainState.step — advance it so a converted step-100K checkpoint
    # doesn't silently re-run warmup (Adam's own count stays 0: the zero
    # moments it bias-corrects ARE fresh).
    state = state.replace(step=jnp.asarray(step, jnp.int32),
                          opt_state=advance_schedule(state.opt_state, step))

    mgr = CheckpointManager(args.out, keep=1)
    mgr.save(state, force=True)
    mgr.wait()
    mgr.close()
    n = sum(int(p.size) for p in jax.tree.leaves(params))
    logging.info("converted %s (%.1fM params, step %d) -> %s",
                 args.torch_ckpt, n / 1e6, step, args.out)


if __name__ == "__main__":
    main()
