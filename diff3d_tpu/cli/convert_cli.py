"""Convert a reference PyTorch checkpoint into this framework's format.

Takes the reference's ``.pt`` files (``{'model': state_dict, 'optim': ...,
'step': ...}`` — ``/root/reference/train.py:287-298``, incl. the published
pretrained weights) and writes an Orbax checkpoint that ``train_cli
--transfer``, ``sample_cli`` and ``eval_cli`` load directly.  The optimizer
state is NOT converted (torch Adam moments don't map onto optax's tree);
the step counter is preserved so schedules resume at the right point, and
the EMA is seeded from the converted weights (the reference never
implemented its documented EMA, SURVEY.md §2.3).

Usage:
    python -m diff3d_tpu.cli.convert_cli --torch_ckpt latest.pt \
        --out ./checkpoints [--config srn64]
"""

from __future__ import annotations

import argparse
import logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--torch_ckpt", required=True, help="reference .pt file")
    p.add_argument("--out", required=True,
                   help="Orbax checkpoint root to write")
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="srn64")
    p.add_argument("--step", type=int, default=None,
                   help="override the step recorded in the checkpoint")
    p.add_argument("--verify", action="store_true",
                   help="verify-only dry run: reconstruct the expected "
                        "reference key set from --config, report every "
                        "missing/extra/shape-mismatched key, and exit "
                        "without writing (non-zero on mismatch).  The "
                        "same verification always runs before a real "
                        "conversion.")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    logging.getLogger("absl").setLevel(logging.WARNING)

    import jax
    import jax.numpy as jnp

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.train import CheckpointManager, create_train_state
    from diff3d_tpu.train.state import advance_schedule

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()

    # Verify the INPUT key set first (torch keys + shapes reconstructed
    # from config): the real published .pt deserves a complete report of
    # what is wrong, not a KeyError mid-conversion.
    import torch

    from diff3d_tpu.convert import convert_state_dict, verify_state_dict

    raw = torch.load(args.torch_ckpt, map_location="cpu",
                     weights_only=True)
    if isinstance(raw, dict) and "model" in raw:
        sd, ckpt_step = raw["model"], int(raw.get("step", 0))
    else:
        sd, ckpt_step = raw, 0
    report = verify_state_dict(sd, cfg.model)
    n_bad = sum(map(len, report.values()))
    if n_bad:
        for kind, items in report.items():
            for it in items:
                logging.error("verify: %s: %s", kind, it)
        raise SystemExit(
            f"{args.torch_ckpt} does not match --config {args.config}: "
            f"{len(report['missing'])} missing, {len(report['extra'])} "
            f"extra, {len(report['shape_mismatch'])} shape-mismatched "
            "keys (full list above)")
    logging.info("verify: %s matches the expected %s key set "
                 "(%d tensors)", args.torch_ckpt, args.config, len(sd))
    if args.verify:
        return

    params = convert_state_dict(sd, cfg.model)
    step = args.step if args.step is not None else ckpt_step

    params = jax.tree.map(jnp.asarray, params)

    # Fail fast on config/checkpoint mismatch (e.g. a 64px .pt converted
    # with --config srn128): compare against the model's expected tree
    # BEFORE writing a checkpoint that would only blow up at restore time.
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train.trainer import init_params as _init_params
    expected = jax.eval_shape(
        lambda: _init_params(XUNet(cfg.model), cfg, jax.random.PRNGKey(0)))
    exp_flat = dict(jax.tree_util.tree_flatten_with_path(expected)[0])
    got_flat = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    missing = exp_flat.keys() - got_flat.keys()
    extra = got_flat.keys() - exp_flat.keys()
    bad = [jax.tree_util.keystr(k) for k in exp_flat.keys() & got_flat.keys()
           if exp_flat[k].shape != got_flat[k].shape]
    if missing or extra or bad:
        raise SystemExit(
            f"checkpoint does not match --config {args.config}: "
            f"missing={sorted(map(jax.tree_util.keystr, missing))[:5]} "
            f"extra={sorted(map(jax.tree_util.keystr, extra))[:5]} "
            f"shape-mismatch={sorted(bad)[:5]}")
    state = create_train_state(params, cfg.train)
    # The lr schedule's position is optax's internal count, not
    # TrainState.step — advance it so a converted step-100K checkpoint
    # doesn't silently re-run warmup (Adam's own count stays 0: the zero
    # moments it bias-corrects ARE fresh).
    state = state.replace(step=jnp.asarray(step, jnp.int32),
                          opt_state=advance_schedule(state.opt_state, step))

    mgr = CheckpointManager(args.out, keep=1)
    mgr.save(state, force=True)
    mgr.wait()
    mgr.close()
    n = sum(int(p.size) for p in jax.tree.leaves(params))
    logging.info("converted %s (%.1fM params, step %d) -> %s",
                 args.torch_ckpt, n / 1e6, step, args.out)


if __name__ == "__main__":
    main()
