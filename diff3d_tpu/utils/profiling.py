"""Tracing / profiling utilities.

The reference has no observability at all — two dead ``strt=time.time()``
assignments and tqdm bars (``/root/reference/train.py:136,150,264``;
SURVEY.md §5.1).  Here:

  * :func:`profile_window` — capture a ``jax.profiler`` device trace for a
    window of steps, viewable in TensorBoard/Perfetto (the TPU-world
    nsight/torch-profiler equivalent).
  * :class:`StepTimer` — cheap wall-clock step timing with percentile
    summaries, no device syncs outside window boundaries.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, List, Optional

import jax
import numpy as np


@contextlib.contextmanager
def profile_window(logdir: str, enabled: bool = True) -> Iterator[None]:
    """Trace everything inside the ``with`` body to ``logdir``.

    Use around a few already-compiled steps (never the first — tracing a
    compile produces a useless giant trace)::

        with profile_window(os.path.join(workdir, "profile")):
            for _ in range(3):
                state, metrics = step_fn(state, batch, rng)
            jax.block_until_ready(metrics["loss"])
    """
    if not enabled:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock per-step timing.

    ``tick()`` marks a step boundary; ``summary()`` reports mean / p50 /
    p95 / max milliseconds over the retained window.  Pure host-side —
    call ``jax.block_until_ready`` yourself at window edges if you want
    device-inclusive times (the trainer does, at log boundaries).
    """

    def __init__(self, window: int = 512):
        self._window = window
        self._times: List[float] = []
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            if len(self._times) > self._window:
                self._times = self._times[-self._window:]
        self._last = now

    def reset(self) -> None:
        self._times.clear()
        self._last = None

    def summary(self) -> dict:
        if not self._times:
            return {}
        ms = np.asarray(self._times) * 1e3
        return {
            "step_ms_mean": float(ms.mean()),
            "step_ms_p50": float(np.percentile(ms, 50)),
            "step_ms_p95": float(np.percentile(ms, 95)),
            "step_ms_max": float(ms.max()),
        }
