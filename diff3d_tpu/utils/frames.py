"""Frame-sequence writer: the ``save_image`` sibling for trajectories.

A trajectory result is an ordered stack of frames; qualitative review
wants two artefacts per sequence: the ordered ``frame_%03d.png``
directory (drop into ffmpeg or a viewer) and a single contact-sheet
strip for eyeballing the whole turntable at a glance.  Used by
``eval_cli --orbit`` and handy from notebooks.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from diff3d_tpu.sampling.runtime import save_image, to_uint8

__all__ = ["save_frame_sequence"]


def save_frame_sequence(out_dir: str, frames: np.ndarray,
                        prefix: str = "frame",
                        contact_sheet: bool = True,
                        columns: Optional[int] = None) -> dict:
    """Write ``frames`` as ``<out_dir>/<prefix>_%03d.png`` plus a
    ``contact_sheet.png`` strip.

    ``frames`` is ``[n, H, W, 3]`` in [-1, 1] (a guidance axis
    ``[n, B, H, W, 3]`` is accepted — lane 0 is written, matching how
    single-view results are reviewed).  The contact sheet tiles frames
    row-major, ``columns`` per row (default: all frames in one strip).
    Returns ``{"dir", "frames", "contact_sheet"}`` with the paths
    written, so CLI callers can report artefact locations.
    """
    frames = np.asarray(frames, np.float32)
    if frames.ndim == 5:
        frames = frames[:, 0]
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(
            f"frames must be [n, H, W, 3] (or [n, B, H, W, 3]), got "
            f"{frames.shape}")
    n = frames.shape[0]
    if n == 0:
        raise ValueError("no frames to write")
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for k in range(n):
        path = os.path.join(out_dir, f"{prefix}_{k:03d}.png")
        save_image(path, frames[k])
        paths.append(path)
    out = {"dir": out_dir, "frames": paths, "contact_sheet": None}
    if contact_sheet:
        from PIL import Image

        cols = n if columns is None else max(1, min(int(columns), n))
        rows = -(-n // cols)
        H, W = frames.shape[1:3]
        sheet = np.zeros((rows * H, cols * W, 3), np.uint8)
        for k in range(n):
            r, c = divmod(k, cols)
            sheet[r * H:(r + 1) * H, c * W:(c + 1) * W] = to_uint8(
                frames[k])
        sheet_path = os.path.join(out_dir, "contact_sheet.png")
        Image.fromarray(sheet).save(sheet_path)
        out["contact_sheet"] = sheet_path
    return out
