from diff3d_tpu.utils.profiling import StepTimer, profile_window

__all__ = ["StepTimer", "profile_window"]
