from diff3d_tpu.utils.frames import save_frame_sequence
from diff3d_tpu.utils.profiling import StepTimer, profile_window

__all__ = ["StepTimer", "profile_window", "save_frame_sequence"]
