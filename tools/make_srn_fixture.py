"""Render a synthetic-scenes dataset TO DISK in SRN format.

Produces the exact on-disk tree the reference trains from
(``/root/reference/SRNdataset.py:42-95`` / ``README.md:25-29``):

    <out>/<obj>/rgb/<view>.png            8-bit RGB renders
    <out>/<obj>/pose/<view>.txt           flat 4x4 world-from-camera
    <out>/<obj>/intrinsics/<view>.txt     flat 3x3 K (shared per object)
    [--picklefile] dict obj-id -> [png names]   (reference pickle format)

The images are ray-traced :class:`SyntheticScenesDataset` renders — real
projections of consistent 3D scenes — so a model trained from this tree
learns an actual novel-view task, not noise.  This makes the full
real-data path (native C++ png decode, pickle regen, 90/10 split,
threaded loader) rehearsable end-to-end without the SRN zips:

    python tools/make_srn_fixture.py --out /tmp/srn_fixture/cars_train \
        --objects 12 --views 6 --imgsize 64
    python -m diff3d_tpu.cli.train_cli --train_data /tmp/srn_fixture/cars_train

Exercised by ``tests/test_srn_turnkey.py``.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def write_fixture(out: str, objects: int = 12, views: int = 6,
                  imgsize: int = 64, seed: int = 0,
                  picklefile: str | None = None) -> dict:
    """Render ``objects`` scenes x ``views`` orbit views into ``out``;
    returns the object-id -> [view names] index."""
    from PIL import Image

    from diff3d_tpu.data import SyntheticScenesDataset
    from diff3d_tpu.data.images import quantize_uint8

    ds = SyntheticScenesDataset(num_objects=objects, num_views=views,
                                imgsize=imgsize, seed=seed)
    index: dict = {}
    for o in range(objects):
        rec = ds.all_views(o)
        obj_id = f"scene{seed}_{o:04d}"
        obj_dir = os.path.join(out, obj_id)
        for sub in ("rgb", "pose", "intrinsics"):
            os.makedirs(os.path.join(obj_dir, sub), exist_ok=True)
        names = []
        for v in range(views):
            name = f"{v:06d}"
            Image.fromarray(quantize_uint8(rec["imgs"][v])).save(
                os.path.join(obj_dir, "rgb", name + ".png"))
            pose = np.eye(4)
            pose[:3, :3] = rec["R"][v]
            pose[:3, 3] = rec["T"][v]
            # reference format: one flat row, np.loadtxt(...).reshape(4,4)
            np.savetxt(os.path.join(obj_dir, "pose", name + ".txt"),
                       pose.reshape(1, 16))
            np.savetxt(os.path.join(obj_dir, "intrinsics", name + ".txt"),
                       np.asarray(rec["K"], np.float64).reshape(1, 9))
            names.append(name + ".png")
        index[obj_id] = names
    if picklefile:
        import pickle
        os.makedirs(os.path.dirname(picklefile) or ".", exist_ok=True)
        with open(picklefile, "wb") as f:
            pickle.dump(index, f)
    return index


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True,
                   help="split dir to create (e.g. .../cars_train)")
    p.add_argument("--objects", type=int, default=12)
    p.add_argument("--views", type=int, default=6)
    p.add_argument("--imgsize", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--picklefile", default=None,
                   help="also save the reference-format index pickle here "
                        "(omitted -> exercise the glob-regen path)")
    args = p.parse_args(argv)
    index = write_fixture(args.out, args.objects, args.views, args.imgsize,
                          args.seed, args.picklefile)
    n_views = sum(len(v) for v in index.values())
    print(f"wrote {len(index)} objects / {n_views} views at "
          f"{args.imgsize}x{args.imgsize} under {args.out}")


if __name__ == "__main__":
    main()
