"""Measure train-step throughput across config variants on the attached
accelerator, to pick the fastest default for ``bench.py``.

Usage: python tools/tune_train.py [--config srn64|srn128] [variant ...]

Each variant is ``batch,accum,remat,policy,attn`` e.g. ``128,2,1,nothing,auto``.
With no args, runs a standard sweep at the srn64 config.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

CONFIG = "srn64"


def run_variant(global_batch: int, accum: int, remat: bool, policy: str,
                attn: str, n_steps: int = 10) -> float:
    import jax

    from diff3d_tpu import config as config_mod

    srn64_config = getattr(config_mod, f"{CONFIG}_config")
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    cfg = srn64_config()
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, remat=remat,
                                  remat_policy=policy, attn_impl=attn),
        train=dataclasses.replace(cfg.train, global_batch=global_batch,
                                  accum_steps=accum))

    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))

    ds = SyntheticDataset(num_objects=8, num_views=16,
                          imgsize=cfg.model.H, seed=0)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    step_fn = make_train_step(model, cfg, env)
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])
    return n_steps / (time.perf_counter() - t0)


def main() -> None:
    global CONFIG
    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        CONFIG = sys.argv[2]
        del sys.argv[1:3]
    if len(sys.argv) > 1:
        variants = []
        for arg in sys.argv[1:]:
            b, a, r, p, at = arg.split(",")
            variants.append((int(b), int(a), bool(int(r)), p, at))
    else:
        variants = [
            (128, 2, True, "nothing", "auto"),   # current bench default
            (128, 2, True, "dots", "auto"),
            (128, 1, True, "nothing", "auto"),
            (128, 2, True, "nothing", "xla"),
            (64, 1, True, "dots", "auto"),
            (64, 1, False, "nothing", "auto"),
        ]

    for (b, a, r, p, at) in variants:
        tag = f"b{b} accum{a} remat={int(r)} policy={p} attn={at}"
        try:
            sps = run_variant(b, a, r, p, at)
            print(f"{tag}: {sps:.3f} steps/s = {sps * b:.1f} examples/s",
                  flush=True)
        except Exception as e:
            msg = str(e).splitlines()[0][:160]
            print(f"{tag}: FAILED {msg}", flush=True)


if __name__ == "__main__":
    main()
