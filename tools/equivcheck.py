"""StableHLO semantic-equivalence gate, runnable as a plain script:
``python tools/equivcheck.py [--program NAME | --update | --list]``.

Thin wrapper over ``diff3d_tpu.analysis.equivcheck`` (also installed as
the ``equivcheck`` console script) so the gate works from a checkout
without installing the package.  All arguments pass through — see
``--help`` for the program registry and manifest workflow, and
docs/DESIGN.md §18 for policy.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from diff3d_tpu.analysis.equivcheck import main as equivcheck_main
    return equivcheck_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
