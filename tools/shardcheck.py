"""IR-level sharding/communication gate, runnable as a plain script:
``python tools/shardcheck.py [--program NAME | --update | --list]``.

Thin wrapper over ``diff3d_tpu.analysis.shardcheck`` (also installed as
the ``shardcheck`` console script) so the gate works from a checkout
without installing the package.  All arguments pass through — see
``--help`` for the program registry and manifest workflow, and
docs/DESIGN.md §10 for policy.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from diff3d_tpu.analysis.shardcheck import main as shardcheck_main
    return shardcheck_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
