"""Injected-fault soak for the serving layer.

Drives the in-process service (scheduler + engine, no HTTP) with
synthetic requests while a :class:`diff3d_tpu.testing.faults.FaultInjector`
randomly fails and stalls device dispatches, then clears the faults and
checks the engine recovers.  The survival report counts every submitted
request into exactly one terminal bucket:

  * ``completed``        — resolved with a result,
  * ``failed_retryable`` — rejected with a typed RetryableError (the
    client could resubmit: EngineStepError, EngineOverloaded, ...),
  * ``failed_other``     — any non-retryable error (a contract breach
    under pure transient faults),
  * ``hung``             — future unresolved within the client budget,
  * ``lost``             — future STILL unresolved after a final drain.

Exit status is 0 iff ``failed_other == hung == lost == 0`` and the
engine's health is back to ``ok`` after the recovery window — the
fault-tolerance contract of DESIGN.md §7.

Usage (CPU):
    JAX_PLATFORMS=cpu python tools/chaos_serving.py \
        --requests 24 --fault-rate 0.3 --slow-rate 0.1 --json

Set ``--slow-s`` above ``--watchdog-s`` to exercise watchdog trips
instead of mere latency.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _synthetic_views(n_views: int, size: int, seed: int):
    import numpy as np

    r = np.random.RandomState(seed)
    return {
        "imgs": r.randn(n_views, size, size, 3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": r.randn(n_views, 3).astype(np.float32),
        "K": np.array([[size * 1.2, 0, size / 2],
                       [0, size * 1.2, size / 2],
                       [0, 0, 1]], np.float32),
    }


def _build(args):
    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.config import ServingConfig
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.serving import ServingService
    from diff3d_tpu.testing.faults import FaultInjector, wrap_sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        max_batch=4, max_queue=max(32, args.requests),
        max_wait_ms=30.0, max_views=6,
        default_timeout_s=args.timeout_s,
        watchdog_timeout_s=args.watchdog_s,
        step_retry_attempts=2, step_retry_backoff_s=0.05,
        degraded_recovery_steps=2, retry_after_s=1.0,
        result_cache_entries=0))     # a soak must not replay results
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    inj = FaultInjector(seed=args.seed)
    service = ServingService(wrap_sampler(sampler, inj), cfg)
    return service, inj, cfg, int(sampler.w.shape[0])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="test")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--fault-rate", type=float, default=0.3,
                   help="per-dispatch probability of an injected step "
                        "exception")
    p.add_argument("--slow-rate", type=float, default=0.1,
                   help="per-dispatch probability of an injected stall")
    p.add_argument("--slow-s", type=float, default=0.4,
                   help="injected stall duration; set above --watchdog-s "
                        "to force watchdog trips")
    p.add_argument("--watchdog-s", type=float, default=2.0)
    p.add_argument("--timeout_s", type=float, default=120.0,
                   help="per-request deadline and client wait budget")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true",
                   help="emit the survival report as one JSON line on "
                        "stdout")
    args = p.parse_args(argv)

    service, inj, cfg, guidance_B = _build(args)
    service.start(serve_http=False)

    from diff3d_tpu.runtime.retry import RetryableError
    from diff3d_tpu.sampling import record_capacity
    from diff3d_tpu.serving.engine import lane_count
    from diff3d_tpu.serving.scheduler import ViewRequest

    # Pre-compile every (bucket, lanes) shape traffic can launch so an
    # XLA compile can't masquerade as a stuck step under the watchdog.
    # The injector has no specs yet, so warmup dispatches run clean.
    eng = service.engine
    n_views_cycle = (3, 4, 5)
    t0 = time.perf_counter()
    for nv in sorted(set(n_views_cycle)):
        bucket = (cfg.model.H, cfg.model.W, record_capacity(nv))
        for lanes in {lane_count(n, eng.max_batch, eng.lane_multiple)
                      for n in (1, 2, eng.max_batch)}:
            eng.programs.warmup(bucket, lanes, guidance_B)
    print(f"chaos_serving: warmed programs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # Health-transition recorder (sampled, 20ms).
    transitions, stop_poll = [], threading.Event()

    def _poll():
        last = None
        while not stop_poll.is_set():
            h = eng.health
            if h != last:
                transitions.append(h)
                last = h
            time.sleep(0.02)

    poller = threading.Thread(target=_poll, daemon=True)
    poller.start()

    inj.add("engine.step", prob=args.fault_rate)
    inj.add("engine.step", prob=args.slow_rate, kind="slow",
            delay_s=args.slow_s)

    views = [_synthetic_views(n_views_cycle[i % len(n_views_cycle)],
                              cfg.model.H, i)
             for i in range(args.requests)]
    counts = {"submitted": 0, "completed": 0, "failed_retryable": 0,
              "failed_other": 0, "hung": 0}
    errors = []
    lock = threading.Lock()
    reqs, waiters = [], []

    def waiter(req):
        try:
            req.result(timeout=args.timeout_s + 30)
            with lock:
                counts["completed"] += 1
        except Exception as e:
            with lock:
                if not req.done():
                    counts["hung"] += 1
                elif isinstance(e, RetryableError):
                    counts["failed_retryable"] += 1
                else:
                    counts["failed_other"] += 1
                errors.append(f"{type(e).__name__}: {e}")

    wall0 = time.perf_counter()
    for i, v in enumerate(views):
        req = ViewRequest(v, seed=1000 + i,
                          n_views=n_views_cycle[i % len(n_views_cycle)])
        try:
            eng.submit(req)
        except Exception as e:
            with lock:
                if isinstance(e, RetryableError):
                    counts["failed_retryable"] += 1
                else:
                    counts["failed_other"] += 1
                errors.append(f"submit {type(e).__name__}: {e}")
            counts["submitted"] += 1
            continue
        counts["submitted"] += 1
        reqs.append(req)
        w = threading.Thread(target=waiter, args=(req,), daemon=True)
        w.start()
        waiters.append(w)
        time.sleep(0.01)
    for w in waiters:
        w.join()
    wall = time.perf_counter() - wall0

    # Recovery window: faults off, a couple of clean probes, health must
    # return to ok.
    inj.clear("engine.step")
    probe_fail = 0
    for i in range(2):
        try:
            eng.submit(ViewRequest(_synthetic_views(3, cfg.model.H, 9000 + i),
                                   seed=9000 + i, n_views=3)
                       ).result(timeout=args.timeout_s)
        except Exception as e:
            probe_fail += 1
            errors.append(f"probe {type(e).__name__}: {e}")
    deadline = time.monotonic() + 60.0
    while eng.health != "ok" and time.monotonic() < deadline:
        time.sleep(0.05)

    lost = sum(1 for r in reqs if not r.done())
    snap = service.metrics_snapshot()
    stop_poll.set()
    poller.join(2)
    final_health = eng.health
    service.stop()

    c = snap["counters"]
    record = {
        "soak": "chaos_serving",
        "seed": args.seed,
        "fault_rate": args.fault_rate,
        "slow_rate": args.slow_rate,
        "slow_s": args.slow_s,
        "watchdog_s": args.watchdog_s,
        "wall_s": round(wall, 2),
        **counts,
        "lost": lost,
        "probe_failures": probe_fail,
        "injected_faults": inj.fired.get("engine.step", 0),
        "step_faults": c.get("serving_engine_step_faults_total", 0),
        "watchdog_trips": c.get("serving_engine_watchdog_trips_total", 0),
        "engine_restarts": c.get("serving_engine_restarts_total", 0),
        "shed": c.get("serving_requests_shed_total", 0),
        "health_transitions": transitions,
        "final_health": final_health,
        "error_sample": errors[:5],
    }
    ok = (counts["failed_other"] == 0 and counts["hung"] == 0
          and lost == 0 and probe_fail == 0 and final_health == "ok")
    record["survived"] = ok
    print(f"chaos_serving: {counts['completed']}/{counts['submitted']} "
          f"completed, {counts['failed_retryable']} retryable-failed, "
          f"{counts['failed_other']} other, {counts['hung']} hung, "
          f"{lost} lost; {record['injected_faults']} faults injected, "
          f"{record['watchdog_trips']} watchdog trips, final health "
          f"{final_health} -> {'SURVIVED' if ok else 'FAILED'}",
          file=sys.stderr)
    if args.json:
        print(json.dumps(record))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
