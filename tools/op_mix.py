"""Static per-op FLOP inventory of one X-UNet forward pass.

Mirrors ``models/xunet.py``'s structure exactly (stem -> down blocks +
downsamples -> middle -> up blocks + upsamples -> head; ResnetBlock =
conv1/conv2 + optional 1x1 skip_proj, attention = q/k/v/out projections
+ the sdpa core + a 1x1 out_conv) and prints FLOPs grouped by op class
and UNet level.  Pure arithmetic — runs anywhere, no devices.

Counted: every conv (stem/blocks/resamples/head/ConditioningProcessor
per-level strided convs), every attention projection + sdpa core, and
every FiLM dense — FiLM's conditioning input is [B, F, h, w, emb_ch]
(full spatial extent, models/xunet.py:78-80), so its
emb_ch -> 2*features dense is real per-pixel matmul work, ~17%% of the
srn128 forward.  The fused-kernel sites (``ops/pallas_film.py``) are
inventoried as their own classes — ``fused_gn_silu`` (ResnetBlock entry
GroupNorm->SiLU + the head's last_gn) and ``fused_film`` (the
GroupNorm->FiLM->SiLU epilogue) — with elementwise FLOPs (~10-12 per
element), so the share the kernel layer covers is a number, not a
hand-wave; their HBM-traffic share is far larger than their FLOP share,
which is exactly why they are fused.  Still omitted: residual adds,
plain attention GroupNorms, and the two logsnr MLP denses (spatial
size 1).

Why it exists (VERDICT r4 weak #6): the srn128 train step measures far
below the chip's big-matmul ceiling.  ``tools/roofline.py`` measures
what each conv SHAPE CLASS can sustain; this tool says how much of the
step's work sits in each shape class, so ceiling-x-share gives the
op-mix ceiling prediction without hand-waving.

Usage: python -m tools.op_mix [--config srn128] [--microbatch 4]
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict


def conv_flops(b, h, w, cin, cout, k):
    return 2.0 * b * h * w * cin * cout * k * k


def dense_flops(b, l, cin, cout):
    return 2.0 * b * l * cin * cout


def inventory(cfg_model, microbatch: int):
    """Returns a list of op records for ONE forward pass at
    ``microbatch`` examples (x2 frames folded into the batch axis,
    matching the model's reshape)."""
    ops = []
    BF = microbatch * 2
    num_res = cfg_model.num_resolutions
    dims = [cfg_model.ch * m for m in cfg_model.ch_mult]
    H = cfg_model.H

    def res_at(lvl):
        return H // (2 ** lvl)

    def add(kind, lvl, flops, shape):
        ops.append({"kind": kind, "level": lvl, "flops": flops,
                    "shape": shape})

    def resnet(lvl, cin, cout, tag):
        h = res_at(lvl)
        # entry GroupNorm->SiLU, fused (pallas_film): ~10 elementwise
        # flops/element (two-pass stats + normalize/affine + silu)
        add("fused_gn_silu", lvl, 10.0 * BF * h * h * cin,
            [BF, h, h, cin])
        add(f"conv3x3_{tag}", lvl, conv_flops(BF, h, h, cin, cout, 3),
            [BF, h, h, cin, cout, 3])
        add(f"conv3x3_{tag}", lvl, conv_flops(BF, h, h, cout, cout, 3),
            [BF, h, h, cout, cout, 3])
        # FiLM: Dense(emb_ch -> 2*cout) at EVERY spatial position (the
        # level emb carries pose information per pixel)
        add("film_dense", lvl,
            dense_flops(BF, h * h, cfg_model.emb_ch, 2 * cout),
            [BF, h * h, cfg_model.emb_ch, 2 * cout])
        # GroupNorm->FiLM(scale/shift)->SiLU epilogue, fused: the GN's
        # ~10 flops/element plus the modulate multiply-add
        add("fused_film", lvl, 12.0 * BF * h * h * cout,
            [BF, h, h, cout])
        if cin != cout:
            add(f"conv1x1_skip", lvl, conv_flops(BF, h, h, cin, cout, 1),
                [BF, h, h, cin, cout, 1])

    def attention(lvl, c):
        h = res_at(lvl)
        L = h * h
        for name in ("q", "k", "v", "out"):
            add("attn_proj", lvl, dense_flops(BF, L, c, c), [BF, L, c, c])
        # sdpa core: QK^T + PV, each 2*L*L*C
        add("attn_sdpa", lvl, 2 * (2.0 * BF * L * L * c), [BF, L, c])
        add("conv1x1_attnout", lvl, conv_flops(BF, h, h, c, c, 1),
            [BF, h, h, c, c, 1])

    def xunet_block(lvl, cin, cout, use_attn):
        resnet(lvl, cin, cout, "block")
        if use_attn:
            for _ in ("self", "cross"):
                attention(lvl, cout)

    # conditioning: one strided 3x3 conv per level, 144ch posenc ->
    # emb_ch at that level's resolution (models/conditioning.py:108-117)
    POSENC_CH = 144
    for lvl in range(num_res):
        h = res_at(lvl)
        add("cond_conv", lvl,
            conv_flops(BF, h, h, POSENC_CH, cfg_model.emb_ch, 3),
            [BF, h, h, POSENC_CH, cfg_model.emb_ch, 3])

    # stem
    add("conv3x3_stem", 0, conv_flops(BF, H, H, 3, cfg_model.ch, 3),
        [BF, H, H, 3, cfg_model.ch, 3])
    c = cfg_model.ch

    # down path (track the skip stack's channel dims like xunet.py's hs)
    hs = [c]
    for lvl in range(num_res):
        use_attn = lvl in cfg_model.attn_levels
        for _ in range(cfg_model.num_res_blocks):
            xunet_block(lvl, c, dims[lvl], use_attn)
            c = dims[lvl]
            hs.append(c)
        if lvl != num_res - 1:
            resnet(lvl, c, dims[lvl], "downsample")
            hs.append(c)

    # middle
    xunet_block(num_res - 1, c, dims[-1], num_res in cfg_model.attn_levels)
    c = dims[-1]

    # up path
    for lvl in reversed(range(num_res)):
        use_attn = lvl in cfg_model.attn_levels
        for _ in range(cfg_model.num_res_blocks + 1):
            cin = c + hs.pop()
            xunet_block(lvl, cin, dims[lvl], use_attn)
            c = dims[lvl]
        if lvl != 0:
            resnet(lvl, c, dims[lvl], "upsample")
    assert not hs

    # head: last_gn (GroupNorm->SiLU, fused) then the zero-init conv
    add("fused_gn_silu", 0, 10.0 * BF * H * H * dims[0],
        [BF, H, H, dims[0]])
    add("conv3x3_head", 0, conv_flops(BF, H, H, dims[0], 3, 3),
        [BF, H, H, dims[0], 3, 3])
    return ops


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=["srn64", "srn128"],
                   default="srn128")
    p.add_argument("--microbatch", type=int, default=4,
                   help="examples per device program (bench srn128 runs "
                        "global 16 / accum 4 = 4)")
    p.add_argument("--out", default=None, help="write full JSON here")
    args = p.parse_args(argv)

    from diff3d_tpu.config import srn64_config, srn128_config

    cfg = {"srn64": srn64_config, "srn128": srn128_config}[args.config]()
    ops = inventory(cfg.model, args.microbatch)
    total = sum(o["flops"] for o in ops)

    by_level = defaultdict(float)
    by_class = defaultdict(float)
    by_level_class = defaultdict(float)
    for o in ops:
        by_level[o["level"]] += o["flops"]
        if o["kind"] == "attn_sdpa":
            cls = "attn_sdpa"
        elif o["kind"].startswith("attn"):
            cls = "attn_proj"
        elif o["kind"] == "film_dense":
            cls = "film"
        elif o["kind"] in ("fused_gn_silu", "fused_film"):
            cls = o["kind"]         # the pallas_film kernel classes
        elif o["kind"] == "cond_conv":
            cls = "cond_conv"
        else:
            # bucket convs by their widest channel count — the quantity
            # that sets MXU result-tile fill (tools/roofline.py classes)
            cls = f"conv_ch{max(o['shape'][3], o['shape'][4])}"
        by_class[cls] += o["flops"]
        by_level_class[(o["level"], cls)] += o["flops"]

    report = {
        "config": args.config,
        "microbatch": args.microbatch,
        "total_fwd_gflops": round(total / 1e9, 2),
        "note": "forward only; backward ~2x, remat adds ~1x fwd",
        "share_by_level": {
            str(l): round(v / total, 4) for l, v in sorted(by_level.items())},
        "share_by_class": {
            k: round(v / total, 4) for k, v in sorted(by_class.items())},
        "share_by_level_class": {
            f"L{l}/{c}": round(v / total, 4)
            for (l, c), v in sorted(by_level_class.items())},
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"report": report, "ops": ops}, f, indent=1)


if __name__ == "__main__":
    main()
