"""First-ever 128^2 sampler execution: compile + time s/view.

VERDICT.md round 2 flagged that the sampler (16384-token attention inside
the compiled scan, reference hot spot /root/reference/xunet.py:199-208)
had never executed at the flagship resolution.  This smoke runs it with
random-init params at a given width and reports steady-state s/view.

Usage: python tools/smoke_srn128_sampler.py [--full_width] [--views 3]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--full_width", action="store_true",
                   help="paper width ch=256 (default: the reduced "
                        "ch64/emb512/nrb2 quality-run width)")
    p.add_argument("--views", type=int, default=3)
    p.add_argument("--timesteps", type=int, default=256)
    p.add_argument("--scan_chunks", type=int, default=4,
                   help="device executions per view scan (must divide "
                        "timesteps; bit-identical to 1 — keeps each "
                        "execution under the dev tunnel's RPC deadline)")
    args = p.parse_args()

    import dataclasses

    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.data import SyntheticScenesDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = config_lib.srn128_config()
    if not args.full_width:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(
                cfg.model, ch=64, emb_ch=512, num_res_blocks=2))
    cfg = dataclasses.replace(
        cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                           timesteps=args.timesteps))

    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    print(f"params: {n_params / 1e6:.1f}M  H={cfg.model.H}  "
          f"timesteps={args.timesteps}")

    ds = SyntheticScenesDataset(num_objects=1, num_views=args.views + 1,
                                imgsize=cfg.model.H, seed=0)
    views = ds.all_views(0)
    sampler = Sampler(model, params, cfg,
                      scan_chunks=args.scan_chunks)

    # The record buffer is sized to the next power of two of max_views, so
    # a DIFFERENT max_views can mean a fresh jit signature.  Warm up at
    # the SAME capacity as the timed run, or the "steady" numbers would
    # silently include minutes of 128^2 recompile.
    n = args.views + 1
    t0 = time.time()
    out = sampler.synthesize(views, jax.random.PRNGKey(1), max_views=n)
    # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
    t_first = time.time() - t0
    print(f"{args.views} views (incl. compile): {t_first:.1f}s  "
          f"out {out.shape}")

    t0 = time.time()
    out = sampler.synthesize(views, jax.random.PRNGKey(2), max_views=n)
    # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
    dt = time.time() - t0
    print(f"steady: {args.views} views in {dt:.1f}s -> "
          f"{dt / args.views:.2f} s/view")
    import numpy as np
    assert np.isfinite(np.asarray(out)).all(), "non-finite sampler output"
    print("OK: finite output at 128^2")


if __name__ == "__main__":
    main()
