"""Microbench the fused GroupNorm->FiLM/SiLU Pallas kernels against the
unfused XLA composition, roofline-anchored.

    python tools/bench_kernels.py [--out runs/bench_kernels.json]
                                  [--dtype bf16|f32] [--interpret]
                                  [--backward]

Shapes are the X-UNet's REAL GroupNorm sites: one point per
(level tokens, level width) pair of the srn64 and srn128 configs at the
train-step's flattened batch (``N = global_batch/8 * 2 frames`` per
chip), in both "fire" variants the model uses (the ResnetBlock entry
GroupNorm->SiLU and the GroupNorm->FiLM->SiLU epilogue).

The fused kernel is memory-bound (~10 flops/element vs 8-16 bytes
moved), so the honest headline is achieved HBM bandwidth and its
fraction of the chip's datasheet peak — reported NEXT TO the measured
compute ceiling imported from ``runs/roofline_r4.json`` (the same
anchoring DESIGN.md §13 uses for MFU claims): ``speedup_vs_xla`` says
whether fusion won, ``pct_of_hbm_peak`` says how close to the roof the
win sits, and the roofline block says what roof the numbers were scored
against.

``--interpret`` (forced on CPU) runs the kernels through the Pallas
interpreter: timings are then compile-path smoke only — the mode exists
to commit a parity-checked artifact (``max_abs_err`` per point) from
hosts with no TPU attached, and the record says so.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# v5e datasheet HBM bandwidth; quoted (not measured) — the denominator
# for pct_of_hbm_peak on TPU.  Non-TPU platforms get null.
TPU_V5E_HBM_GBPS = 819.0

ROOFLINE_PATH = "runs/roofline_r4.json"

#: (label, N, L, C): flattened [B*F, H*W, C] GroupNorm sites per level.
#: N = 16 flattened frames/chip (global batch 128 / 8 way * 2 frames at
#: srn64; srn128's per-chip batch is smaller but the site shapes are
#: what matter).  srn128's shallow levels hit the same C at 4x L.
SHAPES = [
    ("srn64_L0", 16, 4096, 128, 32),
    ("srn64_L1", 16, 1024, 256, 32),
    ("srn64_L2", 16, 256, 256, 32),
    ("srn64_L3", 16, 64, 512, 32),
    ("srn128_L0", 4, 16384, 256, 32),
    ("srn128_L3", 4, 256, 1024, 32),
]

VARIANTS = [
    ("gn_silu", False, True),       # ResnetBlock entry GroupNorm->SiLU
    ("gn_film_silu", True, True),   # FiLM epilogue (scale/shift fire)
]


def _time_windows(fn, sync, windows=3, reps=8):
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        sync(out)
        # graftlint: disable-next-line=GL106(sync() concretizes via float fetch - value-synced)
        times.append((time.perf_counter() - t0) / reps)
    return sorted(times)


def _roofline_ref():
    try:
        with open(ROOFLINE_PATH) as f:
            r = json.load(f)
        return {
            "path": ROOFLINE_PATH,
            "device": r.get("device"),
            "measured_ceiling_bf16_tflops":
                r.get("measured_ceiling_bf16_tflops"),
            "datasheet_peak_bf16_tflops":
                r.get("datasheet_peak_bf16_tflops"),
        }
    except Exception as e:
        return {"path": ROOFLINE_PATH,
                "error": str(e).splitlines()[0][:200]}


def _bench_point(label, N, L, C, G, film, silu, dtype_name, interpret,
                 backward):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from diff3d_tpu.ops.pallas_film import fused_groupnorm, xla_groupnorm

    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[dtype_name]
    dsize = jnp.dtype(dtype).itemsize
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(N, L, C), dtype)
    gamma = jnp.asarray(rs.randn(C), jnp.float32)
    beta = jnp.asarray(rs.randn(C), jnp.float32)
    kw = dict(num_groups=G, silu=silu)
    if film:
        kw["scale"] = jnp.asarray(0.3 * rs.randn(N, L, C), dtype)
        kw["shift"] = jnp.asarray(0.3 * rs.randn(N, L, C), dtype)

    def call(fn, extra):
        if backward:
            def loss(x, gamma, beta):
                return jnp.mean(fn(x, gamma, beta, **kw,
                                   **extra).astype(jnp.float32) ** 2)
            return jax.jit(jax.grad(loss))
        return jax.jit(
            lambda x, gamma, beta: fn(x, gamma, beta, **kw, **extra))

    jp = call(fused_groupnorm, {"interpret": interpret})
    jx = call(xla_groupnorm, {})
    f_pallas = lambda: jp(x, gamma, beta)
    f_xla = lambda: jx(x, gamma, beta)
    sync = lambda y: float(jnp.sum(y.astype(jnp.float32)))
    out_p, out_x = f_pallas(), f_xla()
    err = float(jnp.max(jnp.abs(out_p.astype(jnp.float32)
                                - out_x.astype(jnp.float32))))
    sync(out_p)

    t_pallas = _time_windows(f_pallas, sync)
    t_xla = _time_windows(f_xla, sync)
    med_p = t_pallas[len(t_pallas) // 2]
    med_x = t_xla[len(t_xla) // 2]

    # Fused-path HBM traffic: x in + y out (+ scale/shift in when the
    # FiLM port fires); backward reads x/g and writes dx (+ds/dt).
    # gamma/beta and the group stats live in VMEM — that's the point.
    streams = (2 + 2 * int(film)) * (1 + 2 * int(backward))
    bytes_moved = streams * N * L * C * dsize
    gbps = bytes_moved / med_p / 1e9
    return {
        "site": label,
        "shape": [N, L, C],
        "num_groups": G,
        "dtype": dtype_name,
        "variant": ("gn_film_silu" if film else "gn_silu")
                   + ("_bwd" if backward else ""),
        "pallas_ms": round(med_p * 1e3, 4),
        "xla_ms": round(med_x * 1e3, 4),
        "speedup_vs_xla": round(med_x / med_p, 3) if med_p else None,
        "bytes_moved": bytes_moved,
        "achieved_gbps": round(gbps, 2),
        "max_abs_err": err,
        "windows_ms": {
            "pallas": [round(t * 1e3, 4) for t in t_pallas],
            "xla": [round(t * 1e3, 4) for t in t_xla],
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=None,
                   help="write the JSON record here (default: stdout)")
    p.add_argument("--dtype", default="bf16", choices=("bf16", "f32"))
    p.add_argument("--interpret", action="store_true",
                   help="Pallas interpreter (parity smoke; forced on "
                        "non-TPU platforms)")
    p.add_argument("--backward", action="store_true",
                   help="also time the fwd+bwd (custom_vjp) path")
    p.add_argument("--shapes", default=None,
                   help="comma list of site labels to run (default all)")
    args = p.parse_args(argv)

    import jax

    dev = jax.devices()[0]
    interpret = args.interpret or dev.platform != "tpu"
    # Interpret mode at the real 4096/16384-token sites is minutes per
    # point for numbers nobody reads; shrink to the smallest sites and
    # a scaled-down big-L so the smoke still crosses row-tile bounds.
    shapes = SHAPES
    if interpret:
        shapes = [("srn64_L3", 2, 64, 512, 32),
                  ("srn64_L2_small", 2, 200, 256, 32)]
    if args.shapes:
        want = {s.strip() for s in args.shapes.split(",")}
        shapes = [s for s in shapes if s[0] in want]

    points = []
    passes = [False] + ([True] if args.backward else [])
    for label, N, L, C, G in shapes:
        for vname, film, silu in VARIANTS:
            for backward in passes:
                pt = _bench_point(label, N, L, C, G, film, silu,
                                  args.dtype, interpret, backward)
                points.append(pt)
                print(f"bench_kernels: {label} {pt['variant']} "
                      f"pallas {pt['pallas_ms']}ms xla {pt['xla_ms']}ms "
                      f"({pt['speedup_vs_xla']}x)", file=sys.stderr)

    hbm = TPU_V5E_HBM_GBPS if dev.platform == "tpu" else None
    for pt in points:
        pt["pct_of_hbm_peak"] = (round(100 * pt["achieved_gbps"] / hbm, 1)
                                 if hbm else None)
    record = {
        "metric": "fused_groupnorm_kernels",
        "device": str(dev.device_kind if hasattr(dev, "device_kind")
                      else dev),
        "platform": dev.platform,
        "mode": "interpret" if interpret else "compiled",
        "note": ("interpret-mode smoke: parity evidence only, timings "
                 "are the interpreter's, not the chip's"
                 if interpret else None),
        "hbm_gbps_datasheet": hbm,
        "roofline_ref": _roofline_ref(),
        "points": points,
    }
    out = json.dumps(record, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print(f"bench_kernels: wrote {args.out}", file=sys.stderr)
    else:
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
