"""Time the autoregressive sampler: seconds per synthesised view at the
reference's config (256 steps, 8-weight guidance sweep, 64x64).

The reference's sampler does 2 model forwards per step with host round
trips per step (``/root/reference/sampling.py:97-103``); here one view is
one compiled ``lax.scan``.
"""

from __future__ import annotations

import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> None:
    import dataclasses

    import jax

    try:  # persistent compile cache across runs
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass
    import numpy as np

    from diff3d_tpu.config import srn64_config
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling.runtime import Sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = srn64_config()
    if len(sys.argv) > 1:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, attn_impl=sys.argv[1]))
        print(f"attn_impl={sys.argv[1]}")
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    sampler = Sampler(model, params, cfg)

    rs = np.random.RandomState(0)
    n_views = 4
    views = {
        "imgs": rs.randn(n_views, cfg.model.H, cfg.model.W,
                         3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": rs.randn(n_views, 3).astype(np.float32),
        "K": np.array([[64 * 1.2, 0, 32], [0, 64 * 1.2, 32], [0, 0, 1]],
                      np.float32),
    }

    # Warmup (compile) at the SAME record-buffer capacity as the timed run.
    sampler.synthesize(views, rng, max_views=n_views)

    t0 = time.perf_counter()
    sampler.synthesize(views, rng, max_views=n_views)
    # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
    dt = time.perf_counter() - t0
    per_view = dt / (n_views - 1)
    print(f"sampler: {per_view:.2f}s/view "
          f"({per_view / cfg.diffusion.timesteps * 1e3:.1f}ms per "
          f"diffusion step, {len(cfg.diffusion.guidance_weights)}-weight "
          "sweep)")


if __name__ == "__main__":
    main()
