"""Offered-load sweep for the serving layer.

Drives the in-process service (scheduler + engine, no HTTP overhead) with
synthetic requests at a sweep of arrival rates and reports, per rate:

  * throughput (synthesised views/s),
  * end-to-end latency p50/p99,
  * mean batch occupancy and padding fraction (how well the microbatcher
    filled the device batch at that load).

The interesting curve is occupancy vs. latency: at low offered load every
request rides alone (occupancy 1, minimal latency); as load rises the
microbatcher amortises the compiled scan across requests (occupancy ->
max_batch) and throughput climbs at bounded latency cost until the queue
saturates.  A fresh service per rate keeps the metrics windows clean.

With ``--replicas N`` the sweep runs against the fleet router
(``serving/router.py``) instead of a bare engine: sessionless requests
take the least-loaded path, and each rate point additionally reports
per-replica view counts and the utilization skew (hottest replica /
even-split share; 1.0 = perfectly balanced).

With ``--trajectory_lens L1,L2,...`` the bench switches to the
trajectory sweep: each point submits ``--requests`` concurrent
orbit-path trajectories of that length (one object session each — the
interleaved multi-object load the shared compiled scan co-batches) and
a streaming client drains each request's commit buffer, reporting
frames/s, time-to-first-frame vs. path length, end-to-end latency and
(with a fleet) the per-replica utilization skew plus a
``sessions_migrated`` count asserting the zero-migration contract
(must be 0).

Usage (CPU smoke):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --config test \
        --rates 2,8,32 --requests 12 --out runs/bench_serving.json
    JAX_PLATFORMS=cpu python tools/bench_serving.py --config test \
        --trajectory_lens 3,5 --requests 4 --replicas 3 \
        --out runs/bench_trajectory.json

On a real chip, use the model config the service will run
(``--config srn64``) and rates around the measured per-view service time.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _build_service(args):
    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.config import ServingConfig
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.serving import ServingService
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    if args.steps:
        cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion,
                                               timesteps=args.steps))
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        max_batch=args.max_batch, max_queue=args.max_queue,
        max_wait_ms=args.max_wait_ms, default_timeout_s=args.timeout_s,
        max_views=max(16, args.n_views),
        result_cache_entries=0))     # load bench must not replay results
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    mesh_env = None
    if args.mesh:
        from diff3d_tpu.parallel import make_mesh

        mesh_env = make_mesh(cfg.mesh)
        print(f"bench_serving: mesh {dict(mesh_env.mesh.shape)} "
              f"(lane multiple {mesh_env.data_size})", file=sys.stderr)
    sampler = Sampler(model, params, cfg, mesh=mesh_env,
                      sampler_kind=args.sampler, steps=args.sampler_steps)
    return sampler, cfg


def _synthetic_views(n_views: int, size: int, seed: int):
    import numpy as np

    r = np.random.RandomState(seed)
    return {
        "imgs": r.randn(n_views, size, size, 3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": r.randn(n_views, 3).astype(np.float32),
        "K": np.array([[size * 1.2, 0, size / 2],
                       [0, size * 1.2, size / 2],
                       [0, 0, 1]], np.float32),
    }


def _aggregate_snaps(snaps):
    """Sum counters / count-weight histogram means across replica
    metric snapshots (one replica = the single-service case)."""
    counters, hists = {}, {}
    for snap in snaps:
        for k, v in snap["counters"].items():
            counters[k] = counters.get(k, 0) + v
        for k, h in snap["histograms"].items():
            agg = hists.setdefault(k, {"count": 0, "_wsum": 0.0,
                                       "p50": 0.0})
            n = h.get("count", 0)
            agg["count"] += n
            agg["_wsum"] += h.get("mean", 0.0) * n
            agg["p50"] = max(agg["p50"], h.get("p50", 0.0))
    for h in hists.values():
        h["mean"] = h["_wsum"] / h["count"] if h["count"] else 0.0
    return counters, hists


def _build_fleet_or_single(sampler, cfg, args, cascade=None):
    """Fresh service per sweep point (clean metrics windows).  Returns
    ``(service, replicas_or_None, engines)``."""
    from diff3d_tpu.serving import FleetService, ServingService

    if args.replicas > 1:
        service = FleetService.build(sampler, cfg, n=args.replicas,
                                     cascade=cascade)
        service.start(serve_http=False)
        return service, service.replicas, [rep.engine
                                           for rep in service.replicas]
    service = ServingService(sampler, cfg,
                             cascade=cascade).start(serve_http=False)
    return service, None, [service.engine]


def _warmup(engines, sampler, cfg, n_views: int, n_requests: int) -> None:
    # Warm the fullest lane count so the first request doesn't pay the
    # compile (every sweep point would otherwise time one compile each).
    # Lane counts go through the engine's rounding (power of two, then up
    # to the mesh's lane multiple) so the warmed shapes are exactly the
    # ones traffic will launch.  Fleet replicas share the sampler's jit
    # cache, so only the first replica's warmup compiles.
    from diff3d_tpu.sampling import record_capacity
    from diff3d_tpu.serving import Bucket
    from diff3d_tpu.serving.engine import lane_count
    bucket = Bucket(cfg.model.H, cfg.model.W, record_capacity(n_views),
                    sampler.steps, sampler.sampler_kind)
    for eng in engines:
        for lanes in {lane_count(1, eng.max_batch, eng.lane_multiple),
                      lane_count(min(eng.max_batch, n_requests or 1),
                                 eng.max_batch, eng.lane_multiple)}:
            eng.programs.warmup(bucket, lanes, sampler.w.shape[0])


def _run_rate(sampler, cfg, rate: float, args) -> dict:
    import numpy as np

    service, replicas, engines = _build_fleet_or_single(sampler, cfg, args)
    fleet = replicas is not None
    submit = service.router.submit if fleet else service.engine.submit
    views = [_synthetic_views(args.n_views, cfg.model.H, i)
             for i in range(args.requests)]
    _warmup(engines, sampler, cfg, args.n_views, args.requests)

    from diff3d_tpu.serving.scheduler import ViewRequest
    reqs, latencies, errors = [], [], []
    lock = threading.Lock()

    def waiter(req):
        try:
            req.result(timeout=args.timeout_s + 30)
            with lock:
                latencies.append(req.done_time - req.submit_time)
        except Exception as e:
            with lock:
                errors.append(str(e))

    t0 = time.perf_counter()
    waiters = []
    for i in range(args.requests):
        req = ViewRequest(views[i], seed=i, n_views=args.n_views)
        try:
            submit(req)
        except Exception as e:
            errors.append(str(e))
            continue
        reqs.append(req)
        w = threading.Thread(target=waiter, args=(req,), daemon=True)
        w.start()
        waiters.append(w)
        if rate > 0:
            time.sleep(1.0 / rate)
    for w in waiters:
        w.join()
    wall = time.perf_counter() - t0
    if fleet:
        per_replica_views = {
            rep.name: rep.metrics.snapshot()["counters"].get(
                "serving_views_completed_total", 0) for rep in replicas}
        counters, hists = _aggregate_snaps(
            [rep.metrics.snapshot() for rep in replicas])
        router_snap = service.metrics_snapshot()["counters"]
    else:
        per_replica_views, router_snap = None, {}
        snap = service.metrics_snapshot()
        counters, hists = snap["counters"], snap["histograms"]
    service.stop()

    lat = np.asarray(sorted(latencies)) if latencies else np.zeros(0)
    views_done = counters.get("serving_views_completed_total", 0)
    occ = hists.get("serving_batch_occupancy", {})
    padf = hists.get("serving_batch_padding_fraction", {})
    up_bytes = counters.get("serving_host_upload_bytes_total", 0)
    fetch_bytes = counters.get("serving_host_fetch_bytes_total", 0)
    point = {
        "chips_used": engines[0].lane_multiple,
        "lane_multiple": engines[0].lane_multiple,
        "host_upload_bytes_per_view": (round(up_bytes / views_done)
                                       if views_done else None),
        "host_fetch_bytes_per_view": (round(fetch_bytes / views_done)
                                      if views_done else None),
        "offered_rate_rps": rate,
        "requests": args.requests,
        "completed": len(latencies),
        "errors": len(errors),
        "error_sample": errors[:3],
        "wall_s": round(wall, 3),
        "views_per_sec": round(views_done / wall, 3) if wall else None,
        "latency_p50_s": (round(float(np.percentile(lat, 50)), 3)
                          if lat.size else None),
        "latency_p99_s": (round(float(np.percentile(lat, 99)), 3)
                          if lat.size else None),
        "occupancy_mean": round(occ.get("mean", 0.0), 3),
        "padding_fraction_mean": round(padf.get("mean", 0.0), 3),
        "ttfv_p50_s": round(hists.get(
            "serving_time_to_first_view_seconds", {}).get("p50", 0.0), 3),
    }
    if fleet:
        vals = list(per_replica_views.values())
        mean = sum(vals) / len(vals) if vals else 0.0
        point.update({
            "replicas": args.replicas,
            "per_replica_views": per_replica_views,
            # Utilization skew: hottest replica's share of a perfectly
            # even split (1.0 = balanced; R = everything on one of R).
            "utilization_skew": (round(max(vals) / mean, 3)
                                 if mean else None),
            "router_failover_total": router_snap.get(
                "router_failover_total", 0),
            "router_rejected_total": router_snap.get(
                "router_rejected_total", 0),
        })
    return point


def _trajectory_payload(n_frames: int, size: int, seed: int) -> dict:
    """An orbit trajectory over a synthetic object: random conditioning
    image, conditioning camera on the same orbit shell (one azimuth
    back), path compiled server-side from the JSON spec — exactly the
    ``POST /trajectory`` wire shape."""
    import numpy as np

    from diff3d_tpu.trajectory import orbit_path

    r = np.random.RandomState(seed)
    radius, elevation = 2.6, 20.0
    step = 360.0 / max(1, n_frames)
    cond_R, cond_T = orbit_path(1, radius=radius, elevation_deg=elevation,
                                azimuth0_deg=-step)
    return {
        "cond": {
            "img": r.randn(size, size, 3).astype(np.float32),
            "R": cond_R[0], "T": cond_T[0],
            "K": np.array([[size * 1.2, 0, size / 2],
                           [0, size * 1.2, size / 2],
                           [0, 0, 1]], np.float32),
        },
        "path": {"kind": "orbit", "frames": n_frames, "radius": radius,
                 "elevation_deg": elevation},
        "seed": seed,
        "session_id": f"bench-obj-{seed}",
    }


def _run_trajectory(sampler, cfg, n_frames: int, args) -> dict:
    """One trajectory sweep point: ``args.requests`` concurrent orbit
    trajectories of ``n_frames`` frames, one object session each, every
    request drained by a streaming client as frames commit."""
    import numpy as np

    service, replicas, engines = _build_fleet_or_single(sampler, cfg, args)
    fleet = replicas is not None
    payloads = [_trajectory_payload(n_frames, cfg.model.H, i)
                for i in range(args.requests)]
    _warmup(engines, sampler, cfg, n_frames + 1, args.requests)

    lock = threading.Lock()
    ttffs, latencies, errors = [], [], []

    def drain(req, t_submit):
        # Streaming client: consume the commit buffer as the engine
        # fills it, like the chunked-HTTP reader would.
        try:
            sent, first = 0, None
            while True:
                chunk = req.wait_frames(sent,
                                        timeout=args.timeout_s + 30)
                if chunk and first is None:
                    first = time.perf_counter() - t_submit
                sent += len(chunk)
                if not chunk:
                    break
            req.result(timeout=args.timeout_s + 30)
            with lock:
                ttffs.append(first)
                latencies.append(req.done_time - req.submit_time)
        except Exception as e:
            with lock:
                errors.append(str(e))

    t0 = time.perf_counter()
    drainers = []
    for payload in payloads:
        t_submit = time.perf_counter()
        try:
            req = service.submit_trajectory(payload)
        except Exception as e:
            errors.append(str(e))
            continue
        th = threading.Thread(target=drain, args=(req, t_submit),
                              daemon=True)
        th.start()
        drainers.append(th)
    for th in drainers:
        th.join()
    wall = time.perf_counter() - t0

    if fleet:
        snaps = [rep.metrics.snapshot() for rep in replicas]
        counters, hists = _aggregate_snaps(snaps)
        per_replica_views = {
            rep.name: snap["counters"].get(
                "serving_views_completed_total", 0)
            for rep, snap in zip(replicas, snaps)}
        ledgers = [rep.session_records() for rep in replicas]
    else:
        snap = service.metrics_snapshot()
        counters, hists = snap["counters"], snap["histograms"]
        per_replica_views, ledgers = None, None
    service.stop()

    frames_done = counters.get("serving_trajectory_frames_total", 0)
    lat = np.asarray(sorted(latencies)) if latencies else np.zeros(0)
    tf = np.asarray(sorted(t for t in ttffs if t is not None))
    occ = hists.get("serving_batch_occupancy", {})
    point = {
        "trajectory_frames": n_frames,
        "requests": args.requests,
        "completed": len(latencies),
        "errors": len(errors),
        "error_sample": errors[:3],
        "wall_s": round(wall, 3),
        "frames_committed": frames_done,
        "frames_per_sec": (round(frames_done / wall, 3)
                           if wall else None),
        "ttff_p50_s": (round(float(np.percentile(tf, 50)), 3)
                       if tf.size else None),
        "ttff_max_s": (round(float(tf[-1]), 3) if tf.size else None),
        "latency_p50_s": (round(float(np.percentile(lat, 50)), 3)
                          if lat.size else None),
        "latency_p99_s": (round(float(np.percentile(lat, 99)), 3)
                          if lat.size else None),
        "occupancy_mean": round(occ.get("mean", 0.0), 3),
    }
    if fleet:
        vals = list(per_replica_views.values())
        mean = sum(vals) / len(vals) if vals else 0.0
        owners = {}
        for ledger in ledgers:
            for sid in ledger:
                owners[sid] = owners.get(sid, 0) + 1
        point.update({
            "replicas": args.replicas,
            "per_replica_views": per_replica_views,
            "utilization_skew": (round(max(vals) / mean, 3)
                                 if mean else None),
            # Sessions whose records appear on >1 replica's ledger —
            # any non-zero value is a broken zero-migration contract.
            "sessions_migrated": sum(
                1 for n in owners.values() if n > 1),
        })
    return point


def _warmup_cascade(engines, cascade, n_views: int,
                    n_requests: int) -> None:
    """Warm both phase buckets at the lane counts cascade traffic will
    launch (same rounding contract as :func:`_warmup`)."""
    from diff3d_tpu.sampling import record_capacity
    from diff3d_tpu.serving import Bucket
    from diff3d_tpu.serving.engine import lane_count

    cap = record_capacity(n_views)
    buckets = []
    for phase, s in (("draft", cascade.draft), ("refine", cascade.refine)):
        H = s.cfg.model.H
        buckets.append((Bucket(H, H, cap, s.steps, s.sampler_kind, phase),
                        s.w.shape[0]))
    for eng in engines:
        for bucket, guidance_B in buckets:
            for lanes in {lane_count(1, eng.max_batch, eng.lane_multiple),
                          lane_count(min(eng.max_batch, n_requests or 1),
                                     eng.max_batch, eng.lane_multiple)}:
                eng.programs.warmup(bucket, lanes, guidance_B)


def _run_cascade(sampler, cascade, cfg, rate: float, args) -> dict:
    """One cascade sweep point: ``args.requests`` progressive-preview
    requests at ``rate`` offered load, each drained by a streaming
    client walking the phase-tagged event buffer — reporting
    time-to-first-DRAFT-frame (the preview latency the cascade exists
    for) and time-to-first-REFINED-frame percentiles next to the usual
    end-to-end numbers."""
    import numpy as np

    service, replicas, engines = _build_fleet_or_single(
        sampler, cfg, args, cascade=cascade)
    fleet = replicas is not None
    payloads = [{"views": _synthetic_views(args.n_views, cfg.model.H, i),
                 "seed": i, "n_views": args.n_views}
                for i in range(args.requests)]
    _warmup_cascade(engines, cascade, args.n_views, args.requests)

    lock = threading.Lock()
    ttfds, ttfrs, latencies, errors = [], [], [], []

    def drain(req, t_submit):
        try:
            sent, first_draft, first_refined = 0, None, None
            while True:
                events = req.wait_events(sent,
                                         timeout=args.timeout_s + 30)
                now = time.perf_counter() - t_submit
                for e in events:
                    if e["phase"] == "draft" and first_draft is None:
                        first_draft = now
                    if e["phase"] == "refine" and first_refined is None:
                        first_refined = now
                sent += len(events)
                if not events:
                    break
            req.result(timeout=args.timeout_s + 30)
            with lock:
                ttfds.append(first_draft)
                ttfrs.append(first_refined)
                latencies.append(req.done_time - req.submit_time)
        except Exception as e:
            with lock:
                errors.append(str(e))

    t0 = time.perf_counter()
    drainers = []
    for payload in payloads:
        t_submit = time.perf_counter()
        try:
            req = service.submit_cascade(payload)
        except Exception as e:
            errors.append(str(e))
            continue
        th = threading.Thread(target=drain, args=(req, t_submit),
                              daemon=True)
        th.start()
        drainers.append(th)
        if rate > 0:
            time.sleep(1.0 / rate)
    for th in drainers:
        th.join()
    wall = time.perf_counter() - t0

    if fleet:
        counters, hists = _aggregate_snaps(
            [rep.metrics.snapshot() for rep in replicas])
    else:
        snap = service.metrics_snapshot()
        counters, hists = snap["counters"], snap["histograms"]
    service.stop()

    def _pcts(xs):
        a = np.asarray(sorted(x for x in xs if x is not None))
        if not a.size:
            return None, None
        return (round(float(np.percentile(a, 50)), 3),
                round(float(a[-1]), 3))

    lat = np.asarray(sorted(latencies)) if latencies else np.zeros(0)
    ttfd_p50, ttfd_max = _pcts(ttfds)
    ttfr_p50, ttfr_max = _pcts(ttfrs)
    occ = hists.get("serving_batch_occupancy", {})
    return {
        "offered_rate_rps": rate,
        "requests": args.requests,
        "completed": len(latencies),
        "errors": len(errors),
        "error_sample": errors[:3],
        "wall_s": round(wall, 3),
        "cascade_requests": counters.get(
            "serving_cascade_requests_total", 0),
        "cascade_frames": counters.get(
            "serving_cascade_frames_total", 0),
        "ttfd_p50_s": ttfd_p50,       # time to first DRAFT frame
        "ttfd_max_s": ttfd_max,
        "ttfr_p50_s": ttfr_p50,       # time to first REFINED frame
        "ttfr_max_s": ttfr_max,
        "latency_p50_s": (round(float(np.percentile(lat, 50)), 3)
                          if lat.size else None),
        "latency_p99_s": (round(float(np.percentile(lat, 99)), 3)
                          if lat.size else None),
        "occupancy_mean": round(occ.get("mean", 0.0), 3),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="test")
    p.add_argument("--rates", default="2,8,32",
                   help="comma-separated offered loads in requests/s "
                        "(0 = submit everything at once)")
    p.add_argument("--requests", type=int, default=8,
                   help="requests per rate point")
    p.add_argument("--n_views", type=int, default=3,
                   help="views per request (incl. the conditioning view)")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps per view (test config: 4)")
    p.add_argument("--sampler", choices=["ancestral", "ddim"],
                   default="ancestral",
                   help="reverse-process update served by the engine")
    p.add_argument("--sampler_steps", type=int, default=None,
                   help="few-step schedule: reverse steps per view, a "
                        "divisor of the dense grid (default = full grid) "
                        "— e.g. --sampler ddim --sampler_steps 16 vs the "
                        "256-step default for an end-to-end comparison")
    p.add_argument("--max_batch", type=int, default=8)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--max_wait_ms", type=float, default=50.0)
    p.add_argument("--timeout_s", type=float, default=600.0)
    p.add_argument("--mesh", action="store_true",
                   help="shard the sampler over cfg.mesh (lane counts "
                        "round up to the data-axis size)")
    p.add_argument("--replicas", type=int, default=1,
                   help="run the sweep against the fleet router over "
                        "this many in-process replicas (sessionless "
                        "least-loaded placement); reports "
                        "per_replica_views + utilization_skew per rate")
    p.add_argument("--trajectory_lens", default="",
                   help="comma-separated orbit lengths (frames per "
                        "path); when set the bench runs the trajectory "
                        "sweep instead of the offered-load sweep: "
                        "--requests concurrent single-object "
                        "trajectories per point, streaming clients, "
                        "frames/s + time-to-first-frame vs. length")
    p.add_argument("--cascade", default="",
                   help="cascade plan spec, e.g. "
                        "'draft=64:ddim:8,refine=128:ancestral:64@t0.4' "
                        "(refine resolution must equal the config's); "
                        "when set the bench runs the progressive-preview "
                        "sweep over --rates: time-to-first-DRAFT-frame "
                        "and time-to-first-REFINED-frame percentiles vs "
                        "offered load")
    p.add_argument("--out", default="runs/bench_serving.json")
    args = p.parse_args(argv)

    traj_lens = [int(v) for v in args.trajectory_lens.split(",")
                 if v.strip()]
    if traj_lens:
        # The service's n_views ceiling must clear the longest path
        # (+1 for the conditioning view).
        args.n_views = max(args.n_views, max(traj_lens) + 1)
    sampler, cfg = _build_service(args)
    cascade = None
    if args.cascade:
        from diff3d_tpu.cascade import CascadePlan, CascadeSampler

        plan = CascadePlan.parse(args.cascade)
        cascade = CascadeSampler(sampler.model, sampler.params, cfg,
                                 plan, mesh=sampler.mesh)
    points = []
    if cascade is not None:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
        for rate in rates:
            print(f"bench_serving: cascade rate={rate} rps ...",
                  file=sys.stderr)
            pt = _run_cascade(sampler, cascade, cfg, rate, args)
            print(f"bench_serving:   -> ttfd_p50={pt['ttfd_p50_s']}s "
                  f"ttfr_p50={pt['ttfr_p50_s']}s "
                  f"p50={pt['latency_p50_s']}s errors={pt['errors']}",
                  file=sys.stderr)
            points.append(pt)
    elif traj_lens:
        for n_frames in traj_lens:
            print(f"bench_serving: trajectory {n_frames} frames x "
                  f"{args.requests} objects ...", file=sys.stderr)
            pt = _run_trajectory(sampler, cfg, n_frames, args)
            print(f"bench_serving:   -> {pt['frames_per_sec']} frames/s, "
                  f"ttff_p50={pt['ttff_p50_s']}s "
                  f"p50={pt['latency_p50_s']}s "
                  f"occupancy={pt['occupancy_mean']}", file=sys.stderr)
            points.append(pt)
    else:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
        for rate in rates:
            print(f"bench_serving: rate={rate} rps ...", file=sys.stderr)
            pt = _run_rate(sampler, cfg, rate, args)
            print(f"bench_serving:   -> {pt['views_per_sec']} views/s, "
                  f"p50={pt['latency_p50_s']}s p99={pt['latency_p99_s']}s "
                  f"occupancy={pt['occupancy_mean']}", file=sys.stderr)
            points.append(pt)

    import jax

    record = {
        "bench": ("serving_cascade_sweep" if cascade is not None
                  else "serving_trajectory_sweep" if traj_lens
                  else "serving_offered_load"),
        "cascade": args.cascade or None,
        "config": args.config,
        "platform": jax.devices()[0].platform,
        "num_devices": len(jax.devices()),
        "mesh": bool(args.mesh),
        "lane_multiple": sampler.lane_multiple,
        "diffusion_steps": cfg.diffusion.timesteps,
        "sampler": sampler.sampler_kind,
        "sampler_steps": sampler.steps,
        "n_views": args.n_views,
        "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms,
        "replicas": args.replicas,
        "points": points,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
