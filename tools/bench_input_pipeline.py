"""Input-pipeline throughput: can the host loader feed the device?

Measures the REAL data path — SRN-format PNGs on disk, decoded by the
native C++ pool (``native/decoder.cpp``), 2-view sampling, uint8
quantization, collate — with no device in the loop, so the number is
immune to the dev tunnel's 10x bandwidth variance (see DESIGN.md §3).
Compare ``loader_examples_per_sec`` against the train step's device
demand (BENCH_r*.json): the pipeline sustains the step rate iff
loader >= device demand.

A synthetic SRN directory (objects x views of 64^2 PNGs, poses,
intrinsics) is generated under ``--workdir`` on first run and reused.

Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np


def make_srn_dir(root: str, n_objects: int, n_views: int, size: int) -> str:
    from PIL import Image

    d = os.path.join(root, f"srn_bench_{n_objects}x{n_views}_{size}")
    marker = os.path.join(d, ".complete")
    if os.path.exists(marker):
        return d
    rng = np.random.default_rng(0)
    K = np.array([[size * 1.2, 0, size / 2], [0, size * 1.2, size / 2],
                  [0, 0, 1.0]])
    for o in range(n_objects):
        obj = os.path.join(d, f"obj{o:04d}")
        for sub in ("rgb", "pose", "intrinsics"):
            os.makedirs(os.path.join(obj, sub), exist_ok=True)
        for v in range(n_views):
            name = f"{v:06d}"
            img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(img).save(
                os.path.join(obj, "rgb", f"{name}.png"))
            pose = np.eye(4)
            pose[:3, 3] = rng.normal(0, 1, 3)
            np.savetxt(os.path.join(obj, "pose", f"{name}.txt"),
                       pose.reshape(1, 16))
            np.savetxt(os.path.join(obj, "intrinsics", f"{name}.txt"),
                       K.reshape(1, 9))
    open(marker, "w").close()
    return d


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", default="/tmp")
    p.add_argument("--objects", type=int, default=32)
    p.add_argument("--views", type=int, default=16)
    p.add_argument("--imgsize", type=int, default=64)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--batches", type=int, default=20)
    p.add_argument("--num_workers", type=int, default=8)
    args = p.parse_args()

    from diff3d_tpu.data import InfiniteLoader, SRNDataset

    d = make_srn_dir(args.workdir, args.objects, args.views, args.imgsize)
    ds = SRNDataset("train", d, None, imgsize=args.imgsize,
                    train_fraction=1.0)
    loader = InfiniteLoader(ds, args.batch, num_workers=args.num_workers)

    next(loader)                        # warm (index, pools, page cache)
    t0 = time.perf_counter()
    for _ in range(args.batches):
        b = next(loader)
    dt = time.perf_counter() - t0
    assert b["imgs"].dtype == np.uint8 and b["imgs"].shape[0] == args.batch

    from diff3d_tpu import native

    print(json.dumps({
        "metric": "input_pipeline_examples_per_sec",
        "value": round(args.batches * args.batch / dt, 1),
        "unit": "examples/s",
        "imgsize": args.imgsize,
        "batch": args.batch,
        "num_workers": args.num_workers,
        "native_decoder": native.available(),
        "n_cores": os.cpu_count(),
    }))


if __name__ == "__main__":
    main()
