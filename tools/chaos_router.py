"""Injected-fault soak for the fleet router.

Drives an in-process :class:`~diff3d_tpu.serving.router.FleetService`
(N replicas, no HTTP) with concurrent multi-view *sessions* plus
sessionless traffic, then mid-run:

  * kills one session-owning replica through a seeded
    :class:`~diff3d_tpu.testing.faults.FaultInjector` ``kill`` spec
    (:func:`~diff3d_tpu.testing.faults.arm_replica`), and
  * runs a blue/green params rollout on an operator thread.

Every submitted request lands in exactly one terminal bucket
(completed / failed_retryable / failed_other / hung / lost, as in
``tools/chaos_serving.py``), and the router contract is checked on top:

  * zero record migration — each session's ledger entries live on
    exactly ONE replica (``Replica.session_records``),
  * sessions orphaned by the kill end in a typed
    :class:`~diff3d_tpu.serving.scheduler.SessionLost` naming the lost
    replica — never a hang, never a silent re-place,
  * sessionless traffic keeps completing on the survivors
    (``router_failover_total`` > 0 once a replica is dead),
  * surviving replicas report ``ok`` after the rollout + recovery
    window.

Exit status is 0 iff ``failed_other == hung == lost == migrations == 0``
and every surviving replica is healthy — the fleet contract of
DESIGN.md §14.

``--remote`` runs the same soak against a *cross-process* fleet
(DESIGN.md §19): each replica is a real ``worker_cli`` subprocess
pinned to a disjoint CPU device slice, fronted over the socket
transport, and the kill is a real ``SIGKILL`` of the victim's process
— the in-process kill sites only simulate death; this one delivers it.
The contract checked is identical: typed ``SessionLost`` naming the
victim, sessionless failover to the survivors, zero hung requests,
zero migrations (the dead worker's ledger survives in the router's
last-heartbeat cache, so the audit still sees its sessions).

Usage (CPU):
    JAX_PLATFORMS=cpu python tools/chaos_router.py \
        --replicas 3 --sessions 6 --views 3 --json
    JAX_PLATFORMS=cpu python tools/chaos_router.py \
        --remote --replicas 2 --sessions 4 --views 2 --json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _synthetic_views(n_views: int, size: int, seed: int):
    import numpy as np

    r = np.random.RandomState(seed)
    return {
        "imgs": r.randn(n_views, size, size, 3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": r.randn(n_views, 3).astype(np.float32),
        "K": np.array([[size * 1.2, 0, size / 2],
                       [0, size * 1.2, size / 2],
                       [0, 0, 1]], np.float32),
    }


def _build(args):
    import jax

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.config import ServingConfig
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.serving import FleetService
    from diff3d_tpu.testing.faults import FaultInjector
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config,
           "test": config_lib.test_config}[args.config]()
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        max_batch=4, max_queue=max(16, args.sessions * args.views),
        max_wait_ms=20.0, max_views=6,
        default_timeout_s=args.timeout_s,
        step_retry_attempts=2, step_retry_backoff_s=0.05,
        degraded_recovery_steps=2, retry_after_s=0.2,
        replicas=args.replicas,
        heartbeat_interval_s=0.1, heartbeat_timeout_s=2.0,
        result_cache_entries=0))     # a soak must not replay results
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    inj = FaultInjector(seed=args.seed)
    if args.remote:
        service, procs = _build_remote_fleet(args, cfg)
    else:
        service = FleetService.build(sampler, cfg, params_version="v0")
        procs = {}
    return service, inj, cfg, sampler, procs


def _build_remote_fleet(args, cfg):
    """Spawn ``--replicas`` worker_cli subprocesses on disjoint CPU
    device slices and front them with RemoteReplicas — the fleet shape
    the in-process soak simulates, made real."""
    import json as json_lib
    import subprocess

    from diff3d_tpu.serving import FleetService
    from diff3d_tpu.serving.transport import RemoteReplica

    n = args.replicas
    host_devices = 8
    if n > host_devices:
        raise SystemExit(
            f"--remote --replicas {n}: at most {host_devices} workers "
            f"(one device each on the {host_devices}-virtual-device "
            "CPU backend)")
    per = host_devices // n
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)   # workers pick their own device count
    procs = {}
    for i in range(n):
        lo, hi = i * per, (i + 1) * per - 1
        cmd = [sys.executable, "-m", "diff3d_tpu.cli.worker_cli",
               "--config", args.config, "--init", "random",
               "--devices", f"{lo}-{hi}", "--port", "0",
               "--name", f"w{i}", "--host_device_count",
               str(host_devices), "--timeout_s", str(args.timeout_s),
               "--max_views", "6"]
        if args.compile_cache:
            cmd += ["--compile_cache", args.compile_cache]
        procs[f"w{i}"] = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)
    remotes = []
    for name, proc in procs.items():
        line = proc.stdout.readline()
        if not line:
            raise SystemExit(f"worker {name} died before its ready line")
        ready = json_lib.loads(line)
        print(f"chaos_router: worker {ready['name']} ready on "
              f"port {ready['port']}", file=sys.stderr)
        remotes.append(RemoteReplica(
            "127.0.0.1", ready["port"], name=ready["name"],
            heartbeat_interval_s=cfg.serving.heartbeat_interval_s,
            heartbeat_timeout_s=cfg.serving.heartbeat_timeout_s))
    return FleetService(remotes, cfg), procs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=["srn64", "srn128", "test"],
                   default="test")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--sessions", type=int, default=6,
                   help="concurrent multi-view object sessions")
    p.add_argument("--views", type=int, default=3,
                   help="sequential views per session (each waits for "
                        "the previous view's result — the autoregressive "
                        "record contract)")
    p.add_argument("--sessionless", type=int, default=6,
                   help="sessionless one-shot requests (may fail over)")
    p.add_argument("--timeout_s", type=float, default=120.0)
    p.add_argument("--retries", type=int, default=20,
                   help="client resubmits per view on a retryable "
                        "rejection (FleetOverloaded / ReplicaDraining)")
    p.add_argument("--no-kill", action="store_true",
                   help="skip the replica kill (rollout-only soak)")
    p.add_argument("--remote", action="store_true",
                   help="cross-process fleet: each replica is a real "
                        "worker_cli subprocess on a disjoint CPU device "
                        "slice; the kill is a real SIGKILL of the "
                        "victim's process")
    p.add_argument("--compile_cache", default=None,
                   help="with --remote: shared persistent XLA "
                        "compile-cache dir for the workers")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true",
                   help="emit the survival report as one JSON line on "
                        "stdout")
    args = p.parse_args(argv)

    service, inj, cfg, sampler, worker_procs = _build(args)
    service.start(serve_http=False)
    router = service.router

    from diff3d_tpu.runtime.retry import RetryableError
    from diff3d_tpu.sampling import record_capacity
    from diff3d_tpu.serving.engine import lane_count
    from diff3d_tpu.serving.scheduler import SessionLost, ViewRequest
    from diff3d_tpu.testing.faults import arm_replica, replica_site

    # Pre-compile the program shapes traffic will launch.  In-process
    # replicas share the sampler's jit cache, so only the first warmup
    # compiles; remote workers compile in their own process on first
    # traffic (or reuse --compile_cache).
    n_views = 3
    bucket = (cfg.model.H, cfg.model.W, record_capacity(n_views))
    t0 = time.perf_counter()
    for rep in service.replicas:
        if not hasattr(rep, "engine"):
            continue
        for lanes in {lane_count(n, rep.engine.max_batch,
                                 rep.engine.lane_multiple)
                      for n in (1, 2, rep.engine.max_batch)}:
            rep.engine.programs.warmup(bucket, lanes,
                                       int(sampler.w.shape[0]))
    print(f"chaos_router: warmed programs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    for rep in service.replicas:
        if hasattr(rep, "engine"):    # fault sites live in-process
            arm_replica(rep, inj)

    counts = {"submitted": 0, "completed": 0, "failed_retryable": 0,
              "failed_other": 0, "hung": 0, "sessions_lost": 0}
    errors = []
    lock = threading.Lock()
    live_reqs = []

    def _bump(key, err=None):
        with lock:
            counts[key] += 1
            if err is not None:
                errors.append(err)

    def run_view(sid, view_idx, seed):
        """Submit one view (resubmitting on retryable rejections) and
        wait for its result.  Returns 'done', 'session_lost' or a
        terminal failure bucket already counted."""
        for attempt in range(args.retries + 1):
            req = ViewRequest(_synthetic_views(n_views, cfg.model.H, seed),
                              seed=seed, n_views=n_views, session_id=sid)
            try:
                router.submit(req)
                _bump("submitted")
            except SessionLost as e:
                _bump("submitted")
                _bump("sessions_lost",
                      f"{sid}/v{view_idx}: {type(e).__name__}: {e}")
                return "session_lost"
            except RetryableError as e:
                _bump("submitted")
                time.sleep(max(getattr(e, "retry_after_s", None) or 0.1,
                               0.05))
                continue
            except Exception as e:
                _bump("submitted")
                _bump("failed_other",
                      f"{sid}/v{view_idx}: submit {type(e).__name__}: {e}")
                return "failed"
            with lock:
                live_reqs.append(req)
            try:
                req.result(timeout=args.timeout_s + 30)
                _bump("completed")
                return "done"
            except RetryableError:
                if not req.done():
                    _bump("hung", f"{sid}/v{view_idx}: hung")
                    return "failed"
                # In-flight work died (kill / drain race) — resubmit;
                # a dead owner surfaces SessionLost on the next submit.
                time.sleep(0.05)
                continue
            except Exception as e:
                _bump("failed_other",
                      f"{sid}/v{view_idx}: {type(e).__name__}: {e}")
                return "failed"
        _bump("failed_retryable", f"{sid}: retries exhausted")
        return "failed"

    def run_session(si):
        sid = f"sess-{si}"
        for v in range(args.views):
            if run_view(sid, v, seed=1000 + si * 100 + v) != "done":
                return

    def run_sessionless(i):
        run_view(None, i, seed=9000 + i)

    threads = [threading.Thread(target=run_session, args=(i,), daemon=True)
               for i in range(args.sessions)]
    threads += [threading.Thread(target=run_sessionless, args=(i,),
                                 daemon=True)
                for i in range(args.sessionless)]
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
        time.sleep(0.01)

    # Mid-run chaos, once at least one session has pinned an owner.
    deadline = time.monotonic() + 30.0
    victim = None
    while time.monotonic() < deadline:
        per = service.fleet_snapshot()["sessions"]["per_replica"]
        if per:
            victim = max(per, key=per.get)
            break
        time.sleep(0.02)
    if victim is not None and not args.no_kill:
        if args.remote:
            # The real thing: SIGKILL the victim's worker process.  The
            # router's heartbeat declares it dead within
            # heartbeat_timeout_s; until then sticky submits surface
            # retryable TransportErrors, after it typed SessionLost.
            import signal
            worker_procs[victim].send_signal(signal.SIGKILL)
            print(f"chaos_router: SIGKILLed worker {victim} "
                  f"(pid {worker_procs[victim].pid})", file=sys.stderr)
        else:
            # Fire on the victim's next step dispatch, exactly once.
            inj.add(replica_site(victim), kind="kill", first_n=1 << 30,
                    max_fires=1)
            print(f"chaos_router: kill armed on {victim}",
                  file=sys.stderr)

    rollout_box = {}

    def _rollout():
        time.sleep(0.3)
        try:
            rollout_box.update(service.rollout(sampler.params,
                                               version="v1",
                                               drain_timeout_s=60.0))
        except Exception as e:  # SIGKILL between drain-ok and swap:
            # the worker died mid-rollout; record it instead of leaving
            # the box empty (which reads as "rollout never ran").
            rollout_box.update(
                {"ok": False, "error": f"{type(e).__name__}: {e}"})

    ro = threading.Thread(target=_rollout, daemon=True)
    ro.start()

    for t in threads:
        t.join()
    ro.join()
    wall = time.perf_counter() - wall0

    # Recovery window: surviving replicas must settle back to ok.
    survivors = [r for r in service.replicas if r.health != "dead"]
    deadline = time.monotonic() + 60.0
    while (any(r.health != "ok" for r in survivors)
           and time.monotonic() < deadline):
        time.sleep(0.05)

    # Zero-migration audit: each session's ledger lives on one replica.
    owners = {}
    migrations = []
    for rep in service.replicas:
        for sid in rep.session_records():
            if sid in owners:
                migrations.append(f"{sid}: {owners[sid]} AND {rep.name}")
            owners[sid] = rep.name

    lost = sum(1 for r in live_reqs if not r.done())
    snap = service.metrics_snapshot()
    final_health = {r.name: r.health for r in service.replicas}
    service.stop()
    for proc in worker_procs.values():
        if proc.poll() is None:
            proc.terminate()
    for proc in worker_procs.values():
        try:
            proc.wait(timeout=15)
        except Exception:
            proc.kill()
            proc.wait()
        if proc.stdout is not None:
            proc.stdout.close()

    c = snap["counters"]
    kill_armed = victim is not None and not args.no_kill
    record = {
        "soak": "chaos_router",
        "seed": args.seed,
        "replicas": args.replicas,
        "sessions": args.sessions,
        "views": args.views,
        "wall_s": round(wall, 2),
        **counts,
        "lost": lost,
        "migrations": migrations,
        "victim": victim if kill_armed else None,
        "rollout": rollout_box,
        "router_requests_total": c.get("router_requests_total", 0),
        "router_rejected_total": c.get("router_rejected_total", 0),
        "router_failover_total": c.get("router_failover_total", 0),
        "router_sessions_lost_total": c.get("router_sessions_lost_total",
                                            0),
        "final_health": final_health,
        "error_sample": errors[:8],
    }
    survivors_ok = all(h == "ok" for n, h in final_health.items()
                       if h != "dead")
    ok = (counts["failed_other"] == 0 and counts["hung"] == 0
          and lost == 0 and not migrations and survivors_ok
          and bool(rollout_box) and counts["completed"] > 0)
    if kill_armed:
        # The kill must be visible: a dead replica and, if it owned
        # sessions at death, typed SessionLost rejections for them.
        ok = ok and "dead" in final_health.values()
    record["survived"] = ok
    print(f"chaos_router: {counts['completed']} completed, "
          f"{counts['sessions_lost']} sessions lost (typed), "
          f"{counts['failed_retryable']} retryable-failed, "
          f"{counts['failed_other']} other, {counts['hung']} hung, "
          f"{lost} lost, {len(migrations)} migrations; "
          f"victim={record['victim']}, rollout ok={rollout_box.get('ok')},"
          f" final={final_health} -> "
          f"{'SURVIVED' if ok else 'FAILED'}", file=sys.stderr)
    if args.json:
        print(json.dumps(record))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
