"""Interprocedural RNG-lineage & precision-flow gate, runnable as a
plain script: ``python tools/rngcheck.py [--ast-only | --streams-tier1
| --update | --list-rules | --list-streams]``.

Thin wrapper over ``diff3d_tpu.analysis.rngcheck`` (also installed as
the ``rngcheck`` console script) so the gate works from a checkout
without installing the package.  All arguments pass through — see
``--help`` for the stream registry and manifest workflow, and
docs/DESIGN.md §17 for policy.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from diff3d_tpu.analysis.rngcheck import main as rngcheck_main
    return rngcheck_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
