"""Seeded kill/shrink/grow soak for the elastic training loop.

Drives :class:`~diff3d_tpu.train.trainer.ElasticSupervisor` on a tiny
synthetic config over virtual CPU devices, with a seeded
:class:`~diff3d_tpu.testing.faults.FaultInjector` delivering real
SIGTERMs at scripted batch fetches and a scripted topology schedule that
alternates the mesh between the full device set and half of it — so
every re-mesh cycle is also a shrink (8→4) or grow (4→8) reshard of the
``full_sliced`` checkpoint.

Contract checked (DESIGN.md §16):

  * the run reaches the target step despite every kill (no GAVE_UP),
  * **zero lost steps** — every ``REMESHING`` at step ``S`` is followed
    by a ``RESUMED`` at exactly ``S``: nothing replayed, nothing
    skipped,
  * every scheduled kill was delivered and produced a typed
    ``REMESHING``/``RESUMED`` pair in the event log,
  * device counts actually changed across cycles (the reshard path ran).

Exit status is 0 iff all of the above hold.

Usage (CPU):
    python tools/chaos_train.py --devices 8 --steps 8 --kills 3 --json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _ensure_devices(n: int) -> None:
    """Force ``n`` virtual CPU devices — must run before jax imports."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def run_soak(devices: int, steps: int, kills: int, seed: int,
             workdir: str) -> dict:
    import dataclasses

    import jax

    from diff3d_tpu.config import test_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.testing.faults import FaultInjector, wrap_iter
    from diff3d_tpu.train.trainer import (ELASTIC_REMESHING,
                                          ELASTIC_RESUMED,
                                          ElasticityGaveUp,
                                          ElasticSupervisor)

    n_all = len(jax.devices())
    half = max(1, n_all // 2)
    cfg = test_config(imgsize=8, ch=8, shallow=True)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, max_steps=steps, ckpt_every=2, log_every=0,
            global_batch=8, ckpt_mode="full_sliced", ckpt_async=True))

    rng = random.Random(seed)
    inj = FaultInjector(seed=seed)
    # Kill schedule over the loader's absolute call counter (it spans
    # re-mesh cycles).  Each kill costs one extra fetch on resume (the
    # preempted step's batch is re-derived), so consecutive kills sit
    # >= 2 calls apart to guarantee forward progress between them.
    at, c = [], 1
    for _ in range(kills):
        c += rng.randint(2, max(2, steps // max(1, kills)))
        at.append(c)
    inj.add("loader", kind="sigterm", at_calls=tuple(at))

    ds = SyntheticDataset(num_objects=4, num_views=4, imgsize=cfg.model.H)
    cycle_devs: list = []

    def topology_fn():
        # Alternate full/half device sets: every re-mesh is a real
        # shrink or grow, so every resume exercises the reshard path.
        n = n_all if len(cycle_devs) % 2 == 0 else half
        cycle_devs.append(n)
        return jax.devices()[:n]

    def make_loader(step, env):
        inner = InfiniteLoader(ds, cfg.train.global_batch,
                               seed=cfg.train.seed, num_workers=0,
                               start_step=step)
        return wrap_iter(inner, inj, "loader")

    supervisor = ElasticSupervisor(cfg, make_loader, workdir=workdir,
                                   topology_fn=topology_fn,
                                   reinit_fn=lambda: None)
    gave_up = None
    final_step = -1
    try:
        state = supervisor.run(steps)
        final_step = int(state.step)
    except ElasticityGaveUp as e:
        gave_up = str(e)

    events = supervisor.events
    remesh = [e for e in events if e.state == ELASTIC_REMESHING]
    resumed = [e for e in events if e.state == ELASTIC_RESUMED]
    # Zero-lost-steps accounting: REMESHING at S must resume at S.
    lost = sum(abs(r.step - m.step) for m, r in zip(remesh, resumed))
    dev_counts = [e.n_devices for e in events]
    result = {
        "survived": (gave_up is None and final_step >= steps
                     and lost == 0
                     and int(inj.fired["loader"]) >= kills
                     and len(set(dev_counts)) > 1),
        "target_steps": steps,
        "final_step": final_step,
        "cycles": len(resumed) + 1,
        "kills_scheduled": kills,
        "kills_delivered": int(inj.fired["loader"]),
        "lost_steps": lost,
        "device_counts": dev_counts,
        "gave_up": gave_up,
        "events": [e.record() for e in events],
    }
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU device count (shrink runs at half)")
    p.add_argument("--steps", type=int, default=8,
                   help="target optimizer step the soak must reach")
    p.add_argument("--kills", type=int, default=3,
                   help="SIGTERMs delivered across the run")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="run directory (default: fresh tempdir, removed "
                        "on success)")
    p.add_argument("--json", action="store_true",
                   help="print the full result record as one JSON line")
    args = p.parse_args(argv)

    _ensure_devices(args.devices)
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_train_")
    try:
        result = run_soak(args.devices, args.steps, args.kills,
                          args.seed, workdir)
    finally:
        if args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)
    if args.json:
        print(json.dumps(result))
    else:
        print(f"chaos_train: step {result['final_step']}/"
              f"{result['target_steps']}, {result['kills_delivered']} "
              f"kills, {result['cycles']} cycles over device sets "
              f"{result['device_counts']}, lost_steps="
              f"{result['lost_steps']}, survived={result['survived']}")
    return 0 if result["survived"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
