"""Measure the attached chip's ACHIEVABLE compute ceiling: bf16 (and f32)
matmul sweep plus one conv shape, value-fetch-synced, median-of-windows.

    python tools/roofline.py [--out runs/roofline.json]

Why this exists (VERDICT r3): DESIGN.md normalized train-step utilisation
against an assumed "~50 TFLOP/s effective ceiling through the dev tunnel"
that no committed measurement produced.  This tool produces that number:
the best sustained TFLOP/s any shape reaches here IS the measured ceiling,
to be quoted next to the v5e datasheet peak (~197 bf16 TFLOP/s) so MFU
claims are anchored to evidence at both ends.

Method: for each (M, N, K) a jitted chain of ``steps`` dependent matmuls
(each output feeds the next via a cheap elementwise touch, defeating CSE
while keeping the chain's FLOPs = steps * 2MNK) is timed over >=3 windows;
per-shape TFLOP/s = median window.  The dependent chain means device-side
back-to-back execution — host/tunnel latency amortises across the chain
exactly as it does across a train step's layers.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _time_windows(fn, sync, windows: int = 3):
    """Call ``fn()`` (device work) ``windows`` times, value-syncing via
    ``sync(result)``; returns per-window seconds."""
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        sync(fn())
        # graftlint: disable-next-line=GL106(sync() concretizes via float(jnp.sum) - value-synced by the caller-supplied closure)
        times.append(time.perf_counter() - t0)
    return times


def _matmul_chain(M, N, K, dtype, steps, b_std: float):
    import jax
    import jax.numpy as jnp

    # One c@b step scales magnitudes by ~ b_std * sqrt(K) (sum of K
    # iid products); damp by its inverse so chain values stay in a
    # NORMAL float range for all 64 steps.  The old fixed 1e-3 drove
    # bf16 activations to zero within ~20 steps at large K — harmless
    # on the MXU (timing is data-independent) but not the 'bounded
    # magnitudes' the chain intends, and a backend with zero/denormal
    # fast paths would skew the number (ADVICE r4).  The multiply still
    # fuses into the matmul epilogue.
    damp = 1.0 / (b_std * (K ** 0.5))

    def chain(a, b):
        def body(c, _):
            c = jax.lax.dot(c, b, precision=None,
                            preferred_element_type=dtype)
            return c * jnp.asarray(damp, dtype), None

        c, _ = jax.lax.scan(body, a, None, length=steps)
        return c

    return jax.jit(chain)


def measure_matmul(M, N, K, dtype_name: str, steps: int = 64):
    import jax
    import jax.numpy as jnp
    import numpy as np

    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[dtype_name]
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randn(M, K) * 0.1, dtype)
    b = jnp.asarray(rs.randn(K, N) * 0.1, dtype)
    fn = _matmul_chain(M, N, K, dtype, steps, b_std=0.1)
    sync = lambda c: float(jnp.sum(c.astype(jnp.float32)))
    sync(fn(a, b))                                  # compile + warm
    times = _time_windows(lambda: fn(a, b), sync)
    flops = 2.0 * M * N * K * steps
    per_window = sorted(flops / t / 1e12 for t in times)
    return {
        "shape": [M, N, K], "dtype": dtype_name, "chain_steps": steps,
        "tflops_median": round(per_window[len(per_window) // 2], 2),
        "tflops_windows": [round(v, 2) for v in per_window],
    }


def measure_conv(B, H, W, Cin, Cout, k, dtype_name: str, steps: int = 32):
    """One NHWC conv shape (the X-UNet stem/block shape class)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[dtype_name]
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(B, H, W, Cin) * 0.1, dtype)
    w = jnp.asarray(rs.randn(k, k, Cin, Cout) * 0.1, dtype)

    if Cin != Cout:
        raise ValueError("chain needs Cin == Cout")

    # Same normalising damping as _matmul_chain: one conv step scales
    # magnitudes by ~ w_std * sqrt(k*k*Cin) (sum over the receptive
    # field), so damp by its inverse to keep chain values in a normal
    # float range instead of flushing bf16 activations to zero.
    damp = 1.0 / (0.1 * (k * k * Cin) ** 0.5)

    def chain(x, w):
        def body(c, _):
            c = jax.lax.conv_general_dilated(
                c, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=dtype)
            return c * jnp.asarray(damp, dtype), None

        c, _ = jax.lax.scan(body, x, None, length=steps)
        return c

    fn = jax.jit(chain)
    sync = lambda c: float(jnp.sum(c.astype(jnp.float32)))
    sync(fn(x, w))
    times = _time_windows(lambda: fn(x, w), sync)
    flops = 2.0 * B * H * W * k * k * Cin * Cout * steps
    per_window = sorted(flops / t / 1e12 for t in times)
    return {
        "conv": [B, H, W, Cin, Cout, k], "dtype": dtype_name,
        "chain_steps": steps,
        "tflops_median": round(per_window[len(per_window) // 2], 2),
        "tflops_windows": [round(v, 2) for v in per_window],
    }


# MXU-saturating square shapes + one tall batch-like shape.  (Chained
# timing needs output shape == input shape, so K == N throughout.)
MATMUL_SHAPES = [
    (1024, 1024, 1024),
    (2048, 2048, 2048),
    (4096, 4096, 4096),
    (8192, 8192, 8192),
    (16384, 4096, 4096),
]
# X-UNet conv shape classes (B = microbatch * 2 frames folded together,
# as the model runs them).  The first two are the srn64 bench step's
# level-0/level-1 shapes at its microbatch of 64; measured (committed
# runs/roofline_r4.json): 34.9 and 37.9 TFLOP/s against 136.6 for big
# matmuls, while the wide 256ch/64^2/B=128 shape reaches 85 — so the
# model's own levels cap near 35-38 and a train step at ~38 TFLOP/s is
# at its op-mix ceiling, far though that is from the matmul roofline.
CONV_SHAPES = [
    (128, 64, 64, 128, 128, 3),    # srn64 level 0 (ch=128) @ microbatch 64
    (128, 32, 32, 256, 256, 3),    # srn64 level 1
    (128, 64, 64, 256, 256, 3),    # srn128-class wide shallow conv
    (32, 64, 64, 256, 256, 3),     # same at small batch (latency-bound)
]


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="write JSON here too")
    ap.add_argument("--dtypes", default="bf16,f32")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    result = {
        "device": str(dev), "platform": dev.platform,
        "datasheet_peak_bf16_tflops": 197.0,  # v5e (public spec)
        "matmul": [], "conv": [],
    }
    for dtype in args.dtypes.split(","):
        for M, N, K in MATMUL_SHAPES:
            try:
                r = measure_matmul(M, N, K, dtype)
            except Exception as e:  # OOM on the biggest shapes is fine
                r = {"shape": [M, N, K], "dtype": dtype,
                     "error": str(e).splitlines()[0][:120]}
            result["matmul"].append(r)
            print(json.dumps(r), file=sys.stderr)
        for conv_shape in CONV_SHAPES:
            try:
                r = measure_conv(*conv_shape, dtype)
            except Exception as e:
                r = {"conv": list(conv_shape), "dtype": dtype,
                     "error": str(e).splitlines()[0][:120]}
            result["conv"].append(r)
            print(json.dumps(r), file=sys.stderr)

    best = max((r["tflops_median"] for r in result["matmul"]
                if "tflops_median" in r and r["dtype"] == "bf16"),
               default=None)
    result["measured_ceiling_bf16_tflops"] = best
    if best:
        result["ceiling_vs_datasheet"] = round(best / 197.0, 3)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
