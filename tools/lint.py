"""Repo static-analysis gate, runnable as a plain script:
``python tools/lint.py``.

Runs BOTH passes as one gate (nonzero exit if either finds anything
unsuppressed):

  * **graftlint** — the AST pass (rules GL1xx, docs/DESIGN.md §9);
  * **shardcheck** — the IR pass over the tier-1 program set (rules
    SC2xx, docs/DESIGN.md §10): lowers the mesh-sharded train step and
    sampler ``step_many`` on 8 virtual CPU devices and diffs their
    collectives/dtypes/param placement against the committed manifests
    under ``runs/shardcheck/``.

``--ast-only`` / ``--ir-only`` select one pass; all other arguments
pass through to the selected pass(es) — with both passes active only
argument-free invocation is supported (pass-specific flags differ).
Works from a checkout without installing the package.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    argv = sys.argv[1:]
    ast_only = "--ast-only" in argv
    ir_only = "--ir-only" in argv
    argv = [a for a in argv if a not in ("--ast-only", "--ir-only")]
    if ast_only and ir_only:
        print("tools/lint.py: --ast-only and --ir-only are exclusive",
              file=sys.stderr)
        return 2
    if argv and not (ast_only or ir_only):
        print("tools/lint.py: pass-through arguments need --ast-only or "
              "--ir-only (the two passes take different flags)",
              file=sys.stderr)
        return 2

    rc = 0
    if not ir_only:
        from diff3d_tpu.analysis.lint import main as lint_main
        rc = max(rc, lint_main(argv if ast_only else []))
    if not ast_only:
        from diff3d_tpu.analysis.shardcheck import main as shardcheck_main
        rc = max(rc, shardcheck_main(
            argv if ir_only else ["--programs-tier1"]))
    return rc


if __name__ == "__main__":
    sys.exit(main())
