"""Repo lint gate, runnable as a plain script: ``python tools/lint.py``.

Thin wrapper over ``python -m diff3d_tpu.analysis`` (graftlint) so the
gate works from a checkout without installing the package.  All
arguments pass through — see ``--help`` for the rule catalog and
baseline workflow, and docs/DESIGN.md §9 for policy.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from diff3d_tpu.analysis.lint import main as lint_main
    return lint_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
