"""Repo static-analysis gate, runnable as a plain script:
``python tools/lint.py``.

Runs ALL SIX passes as one gate (nonzero exit if any finds anything
unsuppressed):

  * **graftlint** — the AST pass (rules GL1xx, docs/DESIGN.md §9);
  * **shardcheck** — the IR pass over the tier-1 program set (rules
    SC2xx, docs/DESIGN.md §10): lowers the mesh-sharded train step and
    sampler ``step_many`` on 8 virtual CPU devices and diffs their
    collectives/dtypes/param placement against the committed manifests
    under ``runs/shardcheck/``;
  * **lockcheck** — the concurrency pass (rules LC3xx, docs/DESIGN.md
    §12): lock-order graphs, ``# guarded-by:`` discipline and
    blocking-under-lock checks over the threaded serving/checkpoint
    runtime;
  * **memcheck** — the memory pass over the same tier-1 program set
    (rules MC4xx, docs/DESIGN.md §13): peak-HBM/temp budgets,
    donation-effectiveness verification and scan-invariant recompute
    ceilings against the manifests under ``runs/memcheck/``;
  * **rngcheck** — the RNG-lineage pass (rules RC5xx, docs/DESIGN.md
    §17): interprocedural linear-key dataflow + seed hygiene +
    precision flow over the default targets, and the tier-1 stream
    manifests (ordered key-derivation digests) under
    ``runs/rngcheck/``;
  * **equivcheck** — the semantic-equivalence pass over the same
    tier-1 program set (rules EQ6xx, docs/DESIGN.md §18): canonical
    StableHLO fingerprints, dead-output and duplicate-subcomputation
    ceilings against the manifests under ``runs/equivcheck/``.

``--ast-only`` / ``--ir-only`` / ``--lock-only`` / ``--mem-only`` /
``--rng-only`` / ``--equiv-only``
select one pass; all other arguments pass through to the selected pass
— with multiple passes active only argument-free invocation is
supported (pass-specific flags differ).  ``--json`` (no pass selected)
runs every gate with its JSON formatter and emits one machine-readable
summary — per-pillar unsuppressed/suppressed counts and exit status —
without changing the exit semantics.  Works from a checkout without
installing the package.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

_ONLY_FLAGS = ("--ast-only", "--ir-only", "--lock-only", "--mem-only",
               "--rng-only", "--equiv-only")

#: gate name -> (module path, main-attr defaults when running the full
#: gate).  Order is the gate order: cheap AST/source passes first, the
#: lower+compile passes after (they share one report cache).
_GATES = (
    ("graftlint", "diff3d_tpu.analysis.lint", []),
    ("lockcheck", "diff3d_tpu.analysis.lockcheck", []),
    ("shardcheck", "diff3d_tpu.analysis.shardcheck",
     ["--programs-tier1"]),
    ("memcheck", "diff3d_tpu.analysis.memcheck", ["--programs-tier1"]),
    ("rngcheck", "diff3d_tpu.analysis.rngcheck", ["--streams-tier1"]),
    ("equivcheck", "diff3d_tpu.analysis.equivcheck",
     ["--programs-tier1"]),
)

_ONLY_TO_GATE = {
    "--ast-only": "graftlint",
    "--lock-only": "lockcheck",
    "--ir-only": "shardcheck",
    "--mem-only": "memcheck",
    "--rng-only": "rngcheck",
    "--equiv-only": "equivcheck",
}


def _gate_main(module: str):
    import importlib

    return importlib.import_module(module).main


def _run_json_summary() -> int:
    """Run every gate under its JSON formatter, fold the per-pillar
    counts into one summary document.  Exit semantics match the plain
    run: max of the per-gate exit codes."""
    summary = {"gates": {}, "exit": 0}
    for name, module, defaults in _GATES:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            code = _gate_main(module)(defaults + ["--format", "json"])
        entry = {"exit": code, "unsuppressed": None, "suppressed": None}
        try:
            doc = json.loads(buf.getvalue())
            entry["unsuppressed"] = doc.get("unsuppressed")
            entry["suppressed"] = doc.get("suppressed")
        except ValueError:
            # A gate that crashed before printing JSON still reports
            # its exit code; counts stay null rather than fabricated.
            pass
        summary["gates"][name] = entry
        summary["exit"] = max(summary["exit"], code)
    print(json.dumps(summary, indent=1))
    return summary["exit"]


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    argv = sys.argv[1:]
    only = [f for f in _ONLY_FLAGS if f in argv]
    argv = [a for a in argv if a not in _ONLY_FLAGS]
    if len(only) > 1:
        print(f"tools/lint.py: {' and '.join(only)} are exclusive",
              file=sys.stderr)
        return 2
    selected = only[0] if only else None
    if "--json" in argv:
        if selected is not None:
            print("tools/lint.py: --json runs every gate; use "
                  f"'{selected} ... --format json' for one pass",
                  file=sys.stderr)
            return 2
        if [a for a in argv if a != "--json"]:
            print("tools/lint.py: --json takes no other arguments",
                  file=sys.stderr)
            return 2
        return _run_json_summary()
    if argv and selected is None:
        print("tools/lint.py: pass-through arguments need one of "
              f"{', '.join(_ONLY_FLAGS)} (the passes take different "
              "flags)", file=sys.stderr)
        return 2

    rc = 0
    wanted = _ONLY_TO_GATE.get(selected)
    for name, module, defaults in _GATES:
        if selected is not None and name != wanted:
            continue
        rc = max(rc, _gate_main(module)(argv if selected else defaults))
    return rc


if __name__ == "__main__":
    sys.exit(main())
