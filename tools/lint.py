"""Repo static-analysis gate, runnable as a plain script:
``python tools/lint.py``.

Runs ALL FIVE passes as one gate (nonzero exit if any finds anything
unsuppressed):

  * **graftlint** — the AST pass (rules GL1xx, docs/DESIGN.md §9);
  * **shardcheck** — the IR pass over the tier-1 program set (rules
    SC2xx, docs/DESIGN.md §10): lowers the mesh-sharded train step and
    sampler ``step_many`` on 8 virtual CPU devices and diffs their
    collectives/dtypes/param placement against the committed manifests
    under ``runs/shardcheck/``;
  * **lockcheck** — the concurrency pass (rules LC3xx, docs/DESIGN.md
    §12): lock-order graphs, ``# guarded-by:`` discipline and
    blocking-under-lock checks over the threaded serving/checkpoint
    runtime;
  * **memcheck** — the memory pass over the same tier-1 program set
    (rules MC4xx, docs/DESIGN.md §13): peak-HBM/temp budgets,
    donation-effectiveness verification and scan-invariant recompute
    ceilings against the manifests under ``runs/memcheck/``;
  * **rngcheck** — the RNG-lineage pass (rules RC5xx, docs/DESIGN.md
    §17): interprocedural linear-key dataflow + seed hygiene +
    precision flow over the default targets, and the tier-1 stream
    manifests (ordered key-derivation digests) under
    ``runs/rngcheck/``.

``--ast-only`` / ``--ir-only`` / ``--lock-only`` / ``--mem-only`` /
``--rng-only``
select one pass; all other arguments pass through to the selected pass
— with multiple passes active only argument-free invocation is
supported (pass-specific flags differ).  Works from a checkout without
installing the package.
"""

from __future__ import annotations

import os
import sys

_ONLY_FLAGS = ("--ast-only", "--ir-only", "--lock-only", "--mem-only",
               "--rng-only")


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    argv = sys.argv[1:]
    only = [f for f in _ONLY_FLAGS if f in argv]
    argv = [a for a in argv if a not in _ONLY_FLAGS]
    if len(only) > 1:
        print(f"tools/lint.py: {' and '.join(only)} are exclusive",
              file=sys.stderr)
        return 2
    selected = only[0] if only else None
    if argv and selected is None:
        print("tools/lint.py: pass-through arguments need one of "
              f"{', '.join(_ONLY_FLAGS)} (the passes take different "
              "flags)", file=sys.stderr)
        return 2

    rc = 0
    if selected in (None, "--ast-only"):
        from diff3d_tpu.analysis.lint import main as lint_main
        rc = max(rc, lint_main(argv if selected else []))
    if selected in (None, "--lock-only"):
        from diff3d_tpu.analysis.lockcheck import main as lockcheck_main
        rc = max(rc, lockcheck_main(argv if selected else []))
    if selected in (None, "--ir-only"):
        from diff3d_tpu.analysis.shardcheck import main as shardcheck_main
        rc = max(rc, shardcheck_main(
            argv if selected else ["--programs-tier1"]))
    if selected in (None, "--mem-only"):
        from diff3d_tpu.analysis.memcheck import main as memcheck_main
        rc = max(rc, memcheck_main(
            argv if selected else ["--programs-tier1"]))
    if selected in (None, "--rng-only"):
        from diff3d_tpu.analysis.rngcheck import main as rngcheck_main
        rc = max(rc, rngcheck_main(
            argv if selected else ["--streams-tier1"]))
    return rc


if __name__ == "__main__":
    sys.exit(main())
