"""Report the compiled train step's FLOPs (XLA cost analysis) and the
achieved TFLOP/s at the measured step time — how much of the chip the
bench configs actually use.

    python tools/flops_report.py [--config srn64|srn128] [--ceiling 136.6]

srn64 runs the headline bench shape (batch 128, accum 2); srn128 the
north-star paper config shape (batch 16, accum 4 — the per-device
microbatch that fits one chip's HBM, bench.py).  ``--ceiling`` is the
sustained TFLOP/s to quote utilisation against (default 136.6: the bf16
8192³-matmul ceiling MEASURED on this chip by ``tools/roofline.py``,
committed as ``runs/roofline_r4.json``; v5e datasheet peak is ~197).
NOTE the model's own conv shapes cap near 35-38 TFLOP/s on this chip
(roofline.py conv sweep), so a step at ~38 is at its op-mix ceiling even
though it is far from the matmul ceiling — see docs/DESIGN.md §2.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# (global_batch, accum) per config — the shapes bench.py measures.
BENCH_SHAPE = {"srn64": (128, 2), "srn128": (16, 4)}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", choices=["srn64", "srn128"],
                    default="srn64")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--accum", type=int, default=None)
    ap.add_argument("--ceiling", type=float, default=136.6,
                    help="sustained TFLOP/s to quote utilisation against")
    ap.add_argument("--attn_impl", default=None,
                    choices=["auto", "pallas", "xla"])
    args = ap.parse_args()

    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    global_batch, accum = BENCH_SHAPE[args.config]
    if args.batch is not None:
        global_batch = args.batch
    if args.accum is not None:
        accum = args.accum
    cfg = {"srn64": config_lib.srn64_config,
           "srn128": config_lib.srn128_config}[args.config]()
    model_over = {"remat": True}
    if args.attn_impl:
        model_over["attn_impl"] = args.attn_impl
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, **model_over),
        train=dataclasses.replace(cfg.train, global_batch=global_batch,
                                  accum_steps=accum))
    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))
    ds = SyntheticDataset(num_objects=8, num_views=16, imgsize=cfg.model.H)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    # Donated state: the timed loop holds ONE live copy of the train
    # state (donate=False would double it and OOM the full-width srn128
    # state on a 16G chip).
    step_fn = make_train_step(model, cfg, env)
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])

    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / n

    # Cost/comms extraction rides the shared analysis/ir.py path (the
    # shardcheck engine), on ABSTRACT args (ShapeDtypeStructs — a
    # device_get of the full state would drag GBs over the dev tunnel).
    # FLOPs come from the unsharded variant (same math modulo
    # collectives — the global-batch number, not a per-device shard);
    # the collective footprint comes from the REAL sharded step via its
    # ``.lower`` hook.
    from diff3d_tpu.analysis import ir as ir_lib

    fn = make_train_step(model, cfg, env=None, donate=False)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), (state, batch))
    report = ir_lib.analyze_jitted(
        f"train_step_{args.config}", fn, abstract[0], abstract[1], rng)
    flops = float("nan") if report.flops is None else report.flops
    tflops = flops / dt / 1e12
    print(f"config: {args.config}  batch {global_batch} x accum {accum}  "
          f"attn_impl {cfg.model.attn_impl}")
    print(f"step time: {dt*1e3:.1f} ms  ({global_batch / dt:.1f} examples/s)")
    print(f"XLA cost-analysis flops/step: {flops:.3e}")
    print(f"achieved: {tflops:.1f} TFLOP/s "
          f"({100 * tflops / args.ceiling:.0f}% of the "
          f"{args.ceiling:.0f}-TFLOP/s ceiling)")
    try:
        sharded = ir_lib.analyze_lowered(
            f"train_step_{args.config}_sharded",
            step_fn.lower(abstract[0], abstract[1], rng))
        comms = ir_lib.comms_summary(sharded)
        per_op = ", ".join(
            f"{op} x{c['count']} ({c['bytes'] / 1e6:.1f} MB)"
            for op, c in comms["collectives"].items()) or "none"
        print(f"sharded-step collectives: {per_op}")
        print(f"sharded-step collective bytes/device/step: "
              f"{comms['total_collective_bytes'] / 1e6:.1f} MB")
    except Exception as e:  # comms are advisory; never kill the report
        print(f"sharded-step comms report unavailable: "
              f"{str(e).splitlines()[0]}")


if __name__ == "__main__":
    main()
