"""Report the compiled train step's FLOPs (XLA cost analysis) and the
achieved TFLOP/s at the measured step time — how much of the chip the
headline bench config actually uses.
"""

from __future__ import annotations

import dataclasses
import sys
import time

sys.path.insert(0, ".")


def main() -> None:
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass

    from diff3d_tpu.config import srn64_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    global_batch, accum = 128, 2
    cfg = srn64_config()
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, remat=True),
        train=dataclasses.replace(cfg.train, global_batch=global_batch,
                                  accum_steps=accum))
    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))
    ds = SyntheticDataset(num_objects=8, num_views=16, imgsize=cfg.model.H)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    step_fn = make_train_step(model, cfg, env, donate=False)
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])

    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / n

    # The mesh-sharded step jits lazily inside a closure; lower the
    # unsharded variant (same program modulo collectives) for analysis.
    fn = make_train_step(model, cfg, env=None, donate=False)
    # env=None variant jits directly; lower on abstract args.
    traced = fn.lower(jax.device_get(state), jax.device_get(batch), rng)
    compiled = traced.compile()
    ca = compiled.cost_analysis()
    flops = ca.get("flops", float("nan")) if ca else float("nan")
    print(f"step time: {dt*1e3:.1f} ms  ({global_batch / dt:.1f} examples/s)")
    print(f"XLA cost-analysis flops/step: {flops:.3e}")
    print(f"achieved: {flops / dt / 1e12:.1f} TFLOP/s")


if __name__ == "__main__":
    main()
