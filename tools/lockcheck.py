"""Concurrency static-analysis gate, runnable as a plain script:
``python tools/lockcheck.py [paths ...]``.

Thin wrapper over ``diff3d_tpu.analysis.lockcheck`` (also installed as
the ``lockcheck`` console script) so the gate works from a checkout
without installing the package.  All arguments pass through — see
``--help`` for the rule list and baseline workflow, and
docs/DESIGN.md §12 for policy.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from diff3d_tpu.analysis.lockcheck import main as lockcheck_main
    return lockcheck_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
