"""Why does the srn128 train step sit at ~25% of the chip's matmul
ceiling?  (VERDICT r4 weak #6.)  Measures, on the attached accelerator:

  1. the full-width srn128 train step (bench config) under each
     attention-engine assignment (global auto / all-xla / deep-pallas)
     and under larger microbatches (the HBM freed by ema_bf16 training
     states makes these feasible) — median-of-3 windows each;
  2. a per-site ATTENTION microbench: every (level, L, D) attention
     shape the 128^2 X-UNet actually runs, timed standalone for both
     engines — the per-level timing breakdown that either finds a
     faster engine assignment or proves the op-mix-ceiling argument
     the way srn64's was proven (runs/roofline_r4.json).

Writes one JSON to --out (default runs/profile128_r5.json).

Usage:  python -m tools.profile128 [--steps 6] [--out PATH]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time


def _median_window(fn, sync, windows=3):
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        sync(fn())
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2], times


def time_train_step(cfg, n_steps: int):
    """Median seconds/step of the jitted srn128 train step."""
    import jax

    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))
    ds = SyntheticDataset(num_objects=8, num_views=16,
                          imgsize=cfg.model.H, seed=0)
    raw = next(InfiniteLoader(ds, cfg.train.global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())
    step_fn = make_train_step(model, cfg, env)

    def run():
        nonlocal state
        for _ in range(n_steps):
            state, metrics = step_fn(state, batch, rng)
        return metrics["loss"]

    float(run())                     # compile + warm
    med, times = _median_window(lambda: run(), lambda l: float(l))
    return med / n_steps, [t / n_steps for t in times]


def attention_sites(cfg_model):
    """Every distinct (level, L, D) self/cross-attention shape the
    X-UNet runs at this config.  ``blocks`` = XUNetBlocks with attention
    at the level (down + up); ``sdpa_calls`` = blocks x 2, since each
    block runs a self AND a cross attention (models/layers.py:205-208)
    — use sdpa_calls for any per-step cost attribution."""
    sites = []
    num_res = len(cfg_model.ch_mult)
    for lvl in range(num_res):
        if lvl not in cfg_model.attn_levels:
            continue
        h = cfg_model.H // (2 ** lvl)
        dim = cfg_model.ch * cfg_model.ch_mult[lvl]
        blocks = cfg_model.num_res_blocks + (cfg_model.num_res_blocks + 1)
        sites.append({"level": lvl, "L": h * h, "dim": dim,
                      "D": dim // cfg_model.attn_heads,
                      "blocks": blocks, "sdpa_calls": 2 * blocks})
    if num_res in cfg_model.attn_levels:    # middle block
        h = cfg_model.H // (2 ** (num_res - 1))
        dim = cfg_model.ch * cfg_model.ch_mult[-1]
        sites.append({"level": num_res, "L": h * h, "dim": dim,
                      "D": dim // cfg_model.attn_heads, "blocks": 1,
                      "sdpa_calls": 2})
    return sites


def microbench_site(B, L, heads, D, impl: str, n_iters: int = 8):
    """Seconds per sdpa call of one attention shape under one engine."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from diff3d_tpu.ops.attention import sdpa

    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(B, L, heads, D) * 0.1, jnp.bfloat16)
               for _ in range(3))

    @jax.jit
    def many(q, k, v):
        out = q
        for _ in range(n_iters):
            out = sdpa(out, k, v, impl=impl)
        return out

    sync = lambda o: float(jnp.sum(o.astype(jnp.float32)))
    sync(many(q, k, v))
    med, _ = _median_window(lambda: many(q, k, v), sync)
    return med / n_iters


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--out", default="runs/profile128_r5.json")
    p.add_argument("--skip_microbench", action="store_true")
    args = p.parse_args(argv)

    import jax

    from diff3d_tpu.config import srn128_config

    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:
        pass
    platform = jax.devices()[0].platform
    base = srn128_config()

    # FLOPs/step from the compiled step's own cost analysis is not
    # reliable on all backends; reuse roofline_r4's measured figure
    # instead: bench srn128 b16x4 measured 33.6 TFLOP/s at 0.636 s/step
    # => ~21.4 TFLOP per b16 step (VERDICT r4).  Throughput comparisons
    # below are RELATIVE (sec/step), which needs no flop model.
    results = {"platform": platform, "sites": attention_sites(base.model),
               "train_variants": [], "attn_microbench": []}

    def _flush():
        # written after every measurement: a tunnel fault or window kill
        # mid-run still leaves every completed datapoint on disk.
        # tmp + rename so a kill mid-write can't truncate earlier data.
        import os
        with open(args.out + ".tmp", "w") as f:
            json.dump(results, f, indent=1)
        os.replace(args.out + ".tmp", args.out)

    def variant(name, global_batch, accum, attn_impl_levels=None):
        cfg = dataclasses.replace(
            base,
            model=dataclasses.replace(
                base.model, remat=True,
                attn_impl_levels=attn_impl_levels),
            train=dataclasses.replace(base.train,
                                      global_batch=global_batch,
                                      accum_steps=accum))
        try:
            sec, windows = time_train_step(cfg, args.steps)
            rec = {"name": name, "global_batch": global_batch,
                   "accum": accum, "attn_impl_levels": attn_impl_levels,
                   "sec_per_step": round(sec, 4),
                   "examples_per_sec": round(global_batch / sec, 2),
                   "windows_sec_per_step": [round(t, 4) for t in windows]}
        except Exception as e:
            rec = {"name": name, "global_batch": global_batch,
                   "accum": accum,
                   "error": str(e).splitlines()[0][:200]}
        results["train_variants"].append(rec)
        print(json.dumps(rec), file=sys.stderr)
        _flush()

    # Baseline = bench's srn128 config, then the two VERDICT levers.
    variant("b16x4_auto", 16, 4)
    variant("b16x2_auto", 16, 2)          # microbatch 8
    variant("b32x4_auto", 32, 4)          # microbatch 8, more examples
    variant("b32x2_auto", 32, 2)          # microbatch 16
    n_lvl = base.model.num_resolutions
    variant("b16x4_allxla", 16, 4, tuple(["xla"] * n_lvl))
    # index n_lvl-1 covers BOTH level-3 and the middle block (the two
    # D=256 sites) — see ModelConfig.attn_impl_at's middle clamping.
    variant("b16x4_deep_pallas", 16, 4,
            tuple(["auto"] * (n_lvl - 1) + ["pallas"]))
    # level 2 separately: D=128 at L=1024, below auto's L>=4096 pallas
    # threshold — the site the measured auto policy might be wrong about
    variant("b16x4_lvl2_pallas", 16, 4,
            tuple(["auto", "auto", "pallas", "auto"][:n_lvl]))

    if not args.skip_microbench:
        # B_eff = microbatch * 2 frames at the bench baseline (16/4=4 -> 8)
        for B_eff in (8, 16):
            for s in results["sites"]:
                for impl in ("xla", "pallas"):
                    try:
                        sec = microbench_site(B_eff, s["L"],
                                              base.model.attn_heads,
                                              s["D"], impl)
                        rec = {"B": B_eff, **s, "impl": impl,
                               "sec_per_call": round(sec, 6)}
                    except Exception as e:
                        rec = {"B": B_eff, **s, "impl": impl,
                               "error": str(e).splitlines()[0][:200]}
                    results["attn_microbench"].append(rec)
                    print(json.dumps(rec), file=sys.stderr)
                    _flush()

    _flush()
    print(json.dumps({"wrote": args.out,
                      "variants": len(results["train_variants"])}))


if __name__ == "__main__":
    main()
