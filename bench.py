"""Headline benchmark: ALL THREE of PARITY.md's performance claims in one
JSON line.

Primary metric — training throughput on the reference's own config.
Reference baseline (``BASELINE.md``): 101K steps in 120h on 8x RTX 3090 at
SRN Cars 64x64, global batch 128 — 0.2338 train steps/s = 29.9 examples/s.
This bench times the same workload — X-UNet(H=64, W=64, ch=128), full
train step (loss, grad, Adam, EMA), bf16 compute + per-block remat — on
whatever devices are attached (one TPU chip under the driver; the mesh
scales the same program to a pod).

``vs_baseline`` compares **examples/s** against the reference's 29.9: the
hardware differs (8 GPUs there, whatever is attached here), so throughput,
not step cadence, is the comparable quantity.  The global batch adapts
downward (128 -> 64 -> 32 per try) if the attached HBM can't hold the
reference's 128 — a single v5e is ~1/8 the memory of the reference's 8-GPU
rig that the 128-batch config was sized for.

The same JSON line also carries (on accelerator platforms):

  * ``srn128`` — train examples/s at the paper's 128^2 config, which the
    reference could not run at all (OOM on 8x3090, README.md:39);
    ``vs_baseline`` is null because the reference has no number to beat.
  * ``sampler`` — seconds per synthesised novel view at the reference
    sampler's exact config (256 steps x 2-in-1 CFG forwards x 8-weight
    guidance sweep, ``/root/reference/sampling.py:130-158``); the
    reference published no timing, so ``vs_baseline`` is null.
  * ``sampler128`` — the same sampler protocol at the full-width 128^2
    config (16384-token attention inside the compiled scan); the
    reference could not sample at 128^2 at all.

Robustness: every train metric is the MEDIAN of >=3 independently timed
windows (per-window values + step-time stats embedded under ``windows``),
with one automatic full retry if the windows disagree by >3x — a single
timed window proved to be one transient tunnel stall away from a 20x-wrong
official record (round-3 capture).  Sub-benches that fail (e.g. tunnel
compile-helper limits) degrade to an ``error`` note instead of killing the
primary metric.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time

BASELINE_STEPS_PER_SEC = 101_000 / (120 * 3600)   # 8x3090, README.md:39
BASELINE_EXAMPLES_PER_SEC = BASELINE_STEPS_PER_SEC * 128


# The dial-timeout type now lives in the shared retry shim (the serving
# engine and trainer classify against the same type); re-exported here so
# `bench.BackendDialTimeout` keeps working for the guard tests and any
# harness that imports it.  Semantics unchanged: a hang is distinguished
# from transient ``UNAVAILABLE``-style errors because the correct
# responses differ — a fast transient error is worth re-dialing (r4's
# outage recovered between attempts), but a hang consumes its full 180 s
# per attempt, so it fails FAST with a parseable
# ``{"error": "backend-dial-timeout"}`` record instead.
from diff3d_tpu.runtime.retry import BackendDialTimeout  # noqa: E402

#: Telemetry of the most recent ``_acquire_backend`` call: total dial
#: attempts and the per-retry ``{attempt, error, backoff_s}`` records
#: from the retry policy.  ``main`` embeds this in the structured
#: failure JSON so a voided round shows exactly what the retry loop did.
_LAST_DIAL = {"attempts": 0, "retries": []}

#: Last phase the bench entered, and the partial payload accumulated so
#: far.  Rounds r04/r05 died with NOTHING on stdout; now any death —
#: harness SIGTERM, unexpected exception — emits a structured partial
#: record carrying the phase reached, the dial retry trace, and every
#: sub-metric already measured, so a failed round is diagnosable.
_PHASE = {"reached": "start"}
_PARTIAL: dict = {}

_PHASE_SEQUENCE = (
    "start", "dial", "train_srn64", "train_srn128", "sampler_srn64",
    "sampler_srn64_sharded", "sampler_steps_sweep", "sampler_srn128",
    "sampler_srn128_sharded", "sampler128_steps_sweep", "cascade_sweep",
    "kernels_ab", "complete",
)

#: Kernel backends this round was asked to measure (``--kernels``).
#: ``requested[0]`` is the primary — every phase runs with it; extra
#: entries trigger the ``kernels_ab`` phase.  Module-level so partial
#: records stamp WHICH kernel path was live when the round died.
_KERNELS = {"requested": ["xla"]}


def _enter_phase(name: str) -> None:
    _PHASE["reached"] = name


def _partial_record(reason: str) -> dict:
    """A parseable record of an incomplete round: what phase it reached,
    what the dial's retry loop did, and every metric already in hand."""
    return {
        "metric": "bench_partial",
        "value": None,
        "unit": None,
        "vs_baseline": None,
        "error": reason,
        "phase_reached": _PHASE["reached"],
        "kernels": list(_KERNELS["requested"]),
        "dial": {"attempts": _LAST_DIAL["attempts"],
                 "retries": list(_LAST_DIAL["retries"])},
        "partial": dict(_PARTIAL),
    }


def _run(global_batch: int, n_steps: int, accum: int = 1,
         config: str = "srn64", windows: int = 3,
         kernels: str | None = None):
    import jax

    from diff3d_tpu.config import srn64_config, srn128_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": srn64_config, "srn128": srn128_config}[config]()
    model_over = {"remat": True}
    if kernels is not None:
        model_over["kernels"] = kernels     # groupnorm dispatch backend
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, **model_over),
        train=dataclasses.replace(cfg.train, global_batch=global_batch,
                                  accum_steps=accum))

    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))

    ds = SyntheticDataset(num_objects=8, num_views=16,
                          imgsize=cfg.model.H, seed=0)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    step_fn = make_train_step(model, cfg, env)

    # Warmup: compile + 2 steps.
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])

    # Sync by VALUE fetch, not block_until_ready: on tunneled/async
    # backends block_until_ready can return before remote execution
    # finishes, inflating throughput by orders of magnitude; fetching the
    # final loss forces the whole dependent step chain to have run.
    #
    # Round-3 lesson (VERDICT r3): a single timed window is one transient
    # chip/tunnel stall away from a 20x-wrong official number.  Time
    # `windows` independent windows and report the MEDIAN; if the windows
    # disagree by >3x (a stall hit at least one of them), run one full
    # extra set before taking the median, and embed per-window stats so
    # an anomalous capture is self-evident in the recorded JSON.
    def _window() -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = step_fn(state, batch, rng)
        float(metrics["loss"])
        return time.perf_counter() - t0

    times = [_window() for _ in range(windows)]
    retried = max(times) / min(times) > 3.0
    if retried:
        print(f"bench[{config}]: windows disagree >3x "
              f"({[round(t, 2) for t in times]}s); retrying once",
              file=sys.stderr)
        times += [_window() for _ in range(windows)]
    per_window = sorted(n_steps / t for t in times)
    median = per_window[len(per_window) // 2]
    stats = {
        "windows_steps_per_sec": [round(v, 3) for v in per_window],
        "step_ms_min": round(1e3 * min(times) / n_steps, 1),
        # Derived from the SAME window the headline median comes from, so
        # the recorded stats are internally consistent.
        "step_ms_median": round(1e3 / median, 1),
        "steps_per_window": n_steps,
        "retried": retried,
        "kernels": cfg.model.kernels,
    }
    # shardcheck comms report of the program just timed, so perf numbers
    # and collective counts travel in one JSON record (docs/DESIGN.md
    # §10).  Lowered on ABSTRACT args via the sharded step's .lower hook
    # (no extra buffers); best-effort — a report failure must never void
    # the headline metric.
    try:
        from diff3d_tpu.analysis import ir as ir_lib

        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            (state, batch))
        # rngcheck stream digest from the SAME trace: determinism
        # provenance travels with the perf number (docs/DESIGN.md §17).
        from diff3d_tpu.analysis.rngflow import install_rng_witness

        witness, uninstall = install_rng_witness()
        try:
            lowered = step_fn.lower(abstract[0], abstract[1], rng)
        finally:
            uninstall()
        stats["rng_stream"] = {"digest": witness.digest(),
                               "n_events": len(witness.events)}
        report = ir_lib.analyze_lowered(f"train_step_{config}", lowered)
        stats["comms"] = ir_lib.comms_summary(report)
        # memcheck memory block from the SAME lower+compile pass: peak
        # HBM, donation effectiveness, hoistable scan-invariant FLOPs
        # (docs/DESIGN.md §13).
        from diff3d_tpu.analysis import mem as mem_lib

        stats["mem"] = (mem_lib.memory_summary(report.memory)
                        if report.memory is not None else None)
        # equivcheck semantic fingerprint from the SAME lowering: the
        # canonical digest travels with the perf number, so a recorded
        # regression can be split into "same program, slower" vs "the
        # program itself changed" (docs/DESIGN.md §18).
        from diff3d_tpu.analysis import equiv as equiv_lib

        stats["semantic_fingerprint"] = (
            equiv_lib.semantic_summary(report.semantic)
            if report.semantic is not None else None)
    except Exception as e:
        stats["comms"] = {"error": str(e).splitlines()[0][:200]}
    return median, stats


def _train_bench(configs, n_steps: int, config: str,
                 kernels: str | None = None):
    """Try ``(global_batch, accum)`` configs in order; returns
    ``(examples_per_sec, global_batch, accum, window_stats)``."""
    steps_per_sec, stats, global_batch, accum, err = None, None, None, 1, None
    for global_batch, accum in configs:
        # The tunneled compile helper dies transiently on big programs;
        # retry ONLY that error class once before falling back.  OOM
        # (RESOURCE_EXHAUSTED) is deterministic — straight to the next
        # config.  Other INTERNAL errors are real failures and propagate.
        for attempt in (0, 1):
            try:
                steps_per_sec, stats = _run(global_batch, n_steps, accum,
                                            config, kernels=kernels)
                break
            except Exception as e:
                msg = str(e)
                compile_helper_died = ("remote_compile" in msg
                                       or "tpu_compile" in msg)
                oom = ("RESOURCE_EXHAUSTED" in msg
                       or "memory" in msg.lower())
                if not (oom or compile_helper_died):
                    raise
                # Keep only the message: holding the exception would pin
                # the failed attempt's traceback frames (train state,
                # batch) and their HBM buffers across the retry.
                err = msg.splitlines()[0]
                retrying = compile_helper_died and attempt == 0
                print(f"bench[{config}]: b{global_batch}x{accum} failed "
                      f"({err}); "
                      + ("retrying" if retrying else "trying next config"),
                      file=sys.stderr)
                if not retrying:
                    break
        if steps_per_sec is not None:
            break
    if steps_per_sec is None:
        raise RuntimeError(f"all batch sizes failed: {err}")
    return steps_per_sec * global_batch, global_batch, accum, stats


def _sampler_bench(config: str = "srn64", n_views: int = 4,
                   object_batch: int = 1, use_mesh: bool = False,
                   sampler_kind: str = "ancestral",
                   steps: int | None = None,
                   kernels: str | None = None,
                   comms_out: dict | None = None,
                   mem_out: dict | None = None,
                   rng_out: dict | None = None,
                   sem_out: dict | None = None):
    """Seconds per synthesised view, reference sampler config (256 steps,
    8-weight guidance sweep, ``/root/reference/sampling.py:130-158``) —
    one compiled lax.scan per view.  ``srn128`` runs the full-resolution
    model the reference could never sample (OOM before training,
    README.md:39).

    ``object_batch > 1`` times the object-batched path
    (``Sampler.synthesize_many``) — the configuration ``eval_cli`` ships
    with, where N independent objects share each compiled scan; reported
    cost is per *effective* synthesised view (total time / N*(n_views-1)).

    ``use_mesh`` compiles the sampler with the config's device mesh
    (object axis sharded over the data axis — the sharded serving/eval
    runtime); ``object_batch`` should then be a multiple of the data-axis
    size or padding lanes dilute the per-view number.

    ``sampler_kind`` / ``steps`` select the reverse-process update and
    schedule subset (``diffusion/core.py``): the default is the
    reference protocol above; ``("ddim", 16)`` times the few-step
    deterministic path the serving layer exposes.

    ``kernels`` overrides the groupnorm dispatch backend
    (``ops/dispatch.py``): ``"pallas"`` times the fused
    GroupNorm->FiLM/SiLU Pallas path, ``"xla"`` the unfused reference;
    ``None`` keeps the config default.

    ``comms_out``, when given a dict, is filled with the shardcheck
    comms summary of the batched view-step program (collective counts /
    bytes / upcasts — ``analysis/ir.py``), so the recorded JSON carries
    comms next to the perf number.  Best-effort: on failure (e.g. the
    chunked srn128 path has no single program to lower) the dict gets
    an ``error`` note instead.  ``mem_out`` is the same contract for the
    memcheck memory summary (peak HBM / donation table / hoistable
    scan-invariant FLOPs — ``analysis/mem.py``), extracted from the
    same lower+compile pass.  ``rng_out`` is the same contract for the
    rngcheck stream digest (ordered key-derivation events witnessed
    during the lower — ``analysis/rngflow.py``), so bench rounds carry
    determinism provenance next to comms and memory.  ``sem_out`` is
    the same contract for the equivcheck semantic fingerprint (the
    canonical-form digest and dead/duplicate estimates —
    ``analysis/equiv.py``), pinning WHAT program was timed next to how
    fast it ran.
    """
    import jax
    import numpy as np

    from diff3d_tpu.config import srn64_config, srn128_config
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.sampling.runtime import Sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": srn64_config, "srn128": srn128_config}[config]()
    if kernels is not None:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, kernels=kernels))
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    # srn128 full width: one 256-step scan is a ~2-min device execution,
    # past the dev tunnel's RPC deadline — chunk it into 4 executions
    # (bit-identical result, test_sampling pins it; chunks=1 elsewhere).
    chunks = 4 if config == "srn128" else 1
    if steps is not None:
        chunks = min(chunks, steps)    # chunks must divide the schedule
    mesh_env = make_mesh(cfg.mesh) if use_mesh else None
    sampler = Sampler(model, init_params(model, cfg, rng), cfg,
                      scan_chunks=chunks, mesh=mesh_env,
                      sampler_kind=sampler_kind, steps=steps)

    if (comms_out is not None or mem_out is not None
            or rng_out is not None or sem_out is not None):
        try:
            from diff3d_tpu.analysis import equiv as equiv_lib
            from diff3d_tpu.analysis import ir as ir_lib
            from diff3d_tpu.analysis import mem as mem_lib
            from diff3d_tpu.analysis.rngflow import install_rng_witness
            from diff3d_tpu.sampling.runtime import record_capacity

            lanes = max(object_batch, sampler.lane_multiple)
            witness, uninstall = install_rng_witness()
            try:
                lowered = sampler.lower_step_many(
                    lanes, record_capacity(n_views))
            finally:
                uninstall()
            if rng_out is not None:
                rng_out.update({"digest": witness.digest(),
                                "n_events": len(witness.events)})
            report = ir_lib.analyze_lowered(
                f"step_many_{config}", lowered)
            if comms_out is not None:
                comms_out.update(ir_lib.comms_summary(report))
            if mem_out is not None and report.memory is not None:
                mem_out.update(mem_lib.memory_summary(report.memory))
            if sem_out is not None and report.semantic is not None:
                sem_out.update(
                    equiv_lib.semantic_summary(report.semantic))
        except Exception as e:
            for d in (comms_out, mem_out, rng_out, sem_out):
                if d is not None:
                    d["error"] = str(e).splitlines()[0][:200]

    s = cfg.model.H

    def _views(seed):
        r = np.random.RandomState(seed)
        return {
            "imgs": r.randn(n_views, cfg.model.H, cfg.model.W,
                            3).astype(np.float32),
            "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                                 (n_views, 3, 3)).copy(),
            "T": r.randn(n_views, 3).astype(np.float32),
            "K": np.array([[s * 1.2, 0, s / 2], [0, s * 1.2, s / 2],
                           [0, 0, 1]], np.float32),
        }

    # Warmup (compile) at the SAME record-buffer capacity as the timed run;
    # synthesize returns host arrays, so timing is value-fetch-synced.
    if object_batch == 1:
        views = _views(0)
        sampler.synthesize(views, rng, max_views=n_views)
        t0 = time.perf_counter()
        sampler.synthesize(views, rng, max_views=n_views)
        # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
        raw = time.perf_counter() - t0
        return raw / (n_views - 1), raw, n_views - 1
    views_list = [_views(i) for i in range(object_batch)]
    rngs = list(jax.random.split(rng, object_batch))
    sampler.synthesize_many(views_list, rngs, max_views=n_views)
    t0 = time.perf_counter()
    sampler.synthesize_many(views_list, rngs, max_views=n_views)
    # graftlint: disable-next-line=GL106(synthesize_many fetches the record to host before returning - value-synced)
    raw = time.perf_counter() - t0
    return raw / (object_batch * (n_views - 1)), raw, (object_batch
                                                       * (n_views - 1))


def _sampler_steps_sweep(config: str = "srn64",
                         steps_list=(256, 64, 16, 8), n_views: int = 4,
                         object_batch: int = 1, use_mesh: bool = False,
                         kernels: str | None = None,
                         bench_fn=None) -> dict:
    """Few-step sampling sweep: s/view of the deterministic DDIM sampler
    at each schedule subset, plus speedup relative to the first (full
    256-step) point.  Model calls scale linearly with the schedule
    (``Sampler.model_calls_per_view == steps``, pinned by test_ddim), so
    the sweep quantifies how much of the 32x fewer-calls headroom the
    runtime actually converts into wall-clock speedup (per-step overhead,
    warmup amortisation, and host sync eat the rest).

    ``bench_fn`` (default :func:`_sampler_bench`) is injectable so the
    guard test can validate the sweep's structure without compiling four
    full-width samplers.
    """
    bench_fn = bench_fn or _sampler_bench
    points = []
    for steps in steps_list:
        spv, raw, n_eff = bench_fn(config, n_views=n_views,
                                   object_batch=object_batch,
                                   use_mesh=use_mesh,
                                   sampler_kind="ddim", steps=steps,
                                   kernels=kernels)
        points.append({
            "steps": steps,
            "sampler": "ddim",
            "sec_per_view": round(spv, 3),
            "raw_seconds": round(raw, 3),
            "effective_views": n_eff,
            "model_calls_per_view": steps,
        })
    base = points[0]["sec_per_view"]
    for pt in points:
        pt["speedup_vs_256"] = (round(base / pt["sec_per_view"], 2)
                                if pt["sec_per_view"] else None)
    return {
        "metric": f"sampler_steps_sweep_{config}",
        "unit": "s/view",
        "vs_baseline": None,   # reference has no few-step sampler at all
        "n_views": n_views,
        "object_batch": object_batch,
        "kernels": kernels or "default",
        "points": points,
    }


def _cascade_bench(config: str = "srn128", n_views: int = 2,
                   plan_spec: str | None = None):
    """Times the two cascade phases against the matched single-pass
    sampler (DESIGN.md §20): one warmed run each of the draft pass, the
    truncated refine pass, and the full-schedule single pass, same
    views and key stream.  Returns ``(plan_spec, draft_s, refine_s,
    single_s, n_eff)`` — raw seconds per phase plus the effective view
    count the sweep divides by.
    """
    import jax
    import numpy as np

    from diff3d_tpu.cascade import CascadePlan, CascadeSampler
    from diff3d_tpu.config import srn64_config, srn128_config
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling.runtime import Sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = {"srn64": srn64_config, "srn128": srn128_config}[config]()
    H = cfg.model.H
    if plan_spec is None:
        plan_spec = (f"draft={H // 2}:ddim:8,"
                     f"refine={H}:ancestral:64@t0.5")
    plan = CascadePlan.parse(plan_spec)
    rng = jax.random.PRNGKey(0)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, rng)
    cascade = CascadeSampler(model, params, cfg, plan)
    single = Sampler(model, params, cfg)

    s = cfg.model.H

    def _views(seed):
        r = np.random.RandomState(seed)
        return {
            "imgs": r.randn(n_views, cfg.model.H, cfg.model.W,
                            3).astype(np.float32),
            "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                                 (n_views, 3, 3)).copy(),
            "T": r.randn(n_views, 3).astype(np.float32),
            "K": np.array([[s * 1.2, 0, s / 2], [0, s * 1.2, s / 2],
                           [0, 0, 1]], np.float32),
        }

    views = _views(0)
    k_draft, k_refine = jax.random.split(rng)
    # Warmup (compile) each phase, then time value-synced reruns.
    drafts = cascade.synthesize_draft(views, k_draft, max_views=n_views)
    t0 = time.perf_counter()
    drafts = cascade.synthesize_draft(views, k_draft, max_views=n_views)
    # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
    draft_s = time.perf_counter() - t0
    cascade.refine_views(views, drafts, k_refine, max_views=n_views)
    t0 = time.perf_counter()
    cascade.refine_views(views, drafts, k_refine, max_views=n_views)
    # graftlint: disable-next-line=GL106(refine_views block_until_ready-syncs its result)
    refine_s = time.perf_counter() - t0
    single.synthesize(views, rng, max_views=n_views)
    t0 = time.perf_counter()
    single.synthesize(views, rng, max_views=n_views)
    # graftlint: disable-next-line=GL106(synthesize fetches the record to host before returning - value-synced)
    single_s = time.perf_counter() - t0
    return plan_spec, draft_s, refine_s, single_s, n_views - 1


def _cascade_sweep(config: str = "srn128", n_views: int = 2,
                   bench_fn=None) -> dict:
    """Cascade serving economics: draft latency (time to first preview
    frame), refine latency, and end-to-end s/view against the
    single-pass full-schedule sampler at the same resolution.

    ``bench_fn`` (default :func:`_cascade_bench`) is injectable so the
    guard test can validate the record's structure without compiling
    three samplers.
    """
    bench_fn = bench_fn or _cascade_bench
    plan_spec, draft_s, refine_s, single_s, n_eff = bench_fn(
        config, n_views=n_views)
    e2e = draft_s + refine_s
    return {
        "metric": f"cascade_sweep_{config}",
        "unit": "s/view",
        "vs_baseline": None,   # reference has no cascade at all
        "plan": plan_spec,
        "n_views": n_views,
        "effective_views": n_eff,
        "draft_sec_per_view": round(draft_s / n_eff, 3),
        "refine_sec_per_view": round(refine_s / n_eff, 3),
        "end_to_end_sec_per_view": round(e2e / n_eff, 3),
        "single_pass_sec_per_view": round(single_s / n_eff, 3),
        "draft_raw_seconds": round(draft_s, 3),
        "refine_raw_seconds": round(refine_s, 3),
        "single_pass_raw_seconds": round(single_s, 3),
        "speedup_vs_single_pass": (round(single_s / e2e, 2)
                                   if e2e else None),
        "preview_speedup": (round(single_s / draft_s, 2)
                            if draft_s else None),
    }


def _kernels_ab(kernels_list, *, config: str = "srn64",
                configs=((8, 1),), n_steps: int = 3, n_views: int = 4,
                train_fn=None, sampler_fn=None) -> dict:
    """Head-to-head kernel-backend sweep: the SAME train step and the
    SAME 256-step ancestral sampler timed once per requested backend
    (``xla`` = unfused reference graph, ``pallas`` = fused
    GroupNorm->FiLM/SiLU epilogues, ``ops/pallas_film.py``).  Variant 0
    is the comparison base; later variants carry speedups relative to
    it (train: higher examples/s is better; sampler: lower s/view is
    better — both reported as >1 == variant wins).  A variant that
    fails records a per-variant ``*_error`` note instead of voiding the
    others — the A/B is diagnosable even when one backend can't compile.

    ``train_fn`` / ``sampler_fn`` (default the real benches) are
    injectable so the guard test can validate the record's structure
    without compiling anything.
    """
    train_fn = train_fn or _train_bench
    sampler_fn = sampler_fn or _sampler_bench
    variants = []
    for k in kernels_list:
        v: dict = {"kernels": k}
        try:
            eps, gb, ac, stats = train_fn(list(configs), n_steps, config,
                                          kernels=k)
            v["train_examples_per_sec"] = round(eps, 2)
            v["train_global_batch"] = gb
            v["train_step_ms_median"] = stats.get("step_ms_median")
        except Exception as e:
            v["train_error"] = str(e).splitlines()[0][:200]
        try:
            spv, raw, n_eff = sampler_fn(config, n_views=n_views,
                                         kernels=k)
            v["sampler_sec_per_view"] = round(spv, 3)
            v["sampler_raw_seconds"] = round(raw, 3)
        except Exception as e:
            v["sampler_error"] = str(e).splitlines()[0][:200]
        variants.append(v)
    base = variants[0]
    for v in variants[1:]:
        b_eps = base.get("train_examples_per_sec")
        v_eps = v.get("train_examples_per_sec")
        if b_eps and v_eps:
            v[f"train_speedup_vs_{base['kernels']}"] = round(
                v_eps / b_eps, 3)
        b_spv = base.get("sampler_sec_per_view")
        v_spv = v.get("sampler_sec_per_view")
        if b_spv and v_spv:
            v[f"sampler_speedup_vs_{base['kernels']}"] = round(
                b_spv / v_spv, 3)
    return {
        "metric": f"kernels_ab_{config}",
        "dimension": "kernels",
        "unit": None,
        "vs_baseline": None,   # reference has a single (unfused) path
        "variants": variants,
    }


def _parse_args(argv):
    """``--kernels`` is the only flag: a comma list of groupnorm dispatch
    backends.  Entry 0 runs every phase; extra entries add the
    ``kernels_ab`` head-to-head phase."""
    import argparse

    p = argparse.ArgumentParser(
        prog="bench.py",
        description="Headline benchmark (see module docstring).")
    p.add_argument(
        "--kernels", default="xla",
        help="comma list of groupnorm kernel backends to measure "
             "(xla|pallas|auto); first entry drives all phases, extra "
             "entries run the kernels_ab A/B sweep (e.g. 'xla,pallas')")
    args = p.parse_args(list(argv))
    ks = [k.strip() for k in args.kernels.split(",") if k.strip()]
    bad = [k for k in ks if k not in ("xla", "pallas", "auto")]
    if bad:
        p.error(f"unknown kernel backend(s) {bad}; "
                f"choose from xla, pallas, auto")
    return ks or ["xla"]


def _acquire_backend(attempts: int = 6, wait_s: float = 75.0):
    """``jax.devices()`` via the shared retry shim.

    Round 4's official capture was voided by a single transient
    ``UNAVAILABLE`` raised from backend *initialization* — upstream of
    every downstream robustness layer (median-of-3 windows, compile-helper
    retry).  The tunneled chip's faults are transient (the same chip did
    ~30 chip-hours of real work that round), so re-dialing with a backoff
    is the correct response; only after ``attempts`` consecutive failures
    is the error allowed to surface (and ``main`` still turns it into a
    parseable JSON line).  Two fault classes, two responses (both
    encoded in :func:`diff3d_tpu.runtime.retry.acquire_backend`):

      * a dial that raises fast (``UNAVAILABLE``) is retried with a
        constant ``wait_s`` backoff, clearing the poisoned client
        between attempts;
      * a dial that HANGS past its 180 s SIGALRM budget raises
        :class:`BackendDialTimeout` immediately — five rounds of records
        (BENCH_r01..r05) show the harness killing a still-sleeping retry
        loop (rc=124, no JSON) before it could concede.

    Each call resets ``_LAST_DIAL`` and records attempt/backoff
    telemetry there for the structured failure JSON.
    """
    from diff3d_tpu.runtime import retry as _retry

    retries: list = []
    _LAST_DIAL["attempts"] = 0
    _LAST_DIAL["retries"] = retries

    def _notify(attempt, exc, delay):
        print(f"bench: backend init attempt {attempt}/{attempts} "
              f"failed: {str(exc).splitlines()[0][:200]}",
              file=sys.stderr)

    try:
        devices = _retry.acquire_backend(
            attempts=attempts, wait_s=wait_s,
            attempts_log=retries, on_retry=_notify)
    except BaseException:
        _LAST_DIAL["attempts"] = len(retries) + 1
        raise
    _LAST_DIAL["attempts"] = len(retries) + 1
    return devices


def main(argv=()) -> int:
    """Run the bench with an always-parseable exit: a SIGTERM from the
    harness (``timeout`` sends TERM before KILL — round r05 died to
    exactly this with no record) or an unexpected exception both emit a
    structured partial-result record instead of nothing.  The previous
    SIGTERM disposition is restored on return so an embedding process
    (tests, a driving trainer) keeps its own handlers."""
    _PHASE["reached"] = "start"
    _PARTIAL.clear()
    _KERNELS["requested"] = _parse_args(argv)

    def _on_term(signum, frame):  # pragma: no cover - signal path
        print(json.dumps(_partial_record(
            "sigterm: killed before completion")), flush=True)
        os._exit(0)

    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # pragma: no cover - non-main thread
        prev_term = None
    try:
        return _bench_main()
    except BaseException as e:
        msg = str(e).splitlines()[0][:300] if str(e) else ""
        print(json.dumps(_partial_record(
            f"{type(e).__name__}: {msg}" if msg else type(e).__name__)),
            flush=True)
        return 0
    finally:
        if prev_term is not None:
            try:
                signal.signal(signal.SIGTERM, prev_term)
            except ValueError:  # pragma: no cover
                pass


def _bench_main() -> int:
    import jax

    try:  # persistent compile cache across driver rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass

    _enter_phase("dial")
    try:
        devices = _acquire_backend()
    except BackendDialTimeout as e:
        # Fail FAST and parseable: the r01–r05 records are all rc=124
        # with nothing on stdout because the dial hung and the retry
        # loop outlived the harness timeout.
        print(json.dumps({
            "metric": "train_examples_per_sec_srn64",
            "value": None,
            "unit": "examples/s",
            "vs_baseline": None,
            "error": "backend-dial-timeout",
            "detail": str(e).splitlines()[0][:300],
            "phase_reached": _PHASE["reached"],
            "dial": {"attempts": _LAST_DIAL["attempts"],
                     "retries": list(_LAST_DIAL["retries"])},
        }))
        return 0
    except Exception as e:
        # The record must ALWAYS parse: a bench that dies before printing
        # leaves the round with no official perf evidence at all (r4).
        print(json.dumps({
            "metric": "train_examples_per_sec_srn64",
            "value": None,
            "unit": "examples/s",
            "vs_baseline": None,
            "error": f"backend init failed after retries: "
                     f"{str(e).splitlines()[0][:300]}",
            "phase_reached": _PHASE["reached"],
            "dial": {"attempts": _LAST_DIAL["attempts"],
                     "retries": list(_LAST_DIAL["retries"])},
        }))
        return 0

    platform = devices[0].platform
    ndev = len(devices)
    on_accel = platform != "cpu"
    kernels_list = list(_KERNELS["requested"])
    primary = kernels_list[0]
    # srn64 configs in preference order: the reference's exact global batch
    # 128 (2 accumulation microbatches fit one 16G chip), then direct
    # smaller batches.  CPU fallback (no accelerator): tiny so the bench
    # finishes.
    configs = [(128, 2), (64, 1), (32, 1)] if on_accel else [(8, 1)]
    n_steps = 10 if on_accel else 3

    _enter_phase("train_srn64")
    try:
        examples_per_sec, global_batch, accum, stats = _train_bench(
            configs, n_steps, "srn64", kernels=primary)
    except Exception as e:
        print(json.dumps({
            "metric": f"train_examples_per_sec_srn64_{platform}_x{ndev}",
            "value": None,
            "unit": "examples/s",
            "vs_baseline": None,
            "error": str(e).splitlines()[0][:300],
            "phase_reached": _PHASE["reached"],
            "dial": {"attempts": _LAST_DIAL["attempts"],
                     "retries": list(_LAST_DIAL["retries"])},
        }))
        return 0
    name = f"b{global_batch}" + (f"x{accum}accum" if accum > 1 else "")
    payload = _PARTIAL     # alias: a partial record carries it verbatim
    payload.update({
        "metric": f"train_examples_per_sec_srn64_{name}_{platform}"
                  f"_x{ndev}",
        "value": round(examples_per_sec, 2),
        "unit": "examples/s",
        "vs_baseline": round(examples_per_sec / BASELINE_EXAMPLES_PER_SEC,
                             4),
        "kernels": primary,
        "windows": stats,
    })

    # Secondary headline metrics ride in the same JSON line; CPU runs skip
    # them (a 128^2 CPU compile + 256-step sampler adds many minutes for
    # numbers nobody compares).
    if on_accel:
        _enter_phase("train_srn128")
        try:
            eps128, gb128, ac128, stats128 = _train_bench(
                [(16, 4), (8, 4)], 5, "srn128", kernels=primary)
            payload["srn128"] = {
                "metric": f"train_examples_per_sec_srn128_b{gb128}x"
                          f"{ac128}accum_{platform}_x{ndev}",
                "value": round(eps128, 2),
                "unit": "examples/s",
                "vs_baseline": None,   # reference OOMs at 128^2
                "windows": stats128,
            }
        except Exception as e:
            payload["srn128"] = {"error": str(e).splitlines()[0][:200]}
        _enter_phase("sampler_srn64")
        try:
            comms: dict = {}
            mem: dict = {}
            rng_stream: dict = {}
            sem: dict = {}
            sec_per_view, raw_s, n_eff = _sampler_bench(
                kernels=primary, comms_out=comms, mem_out=mem,
                rng_out=rng_stream, sem_out=sem)
            payload["sampler"] = {
                "metric": f"sampler_sec_per_view_srn64_{platform}",
                "value": round(sec_per_view, 2),
                "unit": "s/view",
                "vs_baseline": None,   # reference published no timing
                "kernels": primary,
                "raw_seconds": round(raw_s, 2),
                "effective_views": n_eff,
                "chips_used": 1,
                "comms": comms,
                "mem": mem,
                "rng_stream": rng_stream,
                "semantic_fingerprint": sem,
            }
        except Exception as e:
            payload["sampler"] = {"error": str(e).splitlines()[0][:200]}
        if ndev > 1 and isinstance(payload.get("sampler"), dict) \
                and "value" in payload["sampler"]:
            # Sharded runtime: one object per chip on the data axis.  The
            # unsharded block above keeps its longitudinal metric name;
            # per-chip scaling = value / sharded.sec_per_view.
            _enter_phase("sampler_srn64_sharded")
            try:
                sh_comms: dict = {}
                sh_mem: dict = {}
                sh_rng: dict = {}
                sh_sem: dict = {}
                sh_spv, sh_raw, sh_eff = _sampler_bench(
                    object_batch=ndev, use_mesh=True, kernels=primary,
                    comms_out=sh_comms, mem_out=sh_mem,
                    rng_out=sh_rng, sem_out=sh_sem)
                payload["sampler"]["sharded"] = {
                    "chips_used": ndev,
                    "sec_per_view": round(sh_spv, 2),
                    "raw_seconds": round(sh_raw, 2),
                    "effective_views": sh_eff,
                    "object_batch": ndev,
                    "speedup_vs_single": round(
                        payload["sampler"]["value"] / sh_spv, 2)
                    if sh_spv else None,
                    "comms": sh_comms,
                    "mem": sh_mem,
                    "rng_stream": sh_rng,
                    "semantic_fingerprint": sh_sem,
                }
            except Exception as e:
                payload["sampler"]["sharded"] = {
                    "error": str(e).splitlines()[0][:200]}
        _enter_phase("sampler_steps_sweep")
        try:
            # Few-step DDIM sweep at srn64: how wall-clock tracks the
            # 256 -> 8 model-call reduction on real hardware.
            payload["sampler_steps"] = _sampler_steps_sweep(
                kernels=primary)
        except Exception as e:
            payload["sampler_steps"] = {"error": str(e).splitlines()[0][:200]}
        _enter_phase("sampler_srn128")
        try:
            # Object-batch 2, 2 views each = 2 effective synthesised views
            # per batched 256-step scan at 16384 tokens/frame, full-width
            # srn128 — the configuration eval_cli ships with (the unbatched
            # worst case was r3's 107 s/view; the shipping path amortises
            # the scan across objects).  raw_seconds/effective_views keep
            # the longitudinal record comparable across metric semantics
            # (ADVICE r4): raw_seconds is the wall time of ONE batched
            # scan pass, value = raw_seconds / effective_views.
            sec_per_view128, raw_s128, n_eff128 = _sampler_bench(
                "srn128", n_views=2, object_batch=2, kernels=primary)
            payload["sampler128"] = {
                "metric": f"sampler_sec_per_view_srn128_objbatch2_"
                          f"{platform}",
                "value": round(sec_per_view128, 2),
                "unit": "s/view",
                "vs_baseline": None,   # reference cannot run 128^2 at all
                "kernels": primary,
                "raw_seconds": round(raw_s128, 2),
                "effective_views": n_eff128,
                "chips_used": 1,
            }
        except Exception as e:
            payload["sampler128"] = {"error": str(e).splitlines()[0][:200]}
        if ndev > 1 and isinstance(payload.get("sampler128"), dict) \
                and "value" in payload["sampler128"]:
            _enter_phase("sampler_srn128_sharded")
            try:
                sh_spv, sh_raw, sh_eff = _sampler_bench(
                    "srn128", n_views=2, object_batch=ndev, use_mesh=True,
                    kernels=primary)
                payload["sampler128"]["sharded"] = {
                    "chips_used": ndev,
                    "sec_per_view": round(sh_spv, 2),
                    "raw_seconds": round(sh_raw, 2),
                    "effective_views": sh_eff,
                    "object_batch": ndev,
                    "speedup_vs_single": round(
                        payload["sampler128"]["value"] / sh_spv, 2)
                    if sh_spv else None,
                }
            except Exception as e:
                payload["sampler128"]["sharded"] = {
                    "error": str(e).splitlines()[0][:200]}
        _enter_phase("sampler128_steps_sweep")
        try:
            # Same sweep at the full-width 128^2 config (object-batched
            # like the sampler128 block so the scan stays amortised).
            payload["sampler128_steps"] = _sampler_steps_sweep(
                "srn128", n_views=2, object_batch=2, kernels=primary)
        except Exception as e:
            payload["sampler128_steps"] = {
                "error": str(e).splitlines()[0][:200]}
        _enter_phase("cascade_sweep")
        try:
            # Cascade serving economics at full width: 64²-draft preview
            # latency, truncated 128² refine latency, end-to-end s/view
            # vs the single-pass 256-step sampler (DESIGN.md §20).
            payload["cascade"] = _cascade_sweep("srn128", n_views=2)
        except Exception as e:
            payload["cascade"] = {"error": str(e).splitlines()[0][:200]}

    if len(kernels_list) > 1:
        if on_accel:
            _enter_phase("kernels_ab")
            try:
                # Re-time the srn64 train step and sampler per backend at
                # the batch config the primary phase settled on, so the
                # A/B rides one known-good config instead of re-walking
                # the fallback ladder per variant.
                payload["kernels_ab"] = _kernels_ab(
                    kernels_list, configs=[(global_batch, accum)],
                    n_steps=n_steps)
            except Exception as e:
                payload["kernels_ab"] = {
                    "error": str(e).splitlines()[0][:200]}
        else:
            # CPU has no Pallas backend: the fused path would run in
            # interpret mode, which is a correctness harness, not a perf
            # measurement (tools/bench_kernels.py --interpret is the
            # committed CPU smoke for that).
            payload["kernels_ab"] = {
                "skipped": "cpu: interpret-mode pallas is not a perf "
                           "measurement; see tools/bench_kernels.py"}

    _enter_phase("complete")
    payload["phase_reached"] = "complete"
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
