"""Headline benchmark: training throughput on the reference's own config.

Reference baseline (``BASELINE.md``): 101K steps in 120h on 8x RTX 3090 at
SRN Cars 64x64, global batch 128 — 0.2338 train steps/s = 29.9 examples/s.
This bench times the same workload — X-UNet(H=64, W=64, ch=128), full
train step (loss, grad, Adam, EMA), bf16 compute + per-block remat — on
whatever devices are attached (one TPU chip under the driver; the mesh
scales the same program to a pod) and prints ONE JSON line.

``vs_baseline`` compares **examples/s** against the reference's 29.9: the
hardware differs (8 GPUs there, whatever is attached here), so throughput,
not step cadence, is the comparable quantity.  The global batch adapts
downward (128 -> 64 -> 32 per try) if the attached HBM can't hold the
reference's 128 — a single v5e is ~1/8 the memory of the reference's 8-GPU
rig that the 128-batch config was sized for.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

BASELINE_STEPS_PER_SEC = 101_000 / (120 * 3600)   # 8x3090, README.md:39
BASELINE_EXAMPLES_PER_SEC = BASELINE_STEPS_PER_SEC * 128


def _run(global_batch: int, n_steps: int, accum: int = 1):
    import jax

    from diff3d_tpu.config import srn64_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    cfg = srn64_config()
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, remat=True),
        train=dataclasses.replace(cfg.train, global_batch=global_batch,
                                  accum_steps=accum))

    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))

    ds = SyntheticDataset(num_objects=8, num_views=16,
                          imgsize=cfg.model.H, seed=0)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    step_fn = make_train_step(model, cfg, env)

    # Warmup: compile + 2 steps.
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])

    # Sync by VALUE fetch, not block_until_ready: on tunneled/async
    # backends block_until_ready can return before remote execution
    # finishes, inflating throughput by orders of magnitude; fetching the
    # final loss forces the whole dependent step chain to have run.
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step_fn(state, batch, rng)
    float(metrics["loss"])
    return n_steps / (time.perf_counter() - t0)


def main() -> None:
    import jax

    try:  # persistent compile cache across driver rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass

    platform = jax.devices()[0].platform
    # Configs in preference order: the reference's exact global batch 128
    # (2 accumulation microbatches fit one 16G chip), then direct smaller
    # batches.  CPU fallback (no accelerator): tiny so the bench finishes.
    configs = ([(128, 2), (64, 1), (32, 1)] if platform != "cpu"
               else [(8, 1)])
    n_steps = 10 if platform != "cpu" else 3

    steps_per_sec, global_batch, accum, err = None, None, 1, None
    for global_batch, accum in configs:
        # The tunneled compile helper dies transiently on big programs;
        # retry ONLY that error class once before falling back.  OOM
        # (RESOURCE_EXHAUSTED) is deterministic — straight to the next
        # config.  Other INTERNAL errors are real failures and propagate.
        for attempt in (0, 1):
            try:
                steps_per_sec = _run(global_batch, n_steps, accum)
                break
            except Exception as e:
                msg = str(e)
                compile_helper_died = ("remote_compile" in msg
                                       or "tpu_compile" in msg)
                oom = ("RESOURCE_EXHAUSTED" in msg
                       or "memory" in msg.lower())
                if not (oom or compile_helper_died):
                    raise
                # Keep only the message: holding the exception would pin
                # the failed attempt's traceback frames (train state,
                # batch) and their HBM buffers across the retry.
                err = msg.splitlines()[0]
                retrying = compile_helper_died and attempt == 0
                print(f"bench: b{global_batch}x{accum} failed ({err}); "
                      + ("retrying" if retrying else "trying next config"),
                      file=sys.stderr)
                if not retrying:
                    break
        if steps_per_sec is not None:
            break
    if steps_per_sec is None:
        raise SystemExit(f"bench failed at every batch size: {err}")

    examples_per_sec = steps_per_sec * global_batch
    name = f"b{global_batch}" + (f"x{accum}accum" if accum > 1 else "")
    print(json.dumps({
        "metric": f"train_examples_per_sec_srn64_{name}_{platform}"
                  f"_x{len(jax.devices())}",
        "value": round(examples_per_sec, 2),
        "unit": "examples/s",
        "vs_baseline": round(examples_per_sec / BASELINE_EXAMPLES_PER_SEC,
                             4),
    }))


if __name__ == "__main__":
    sys.exit(main())
