"""Headline benchmark: training throughput on the reference's own config.

Reference baseline (``BASELINE.md``): 101K steps in 120h on 8x RTX 3090 at
SRN Cars 64x64, global batch 128 — ~0.84 train steps/s.  This bench times
the same workload — X-UNet(H=64, W=64, ch=128), global batch 128, full
train step (loss, grad, Adam, EMA) — on whatever devices are attached
(one TPU chip under the driver) and prints ONE JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

BASELINE_STEPS_PER_SEC = 101_000 / (120 * 3600)   # 8x3090, README.md:39


def main() -> None:
    import jax

    try:  # persistent compile cache across driver rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    except Exception:  # pragma: no cover
        pass

    from diff3d_tpu.config import srn64_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.train import TrainState, create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    platform = jax.devices()[0].platform
    cfg = srn64_config()
    global_batch = 128
    # CPU fallback (no accelerator attached): shrink so the bench finishes;
    # the recorded metric is still steps/s at the active batch.
    if platform == "cpu":
        global_batch = 8
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, global_batch=global_batch))

    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(
        state, TrainState(step=env.replicated(),
                          params=env.params(state.params),
                          opt_state=env.params(state.opt_state),
                          ema_params=env.params(state.ema_params)))

    ds = SyntheticDataset(num_objects=8, num_views=16,
                          imgsize=cfg.model.H, seed=0)
    raw = next(InfiniteLoader(ds, global_batch, seed=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())

    step_fn = make_train_step(model, cfg, env)

    # Warmup: compile + 2 steps.
    for _ in range(2):
        state, metrics = step_fn(state, batch, rng)
    jax.block_until_ready(metrics["loss"])

    n_steps = 10 if platform != "cpu" else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step_fn(state, batch, rng)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = n_steps / dt
    print(json.dumps({
        "metric": f"train_steps_per_sec_srn64_b{global_batch}_{platform}",
        "value": round(steps_per_sec, 4),
        "unit": "steps/s",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
