"""lockcheck (static) and the lock witness (runtime), tested from both
sides.

For every LC rule (LC301–LC308) there is a known-BAD fixture that must
fire and a known-GOOD fixture that must stay silent — the silent side
encodes the concurrency idioms this repo actually uses (condition waits
in while-predicate loops, capture-under-lock / invoke-after-release,
``_locked``-suffix methods with def-line ``# guarded-by:``
preconditions).  Then the suppression grammar (lockcheck's namespace is
independent of graftlint's), the baseline round-trip, the runtime
witness against a seeded lock-order inversion and a held-lock wait, the
``lock_witness`` pytest marker end-to-end (including its vacuous-pass
protection), and the tier-1 gates: the threaded modules and the whole
repo must lockcheck clean.
"""

import os
import textwrap
import threading

import pytest

from diff3d_tpu.analysis.lint import (DEFAULT_TARGETS, apply_baseline,
                                      load_baseline, write_baseline)
from diff3d_tpu.analysis.lockcheck import (lockcheck_paths,
                                           lockcheck_source)
from diff3d_tpu.analysis.witness import (LockWitness, WitnessViolation,
                                         install_witness)

pytest_plugins = ("pytester",)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The threaded modules the LC pass is aimed at; pinned individually so
#: a regression names the file, not just "the repo".
_THREADED_MODULES = (
    "diff3d_tpu/serving/engine.py",
    "diff3d_tpu/serving/scheduler.py",
    "diff3d_tpu/serving/cache.py",
    "diff3d_tpu/serving/metrics.py",
    "diff3d_tpu/serving/fleet.py",
    "diff3d_tpu/serving/router.py",
    "diff3d_tpu/serving/server.py",
    "diff3d_tpu/serving/transport.py",
    "diff3d_tpu/serving/worker.py",
    "diff3d_tpu/train/checkpoint.py",
    "diff3d_tpu/train/trainer.py",
    "diff3d_tpu/data/loader.py",
    "diff3d_tpu/native/__init__.py",
)


def _findings(src, rule=None):
    out = lockcheck_source("<fixture>.py", textwrap.dedent(src))
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _live(src, rule=None):
    return [f for f in _findings(src, rule) if not f.suppressed]


# ---------------------------------------------------------------------------
# LC001 / LC002: parse failures and reasonless suppressions
# ---------------------------------------------------------------------------


def test_lc001_syntax_error_is_a_finding():
    (f,) = _live("def f(:\n", "LC001")
    assert f.severity == "error" and "parse" in f.message


def test_lc002_suppression_without_reason():
    src = """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    time.sleep(1.0)  # lockcheck: disable=LC303
    """
    assert not _live(src, "LC303")          # the suppression still works
    (f,) = _live(src, "LC002")
    assert "no (reason)" in f.message


# ---------------------------------------------------------------------------
# LC301: lock-order cycles
# ---------------------------------------------------------------------------


def test_lc301_fires_on_inverted_order():
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass

            def g(self):
                with self._b:
                    with self._a:
                        pass
    """
    (f,) = _live(src, "LC301")
    assert "lock-order cycle" in f.message and "self._a" in f.message


def test_lc301_sees_order_through_self_calls():
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    self._grab_b()

            def _grab_b(self):
                with self._b:
                    pass

            def g(self):
                with self._b:
                    with self._a:
                        pass
    """
    assert _live(src, "LC301")


def test_lc301_silent_on_consistent_order():
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass

            def g(self):
                with self._a:
                    with self._b:
                        pass
    """
    assert not _live(src, "LC301")


# ---------------------------------------------------------------------------
# LC302: guarded-by discipline
# ---------------------------------------------------------------------------


def test_lc302_fires_on_unguarded_access():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0  # guarded-by: self._lock

            def bump(self):
                self._count += 1
    """
    (f,) = _live(src, "LC302")
    assert "self._count" in f.message and "written" in f.message


def test_lc302_silent_under_lock_and_in_init():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0  # guarded-by: self._lock

            def bump(self):
                with self._lock:
                    self._count += 1

            def snapshot(self):
                with self._lock:
                    return self._count
    """
    assert not _live(src, "LC302")


def test_lc302_def_line_precondition_counts_as_held():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):  # guarded-by: self._lock
                self._n += 1
    """
    assert not _live(src, "LC302")


def test_lc302_warns_on_unknown_guard():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._mutex
    """
    (f,) = _live(src, "LC302")
    assert f.severity == "warning" and "self._mutex" in f.message


def test_lc302_module_global_guard():
    src = """
        import threading

        _lock = threading.Lock()
        _cache = None  # guarded-by: _lock

        def get():
            return _cache

        def get_locked():
            with _lock:
                return _cache
    """
    (f,) = _live(src, "LC302")
    assert "_cache" in f.message and "read" in f.message


# ---------------------------------------------------------------------------
# LC303: blocking under a lock
# ---------------------------------------------------------------------------


def test_lc303_fires_on_sleep_and_event_wait_under_lock():
    src = """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()

            def f(self):
                with self._lock:
                    time.sleep(0.5)

            def g(self):
                with self._lock:
                    self._done.wait()
    """
    live = _live(src, "LC303")
    assert len(live) == 2
    assert any("time.sleep" in f.message for f in live)
    assert any("Event.wait" in f.message for f in live)


def test_lc303_silent_outside_lock_and_on_bounded_queue():
    src = """
        import queue
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def f(self):
                time.sleep(0.5)
                with self._lock:
                    item = self._q.get(timeout=1.0)
                    self._q.put(item, block=False)
                return item
    """
    assert not _live(src, "LC303")


def test_lc303_fires_on_condition_wait_holding_other_lock():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv_lock = threading.Lock()
                self._cv = threading.Condition(self._cv_lock)
                self._ready = False

            def f(self):
                with self._lock:
                    with self._cv:
                        while not self._ready:
                            self._cv.wait()
    """
    live = _live(src, "LC303")
    assert live and "Condition.wait" in live[0].message


# ---------------------------------------------------------------------------
# LC304: Condition.wait without a predicate loop
# ---------------------------------------------------------------------------


def test_lc304_fires_on_bare_wait():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def f(self):
                with self._cv:
                    self._cv.wait()
    """
    (f,) = _live(src, "LC304")
    assert "while-predicate" in f.message


def test_lc304_silent_in_while_loop():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._ready = False

            def f(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait()
    """
    assert not _live(src, "LC304")


# ---------------------------------------------------------------------------
# LC305: thread leaks
# ---------------------------------------------------------------------------


def test_lc305_fires_on_unjoined_nondaemon_thread():
    src = """
        import threading

        def work():
            pass

        def start():
            t = threading.Thread(target=work)
            t.start()
            return t
    """
    (f,) = _live(src, "LC305")
    assert f.severity == "warning" and "daemon" in f.message


def test_lc305_silent_on_daemon_or_joined():
    src = """
        import threading

        class C:
            def work(self):
                pass

            def start(self):
                self._t = threading.Thread(target=self.work)
                self._t.start()
                threading.Thread(target=self.work, daemon=True).start()

            def stop(self):
                self._t.join()
    """
    assert not _live(src, "LC305")


# ---------------------------------------------------------------------------
# LC306: callbacks invoked under the lock
# ---------------------------------------------------------------------------


def test_lc306_fires_on_callback_attr_under_lock():
    src = """
        import threading
        from typing import Callable, Optional

        class C:
            def __init__(self, on_done: Callable[[], None]):
                self._lock = threading.Lock()
                self._on_done = on_done

            def finish(self):
                with self._lock:
                    self._on_done()
    """
    (f,) = _live(src, "LC306")
    assert "self._on_done" in f.message and "after release" in f.message


def test_lc306_fires_on_callback_param_under_lock():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def each(self, callback):
                with self._lock:
                    callback()
    """
    assert _live(src, "LC306")


def test_lc306_silent_on_capture_then_invoke():
    src = """
        import threading
        from typing import Callable

        class C:
            def __init__(self, on_done: Callable[[], None]):
                self._lock = threading.Lock()
                self._on_done = on_done

            def finish(self):
                with self._lock:
                    cb = self._on_done
                cb()
    """
    assert not _live(src, "LC306")


# ---------------------------------------------------------------------------
# LC307: double acquire of a non-reentrant Lock
# ---------------------------------------------------------------------------


def test_lc307_fires_on_nested_acquire():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """
    (f,) = _live(src, "LC307")
    assert "not reentrant" in f.message


def test_lc307_fires_through_self_call():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """
    assert any("may re-acquire" in f.message
               for f in _live(src, "LC307"))


def test_lc307_silent_on_rlock():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()
                    with self._lock:
                        pass

            def inner(self):
                with self._lock:
                    pass
    """
    assert not _live(src, "LC307")


# ---------------------------------------------------------------------------
# LC308: unguarded global mutation from a thread target
# ---------------------------------------------------------------------------


def test_lc308_fires_on_bare_global_write_from_thread_target():
    src = """
        import threading

        _stats = {}

        def worker():
            _stats["n"] = 1

        def start():
            threading.Thread(target=worker, daemon=True).start()
    """
    (f,) = _live(src, "LC308")
    assert "_stats" in f.message


def test_lc308_silent_when_locked_or_not_a_thread_target():
    src = """
        import threading

        _lock = threading.Lock()
        _stats = {}
        _other = {}

        def worker():
            with _lock:
                _stats["n"] = 1

        def not_a_target():
            _other["n"] = 1

        def start():
            threading.Thread(target=worker, daemon=True).start()
    """
    assert not _live(src, "LC308")


# ---------------------------------------------------------------------------
# Suppression namespace + baseline round-trip
# ---------------------------------------------------------------------------

_SLEEPY = """
    import threading
    import time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                time.sleep(1.0){comment}
"""


def test_suppression_with_reason_is_clean():
    src = _SLEEPY.format(
        comment="  # lockcheck: disable=LC303(bench-only; lock uncontended)")
    assert not _live(src)
    supp = [f for f in _findings(src, "LC303") if f.suppressed]
    assert len(supp) == 1


def test_graftlint_suppression_does_not_reach_lockcheck():
    src = _SLEEPY.format(comment="  # graftlint: disable=LC303(wrong tool)")
    assert _live(src, "LC303")


def test_baseline_round_trip(tmp_path):
    src = _SLEEPY.format(comment="")
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(src))
    findings = lockcheck_paths([str(path)])
    assert [f for f in findings if not f.suppressed]

    baseline = tmp_path / "baseline.json"
    n = write_baseline(str(baseline), findings, str(tmp_path),
                       tool="lockcheck")
    assert n == 1
    rebased = apply_baseline(lockcheck_paths([str(path)]),
                             load_baseline(str(baseline)), str(tmp_path))
    assert not [f for f in rebased if not f.suppressed]


# ---------------------------------------------------------------------------
# The runtime witness
# ---------------------------------------------------------------------------


def test_witness_catches_seeded_lock_inversion():
    witness, uninstall = install_witness()
    try:
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        # Strictly sequenced — the witness flags the *order*, so no
        # interleaving (and no real deadlock) is needed.
        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
    finally:
        uninstall()
    cycles = witness.cycles()
    assert len(cycles) == 1 and len(set(cycles[0])) == 2
    with pytest.raises(WitnessViolation, match="lock-order cycle"):
        witness.check()


def test_witness_catches_held_lock_event_wait():
    witness, uninstall = install_witness()
    try:
        lock = threading.Lock()
        ev = threading.Event()
        ev.set()
        with lock:
            assert ev.wait(0.1)
    finally:
        uninstall()
    assert witness.wait_violations
    assert "Event.wait" in witness.wait_violations[0]
    with pytest.raises(WitnessViolation, match="held-lock wait"):
        witness.check()


def test_witness_clean_on_consistent_order_and_reset():
    witness, uninstall = install_witness()
    try:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        cv = threading.Condition()
        done = []

        def setter():
            with cv:
                done.append(1)
                cv.notify_all()

        t = threading.Thread(target=setter)
        t.start()
        with cv:
            while not done:
                cv.wait(1.0)
        t.join()
    finally:
        uninstall()
    assert witness.acquisitions >= 4
    witness.check()                     # no cycles, no bad waits
    witness.reset()
    assert witness.acquisitions == 0 and not witness.cycles()


def test_witness_rlock_reacquire_is_not_a_cycle():
    witness, uninstall = install_witness()
    try:
        r = threading.RLock()
        with r:
            with r:
                pass
    finally:
        uninstall()
    witness.check()


def test_install_witness_restores_factories():
    orig = (threading.Lock, threading.RLock, threading.Condition,
            threading.Event)
    witness, uninstall = install_witness()
    assert threading.Lock is not orig[0]
    uninstall()
    uninstall()                         # idempotent
    assert (threading.Lock, threading.RLock, threading.Condition,
            threading.Event) == orig
    assert isinstance(witness, LockWitness)


# ---------------------------------------------------------------------------
# The lock_witness pytest marker, end to end
# ---------------------------------------------------------------------------

_INNER_PREAMBLE = "import threading\nimport pytest\n"


def _run_inner(pytester, body):
    pytester.makepyfile(_INNER_PREAMBLE + textwrap.dedent(body))
    return pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider")


def test_marker_passes_on_clean_locking(pytester):
    result = _run_inner(pytester, """
        @pytest.mark.lock_witness
        def test_clean(lock_witness):
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
    """)
    result.assert_outcomes(passed=1)


def test_marker_fails_on_seeded_inversion(pytester):
    result = _run_inner(pytester, """
        @pytest.mark.lock_witness
        def test_inverted(lock_witness):
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
    """)
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*lock-order cycle*"])


def test_marker_rejects_vacuous_pass(pytester):
    result = _run_inner(pytester, """
        @pytest.mark.lock_witness
        def test_nothing(lock_witness):
            pass
    """)
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*vacuous*"])


def test_marker_requires_fixture(pytester):
    result = _run_inner(pytester, """
        @pytest.mark.lock_witness
        def test_forgot_fixture():
            pass
    """)
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*requires the*lock_witness fixture*"])


# ---------------------------------------------------------------------------
# The tier-1 gates: the threaded modules and the whole repo are clean
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rel", _THREADED_MODULES)
def test_threaded_module_lockchecks_clean(rel):
    """Regression pin for the audited runtime modules: any new blocking
    call under a lock, unguarded access to annotated state, or callback
    under a scheduler/engine lock fails here with the file named."""
    path = os.path.join(_REPO_ROOT, rel)
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    live = [f for f in lockcheck_source(path, src) if not f.suppressed]
    assert not live, f"unsuppressed lockcheck findings in {rel}:\n" + \
        "\n".join(f.render() for f in live)


def test_repo_lockchecks_clean():
    """The same invariant ``python tools/lint.py`` gates in CI, pinned
    here so plain ``pytest`` enforces it too."""
    targets = [os.path.join(_REPO_ROOT, t) for t in DEFAULT_TARGETS]
    targets = [t for t in targets if os.path.exists(t)]
    assert targets, "lockcheck targets missing from the checkout"
    live = [f for f in lockcheck_paths(targets) if not f.suppressed]
    assert not live, "unsuppressed lockcheck findings:\n" + "\n".join(
        f.render() for f in live)
