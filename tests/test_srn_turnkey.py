"""Turnkey real-data rehearsal: render an SRN-format tree to disk
(tools/make_srn_fixture.py), then run the REAL ``train_cli -> eval_cli``
path on it — native C++ png decode, pickle regen, 90/10 split, threaded
loader, checkpoint, sampler-protocol eval — with no SRN zips needed.

This is the day-1 real-data path (reference format:
``/root/reference/SRNdataset.py:42-95``): when the actual cars/chairs
zips appear, the only change is the --train_data argument.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from make_srn_fixture import write_fixture  # noqa: E402


def test_fixture_roundtrip_exact_poses_and_quantized_images(tmp_path):
    """What the fixture writes, SRNDataset reads back: poses/K exact to
    txt precision, images to png-quantization tolerance (<=1/255 + the
    decoder's box-resample identity at native size)."""
    from diff3d_tpu.data import SyntheticScenesDataset
    from diff3d_tpu.data.srn import load_object_views

    out = str(tmp_path / "cars_train")
    index = write_fixture(out, objects=2, views=3, imgsize=16, seed=0)
    assert len(index) == 2 and all(len(v) == 3 for v in index.values())

    ds = SyntheticScenesDataset(num_objects=2, num_views=3, imgsize=16,
                                seed=0)
    obj0 = sorted(index.keys())[0]
    got = load_object_views(os.path.join(out, obj0), imgsize=16)
    want = ds.all_views(0)
    np.testing.assert_allclose(got["R"], want["R"], atol=1e-6)
    np.testing.assert_allclose(got["T"], want["T"], atol=1e-6)
    np.testing.assert_allclose(got["K"], want["K"], atol=1e-6)
    # [-1,1] images through uint8 png: half-step quantization error
    np.testing.assert_allclose(got["imgs"], want["imgs"], atol=1.5 / 127.5)


@pytest.mark.slow
def test_train_cli_then_eval_cli_on_srn_disk_fixture(tmp_path):
    """The full user path on SRN-format disk data (glob-regen index: no
    pickle given), asserting the trainer consumed the REAL dataset and
    the eval CLI scores its val split."""
    from diff3d_tpu.cli import eval_cli, train_cli

    data = str(tmp_path / "cars_train")
    write_fixture(data, objects=10, views=4, imgsize=16, seed=0)

    wd = str(tmp_path / "run")
    train_cli.main(["--train_data", data, "--config", "test",
                    "--steps", "2", "--batch", "8", "--workdir", wd,
                    "--num_workers", "2", "--eval_every", "2"])
    with open(os.path.join(wd, "metrics.jsonl")) as f:
        recs = [json.loads(l) for l in f]
    train_recs = [r for r in recs if "loss" in r]
    assert train_recs[-1]["step"] == 2
    assert np.isfinite(train_recs[-1]["loss"])
    # the val split of the SAME disk tree was scored in-training
    # (val_loss records are separate JSONL lines)
    assert any("val_loss" in r for r in recs)

    out = str(tmp_path / "eval.jsonl")
    eval_cli.main(["--model", os.path.join(wd, "checkpoints"),
                   "--val_data", data, "--config", "test",
                   "--objects", "1", "--max_views", "3", "--steps", "4",
                   "--out", out])
    with open(out) as f:
        rec = json.loads(f.readlines()[-1])
    assert np.isfinite(rec["psnr"]) and rec["views"] >= 1
    assert np.isfinite(rec["psnr_copy_view0_baseline"])
