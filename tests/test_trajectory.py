"""Trajectory service: camera-path math, the multi-view consistency
metric, TrajectoryRequest streaming semantics, and the serving e2e.

Four layers, cheapest first:

* **Pose math** — property-style checks over radii/elevations: every
  generated pose is exactly SO(3) with det +1, orbits close seamlessly
  (the virtual frame ``n`` coincides with frame 0), look-at centers the
  target on the principal point, and the convention matches
  ``data/synthetic.py::_look_at`` bit-for-bit.
* **Consistency metric** — ray-traced sphere scenes (exact multi-view
  geometry by construction) rendered along a 16-pose orbit: the
  plane-homography reprojection score must rank the ordered sequence
  strictly better than shuffled frames and per-frame identity drift.
* **TrajectoryRequest units** — the commit buffer: in-order commits,
  out-of-order drops, blocking ``wait_frames``, backfill on resolve,
  error delivery only after committed frames are drained.
* **Serving e2e** on the CPU backend — frames streamed in commit order
  and bit-identical to ``Sampler.synthesize``; incremental HTTP poll
  (``?from=K``) and chunked NDJSON streaming; typed backpressure; and
  the acceptance run: a 3-replica fleet serves an 8-pose orbit whose
  frames are bit-identical to the sequential prefix oracle, with zero
  record migration across the per-replica ledgers.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from diff3d_tpu.config import MeshConfig, ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.data.synthetic import _look_at, _rays_np, render_spheres
from diff3d_tpu.evaluation import (plane_homography,
                                   reprojection_consistency, warp_frame)
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.serving import (FleetService, QueueFullError,
                                ServingService, TrajectoryRequest,
                                ViewRequest)
from diff3d_tpu.serving.scheduler import Scheduler
from diff3d_tpu.train.trainer import init_params
from diff3d_tpu.trajectory import (PATH_KINDS, keyframe_path, look_at,
                                   orbit_path, path_from_spec, spiral_path,
                                   trajectory_views)

RADII = (0.5, 2.0, 7.5)
ELEVATIONS = (-45.0, 0.0, 20.0, 70.0)


def _assert_so3(R, atol=1e-5):
    R = np.asarray(R, np.float64)
    eye = np.broadcast_to(np.eye(3), R.shape)
    np.testing.assert_allclose(R @ np.swapaxes(R, -1, -2), eye, atol=atol)
    np.testing.assert_allclose(np.linalg.det(R), 1.0, atol=atol)


def _project(K, R, T, point):
    """Pixel coordinates of a world point (OpenCV convention)."""
    x_cam = np.asarray(R, np.float64).T @ (np.asarray(point, np.float64)
                                           - np.asarray(T, np.float64))
    px = np.asarray(K, np.float64) @ x_cam
    return px[:2] / px[2], x_cam[2]


def _K(size):
    return np.array([[size * 1.2, 0, size / 2],
                     [0, size * 1.2, size / 2],
                     [0, 0, 1]], np.float32)


# ---------------------------------------------------------------------------
# Pose math
# ---------------------------------------------------------------------------


def test_orbit_poses_are_so3_over_parameter_grid():
    for radius in RADII:
        for elev in ELEVATIONS:
            R, T = orbit_path(7, radius=radius, elevation_deg=elev,
                              azimuth0_deg=33.0)
            assert R.shape == (7, 3, 3) and T.shape == (7, 3)
            assert R.dtype == np.float32 and T.dtype == np.float32
            _assert_so3(R)
            np.testing.assert_allclose(np.linalg.norm(T, axis=-1),
                                       radius, rtol=1e-5)


def test_orbit_closes_seamlessly_without_duplicated_endpoint():
    """A one-turn orbit's virtual frame ``n`` is frame 0 (loops as
    video), and frame ``n-1`` is NOT frame 0 (no duplicated endpoint)."""
    for n in (4, 9, 16):
        for radius, elev in ((0.5, -45.0), (2.0, 20.0), (7.5, 70.0)):
            R, T = orbit_path(n, radius=radius, elevation_deg=elev)
            Rn, Tn = orbit_path(1, radius=radius, elevation_deg=elev,
                                azimuth0_deg=360.0)
            np.testing.assert_allclose(Rn[0], R[0], atol=1e-6)
            np.testing.assert_allclose(Tn[0], T[0], atol=1e-5)
            assert not np.allclose(T[n - 1], T[0], atol=1e-3)


def test_paths_center_the_target_on_the_principal_point():
    K = _K(16)
    target = (0.3, -0.2, 0.1)
    paths = [
        orbit_path(5, radius=2.0, elevation_deg=15.0, target=target),
        spiral_path(5, radius=3.0, target=target),
        keyframe_path([[2.0, 0, 0.5], [0, 2.0, 0.5], [-2.0, 0, 1.0]], 5,
                      targets=[target] * 3),
    ]
    for R, T in paths:
        _assert_so3(R)
        for i in range(R.shape[0]):
            uv, depth = _project(K, R[i], T[i], target)
            assert depth > 0, "target must be in front (+z forward)"
            np.testing.assert_allclose(uv, [K[0, 2], K[1, 2]], atol=1e-3)


def test_look_at_matches_synthetic_dataset_convention():
    """The serving path generators and the training data pipeline must
    agree on what a camera pose means."""
    r = np.random.RandomState(0)
    for _ in range(20):
        eye = r.uniform(-3, 3, 3)
        if np.linalg.norm(eye) < 0.5:
            continue
        np.testing.assert_allclose(look_at(eye), _look_at(eye), atol=1e-6)


def test_look_at_degenerate_inputs():
    with pytest.raises(ValueError):
        look_at((1.0, 2.0, 3.0), target=(1.0, 2.0, 3.0))
    # Straight-down view: the fallback up-vector keeps the frame
    # non-degenerate (same escape hatch as data/synthetic.py).
    R = look_at((0.0, 0.0, 2.0))
    assert np.all(np.isfinite(R))
    _assert_so3(R[None])


def test_spiral_sweeps_and_clamps_elevation():
    R, T = spiral_path(9, radius=2.0, elevation_start_deg=-10.0,
                       elevation_end_deg=45.0)
    el = np.rad2deg(np.arcsin(T[:, 2] / np.linalg.norm(T, axis=-1)))
    assert np.all(np.diff(el) > 0)                   # monotone rise
    np.testing.assert_allclose(el[0], -10.0, atol=1e-3)
    np.testing.assert_allclose(el[-1], 45.0, atol=1e-3)
    _, T2 = spiral_path(3, elevation_start_deg=-89.0,
                        elevation_end_deg=89.0)
    el2 = np.rad2deg(np.arcsin(T2[:, 2] / np.linalg.norm(T2, axis=-1)))
    assert np.all(np.abs(el2) <= 80.0 + 1e-3)        # pole clamp


def test_keyframe_path_interpolates_and_validates():
    keys = np.array([[2.0, 0, 0], [0, 2.0, 0], [0, 0, 2.0]])
    R, T = keyframe_path(keys, 5)
    _assert_so3(R)
    np.testing.assert_allclose(T[0], keys[0], atol=1e-6)
    np.testing.assert_allclose(T[2], keys[1], atol=1e-6)  # mid keyframe
    np.testing.assert_allclose(T[-1], keys[2], atol=1e-6)
    with pytest.raises(ValueError):
        keyframe_path(keys[:1], 5)                   # k < 2
    with pytest.raises(ValueError):
        keyframe_path(keys, 5, targets=keys)         # eye == target


def test_path_from_spec_grammar():
    R, T = path_from_spec({"kind": "orbit", "frames": 6, "radius": 3.0,
                           "elevation_deg": 10.0})
    Rd, Td = orbit_path(6, radius=3.0, elevation_deg=10.0)
    np.testing.assert_array_equal(R, Rd)
    np.testing.assert_array_equal(T, Td)
    path_from_spec({"kind": "keyframes", "frames": 4,
                    "keyframes": [[2, 0, 0], [0, 2, 0]]})
    assert set(PATH_KINDS) == {"orbit", "spiral", "keyframes"}
    with pytest.raises(ValueError, match="kind"):
        path_from_spec({"kind": "helix", "frames": 4})
    with pytest.raises(ValueError, match="frames"):
        path_from_spec({"kind": "orbit"})
    with pytest.raises(ValueError, match="unknown"):
        path_from_spec({"kind": "orbit", "frames": 4, "elevation": 10})
    with pytest.raises(ValueError):
        path_from_spec(["orbit", 4])


def test_trajectory_views_assembly():
    img = np.zeros((8, 8, 3), np.float32)
    R, T = orbit_path(3, radius=2.0)
    cond_R, cond_T = look_at((2.0, 0.1, 0.8)), np.array([2.0, 0.1, 0.8],
                                                        np.float32)
    v = trajectory_views(img, cond_R, cond_T, _K(8), R, T)
    assert v["imgs"].shape == (1, 8, 8, 3)
    assert v["R"].shape == (4, 3, 3) and v["T"].shape == (4, 3)
    np.testing.assert_array_equal(v["R"][0], cond_R)
    np.testing.assert_array_equal(v["R"][1:], R)
    with pytest.raises(ValueError):
        trajectory_views(np.zeros((8, 8)), cond_R, cond_T, _K(8), R, T)


# ---------------------------------------------------------------------------
# Multi-view consistency metric (exact geometry via ray-traced spheres)
# ---------------------------------------------------------------------------


def _sphere_orbit_frames(n, size=32, radius=2.6, elevation=20.0,
                         scene_seed=0):
    """Frames of a fixed sphere scene along an orbit: geometrically
    consistent by construction (one 3D scene, exact ray tracing)."""
    r = np.random.RandomState(scene_seed)
    centers = r.uniform(-0.35, 0.35, (3, 3))
    radii = r.uniform(0.25, 0.5, 3)
    colors = r.uniform(-0.6, 0.9, (3, 3))
    K = _K(size).astype(np.float64)
    R, T = orbit_path(n, radius=radius, elevation_deg=elevation)
    frames = [render_spheres(*_rays_np(R[i].astype(np.float64),
                                       T[i].astype(np.float64),
                                       K, size, size),
                             centers, radii, colors) for i in range(n)]
    return np.stack(frames).astype(np.float32), R, T, K.astype(np.float32)


def test_consistency_identical_views_score_near_zero():
    frames, R, T, K = _sphere_orbit_frames(2)
    score = reprojection_consistency(frames[[0, 0]], R[[0, 0]], T[[0, 0]],
                                     K)
    assert score["num_pairs"] == 1
    # Round-off at the exact image border may invalidate one row/col.
    assert score["valid_frac"] > 0.9
    assert score["consistency_l1"] < 1e-6
    assert score["consistency_psnr"] > 60.0


def test_consistency_ranks_ordered_above_shuffled_and_drift():
    """The regression-gate property: frames that do not share one 3D
    scene must score strictly worse.  16-pose orbits keep the adjacent
    baseline small enough for the plane approximation to discriminate."""
    n = 16
    frames, R, T, K = _sphere_orbit_frames(n)
    good = reprojection_consistency(frames, R, T, K)
    assert good["num_pairs"] == n - 1
    assert good["valid_frac"] > 0.5

    perm = np.random.RandomState(1).permutation(n)
    bad = reprojection_consistency(frames[perm], R, T, K)
    # Per-frame identity drift: frames alternate between two different
    # scenes under the same poses.
    other, _, _, _ = _sphere_orbit_frames(n, scene_seed=9)
    drifted = np.where((np.arange(n) % 2 == 0)[:, None, None, None],
                       frames, other)
    drift = reprojection_consistency(drifted, R, T, K)

    for worse in (bad, drift):
        assert good["consistency_l1"] < 0.8 * worse["consistency_l1"], (
            good["consistency_l1"], worse["consistency_l1"])
        assert good["consistency_psnr"] > worse["consistency_psnr"]


def test_consistency_guidance_axis_and_custom_pairs():
    frames, R, T, K = _sphere_orbit_frames(4)
    with_b = np.repeat(frames[:, None], 2, axis=1)   # [N, B, H, W, 3]
    a = reprojection_consistency(frames, R, T, K)
    b = reprojection_consistency(with_b, R, T, K)
    assert a["consistency_l1"] == b["consistency_l1"]  # lane 0 scored
    c = reprojection_consistency(frames, R, T, K, pairs=[(0, 2), (1, 3)])
    assert [(p["i"], p["j"]) for p in c["pairs"]] == [(0, 2), (1, 3)]


def test_consistency_validation_and_behind_camera():
    frames, R, T, K = _sphere_orbit_frames(3)
    with pytest.raises(ValueError, match="2 frames"):
        reprojection_consistency(frames[:1], R[:1], T[:1], K)
    with pytest.raises(ValueError, match="poses"):
        reprojection_consistency(frames, R[:2], T[:2], K)
    # Camera looking away from the target: the plane is behind it.
    eye = np.array([2.0, 0.0, 0.0])
    R_away = look_at(eye, target=2 * eye)
    with pytest.raises(ValueError, match="behind"):
        plane_homography(K, R_away, eye, R[1], T[1])


def test_warp_identity_homography_is_a_noop():
    frames, _, _, _ = _sphere_orbit_frames(1)
    warped, valid = warp_frame(frames[0], np.eye(3))
    assert valid.all()
    np.testing.assert_allclose(warped, frames[0], atol=1e-6)


# ---------------------------------------------------------------------------
# TrajectoryRequest commit-buffer semantics (no device work)
# ---------------------------------------------------------------------------


def _traj_req(n_frames=3, size=4, **kw):
    R, T = orbit_path(n_frames, radius=2.0)
    v = trajectory_views(np.zeros((size, size, 3), np.float32),
                         look_at((2.0, 0.0, 0.7)),
                         np.array([2.0, 0.0, 0.7], np.float32),
                         _K(size), R, T)
    return TrajectoryRequest(v, **kw)


def test_trajectory_request_commit_order_and_backfill():
    req = _traj_req(3)
    assert req.is_trajectory and req.n_frames == 3 and req.n_views == 4
    plain = ViewRequest({"imgs": np.zeros((2, 4, 4, 3), np.float32),
                         "R": np.stack([np.eye(3, dtype=np.float32)] * 2),
                         "T": np.zeros((2, 3), np.float32),
                         "K": _K(4)})
    assert not plain.is_trajectory
    plain._commit_frame(1, np.zeros(1))              # no-op, no error

    f0, f1 = np.full((1, 4, 4, 3), 0.1), np.full((1, 4, 4, 3), 0.2)
    req._commit_frame(1, f0)
    req._commit_frame(3, np.full((1, 4, 4, 3), 9.0))  # out of order: drop
    req._commit_frame(1, np.full((1, 4, 4, 3), 9.0))  # duplicate: drop
    assert req.frames_done() == 1
    np.testing.assert_array_equal(req.wait_frames(0, timeout=0)[0], f0)
    req._commit_frame(2, f1)
    got = req.frames_since(0)
    assert len(got) == 2
    np.testing.assert_array_equal(got[1], f1)

    # Resolve with the full result: frame 3 is backfilled, the already
    # streamed frames keep their identity.
    result = np.stack([f0[0], f1[0], np.full((4, 4, 3), 0.3)])
    req._resolve(result)
    assert req.frames_done() == 3
    np.testing.assert_array_equal(req.frames_since(2)[0], result[2])
    assert req.wait_frames(3, timeout=0) == []       # past the end, done


def test_trajectory_request_wait_blocks_until_commit():
    req = _traj_req(2)
    got = {}

    def consumer():
        got["frames"] = req.wait_frames(0, timeout=30)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    req._commit_frame(1, np.full((1, 4, 4, 3), 0.5))
    t.join(30)
    assert not t.is_alive() and len(got["frames"]) == 1
    assert req.wait_frames(1, timeout=0.01) == []    # timeout, not done


def test_trajectory_request_error_after_draining_committed_frames():
    req = _traj_req(3)
    f0 = np.full((1, 4, 4, 3), 0.1)
    req._commit_frame(1, f0)
    req._reject(RuntimeError("replica died"))
    # Frames that committed are still deliverable...
    np.testing.assert_array_equal(req.wait_frames(0, timeout=0)[0], f0)
    # ...and the error surfaces once the stream is drained.
    with pytest.raises(RuntimeError, match="replica died"):
        req.wait_frames(1, timeout=0)


def test_trajectory_backpressure_and_validation():
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        port=0, max_queue=1, max_views=4))
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    stalled = ServingService(sampler, cfg)           # engine NOT started
    try:
        ds = SyntheticDataset(num_objects=1, num_views=2, imgsize=8)
        v = ds.all_views(0)
        payload = {"cond": {"img": v["imgs"][0], "R": v["R"][0],
                            "T": v["T"][0], "K": v["K"]},
                   "path": {"kind": "orbit", "frames": 3}}
        stalled.submit_trajectory(payload)
        with pytest.raises(QueueFullError):          # typed backpressure
            stalled.submit_trajectory(dict(payload, seed=2))
        with pytest.raises(ValueError, match="ceiling"):
            stalled.submit_trajectory(
                {**payload, "path": {"kind": "orbit", "frames": 9}})
        with pytest.raises(ValueError, match="kind"):
            stalled.submit_trajectory(
                {**payload, "path": {"kind": "helix", "frames": 3}})
        with pytest.raises(ValueError, match="cond"):
            stalled.submit_trajectory({"path": {"kind": "orbit",
                                                "frames": 3}})
    finally:
        stalled.scheduler.close()


# ---------------------------------------------------------------------------
# Serving e2e on the CPU backend
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traj_env():
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    ds = SyntheticDataset(num_objects=2, num_views=3, imgsize=8)
    return cfg, model, params, sampler, ds


def _orbit_views(ds, obj, n_frames):
    """Trajectory views for an orbit around ``ds``'s object, conditioned
    on its view 0 (radius/elevation match the dataset's camera shell)."""
    v = ds.all_views(obj)
    T0 = np.asarray(v["T"][0], np.float64)
    radius = float(np.linalg.norm(T0))
    elevation = float(np.rad2deg(np.arcsin(T0[2] / radius)))
    path_R, path_T = orbit_path(n_frames, radius=radius,
                                elevation_deg=elevation,
                                azimuth0_deg=17.0)
    return trajectory_views(v["imgs"][0], v["R"][0], v["T"][0], v["K"],
                            path_R, path_T)


def _tile_imgs(tviews):
    """synthesize sizes the record from imgs.shape[0]: tile the
    conditioning image across all views (only imgs[0] is consumed)."""
    n = tviews["R"].shape[0]
    out = dict(tviews)
    out["imgs"] = np.broadcast_to(tviews["imgs"][:1],
                                  (n,) + tviews["imgs"].shape[1:])
    return out


def _serving(cfg, **over):
    serving = dict(port=0, max_batch=4, max_queue=8, max_wait_ms=50.0,
                   max_views=10, default_timeout_s=120.0,
                   result_cache_entries=0)
    serving.update(over)
    return dataclasses.replace(cfg, serving=ServingConfig(**serving))


@pytest.mark.lock_witness
def test_trajectory_streams_bit_identical_frames(traj_env, lock_witness):
    """Unsharded e2e: frames stream through ``wait_frames`` in commit
    order, and the assembled trajectory is bit-identical to the offline
    sampler with the same seed."""
    cfg, model, params, sampler, ds = traj_env
    service = ServingService(sampler, _serving(cfg)).start(
        serve_http=False)
    try:
        tviews = _orbit_views(ds, 0, 3)
        req = service.submit_trajectory({"views": tviews, "seed": 21,
                                         "session_id": "stream-0"})
        assert req.is_trajectory and req.n_frames == 3
        streamed, sent = [], 0
        while True:
            chunk = req.wait_frames(sent, timeout=120)
            if not chunk:
                break
            streamed.extend(chunk)
            sent += len(chunk)
        result = req.result(timeout=0)
        assert req.done() and sent == 3

        direct = sampler.synthesize(_tile_imgs(tviews),
                                    jax.random.PRNGKey(21))
        np.testing.assert_array_equal(result, direct)
        for k, frame in enumerate(streamed):         # commit order
            np.testing.assert_array_equal(frame, direct[k])

        snap = service.metrics_snapshot()
        assert snap["counters"]["serving_trajectory_requests_total"] == 1
        assert snap["counters"]["serving_trajectory_frames_total"] == 3
        assert snap["gauges"]["serving_active_trajectories"] == 0
        assert snap["engine"]["trajectories"] == []  # nothing in flight
    finally:
        service.stop()


def test_trajectory_sharded_engine_matches_sharded_sampler(traj_env):
    """Sharded e2e (data=2 mesh): the engine pads the trajectory to the
    lane multiple and the result still matches the sampler bitwise."""
    cfg, model, params, sampler, ds = traj_env
    env = make_mesh(MeshConfig(data_parallel=2, model_parallel=1),
                    devices=jax.devices()[:2])
    sh_sampler = Sampler(model, params, cfg, mesh=env)
    service = ServingService(sh_sampler, _serving(cfg)).start(
        serve_http=False)
    try:
        assert service.engine.lane_multiple == 2
        tviews = _orbit_views(ds, 1, 3)
        req = service.submit_trajectory({"views": tviews, "seed": 5})
        out = req.result(timeout=180)
        direct = sh_sampler.synthesize(_tile_imgs(tviews),
                                       jax.random.PRNGKey(5))
        np.testing.assert_array_equal(out, direct)
        assert req.frames_done() == 3
    finally:
        service.stop()


@pytest.mark.lock_witness
def test_trajectory_http_poll_and_ndjson_stream(traj_env, lock_witness):
    """The two HTTP streaming surfaces: incremental poll
    (``GET /result/<id>?from=K`` — gapless, repeat-free via ``next``)
    and chunked NDJSON (``POST /trajectory`` with ``stream: true``)."""
    cfg, model, params, sampler, ds = traj_env
    service = ServingService(sampler, _serving(cfg)).start(serve_http=True)
    try:
        base = f"http://127.0.0.1:{service.port}"
        tviews = _orbit_views(ds, 0, 3)
        wire_views = {k: np.asarray(v).tolist() for k, v in tviews.items()}

        def post(path, payload, timeout=180):
            req = urllib.request.Request(
                f"{base}{path}", data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=timeout)

        # Async submit + incremental poll.
        with post("/trajectory", {"views": wire_views, "seed": 31,
                                  "block": False}) as r:
            assert r.status == 202
            body = json.loads(r.read())
            assert body["n_frames"] == 3
            rid = body["id"]
        polled, nxt = [], 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"{base}/result/{rid}?from={nxt}", timeout=30) as r:
                assert r.status == 200
                poll = json.loads(r.read())
            assert poll["from"] == nxt
            assert poll["next"] == nxt + len(poll["frames"])
            polled.extend(poll["frames"])
            nxt = poll["next"]
            if poll["status"] == "done":
                break
            assert poll["status"] == "running"
            time.sleep(0.05)
        assert nxt == 3 and poll["frames_committed"] == 3
        direct = sampler.synthesize(_tile_imgs(tviews),
                                    jax.random.PRNGKey(31))
        np.testing.assert_array_equal(
            np.asarray(polled, np.float32), direct)
        # Terminal body carries trajectory progress too.
        with urllib.request.urlopen(f"{base}/result/{rid}",
                                    timeout=30) as r:
            final = json.loads(r.read())
        assert final["n_frames"] == final["frames_committed"] == 3
        np.testing.assert_array_equal(
            np.asarray(final["views"], np.float32), direct)

        # Chunked NDJSON stream: header, then one line per frame in
        # order, then the terminal done line.  Same seed as the polled
        # request, so `direct` is the expected payload again.
        with post("/trajectory", {"views": wire_views, "seed": 31,
                                  "stream": True}) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/x-ndjson"
            lines = [json.loads(l) for l in r.read().splitlines() if l]
        assert lines[0]["status"] == "streaming"
        assert lines[0]["n_frames"] == 3
        assert [l["frame"] for l in lines[1:-1]] == [0, 1, 2]
        assert lines[-1]["status"] == "done"
        assert lines[-1]["frames_committed"] == 3
        np.testing.assert_array_equal(
            np.asarray([l["view"] for l in lines[1:-1]], np.float32),
            direct)
    finally:
        service.stop()


@pytest.mark.slow
@pytest.mark.lock_witness
def test_trajectory_cobatches_with_view_requests(traj_env, lock_witness):
    """Interleaving: a trajectory and a plain view request in the same
    bucket share compiled scan launches (occupancy > 1) and both stay
    bit-identical to their offline counterparts."""
    cfg, model, params, sampler, ds = traj_env
    service = ServingService(
        sampler, _serving(cfg, max_wait_ms=300.0)).start(serve_http=False)
    try:
        tviews = _orbit_views(ds, 0, 3)              # 4 views, capacity 4
        plain_views = ds.all_views(1)
        traj = service.submit_trajectory({"views": tviews, "seed": 41})
        plain = service.submit({"views": plain_views, "seed": 42,
                                "n_views": 4})
        t_out = traj.result(timeout=180)
        p_out = plain.result(timeout=180)
        np.testing.assert_array_equal(
            t_out, sampler.synthesize(_tile_imgs(tviews),
                                      jax.random.PRNGKey(41)))
        np.testing.assert_array_equal(
            p_out, sampler.synthesize(plain_views, jax.random.PRNGKey(42),
                                      max_views=4))
        occ = service.metrics_snapshot()["histograms"][
            "serving_batch_occupancy"]
        assert occ["max"] > 1, f"never co-batched: {occ}"
    finally:
        service.stop()


@pytest.mark.lock_witness
def test_fleet_8pose_orbit_oracle_parity_zero_migration(traj_env,
                                                        lock_witness):
    """Acceptance e2e: a 3-replica fleet serves an 8-pose orbit through
    the router.  Frames stream in commit order (incrementally — the
    consumer observes partial progress), the trajectory is bit-identical
    to the sequential prefix oracle (request k renders the first k path
    poses with the same seed; its last view equals trajectory frame
    k-1), everything lands on one owning replica (zero record
    migration), and per-trajectory progress rides the fleet snapshot."""
    cfg, model, params, sampler, ds = traj_env
    svc = FleetService.build(sampler, _serving(cfg, replicas=3),
                             n=3).start(serve_http=False)
    sid, seed, n_frames = "orbit-e2e", 77, 8
    try:
        tviews = _orbit_views(ds, 0, n_frames)       # 9 views

        # Sequential single-view oracle, sticky to the same session:
        # request k conditions on view 0 and renders path poses 1..k.
        # One oracle per record-capacity bucket (2, 4, 8, 16) keeps the
        # tier-1 budget: the prefix property is transitive, so matching
        # frames 0, 1, 3 and 7 pins the whole shared RNG stream.
        oracle_last = {}
        for k in (1, 2, 4, 8):
            req = svc.router.submit(ViewRequest(
                _tile_imgs(tviews), seed=seed, n_views=k + 1,
                session_id=sid))
            oracle_last[k] = req.result(timeout=300)[-1]

        traj = svc.submit_trajectory({"views": tviews, "seed": seed,
                                      "session_id": sid})
        batches, progress_seen, sent = [], set(), 0
        while True:
            chunk = traj.wait_frames(sent, timeout=300)
            if not chunk:
                break
            batches.append(len(chunk))
            sent += len(chunk)
            for rep in svc.replicas:
                for t in rep.snapshot()["trajectories"]:
                    progress_seen.add((t["session_id"], t["frames_done"]))
        result = traj.result(timeout=0)
        assert sent == n_frames

        # Streamed incrementally, not one terminal burst.
        assert len(batches) >= 2, batches
        # /fleet exposed mid-flight progress for this trajectory.
        assert any(s == sid and 0 < done < n_frames
                   for s, done in progress_seen), progress_seen

        # Bit-parity: frame k-1 == the prefix oracle's last view (the
        # autoregressive record + per-view key-split stream are shared).
        for k, last in oracle_last.items():
            np.testing.assert_array_equal(result[k - 1], last)

        # Zero migration: one ledger holds the session, with every
        # request (4 oracles + 1 trajectory) on it.
        ledgers = {r.name: r.session_records() for r in svc.replicas}
        holders = [n for n, led in ledgers.items() if sid in led]
        assert len(holders) == 1, f"{sid} migrated across {holders}"
        assert ledgers[holders[0]][sid] == 5
        # The owning replica's engine did all the trajectory work.
        owner = next(r for r in svc.replicas if r.name == holders[0])
        snap = owner.metrics.snapshot()
        assert snap["counters"][
            "serving_trajectory_requests_total"] == 1
        assert snap["counters"][
            "serving_trajectory_frames_total"] == n_frames
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Frame-sequence writer (utils/frames.py)
# ---------------------------------------------------------------------------


def test_save_frame_sequence_writes_frames_and_contact_sheet(tmp_path):
    import os

    from PIL import Image

    from diff3d_tpu.utils import save_frame_sequence

    frames = np.linspace(-1, 1, 5 * 8 * 8 * 3, dtype=np.float32)
    frames = frames.reshape(5, 8, 8, 3)
    out = save_frame_sequence(str(tmp_path / "seq"), frames, columns=3)
    assert len(out["frames"]) == 5
    assert [os.path.basename(p) for p in out["frames"]] == [
        f"frame_{k:03d}.png" for k in range(5)]
    for p in out["frames"]:
        assert Image.open(p).size == (8, 8)
    sheet = Image.open(out["contact_sheet"])
    assert sheet.size == (3 * 8, 2 * 8)              # 3 cols x 2 rows

    # Guidance axis: lane 0 is written; no contact sheet on request.
    out2 = save_frame_sequence(str(tmp_path / "seq_b"),
                               np.repeat(frames[:, None], 2, axis=1),
                               contact_sheet=False)
    assert out2["contact_sheet"] is None
    a = np.asarray(Image.open(out["frames"][0]))
    b = np.asarray(Image.open(out2["frames"][0]))
    np.testing.assert_array_equal(a, b)

    with pytest.raises(ValueError):
        save_frame_sequence(str(tmp_path / "e"), frames[:0])
    with pytest.raises(ValueError):
        save_frame_sequence(str(tmp_path / "e"), frames[..., :2])


# ---------------------------------------------------------------------------
# eval_cli --orbit (slow: trains a checkpoint first)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_eval_cli_orbit_consistency_readout(tmp_path):
    """--orbit N renders a turntable per object and lands the
    reprojection-consistency numbers (plus frame PNGs under --save_dir)
    in the eval record."""
    import os

    from diff3d_tpu.cli import eval_cli, train_cli

    wd = str(tmp_path)
    train_cli.main(["--synthetic", "--config", "test", "--steps", "2",
                    "--batch", "8", "--workdir", wd, "--num_workers", "0"])
    out = str(tmp_path / "eval.jsonl")
    save = str(tmp_path / "art")
    eval_cli.main(["--model", os.path.join(wd, "checkpoints"),
                   "--synthetic_scenes", "--config", "test",
                   "--objects", "2", "--steps", "2", "--max_views", "2",
                   "--orbit", "3", "--orbit_objects", "1",
                   "--save_dir", save, "--out", out])
    rec = json.loads(open(out).read().strip().splitlines()[-1])
    oc = rec["orbit_consistency"]
    assert oc["frames"] == 3 and oc["objects"] == 1
    assert oc["consistency_l1"] is None or np.isfinite(
        oc["consistency_l1"])
    (entry,) = oc["per_object"]
    assert entry["radius"] > 0
    assert os.path.exists(os.path.join(entry["frames_dir"],
                                       "frame_000.png"))
    assert os.path.exists(os.path.join(entry["frames_dir"],
                                       "contact_sheet.png"))
