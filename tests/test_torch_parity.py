"""Numerical parity of Flax layers + the torch-checkpoint converter
against PyTorch primitives.

The reference model itself cannot be imported here (its visu3d dependency
is not in the image), so these tests rebuild each block's documented
semantics (SURVEY.md §2.1; reference ``xunet.py`` file:line cited per
test) from raw torch primitives with random weights, convert those
weights through :mod:`diff3d_tpu.convert.torch_ckpt`'s mapping helpers,
and assert the Flax modules reproduce the torch outputs — validating both
the layer math and the tensor-layout conversion (the two places silent
parity bugs hide).
"""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from diff3d_tpu.convert.torch_ckpt import (_attn_layer, _conv, _groupnorm,
                                           _linear)
from diff3d_tpu.models.layers import AttnLayer, FiLM, FrameGroupNorm

torch.manual_seed(0)


def _np(t):
    return t.detach().cpu().numpy()


def test_multihead_attention_matches_torch():
    """AttnLayer (q/k/v/out projections + sdpa) vs
    torch.nn.MultiheadAttention(batch_first=True) — reference
    ``xunet.py:161`` — with packed in_proj weights converted."""
    B, L, C, H = 2, 24, 32, 4
    mha = torch.nn.MultiheadAttention(C, H, batch_first=True)
    q = torch.randn(B, L, C)
    kv = torch.randn(B, L, C)
    ref, _ = mha(q, kv, kv, need_weights=False)

    sd = {f"x.attn.{k}": _np(v) for k, v in mha.state_dict().items()}
    params = _attn_layer(sd, "x")
    out = AttnLayer(num_heads=H, attn_impl="xla").apply(
        {"params": params}, jnp.asarray(_np(q)), jnp.asarray(_np(kv)))
    np.testing.assert_allclose(np.asarray(out), _np(ref),
                               atol=1e-5, rtol=1e-5)


def test_groupnorm_over_frames_matches_torch():
    """FrameGroupNorm vs torch GN applied to frames folded into batch
    (reference ``xunet.py:61-71``)."""
    B, F, H, W, C = 2, 2, 6, 6, 32
    gn = torch.nn.GroupNorm(8, C)
    with torch.no_grad():
        gn.weight.uniform_(0.5, 1.5)
        gn.bias.uniform_(-0.5, 0.5)
    x = torch.randn(B * F, C, H, W)
    ref = gn(x)                                      # [B*F, C, H, W]

    sd = {"g.gn.weight": _np(gn.weight), "g.gn.bias": _np(gn.bias)}
    params = _groupnorm(sd, "g")
    x_flax = jnp.asarray(_np(x)).transpose(0, 2, 3, 1).reshape(
        B, F, H, W, C)
    out = FrameGroupNorm(num_groups=8).apply({"params": params}, x_flax)
    ref_nhwc = _np(ref).transpose(0, 2, 3, 1).reshape(B, F, H, W, C)
    np.testing.assert_allclose(np.asarray(out), ref_nhwc,
                               atol=1e-5, rtol=1e-5)


def test_film_matches_torch():
    """FiLM: Linear(emb_ch -> 2*features) on SiLU(emb), h*(1+scale)+shift
    (reference ``xunet.py:74-87``, which transposes around its Linear; the
    channels-last layout here must be numerically identical)."""
    B, F, H, W, C, E = 2, 2, 4, 4, 16, 24
    dense = torch.nn.Linear(E, 2 * C)
    h = torch.randn(B, F, C, H, W)
    emb = torch.randn(B, F, E, H, W)

    e = torch.nn.functional.silu(emb).permute(0, 1, 3, 4, 2)  # [...,E]
    scale, shift = dense(e).chunk(2, dim=-1)                  # [...,C]
    ref = (h.permute(0, 1, 3, 4, 2) * (1 + scale) + shift)    # [B,F,H,W,C]

    sd = {"f.dense.weight": _np(dense.weight),
          "f.dense.bias": _np(dense.bias)}
    params = {"Dense_0": _linear(sd, "f.dense")}
    out = FiLM(features=C).apply(
        {"params": params},
        jnp.asarray(_np(h.permute(0, 1, 3, 4, 2))),
        jnp.asarray(_np(emb.permute(0, 1, 3, 4, 2))))
    np.testing.assert_allclose(np.asarray(out), _np(ref),
                               atol=1e-5, rtol=1e-5)


def test_conv3x3_layout_conversion():
    """Conv2d [O,I,kh,kw] -> Flax [kh,kw,I,O] with SAME padding."""
    import flax.linen as nn

    conv = torch.nn.Conv2d(8, 16, 3, padding=1)
    x = torch.randn(2, 8, 10, 10)
    ref = conv(x)

    sd = {"c.weight": _np(conv.weight), "c.bias": _np(conv.bias)}
    params = _conv(sd, "c")
    out = nn.Conv(16, (3, 3)).apply(
        {"params": params}, jnp.asarray(_np(x.permute(0, 2, 3, 1))))
    np.testing.assert_allclose(np.asarray(out),
                               _np(ref.permute(0, 2, 3, 1)),
                               atol=1e-5, rtol=1e-5)


def test_whole_model_converted_forward_parity():
    """END-TO-END: a full torch-composed X-UNet (tests/_torch_xunet.py,
    reference ``xunet.py:355-536`` semantics, rays injected) -> state dict
    -> ``convert_state_dict`` -> Flax forward must agree <= 1e-4.  Catches
    any layout / epsilon / padding / init drift anywhere in the 40-layer
    converted path — per-block tests can't see cross-block composition
    bugs (e.g. skip-concat channel order, strided-conv alignment)."""
    import jax.numpy as jnp_  # noqa: F401  (jnp already imported)

    from _torch_xunet import TXUNet
    from diff3d_tpu.config import test_config
    from diff3d_tpu.convert.torch_ckpt import convert_state_dict
    from diff3d_tpu.geometry import pinhole_rays
    from diff3d_tpu.models import XUNet

    cfg = test_config(imgsize=16, ch=8).model
    torch.manual_seed(0)
    tm = TXUNet(cfg).eval()
    # Randomise EVERY parameter (zero-init convs included): a trained
    # checkpoint has no zeros, and zeros would mask conversion bugs.
    gen = torch.Generator().manual_seed(1)
    with torch.no_grad():
        for p in tm.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * 0.08)

    B, H, W = 2, cfg.H, cfg.W
    rng = np.random.default_rng(2)
    # random proper rotations via QR
    q, _ = np.linalg.qr(rng.normal(size=(B, 2, 3, 3)))
    det = np.linalg.det(q)[..., None, None]
    R = (q * np.sign(det)).astype(np.float32)
    t = rng.normal(0, 1.5, (B, 2, 3)).astype(np.float32)
    K = np.broadcast_to(np.array([[19.0, 0, 8], [0, 19.0, 8], [0, 0, 1]],
                                 np.float32), (B, 3, 3)).copy()
    batch_np = {
        "x": rng.uniform(-1, 1, (B, H, W, 3)).astype(np.float32),
        "z": rng.uniform(-1, 1, (B, H, W, 3)).astype(np.float32),
        "logsnr": np.stack([np.full(B, 20.0),
                            rng.uniform(-20, 20, B)], 1).astype(np.float32),
        "R": R, "t": t, "K": K,
    }
    cond_mask = np.array([True, False])  # exercise both CFG branches

    # rays from the framework's (visu3d-golden-tested) geometry
    pos, dirs = pinhole_rays(jnp.asarray(R), jnp.asarray(t),
                             jnp.asarray(K)[:, None], H, W)

    with torch.no_grad():
        ref = tm({"x": torch.from_numpy(batch_np["x"]).permute(0, 3, 1, 2),
                  "z": torch.from_numpy(batch_np["z"]).permute(0, 3, 1, 2),
                  "logsnr": torch.from_numpy(batch_np["logsnr"])},
                 torch.from_numpy(np.asarray(pos).copy()),
                 torch.from_numpy(np.asarray(dirs).copy()),
                 torch.from_numpy(cond_mask))

    params = convert_state_dict(tm.state_dict(), cfg)
    out = XUNet(cfg).apply(
        {"params": params},
        {k: jnp.asarray(v) for k, v in batch_np.items()},
        cond_mask=jnp.asarray(cond_mask))

    ref_nhwc = _np(ref.permute(0, 2, 3, 1))
    assert np.asarray(out).shape == ref_nhwc.shape == (B, H, W, 3)
    np.testing.assert_allclose(np.asarray(out), ref_nhwc,
                               atol=1e-4, rtol=1e-4)


def test_resnet_block_matches_torch_composition():
    """Full ResnetBlock vs the reference's documented composition
    (``xunet.py:90-152``): GN -> SiLU -> conv1 -> GN -> FiLM -> conv2,
    (+ 1x1-projected skip), /sqrt(2) — assembled from torch primitives
    with shared weights."""
    from diff3d_tpu.models.layers import ResnetBlock

    B, F, H, W, Cin, Cout, E = 1, 2, 6, 6, 16, 32, 24
    # FrameGroupNorm picks the largest group count <= 32 dividing C
    # (reference hardcodes GN(32), xunet.py:65); match it here.
    gn0 = torch.nn.GroupNorm(16, Cin)
    gn1 = torch.nn.GroupNorm(32, Cout)
    conv1 = torch.nn.Conv2d(Cin, Cout, 3, padding=1)
    conv2 = torch.nn.Conv2d(Cout, Cout, 3, padding=1)
    film = torch.nn.Linear(E, 2 * Cout)
    skip = torch.nn.Conv2d(Cin, Cout, 1)
    for m in (gn0, gn1):
        with torch.no_grad():
            m.weight.uniform_(0.5, 1.5)
            m.bias.uniform_(-0.2, 0.2)

    x = torch.randn(B * F, Cin, H, W)
    emb = torch.randn(B, F, E)                       # broadcast per pixel

    h = conv1(torch.nn.functional.silu(gn0(x)))
    h = gn1(h)
    e = torch.nn.functional.silu(emb)
    scale, shift = film(e).chunk(2, dim=-1)          # [B, F, Cout]
    sc = scale.reshape(B * F, Cout, 1, 1)
    sh = shift.reshape(B * F, Cout, 1, 1)
    h = h * (1 + sc) + sh
    h = conv2(h)
    ref = (h + skip(x)) / np.sqrt(2.0)

    sd = {}
    for name, mod in (("groupnorm0.gn", gn0), ("groupnorm1.gn", gn1),
                      ("conv1", conv1), ("conv2", conv2),
                      ("film.dense", film), ("dense", skip)):
        for k, v in mod.state_dict().items():
            sd[f"r.{name}.{k}"] = _np(v)

    from diff3d_tpu.convert.torch_ckpt import _resnet_block
    params = _resnet_block(sd, "r", has_skip_proj=True)

    x_flax = jnp.asarray(_np(x.permute(0, 2, 3, 1))).reshape(
        B, F, H, W, Cin)
    emb_flax = jnp.broadcast_to(
        jnp.asarray(_np(emb))[:, :, None, None, :], (B, F, H, W, E))
    out = ResnetBlock(features=Cout, dropout=0.0).apply(
        {"params": params}, x_flax, emb_flax, True)
    ref_nhwc = _np(ref.permute(0, 2, 3, 1)).reshape(B, F, H, W, Cout)
    np.testing.assert_allclose(np.asarray(out), ref_nhwc,
                               atol=1e-4, rtol=1e-4)
