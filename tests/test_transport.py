"""Cross-process fleet: framing, error codec, RemoteReplica, workers.

Four layers, cheapest first:

* **Framing + codec unit tests** — length-prefixed JSON frames over a
  socketpair: bit-exact ndarray round-trips, and every malformed input
  (oversized declared length, EOF mid-frame, non-JSON body) is a typed
  error, never a hung socket.  The typed retryable taxonomy crosses the
  wire by class name and comes back as the same class with the same
  payload fields.
* **RemoteReplica over an in-process Worker wrapping test_router.py's
  scripted fakes** — duck-type conformance with the in-process
  :class:`~diff3d_tpu.serving.fleet.Replica` surface (the router needs
  zero placement changes), trajectory frame cursors, rollout RPCs, and
  the heartbeat-death contract: a worker gone silent past the timeout
  is ``dead`` forever and its in-flight requests reject with a typed
  ``SessionLost`` naming it.
* **HBM-budgeted admission** — fire/silent pairs against a synthetic
  ``runs/memcheck/`` manifest: the gate's arithmetic (resident + record
  + program peak vs budget), rejection *at the door* with no ledger
  trace, and the counters surfacing through worker /stats and the
  router's ``fleet_admission_rejects_total{reason="hbm"}``.
* **The 2-worker subprocess e2e** — real ``worker_cli`` processes on
  disjoint 4-device slices of the 8-virtual-device CPU mesh, serving
  concurrent sticky sessions bit-identical to the in-process oracle,
  then a mid-run SIGKILL: typed ``SessionLost`` naming the victim,
  sessionless failover to the survivor, zero migration, zero hangs.
  The larger soak (``tools/chaos_router.py --remote``) is marked slow.
"""

import dataclasses
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from diff3d_tpu.analysis import membudgets
from diff3d_tpu.config import ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.serving.scheduler import (EngineStopped, QueueFullError,
                                          ReplicaDraining, ReplicaOverBudget,
                                          RequestTimeout, SessionLost,
                                          TrajectoryRequest,
                                          UnsupportedSchedule, ViewRequest)
from diff3d_tpu.serving.transport import (Connection, FrameGarbage,
                                          FrameTooLarge, FrameTruncated,
                                          RemoteReplica, TransportError,
                                          decode_error, decode_payload,
                                          encode_error, encode_payload,
                                          recv_frame, request_from_wire,
                                          request_wire, send_frame)
from diff3d_tpu.serving.worker import (HbmAdmission, Worker,
                                       program_for_schedule)

from test_router import FakeReplica

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LEN = struct.Struct("!I")


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def _views(i, n_views=3, size=8):
    r = np.random.RandomState(100 + i)
    return {
        "imgs": r.randn(n_views, size, size, 3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": r.randn(n_views, 3).astype(np.float32),
        "K": np.array([[size * 1.2, 0, size / 2],
                       [0, size * 1.2, size / 2],
                       [0, 0, 1]], np.float32),
    }


def _req(session_id=None, seed=0, trajectory=False, **kw):
    cls = TrajectoryRequest if trajectory else ViewRequest
    return cls(_views(seed), seed=seed, n_views=3,
               session_id=session_id, **kw)


# ---------------------------------------------------------------------------
# Framing: bit-exact round trips, typed faults, never a hung socket
# ---------------------------------------------------------------------------


def test_frame_roundtrip_bit_exact():
    a, b = _pair()
    try:
        msg = {
            "op": "submit",
            "args": {
                "f32": np.random.RandomState(0).randn(2, 3, 3).astype(
                    np.float32),
                "f16": np.arange(6, dtype=np.float16).reshape(2, 3),
                "i64": np.array([[-(1 << 40), 7]], np.int64),
                "bool": np.array([True, False]),
                "nested": [{"x": np.float32(1.5), "n": np.int64(-3)},
                           "str", None, 2.5],
            },
        }
        send_frame(a, msg)
        got = recv_frame(b)
        for key in ("f32", "f16", "i64", "bool"):
            want = msg["args"][key]
            have = got["args"][key]
            assert have.dtype == want.dtype
            assert have.tobytes() == want.tobytes()
        assert got["args"]["nested"][0] == {"x": 1.5, "n": -3}
        assert got["args"]["nested"][1:] == ["str", None, 2.5]
    finally:
        a.close()
        b.close()


def test_payload_codec_normalizes_big_endian():
    big = np.arange(4, dtype=">f4")
    back = decode_payload(encode_payload(big))
    assert back.dtype == np.dtype("<f4")
    np.testing.assert_array_equal(back, big.astype("<f4"))


def test_clean_eof_is_none_not_error():
    a, b = _pair()
    a.close()
    try:
        assert recv_frame(b) is None
    finally:
        b.close()


def test_declared_length_past_cap_is_frame_too_large():
    a, b = _pair()
    try:
        a.sendall(_LEN.pack(1 << 29))
        with pytest.raises(FrameTooLarge):
            recv_frame(b, max_bytes=1 << 16)
    finally:
        a.close()
        b.close()


def test_oversized_outgoing_frame_refused_before_send():
    a, b = _pair()
    try:
        with pytest.raises(FrameTooLarge):
            send_frame(a, {"blob": "x" * 4096}, max_bytes=64)
        a.close()             # nothing was written: peer sees clean EOF
        assert recv_frame(b) is None
    finally:
        b.close()


def test_eof_mid_frame_is_frame_truncated():
    a, b = _pair()
    try:
        a.sendall(_LEN.pack(100) + b'{"op": "tr')
        a.close()
        with pytest.raises(FrameTruncated):
            recv_frame(b)
    finally:
        b.close()


def test_eof_between_header_and_body_is_frame_truncated():
    a, b = _pair()
    try:
        a.sendall(_LEN.pack(64))
        a.close()
        with pytest.raises(FrameTruncated):
            recv_frame(b)
    finally:
        b.close()


@pytest.mark.parametrize("body", [b"not json at all", b"[1, 2, 3]",
                                  b'"a bare string"'])
def test_non_object_body_is_frame_garbage(body):
    a, b = _pair()
    try:
        a.sendall(_LEN.pack(len(body)) + body)
        with pytest.raises(FrameGarbage):
            recv_frame(b)
    finally:
        a.close()
        b.close()


def test_all_frame_faults_are_retryable():
    for cls in (TransportError, FrameTooLarge, FrameTruncated,
                FrameGarbage):
        assert issubclass(cls, RetryableError)


# ---------------------------------------------------------------------------
# Error codec: the typed taxonomy crosses the wire intact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("exc", [
    QueueFullError("queue full"),
    RequestTimeout("req-1: timed out"),
    EngineStopped("stopped"),
    TransportError("socket reset"),
    ReplicaDraining("draining", replica="r2", retry_after_s=0.7),
    SessionLost("record gone", replica="r0", retry_after_s=1.5),
    UnsupportedSchedule("no ddim here", supported=["ancestral:4"],
                        retry_after_s=None),
    ReplicaOverBudget("over", replica="w1", retry_after_s=5.0,
                      budget_bytes=1000, resident_bytes=600,
                      program_peak_bytes=300),
])
def test_error_roundtrip_preserves_class_message_and_fields(exc):
    back = decode_error(encode_error(exc))
    assert type(back) is type(exc)
    assert str(back) == str(exc)
    for field in ("retry_after_s", "replica", "supported", "budget_bytes",
                  "resident_bytes", "program_peak_bytes"):
        assert getattr(back, field, None) == getattr(exc, field, None)


def test_over_budget_headroom_survives_the_wire():
    exc = ReplicaOverBudget("over", replica="w1", retry_after_s=1.0,
                            budget_bytes=1000, resident_bytes=600,
                            program_peak_bytes=300)
    back = decode_error(encode_error(exc))
    assert back.headroom_bytes == 400


def test_unknown_error_type_degrades_to_runtime_error():
    back = decode_error({"type": "SomeExoticError", "msg": "boom"})
    assert type(back) is RuntimeError
    assert "SomeExoticError" in str(back) and "boom" in str(back)


def test_non_retryable_stdlib_errors_rehydrate():
    for exc in (ValueError("bad shape"), KeyError("missing"),
                TypeError("nope")):
        back = decode_error(encode_error(exc))
        assert type(back) is type(exc)


def test_request_wire_roundtrip_plain_and_trajectory():
    for trajectory in (False, True):
        req = _req(session_id="obj-7", seed=3, trajectory=trajectory,
                   sampler_kind="ancestral", steps=4, timeout_s=9.0)
        back = request_from_wire(decode_payload(encode_payload(
            request_wire(req))))
        assert type(back) is type(req)
        assert (back.id, back.seed, back.n_views, back.session_id) == \
            (req.id, req.seed, req.n_views, req.session_id)
        assert (back.sampler_kind, back.steps, back.timeout_s) == \
            (req.sampler_kind, req.steps, req.timeout_s)
        np.testing.assert_array_equal(back.imgs0, req.imgs0)
        np.testing.assert_array_equal(back.R, req.R)
        np.testing.assert_array_equal(back.T, req.T)
        np.testing.assert_array_equal(back.K, req.K)


# ---------------------------------------------------------------------------
# RemoteReplica over an in-process Worker wrapping scripted fakes
# ---------------------------------------------------------------------------


class BootableFake(FakeReplica):
    """test_router's scripted replica + the lifecycle surface Worker
    drives and an optional scripted resolution for submitted requests."""

    def __init__(self, *a, resolve_with=None, commit_frames=None, **kw):
        super().__init__(*a, **kw)
        self.resolve_with = resolve_with      # callable(req) -> ndarray
        self.commit_frames = commit_frames    # list of frames to stream

    def start(self):
        return self

    def stop(self, timeout=None):
        self.events.append("stop")

    def submit(self, req):
        super().submit(req)
        if self.commit_frames is not None:
            for k, frame in enumerate(self.commit_frames):
                req._commit_frame(k + 1, frame)
        if self.resolve_with is not None:
            req._resolve(np.asarray(self.resolve_with(req)))
        return req


def _tiny_cfg(**serving_over):
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    serving = dict(port=0, max_batch=4, max_queue=8, max_wait_ms=20.0,
                   max_views=6, default_timeout_s=60.0,
                   retry_after_s=0.1, result_cache_entries=0)
    serving.update(serving_over)
    return dataclasses.replace(cfg, serving=ServingConfig(**serving))


def _seeded_result(req):
    return np.random.RandomState(req.seed).randn(2, 1, 8, 8, 3).astype(
        np.float32)


def _worker_pair(fake, cfg=None, admission=None, **remote_kw):
    worker = Worker(fake, cfg or _tiny_cfg(), admission=admission).start()
    remote_kw.setdefault("heartbeat_interval_s", 0.05)
    remote_kw.setdefault("heartbeat_timeout_s", 1.0)
    remote = RemoteReplica("127.0.0.1", worker.port, **remote_kw).start()
    return worker, remote


def _wait_for(pred, timeout=10.0, poll=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.lock_witness
def test_remote_replica_duck_types_the_replica_surface(lock_witness):
    """Attribute-for-attribute conformance with the surface the router
    reads — RemoteReplica must be a drop-in for Replica."""
    fake = BootableFake("w-fake", depth=3,
                        schedules={("ancestral", 4), ("ddim", 2)})
    fake.sessions["s1"] = 2
    worker, remote = _worker_pair(fake)
    try:
        for attr in ("name", "health", "depth", "supports",
                     "supported_schedules", "params_version", "submit",
                     "session_records", "session_count", "drain",
                     "resume", "kill", "swap_params", "snapshot",
                     "start", "stop"):
            assert hasattr(remote, attr), f"RemoteReplica lacks {attr}"
        assert remote.name == fake.name     # adopted from the worker
        assert remote.health == fake.health
        assert remote.depth() == fake.depth()
        for kind, steps in (("ancestral", 4), ("ddim", 2), ("ddim", 99)):
            assert remote.supports(kind, steps) == fake.supports(kind,
                                                                 steps)
        assert remote.supported_schedules() == fake.supported_schedules()
        assert remote.params_version == fake.params_version
        assert remote.session_records() == fake.session_records()
        assert remote.session_count("s1") == 2
        snap = remote.snapshot()
        assert snap["name"] == fake.name
        assert snap["transport"]["connected"]
        assert snap["transport"]["remote"].endswith(str(worker.port))
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_remote_submit_resolves_bit_identical(lock_witness):
    fake = BootableFake("w-res", resolve_with=_seeded_result)
    worker, remote = _worker_pair(fake)
    try:
        req = remote.submit(_req(session_id="obj-1", seed=5))
        got = req.result(timeout=10)
        np.testing.assert_array_equal(got, _seeded_result(req))
        assert req.cached is False
        # The ledger entry landed on the worker-side replica.
        assert remote.session_records() == {"obj-1": 1}
        assert remote.transport_stats()["rtt_ms"] is not None
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_remote_submit_rehydrates_typed_rejections(lock_witness):
    fake = BootableFake("w-err")
    worker, remote = _worker_pair(fake)
    try:
        for exc in (QueueFullError("full"),
                    ReplicaDraining("draining", replica="w-err",
                                    retry_after_s=0.3),
                    UnsupportedSchedule("no ddim",
                                        supported=["ancestral:4"]),
                    SessionLost("gone", replica="w-err")):
            fake.submit_exc = exc
            with pytest.raises(type(exc)) as ei:
                remote.submit(_req(seed=1))
            assert str(ei.value) == str(exc)
            for field in ("replica", "supported", "retry_after_s"):
                assert getattr(ei.value, field, None) == \
                    getattr(exc, field, None)
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_remote_trajectory_streams_frames_through_cursors(lock_witness):
    frames = [np.full((1, 8, 8, 3), k, np.float32) for k in range(2)]
    fake = BootableFake("w-traj", commit_frames=frames,
                        resolve_with=lambda req: np.stack(frames))
    worker, remote = _worker_pair(fake)
    try:
        req = remote.submit(_req(seed=2, trajectory=True))
        np.testing.assert_array_equal(req.result(timeout=10),
                                      np.stack(frames))
        got = req.frames_since(0)
        assert len(got) == 2
        for want, have in zip(frames, got):
            np.testing.assert_array_equal(want, have)
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_remote_lifecycle_rpcs_reach_the_replica(lock_witness):
    fake = BootableFake("w-life")
    worker, remote = _worker_pair(fake)
    try:
        assert remote.drain(timeout=1.0) is True
        remote.resume()
        version = remote.swap_params({"w": np.ones(3, np.float32)},
                                     version="v9")
        assert version == "v9"
        _wait_for(lambda: {"drain", "resume", "swap"} <=
                  set(fake.events), what="lifecycle events")
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_heartbeat_timeout_is_terminal_death_with_typed_session_lost(
        lock_witness):
    """The connection-supervision contract: a worker gone silent past
    heartbeat_timeout_s is dead forever, in-flight requests reject with
    SessionLost naming it, and later submits are EngineStopped — never
    a hang."""
    fake = BootableFake("w-dead")          # never resolves
    worker, remote = _worker_pair(fake, heartbeat_interval_s=0.05,
                                  heartbeat_timeout_s=0.4)
    try:
        req = remote.submit(_req(session_id="s-lost", seed=7))
        worker.stop()                      # abrupt close: SIGKILL shape
        with pytest.raises(SessionLost) as ei:
            req.result(timeout=10)
        assert ei.value.replica == "w-dead"
        _wait_for(lambda: remote.health == "dead", what="death")
        stats = remote.transport_stats()
        assert stats["heartbeat_timeouts"] == 1
        assert stats["connected"] is False
        with pytest.raises(EngineStopped):
            remote.submit(_req(seed=8))
        # Death is terminal: the cached ledger still shows the lost
        # session (the zero-migration audit needs the dead owner).
        assert remote.session_records() == {"s-lost": 1}
    finally:
        remote.stop()
        worker.stop()


def test_connection_call_times_out_instead_of_hanging():
    listener = socket.create_server(("127.0.0.1", 0))
    try:
        conn = Connection("127.0.0.1", listener.getsockname()[1],
                          timeout_s=0.3)
        t0 = time.monotonic()
        with pytest.raises(TransportError):
            conn.call("ping")              # nobody ever answers
        assert time.monotonic() - t0 < 5.0
        conn.close()
    finally:
        listener.close()


# ---------------------------------------------------------------------------
# HBM-budgeted admission against a synthetic memcheck manifest
# ---------------------------------------------------------------------------

_PEAK = 50_000


def _manifest_dir(tmp_path, peak=_PEAK, programs=("step_many",)):
    d = str(tmp_path / "memcheck")
    for program in programs:
        membudgets.write_manifest(
            membudgets.manifest_path(program, d),
            membudgets.MemManifest(
                program=program,
                budgets=membudgets.MemBudget(peak_bytes=peak)))
    return d


def test_admission_fire_and_silent_pair(tmp_path):
    d = _manifest_dir(tmp_path)
    req_a, req_b = _req(seed=1), _req(seed=2)
    need = HbmAdmission.record_bytes(req_a)
    assert need > 0
    # Silent: exactly one request + the program peak fits.
    gate = HbmAdmission(budget_bytes=need + _PEAK, manifest_dir=d,
                        replica_name="wA", retry_after_s=2.5)
    gate.admit(req_a, default_kind="ancestral")
    snap = gate.snapshot()
    assert snap["resident_bytes"] == need
    assert snap["headroom_bytes"] == _PEAK
    assert snap["program_peaks"] == {"step_many": _PEAK}
    # Fire: the second identical request pushes past the budget, with
    # the full arithmetic on the exception — and no reservation leaks.
    with pytest.raises(ReplicaOverBudget) as ei:
        gate.admit(req_b, default_kind="ancestral")
    e = ei.value
    assert (e.replica, e.retry_after_s) == ("wA", 2.5)
    assert (e.budget_bytes, e.resident_bytes, e.program_peak_bytes) == \
        (need + _PEAK, need, _PEAK)
    assert e.headroom_bytes == _PEAK
    assert gate.snapshot()["rejects"] == 1
    # Releasing the first reservation lets the second in.
    gate.release(req_a.id)
    gate.admit(req_b, default_kind="ancestral")


def test_admission_unpinned_program_charged_the_largest_peak(tmp_path):
    d = _manifest_dir(tmp_path, programs=("step_many", "step_many_ddim"))
    membudgets.write_manifest(
        membudgets.manifest_path("step_many_ddim", d),
        membudgets.MemManifest(
            program="step_many_ddim",
            budgets=membudgets.MemBudget(peak_bytes=3 * _PEAK)))
    gate = HbmAdmission(budget_bytes=10 * _PEAK, manifest_dir=d)
    assert program_for_schedule(None) == "step_many"
    assert program_for_schedule("ancestral") == "step_many"
    assert gate.program_peak("ancestral") == _PEAK
    assert gate.program_peak("ddim") == 3 * _PEAK
    # A kind with no committed manifest is charged conservatively.
    assert gate.program_peak("exotic") == 3 * _PEAK


def test_admission_disabled_when_budget_unset(tmp_path):
    gate = HbmAdmission(0, manifest_dir=_manifest_dir(tmp_path))
    gate.admit(_req(seed=1))
    snap = gate.snapshot()
    assert snap["enabled"] is False
    assert snap["headroom_bytes"] is None
    assert snap["resident_bytes"] == 0      # disabled gate reserves nothing


@pytest.mark.lock_witness
def test_worker_rejects_at_the_door_before_any_replica_work(
        tmp_path, lock_witness):
    """The fire/silent pair through the wire: an over-budget submit is
    a typed 503-shaped ReplicaOverBudget with zero ledger trace, and
    raising the budget admits the identical request."""
    fake = BootableFake("w-hbm", resolve_with=_seeded_result)
    gate = HbmAdmission(budget_bytes=1, manifest_dir=_manifest_dir(tmp_path),
                        replica_name="w-hbm", retry_after_s=1.0)
    worker, remote = _worker_pair(fake, admission=gate)
    try:
        with pytest.raises(ReplicaOverBudget) as ei:
            remote.submit(_req(session_id="s-budget", seed=4))
        assert ei.value.replica == "w-hbm"
        assert ei.value.budget_bytes == 1
        assert ei.value.retry_after_s == 1.0
        assert fake.submitted == []        # rejected before the replica
        assert fake.sessions == {}         # ... and before the ledger
        assert worker.metrics.snapshot()["counters"][
            "worker_admission_rejects_hbm_total"] == 1
        # The reject count rides the heartbeat into transport_stats.
        _wait_for(lambda: remote.transport_stats()
                  ["admission_rejects_hbm"] == 1, what="hbm stat")
        # Silent half: same request shape under a real budget.
        worker.admission.budget_bytes = 1 << 30
        req = remote.submit(_req(session_id="s-budget", seed=4))
        req.result(timeout=10)
        assert fake.sessions == {"s-budget": 1}
        # /stats (HTTP, include_memory) surfaces the same arithmetic.
        hbm = worker.metrics_snapshot()["hbm"]
        assert hbm["enabled"] and hbm["budget_bytes"] == 1 << 30
    finally:
        remote.stop()
        worker.stop()


@pytest.mark.lock_witness
def test_router_surfaces_admission_rejects_and_remote_metrics(
        tmp_path, lock_witness):
    """Through the front door: the router re-raises the typed
    ReplicaOverBudget (no FleetOverloaded wrap) and folds the worker's
    reject counter into fleet_admission_rejects_total{reason="hbm"}."""
    from diff3d_tpu.serving.router import FleetService

    fake = BootableFake("w-gate", resolve_with=_seeded_result)
    gate = HbmAdmission(budget_bytes=1,
                        manifest_dir=_manifest_dir(tmp_path),
                        replica_name="w-gate", retry_after_s=1.0)
    worker = Worker(fake, _tiny_cfg(), admission=gate).start()
    cfg = _tiny_cfg(replicas=1, heartbeat_interval_s=0.05,
                    heartbeat_timeout_s=1.0)
    remote = RemoteReplica("127.0.0.1", worker.port,
                           heartbeat_interval_s=0.05,
                           heartbeat_timeout_s=1.0)
    svc = FleetService([remote], cfg).start(serve_http=False)
    try:
        with pytest.raises(ReplicaOverBudget) as ei:
            svc.router.submit(_req(session_id="s-r", seed=6))
        assert ei.value.replica == "w-gate"
        _wait_for(lambda: remote.transport_stats()
                  ["admission_rejects_hbm"] >= 1, what="hbm stat")
        snap = svc.metrics_snapshot()
        assert snap["counters"][
            'fleet_admission_rejects_total{reason="hbm"}'] >= 1
        assert snap["counters"]["router_rejected_total"] >= 1
        assert snap["gauges"]["fleet_remote_connected"] == 1.0
        # GET /fleet carries the per-replica transport block (RTT).
        fleet = svc.fleet_snapshot()
        transport = fleet["replicas"]["w-gate"]["transport"]
        assert transport["remote"].endswith(str(worker.port))
        assert transport["rtt_ms"] is not None
    finally:
        svc.stop()
        worker.stop()


def test_worker_http_front_door_exposes_hbm_stats(tmp_path):
    fake = BootableFake("w-http", resolve_with=_seeded_result)
    gate = HbmAdmission(budget_bytes=1 << 20,
                        manifest_dir=_manifest_dir(tmp_path),
                        replica_name="w-http")
    worker = Worker(fake, _tiny_cfg(), admission=gate)
    worker.start(http_port=0)
    try:
        base = f"http://127.0.0.1:{worker.http_port}"
        with urllib.request.urlopen(f"{base}/stats", timeout=5) as resp:
            stats = json.loads(resp.read())
        assert stats["hbm"]["budget_bytes"] == 1 << 20
        assert stats["hbm"]["headroom_bytes"] == 1 << 20
        assert stats["hbm"]["program_peaks"] == {"step_many": _PEAK}
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            health = json.loads(resp.read())
        assert health["replica"] == "w-http"
        assert health["hbm"]["enabled"] is True
    finally:
        worker.stop()


# ---------------------------------------------------------------------------
# The 2-worker subprocess e2e on the split CPU mesh (tier-1: ONE instance)
# ---------------------------------------------------------------------------


def _spawn_worker(name, devices, tmp_path, logs):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)   # --host_device_count sets it pre-import
    log = open(tmp_path / f"{name}.err.log", "wb")
    logs.append(log)
    return subprocess.Popen(
        [sys.executable, "-m", "diff3d_tpu.cli.worker_cli",
         "--config", "test", "--init", "random",
         "--imgsize", "8", "--ch", "8", "--shallow",
         "--devices", devices, "--port", "0", "--name", name,
         "--host_device_count", "8", "--timeout_s", "120",
         "--max_views", "6",
         "--compile_cache", str(tmp_path / "xla_cache")],
        env=env, stdout=subprocess.PIPE, stderr=log, text=True)


def _read_ready(name, proc):
    line = proc.stdout.readline()
    assert line, f"worker {name} exited before its ready line " \
        f"(rc={proc.poll()})"
    ready = json.loads(line)
    assert ready["ready"] and ready["name"] == name
    return ready


@pytest.mark.lock_witness
def test_two_worker_fleet_serves_sessions_and_survives_sigkill(
        tmp_path, lock_witness):
    """The acceptance e2e (DESIGN.md §19): two real worker processes on
    disjoint 4-device slices of the 8-virtual-device CPU mesh serve
    concurrent sticky sessions bit-identical to the in-process oracle
    (zero migration), then one worker is SIGKILLed mid-request: the
    in-flight request rejects with a typed SessionLost naming the
    victim, later sticky submits for its sessions do too, sessionless
    traffic fails over to the survivor, and the router metrics record
    the heartbeat death.  The larger soak is the slow
    tools/chaos_router.py --remote run below."""
    import jax

    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.serving.router import FleetService
    from diff3d_tpu.train.trainer import init_params

    logs, procs = [], {}
    service = None
    try:
        for name, devices in (("e2e-w0", "0-3"), ("e2e-w1", "4-7")):
            procs[name] = _spawn_worker(name, devices, tmp_path, logs)
        # The oracle compiles while the workers boot.
        cfg = _tiny_cfg(replicas=2, default_timeout_s=120.0,
                        heartbeat_interval_s=0.1,
                        heartbeat_timeout_s=2.0)
        model = XUNet(cfg.model)
        params = init_params(model, cfg, jax.random.PRNGKey(0))
        oracle = Sampler(model, params, cfg)
        remotes = []
        for name, proc in procs.items():
            ready = _read_ready(name, proc)
            remotes.append(RemoteReplica(
                "127.0.0.1", ready["port"], name=name,
                heartbeat_interval_s=cfg.serving.heartbeat_interval_s,
                heartbeat_timeout_s=cfg.serving.heartbeat_timeout_s))
        service = FleetService(remotes, cfg).start(serve_http=False)

        # Two concurrent sticky sessions, two views each; every result
        # must be bit-identical to the oracle (worker params come from
        # the same PRNGKey(0) random init; a 4-device slice changes
        # nothing about the math).
        reqs = {}
        for si, sid in enumerate(("s0", "s1")):
            for k in range(2):
                seed = 10 * (si + 1) + k
                reqs[(sid, k)] = service.router.submit(
                    ViewRequest(_views(seed), seed=seed, n_views=3,
                                session_id=sid))
        for (sid, k), req in reqs.items():
            seed = req.seed
            direct = oracle.synthesize(_views(seed),
                                       jax.random.PRNGKey(seed),
                                       max_views=3)
            np.testing.assert_array_equal(req.result(timeout=120), direct)

        # Zero migration: each session's ledger lives on ONE worker.
        owners = {}
        for rep in service.replicas:
            for sid, count in rep.session_records().items():
                assert sid not in owners, f"{sid} migrated"
                owners[sid] = rep.name
                assert count == 2
        assert set(owners) == {"s0", "s1"}

        # SIGKILL the owner of s0 while a request is in flight.
        victim = owners["s0"]
        survivor = next(r.name for r in service.replicas
                        if r.name != victim)
        inflight = service.router.submit(
            ViewRequest(_views(77), seed=77, n_views=3, session_id="s0"))
        os.kill(procs[victim].pid, signal.SIGKILL)
        with pytest.raises(SessionLost) as ei:
            inflight.result(timeout=30)
        assert ei.value.replica == victim
        assert inflight.done()             # terminal, not hung

        # Sticky resubmits for the lost session are typed SessionLost
        # too (the dying window surfaces retryable TransportErrors).
        deadline = time.monotonic() + 20.0
        while True:
            try:
                service.router.submit(
                    ViewRequest(_views(78), seed=78, n_views=3,
                                session_id="s0"))
                raise AssertionError("dead owner accepted a submit")
            except SessionLost as e:
                assert e.replica == victim
                break
            except RetryableError:
                assert time.monotonic() < deadline, "no typed SessionLost"
                time.sleep(0.1)

        # Sessionless traffic fails over to the survivor, bit-exact.
        free = service.router.submit(
            ViewRequest(_views(79), seed=79, n_views=3))
        direct = oracle.synthesize(_views(79), jax.random.PRNGKey(79),
                                   max_views=3)
        np.testing.assert_array_equal(free.result(timeout=120), direct)

        # The death is on the fleet surface: health, metrics, ledger.
        dead = service.router.replica(victim)
        assert dead.health == "dead"
        assert "s0" in dead.session_records()   # cached for the audit
        snap = service.metrics_snapshot()
        assert snap["counters"]["fleet_heartbeat_timeouts_total"] >= 1
        assert snap["gauges"]["fleet_remote_connected"] == 1.0
        transport = service.fleet_snapshot()["replicas"][survivor][
            "transport"]
        assert transport["connected"] and transport["rtt_ms"] is not None
    finally:
        if service is not None:
            service.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            if proc.stdout is not None:
                proc.stdout.close()
        for log in logs:
            log.close()


@pytest.mark.slow
def test_remote_chaos_soak(tmp_path):
    """Superseded in tier 1 by
    test_two_worker_fleet_serves_sessions_and_survives_sigkill (one
    SIGKILL, 2 sessions); this soak adds concurrent session churn,
    sessionless load and a mid-run rollout on the cross-process fleet.
    """
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO_ROOT, "tools", "chaos_router.py"),
         "--remote", "--replicas", "2", "--sessions", "4",
         "--views", "2", "--sessionless", "6", "--json",
         "--compile_cache", str(tmp_path / "xla_cache")],
        env=env, capture_output=True, text=True, timeout=840)
    assert out.returncode == 0, out.stderr[-2000:]
    record = json.loads(out.stdout.strip().splitlines()[-1])
    assert record["survived"] is True
    assert record["hung"] == 0 and record["lost"] == 0
    assert record["migrations"] == []
