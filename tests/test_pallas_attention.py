"""Pallas flash-attention kernel vs the XLA reference.

Runs the exact TPU tile program in Pallas interpret mode on CPU (the
tests' virtual-device platform), checking forward and backward against
``jax.nn.dot_product_attention`` over the shapes the X-UNet actually uses
(token counts 64..1024, head dims 32..128, including the padded /
non-square cases).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.ops.attention import multi_head_attention, sdpa
from diff3d_tpu.ops.pallas_attention import flash_attention, supports

SHAPES = [
    # (B, Lq, Lk, H, D): xunet attention shapes (SURVEY.md §3.4) + padding
    (2, 64, 64, 4, 64),      # 8x8 tokens, 256ch/4heads
    (2, 256, 256, 4, 128),   # 16x16 tokens, 512ch/4heads
    (1, 200, 200, 2, 32),    # non-multiple-of-128 seq (padded)
    (1, 96, 160, 2, 64),     # cross attention, Lq != Lk
    (1, 256, 256, 2, 256),   # srn128 deep level: D spans two lane tiles
    (1, 64, 64, 2, 160),     # D padded up to two lane tiles (160 -> 256)
]


def _qkv(shape, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    B, Lq, Lk, H, D = shape
    q = jnp.asarray(rng.randn(B, Lq, H, D), dtype)
    k = jnp.asarray(rng.randn(B, Lk, H, D), dtype)
    v = jnp.asarray(rng.randn(B, Lk, H, D), dtype)
    return q, k, v


@pytest.mark.parametrize("shape", SHAPES)
def test_forward_matches_xla(shape):
    q, k, v = _qkv(shape)
    ref = jax.nn.dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1e-2, rtol=1e-2)


@pytest.mark.parametrize("shape", SHAPES[:2] + SHAPES[4:6])
def test_backward_matches_xla(shape):
    q, k, v = _qkv(shape)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(jax.nn.dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, interpret=True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(b, a, atol=5e-2, rtol=5e-2)


@pytest.mark.parametrize("shape", SHAPES[:2] + SHAPES[3:])
def test_lse_output_matches_logsumexp(shape):
    from diff3d_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = _qkv(shape)
    o, lse = flash_attention_lse(q, k, v, interpret=True)
    np.testing.assert_allclose(o, jax.nn.dot_product_attention(q, k, v),
                               atol=1e-2, rtol=1e-2)
    D = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->blhm", q, k) / np.sqrt(D)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)     # [B, Lq, H]
    assert lse.shape == ref_lse.shape
    np.testing.assert_allclose(lse, ref_lse, atol=1e-3, rtol=1e-3)


def test_lse_gradients_including_lse_cotangent():
    """Both outputs' cotangents flow: compare against autodiff of the
    same (attention, logsumexp) pair composed from jnp primitives."""
    from diff3d_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = _qkv((1, 64, 64, 2, 32), seed=3)
    D = q.shape[-1]

    def ref_fn(q, k, v):
        s = jnp.einsum("blhd,bmhd->blhm", q, k) / np.sqrt(D)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("blhm,bmhd->blhd", p, v)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def fl_fn(q, k, v):
        o, lse = flash_attention_lse(q, k, v, interpret=True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(b, a, atol=5e-3, rtol=5e-3)


def test_bf16_forward():
    q, k, v = _qkv((2, 128, 128, 4, 64), dtype=jnp.bfloat16)
    ref = jax.nn.dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=5e-2, rtol=5e-2)


def test_supports_gating():
    q, k, v = _qkv((1, 64, 64, 2, 64))
    assert supports(q, k, v)
    # multi-lane-tile head dims up to MAX_D=512 are handled (srn128's
    # deep levels run D=256); beyond that the dispatcher falls back
    d256 = jnp.zeros((1, 64, 2, 256))
    assert supports(d256, d256, d256)
    huge = jnp.zeros((1, 64, 2, 640))
    assert not supports(huge, huge, huge)
    assert not supports(q.astype(jnp.float16), k, v)


def test_dispatcher_jit_consistency():
    """sdpa under jit: pallas and xla backends agree."""
    q, k, v = _qkv((2, 64, 64, 4, 64))

    @jax.jit
    def f(q, k, v):
        return sdpa(q, k, v, impl="xla")

    ref = f(q, k, v)
    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, interpret=True))(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-2, rtol=1e-2)


def test_multi_head_attention_wrapper():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 64, 128), jnp.float32)
    out = multi_head_attention(x, x, x, num_heads=4, impl="xla")
    assert out.shape == (2, 64, 128)


def test_resolve_auto_policy(monkeypatch):
    """'auto' routes per measured policy: XLA off-TPU always; on TPU the
    flash kernel only for lane-filling heads (D > 64) at L >= 4096."""
    from diff3d_tpu.ops import attention as att

    def q(L, D):
        return jnp.zeros((1, L, 4, D))

    monkeypatch.setattr(att.jax, "default_backend", lambda: "cpu")
    assert att._resolve_auto(q(16384, 128)) == "xla"  # off-TPU: always xla

    monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
    assert att._resolve_auto(q(4096, 32)) == "xla"    # 4x lane padding
    assert att._resolve_auto(q(4096, 64)) == "xla"    # 2x lane padding
    assert att._resolve_auto(q(4096, 128)) == "pallas"
    assert att._resolve_auto(q(1024, 128)) == "xla"   # short seq
