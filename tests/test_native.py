"""Native (C++) decoder runtime vs the PIL reference path.

Builds ``libd3dnative.so`` on first use (g++ + libpng are part of the
image); if the toolchain were absent the whole module degrades to PIL and
these tests skip.
"""

import os

import numpy as np
import pytest
from PIL import Image

from diff3d_tpu import native
from diff3d_tpu.data.srn import load_view_image

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native decoder unavailable")


@pytest.fixture(scope="module")
def pngs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("pngs")
    rng = np.random.RandomState(0)
    paths = []
    for i, mode in enumerate(["RGB", "RGBA", "RGB", "L"]):
        shape = (128, 128) if mode == "L" else (
            (128, 128, 4) if mode == "RGBA" else (128, 128, 3))
        arr = rng.randint(0, 256, shape, np.uint8)
        if mode == "RGBA":
            # SRN-style binary alpha (object 255 / background 0); PIL's
            # uint8 premultiply makes fractional alpha pure quantization
            # noise, which no loader should be asked to reproduce.
            arr[..., 3] = np.where(rng.rand(128, 128) > 0.3, 255, 0)
        p = str(tmp / f"{i}_{mode}.png")
        Image.fromarray(arr, mode).save(p)
        paths.append(p)
    return paths


def _pil_box_reference(path, size):
    """Float box filter with PIL's premultiplied-alpha semantics."""
    img = Image.open(path)
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        arr = np.repeat(arr[..., None], 3, -1)
    k = arr.shape[0] // size
    rgb = arr[..., :3]
    w = (arr[..., 3:4] / 255.0 if arr.shape[-1] == 4
         else np.ones_like(arr[..., :1]))
    num = (rgb * w).reshape(size, k, size, k, 3).sum((1, 3))
    den = w.reshape(size, k, size, k, 1).sum((1, 3))
    out = np.where(den > 0, num / np.maximum(den, 1e-12), 0.0)
    return out / 255.0 * 2.0 - 1.0


def test_decode_matches_float_box_filter(pngs):
    for p in pngs[:3]:  # RGB/RGBA (alpha dropped, not composited)
        ref = _pil_box_reference(p, 64)
        out = native.decode_image(p, 64)
        np.testing.assert_allclose(out, ref, atol=1e-6)


def test_grayscale_promoted_to_rgb(pngs):
    out = native.decode_image(pngs[3], 64)
    assert out.shape == (64, 64, 3)
    np.testing.assert_allclose(out[..., 0], out[..., 1])


def test_pool_batch_decode(pngs):
    pool = native.DecoderPool(4)
    try:
        out = pool.decode_batch(pngs[:3] * 4, 64)
        assert out.shape == (12, 64, 64, 3)
        single = native.decode_image(pngs[0], 64)
        np.testing.assert_allclose(out[0], single)
        np.testing.assert_allclose(out[3], single)
    finally:
        pool.close()


def test_fractional_resize_finite(pngs):
    out = native.decode_image(pngs[0], 48)  # 128 -> 48, fractional boxes
    assert out.shape == (48, 48, 3)
    assert np.isfinite(out).all()
    assert out.min() >= -1.0 and out.max() <= 1.0


def test_error_codes(pngs):
    with pytest.raises(IOError):
        native.decode_image("/nonexistent/file.png", 64)
    # non-PNG file
    bad = os.path.join(os.path.dirname(pngs[0]), "bad.png")
    with open(bad, "wb") as f:
        f.write(b"not a png at all")
    with pytest.raises(IOError):
        native.decode_image(bad, 64)


def test_load_view_image_native_vs_pil_agree(pngs):
    for p in pngs[:3]:
        a = load_view_image(p, 64, use_native=True)
        b = load_view_image(p, 64, use_native=False)
        # PIL's box filter works in uint8 fixed point (and RGBA additionally
        # round-trips premultiplied uint8); native stays float throughout —
        # agreement within a few uint8 steps is the best either can claim.
        np.testing.assert_allclose(a, b, atol=4.5 / 255.0)


def test_srn_dataset_batch_decode_via_pool(tmp_path):
    """SRNDataset routes image decode through the shared native pool."""
    rng = np.random.RandomState(3)
    obj = tmp_path / "obj1"
    for d in ("rgb", "pose", "intrinsics"):
        (obj / d).mkdir(parents=True)
    for i in range(4):
        arr = rng.randint(0, 256, (128, 128, 3), np.uint8)
        Image.fromarray(arr, "RGB").save(obj / "rgb" / f"{i:06d}.png")
        pose = np.eye(4)
        pose[:3, 3] = rng.randn(3)
        np.savetxt(obj / "pose" / f"{i:06d}.txt", pose.reshape(1, 16))
        np.savetxt(obj / "intrinsics" / f"{i:06d}.txt",
                   np.eye(3).reshape(1, 9))

    from diff3d_tpu.data.srn import SRNDataset

    ds = SRNDataset("train", str(tmp_path), imgsize=64, train_fraction=1.0)
    s = ds.sample(0, np.random.default_rng(0))
    assert s["imgs"].shape == (2, 64, 64, 3)
    assert s["R"].shape == (2, 3, 3) and s["K"].shape == (3, 3)
    av = ds.all_views("obj1")
    assert av["imgs"].shape == (4, 64, 64, 3)
    # native and PIL paths agree on the decoded batch
    ds_pil = SRNDataset("train", str(tmp_path), imgsize=64,
                        train_fraction=1.0, use_native=False)
    av_pil = ds_pil.all_views("obj1")
    np.testing.assert_allclose(av["imgs"], av_pil["imgs"], atol=4.5 / 255)
