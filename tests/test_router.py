"""Fleet router: session affinity, placement, backpressure, rollout.

Two layers, mirroring the router's own split:

* **Routing-core unit tests** drive :class:`~diff3d_tpu.serving.router.Router`
  against fake replicas (the router duck-types the
  :class:`~diff3d_tpu.serving.fleet.Replica` surface and compiles
  nothing, so the placement/affinity/backpressure logic is testable
  with zero device work): rendezvous stability under churn, sticky vs
  sessionless failover, claim release, the typed rejection taxonomy,
  and the blue/green rollout state machine.
* **Fleet integration tests** run real 3-replica fleets on the tiny
  shallow config — bit-parity through the router, schedule-aware
  placement, HTTP 503 + ``Retry-After``, ``GET /fleet``, the chaos
  kill/failover path, and the acceptance e2e: 8 concurrent multi-view
  sessions with a mid-run params rollout, zero dropped requests and
  zero record migration (asserted against the per-replica session
  ledgers).  Threaded paths run under ``@pytest.mark.lock_witness``.
"""

import dataclasses
import threading
import time

import jax
import numpy as np
import pytest

from diff3d_tpu.config import ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.models import XUNet
from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.serving import (EngineDraining, FleetOverloaded,
                                FleetService, ProgramCache, QueueFullError,
                                ReplicaDraining, Router, SessionLost,
                                UnsupportedSchedule, ViewRequest)
from diff3d_tpu.testing.faults import FaultInjector, arm_replica
from diff3d_tpu.train.trainer import init_params


# ---------------------------------------------------------------------------
# Routing core against fake replicas (no device work)
# ---------------------------------------------------------------------------


class FakeReplica:
    """Just the Replica surface the router reads, fully scripted."""

    def __init__(self, name, depth=0, health="ok", schedules=None,
                 submit_exc=None):
        self.name = name
        self.health = health
        self._depth = depth
        self.schedules = schedules          # None = supports everything
        self.submit_exc = submit_exc        # raise this on submit
        self.submitted = []
        self.sessions = {}
        self.params_version = "v0"
        self.events = []                    # rollout choreography log
        self.drain_ok = True

    def depth(self):
        return self._depth

    def supports(self, kind=None, steps=None):
        return self.schedules is None or (kind, steps) in self.schedules

    def supported_schedules(self):
        return sorted(f"{k}:{s}" for k, s in (self.schedules or ()))

    def submit(self, req):
        if self.submit_exc is not None:
            raise self.submit_exc
        self.submitted.append(req)
        if req.session_id is not None:
            self.sessions[req.session_id] = (
                self.sessions.get(req.session_id, 0) + 1)
        return req

    def session_count(self, sid):
        return self.sessions.get(sid, 0)

    def session_records(self):
        return dict(self.sessions)

    def drain(self, timeout=None):
        self.events.append("drain")
        return self.drain_ok

    def resume(self):
        self.events.append("resume")

    def swap_params(self, params, version=None):
        self.events.append("swap")
        self.params_version = version or "swapped"
        return self.params_version

    def snapshot(self):
        return {"name": self.name, "health": self.health,
                "queue_depth": self._depth, "sessions": len(self.sessions)}


def _tiny_req(session_id=None, seed=0, sampler_kind=None, steps=None):
    views = {
        "imgs": np.zeros((2, 4, 4, 3), np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32), (2, 3, 3)).copy(),
        "T": np.zeros((2, 3), np.float32),
        "K": np.eye(3, dtype=np.float32),
    }
    return ViewRequest(views, seed=seed, n_views=2, session_id=session_id,
                       sampler_kind=sampler_kind, steps=steps)


def test_rendezvous_stability_under_churn():
    """Removing one replica only remaps the sessions it owned; every
    other session keeps its argmax (the affinity-under-churn contract,
    which a mod-N hash would violate wholesale)."""
    reps = [FakeReplica(f"r{i}") for i in range(5)]
    sids = [f"sess-{i}" for i in range(200)]
    before = {sid: Router.rendezvous_order(sid, reps)[0].name
              for sid in sids}
    survivors = [r for r in reps if r.name != "r2"]
    after = {sid: Router.rendezvous_order(sid, survivors)[0].name
             for sid in sids}
    assert any(v == "r2" for v in before.values())  # r2 owned some
    for sid in sids:
        if before[sid] != "r2":
            assert after[sid] == before[sid], f"{sid} remapped needlessly"


def test_session_affinity_survives_fleet_churn():
    """The affinity table, not the hash, is the source of truth: adding
    a replica (which WOULD win the rendezvous for some sessions) and
    killing an unrelated one never moves an established session."""
    reps = [FakeReplica("r0"), FakeReplica("r1"), FakeReplica("r2")]
    router = Router(reps, retry_after_s=0.5)
    router.submit(_tiny_req(session_id="sess-A", seed=1))
    owner = router.fleet_snapshot()["sessions"]["per_replica"]
    (owner_name,) = owner
    # Churn: a newcomer joins, an unrelated replica dies.
    router.add_replica(FakeReplica("r9"))
    for r in reps:
        if r.name != owner_name:
            r.health = "dead"
            break
    for seed in range(2, 6):
        router.submit(_tiny_req(session_id="sess-A", seed=seed))
    by_name = {r.name: r for r in router.replica_list()}
    assert by_name[owner_name].session_count("sess-A") == 5
    assert sum(r.session_count("sess-A")
               for r in router.replica_list()) == 5  # zero migration


def test_sessionless_least_loaded_and_tiebreak():
    reps = [FakeReplica("r0", depth=5), FakeReplica("r1", depth=0),
            FakeReplica("r2", depth=2), FakeReplica("r3", depth=0)]
    router = Router(reps)
    router.submit(_tiny_req(seed=7))
    assert len(reps[1].submitted) == 1      # depth 0, name-tiebreak r1<r3
    assert not reps[0].submitted and not reps[3].submitted


def test_sessionless_fails_over_down_the_order():
    full = QueueFullError("full")
    reps = [FakeReplica("r0", depth=0, submit_exc=full),
            FakeReplica("r1", depth=1, submit_exc=full),
            FakeReplica("r2", depth=2)]
    router = Router(reps)
    router.submit(_tiny_req(seed=8))
    assert len(reps[2].submitted) == 1
    assert router.metrics.counter("router_failover_total", "").value == 1
    # All full -> FleetOverloaded carrying retry_after_s.
    reps[2].submit_exc = EngineDraining("draining", retry_after_s=0.1)
    with pytest.raises(FleetOverloaded) as ei:
        router.submit(_tiny_req(seed=9))
    assert ei.value.retry_after_s == router.retry_after_s


def test_sticky_capacity_never_fails_over():
    """A session at its owner's capacity gets FleetOverloaded — the
    record is on that replica, so routing elsewhere is never correct."""
    reps = [FakeReplica("r0"), FakeReplica("r1")]
    router = Router(reps, retry_after_s=0.25)
    router.submit(_tiny_req(session_id="s", seed=1))
    owner = next(r for r in reps if r.submitted)
    other = next(r for r in reps if not r.submitted)
    owner.submit_exc = QueueFullError("full")
    with pytest.raises(FleetOverloaded) as ei:
        router.submit(_tiny_req(session_id="s", seed=2))
    assert ei.value.retry_after_s == 0.25
    assert not other.submitted               # no silent re-place
    owner.submit_exc = None
    router.submit(_tiny_req(session_id="s", seed=3))
    assert owner.session_count("s") == 2     # still the owner


def test_new_session_claim_released_on_capacity():
    """A first view rejected for capacity leaves no claim behind — the
    session re-places (to the same rendezvous owner) once capacity
    frees, instead of pinning to a replica that never served it."""
    reps = [FakeReplica("r0"), FakeReplica("r1"), FakeReplica("r2")]
    chosen = Router.rendezvous_order("sess-N", reps)[0]
    chosen.submit_exc = QueueFullError("full")
    router = Router(reps)
    with pytest.raises(FleetOverloaded):
        router.submit(_tiny_req(session_id="sess-N", seed=1))
    assert router.fleet_snapshot()["sessions"]["active"] == 0
    chosen.submit_exc = None
    router.submit(_tiny_req(session_id="sess-N", seed=1))
    assert chosen.session_count("sess-N") == 1


def test_sticky_draining_and_dead_rejections():
    reps = [FakeReplica("r0"), FakeReplica("r1")]
    router = Router(reps, retry_after_s=0.5)
    router.submit(_tiny_req(session_id="s", seed=1))
    owner = next(r for r in reps if r.submitted)
    owner.health = "draining"
    with pytest.raises(ReplicaDraining) as ei:
        router.submit(_tiny_req(session_id="s", seed=2))
    assert ei.value.replica == owner.name
    assert ei.value.retry_after_s == 0.5
    owner.health = "dead"
    with pytest.raises(SessionLost) as ei:
        router.submit(_tiny_req(session_id="s", seed=3))
    assert ei.value.replica == owner.name    # names the lost replica
    assert router.fleet_snapshot()["sessions"]["active"] == 0
    m = router.metrics
    assert m.counter("router_sessions_lost_total", "").value == 1
    assert m.counter("router_rejected_total", "").value == 2


def test_schedule_aware_placement_and_union():
    """Requests land only on replicas that compiled their schedule; a
    schedule nobody serves is rejected with the fleet-wide union."""
    reps = [FakeReplica("r0", schedules={("ancestral", 4)}, depth=0),
            FakeReplica("r1", schedules={("ancestral", 4), ("ddim", 2)},
                        depth=9)]
    router = Router(reps)
    router.submit(_tiny_req(seed=1, sampler_kind="ddim", steps=2))
    assert len(reps[1].submitted) == 1       # despite the higher depth
    with pytest.raises(UnsupportedSchedule) as ei:
        router.submit(_tiny_req(seed=2, sampler_kind="ddim", steps=7))
    assert "ddim:2" in ei.value.supported
    assert "ancestral:4" in ei.value.supported


def test_rollout_state_machine():
    """Drain -> swap -> resume per live replica; a drain timeout resumes
    un-swapped and fails the rollout; dead replicas are skipped; the
    rollout flag is single-flight."""
    good = FakeReplica("r0")
    stuck = FakeReplica("r1")
    stuck.drain_ok = False
    dead = FakeReplica("r2", health="dead")
    router = Router([good, stuck, dead])
    out = router.rollout(params=None, version="v1", drain_timeout_s=0.1)
    assert out["ok"] is False
    assert good.events == ["drain", "swap", "resume"]
    assert good.params_version == "v1"
    assert stuck.events == ["drain", "resume"]       # never swapped
    assert stuck.params_version == "v0"
    assert dead.events == []
    statuses = {s["replica"]: s["status"] for s in out["steps"]}
    assert statuses == {"r0": "swapped", "r1": "drain-timeout",
                        "r2": "skipped-dead"}
    assert router.fleet_snapshot()["rollout_active"] is False
    # Single-flight: a rollout observing the active flag is rejected.
    with router._lock:
        router._rollout_active = True
    with pytest.raises(RuntimeError):
        router.rollout(params=None, version="v2")
    with router._lock:
        router._rollout_active = False


# ---------------------------------------------------------------------------
# Real fleets on the tiny shallow config
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_env():
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    # Pre-compile the shapes fleet traffic launches; replicas share the
    # sampler's jit cache, so every fleet reuses these programs.
    pc = ProgramCache(sampler)
    gb = int(sampler.w.shape[0])
    for bucket, lanes in (((8, 8, 4), 1), ((8, 8, 4), 2)):
        pc.warmup(bucket, lanes, gb)
    return cfg, model, params, sampler


def _views(i, n_views=3, size=8):
    r = np.random.RandomState(100 + i)
    return {
        "imgs": r.randn(n_views, size, size, 3).astype(np.float32),
        "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                             (n_views, 3, 3)).copy(),
        "T": r.randn(n_views, 3).astype(np.float32),
        "K": np.array([[size * 1.2, 0, size / 2],
                       [0, size * 1.2, size / 2],
                       [0, 0, 1]], np.float32),
    }


def make_fleet(cfg, sampler, n=3, per_replica_extra=None, **over):
    serving = dict(port=0, max_batch=4, max_queue=8, max_wait_ms=20.0,
                   max_views=6, default_timeout_s=60.0,
                   step_retry_backoff_s=0.02, retry_after_s=0.1,
                   replicas=n, result_cache_entries=0)
    serving.update(over)
    cfg2 = dataclasses.replace(cfg, serving=ServingConfig(**serving))
    return FleetService.build(sampler, cfg2,
                              per_replica_extra=per_replica_extra,
                              params_version="v0")


def _wait_for(pred, timeout=30.0, poll=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


def _owner_of(svc, sid):
    per = svc.fleet_snapshot()["replicas"]
    owners = [n for n, snap in per.items()
              if svc.router.replica(n).session_count(sid)]
    assert len(owners) == 1, f"session {sid} on {owners}"
    return owners[0]


@pytest.mark.lock_witness
def test_router_results_bit_identical_to_direct(fleet_env, lock_witness):
    """Routing adds nothing to the math: a session view and a
    sessionless request through the 3-replica router are bit-equal to
    the sampler called directly."""
    cfg, model, params, sampler = fleet_env
    svc = make_fleet(cfg, sampler).start(serve_http=False)
    try:
        v = _views(0)
        a = svc.router.submit(ViewRequest(v, seed=11, n_views=3,
                                          session_id="obj-0"))
        b = svc.router.submit(ViewRequest(v, seed=11, n_views=3))
        direct = sampler.synthesize(v, jax.random.PRNGKey(11), max_views=3)
        np.testing.assert_array_equal(a.result(timeout=60), direct)
        np.testing.assert_array_equal(b.result(timeout=60), direct)
        assert _owner_of(svc, "obj-0")       # exactly one ledger entry
    finally:
        svc.stop()


@pytest.mark.lock_witness
def test_e2e_sessions_affinity_rollout_zero_drop(fleet_env, lock_witness):
    """Acceptance e2e: 3 replicas, 8 concurrent multi-view sessions,
    a mid-run blue/green rollout — every view of a session lands on its
    owning replica (zero migration, per-replica record counters), zero
    requests dropped (typed retryable rejections are retried by the
    client and all views complete), and every live replica finishes on
    the new params version."""
    cfg, model, params, sampler = fleet_env
    svc = make_fleet(cfg, sampler).start(serve_http=False)
    n_sessions, n_view_reqs = 8, 3
    completed, failures = [], []
    lock = threading.Lock()

    def run_session(si):
        sid = f"obj-{si}"
        for v in range(n_view_reqs):
            req = None
            for _ in range(200):             # client retry loop
                try:
                    req = svc.router.submit(
                        ViewRequest(_views(si * 10 + v), seed=si * 10 + v,
                                    n_views=3, session_id=sid))
                    break
                except RetryableError as e:
                    time.sleep(getattr(e, "retry_after_s", None) or 0.05)
            else:
                with lock:
                    failures.append(f"{sid}/v{v}: retries exhausted")
                return
            try:
                req.result(timeout=60)
                with lock:
                    completed.append((sid, v))
            except Exception as e:
                with lock:
                    failures.append(f"{sid}/v{v}: {type(e).__name__}: {e}")
                return

    try:
        threads = [threading.Thread(target=run_session, args=(i,))
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
        time.sleep(0.2)                      # sessions pin mid-flight
        out = svc.rollout(params, version="v1", drain_timeout_s=60.0)
        for t in threads:
            t.join(120)
        assert not failures, failures
        assert len(completed) == n_sessions * n_view_reqs  # zero dropped
        assert out["ok"] is True
        assert all(s["status"] == "swapped" for s in out["steps"])
        # Zero migration: each session's ledger lives on one replica and
        # counts every one of its views.
        ledgers = {r.name: r.session_records() for r in svc.replicas}
        for si in range(n_sessions):
            sid = f"obj-{si}"
            holders = [n for n, led in ledgers.items() if sid in led]
            assert len(holders) == 1, f"{sid} migrated across {holders}"
            assert ledgers[holders[0]][sid] == n_view_reqs
        assert {r.params_version for r in svc.replicas} == {"v1"}
        snap = svc.metrics_snapshot()
        assert snap["counters"]["router_requests_total"] >= (
            n_sessions * n_view_reqs)
        assert snap["counters"]["router_rollouts_total"] == 1
        assert snap["fleet"]["sessions"]["active"] == n_sessions
    finally:
        svc.stop()


@pytest.mark.lock_witness
def test_http_backpressure_503_retry_after_and_fleet_route(fleet_env,
                                                           lock_witness):
    """The HTTP surface of the fleet contract: a fully-draining fleet
    503s with a ``Retry-After`` header (typed ReplicaDraining), GET
    /fleet exposes topology + sessions, and the router counters ride
    GET /metrics."""
    import json
    import urllib.error
    import urllib.request

    cfg, model, params, sampler = fleet_env
    svc = make_fleet(cfg, sampler, n=2).start(serve_http=True)
    try:
        base = f"http://127.0.0.1:{svc.port}"
        payload = {"views": {k: v.tolist() for k, v in _views(3).items()},
                   "seed": 3, "n_views": 3, "block": False,
                   "session_id": "http-sess"}
        body = json.dumps(payload).encode()

        def post():
            return urllib.request.urlopen(urllib.request.Request(
                f"{base}/synthesize", data=body,
                headers={"Content-Type": "application/json"}), timeout=30)

        for rep in svc.replicas:
            assert rep.drain(timeout=10)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post()
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert "draining" in json.loads(ei.value.read())["error"]

        for rep in svc.replicas:
            rep.resume()
        with post() as resp:
            assert resp.status == 202
            rid = json.loads(resp.read())["id"]
        req = svc.get_request(rid)
        req.result(timeout=60)

        with urllib.request.urlopen(f"{base}/fleet", timeout=30) as resp:
            fleet = json.loads(resp.read())
        assert set(fleet["replicas"]) == {"r0", "r1"}
        assert fleet["sessions"]["active"] == 1
        owner = _owner_of(svc, "http-sess")
        assert fleet["sessions"]["per_replica"] == {owner: 1}

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert "router_requests_total" in text
        assert "router_rejected_total" in text
        for rep in svc.replicas:
            assert f"router_replica_depth_{rep.name}" in text
    finally:
        svc.stop()


def test_schedule_aware_routing_heterogeneous_fleet(fleet_env):
    """per-replica schedules: 2-step DDIM traffic lands on the one
    replica that compiled it (whatever the load), and a schedule nobody
    compiled is rejected with the fleet-wide union."""
    cfg, model, params, sampler = fleet_env
    student = Sampler(model, params, cfg, sampler_kind="ddim", steps=2)
    svc = make_fleet(cfg, sampler, n=3,
                     per_replica_extra={1: {("ddim", 2): student}})
    svc.start(serve_http=False)
    try:
        req = svc.router.submit(
            ViewRequest(_views(5), seed=5, n_views=3, session_id="distill",
                        sampler_kind="ddim", steps=2))
        req.result(timeout=120)              # one tiny 2-step compile
        assert _owner_of(svc, "distill") == "r1"
        with pytest.raises(UnsupportedSchedule) as ei:
            svc.router.submit(ViewRequest(_views(6), seed=6, n_views=3,
                                          sampler_kind="ddim", steps=7))
        assert "ddim:2" in ei.value.supported
        health = svc.health()
        assert "ddim:2" in health["supported_schedules"]
    finally:
        svc.stop()


@pytest.mark.chaos
@pytest.mark.lock_witness
def test_replica_kill_failover_and_session_lost(fleet_env, lock_witness):
    """Chaos: a replica dies mid-dispatch (seeded kill fault).  Its
    sticky sessions get a typed SessionLost NAMING the lost replica
    (never a hang, never a silent re-place); sessionless traffic fails
    over to the survivors and keeps completing."""
    cfg, model, params, sampler = fleet_env
    inj = FaultInjector(seed=0)
    svc = make_fleet(cfg, sampler).start(serve_http=False)
    try:
        sites = {rep.name: arm_replica(rep, inj) for rep in svc.replicas}
        # Pin a session and find its owner — that replica is the victim.
        first = svc.router.submit(ViewRequest(_views(7), seed=7, n_views=3,
                                              session_id="doomed"))
        first.result(timeout=60)
        victim = _owner_of(svc, "doomed")
        inj.add(sites[victim], kind="kill", first_n=1 << 30, max_fires=1)

        # The next sticky view triggers the kill mid-dispatch.
        dying = svc.router.submit(ViewRequest(_views(8), seed=8, n_views=3,
                                              session_id="doomed"))
        with pytest.raises(RetryableError):
            dying.result(timeout=60)
        _wait_for(lambda: svc.router.replica(victim).health == "dead",
                  what="victim death")

        with pytest.raises(SessionLost) as ei:
            svc.router.submit(ViewRequest(_views(9), seed=9, n_views=3,
                                          session_id="doomed"))
        assert ei.value.replica == victim
        assert ei.value.retry_after_s is not None

        ok = svc.router.submit(ViewRequest(_views(10), seed=10, n_views=3))
        ok.result(timeout=60)                # survivors still serve
        snap = svc.metrics_snapshot()
        assert snap["counters"]["router_sessions_lost_total"] == 1
        assert snap["counters"]["router_failover_total"] >= 1
        assert svc.health()["status"] == "ok"
        assert svc.health()["replicas"][victim] == "dead"
    finally:
        svc.stop()
