"""equivcheck (the StableHLO semantic-equivalence pillar), tested from
both sides like the other five: for every invariance the canonicalizer
promises, a pair of programs that must FINGERPRINT EQUAL and a mutation
that must NOT — on synthetic StableHLO for the rewrite rules, and on
real lowered programs for the end-to-end path.  Then the seeded
regressions the issue demands (a single-op mutation of the live
``step_many`` firing EQ601 with the divergent op named; a correct
scan-hoist certified and two broken ones refuted with EQ602), the
manifest round-trip + EQ605 + suppression grammar, the
``semantic_pin`` marker (incl. vacuous-pass protection via an
in-process sub-pytest), the ``tools/lint.py`` six-gate/--json
plumbing, the EQ604-vs-MC404 cross-pillar agreement gate, and the
repo-clean gate: the committed manifests under ``runs/equivcheck/``
must match what the current tree lowers.
"""

import importlib.util
import json
import os
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.analysis import equiv
from diff3d_tpu.analysis import equivcheck as eqc
from diff3d_tpu.analysis import membudgets as mb
from diff3d_tpu.analysis import memcheck as mc
from diff3d_tpu.analysis import shardcheck as sc
from diff3d_tpu.analysis.equivcheck import (EquivBudget, Suppression,
                                            check_report,
                                            check_report_against_dir,
                                            load_manifest,
                                            manifest_from_report,
                                            manifest_path, write_manifest)
from diff3d_tpu.analysis.pytest_plugin import EquivCheck

pytest_plugins = ["pytester"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _live(findings, rule=None):
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _module(body, sig="(%arg0: tensor<8x8xf32>, %arg1: tensor<8x8xf32>)"
                      " -> (tensor<8x8xf32>)"):
    return (f"module @jit_f {{\n  func.func public @main{sig} {{\n"
            + textwrap.indent(textwrap.dedent(body), "    ")
            + "  }\n}\n")


_BASE = _module("""\
    %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
    %1 = stablehlo.subtract %0, %arg1 : tensor<8x8xf32>
    %2 = stablehlo.multiply %1, %0 : tensor<8x8xf32>
    return %2 : tensor<8x8xf32>
""")


# ---------------------------------------------------------------------------
# Canonicalizer invariances on synthetic StableHLO
# ---------------------------------------------------------------------------


def test_alpha_renaming_is_invisible():
    renamed = _BASE.replace("%0", "%40").replace("%1", "%51") \
                   .replace("%2", "%62")
    a = equiv.canonicalize("p", _BASE)
    b = equiv.canonicalize("p", renamed)
    assert a.available and a.digest and a.digest == b.digest
    assert a.lines == b.lines
    # SSA names never leak into the canonical form.
    assert not any("%arg" in l or "%0" in l for l in a.lines)


def test_commutative_operands_sort_noncommutative_do_not():
    swapped = _BASE.replace("stablehlo.add %arg0, %arg1",
                            "stablehlo.add %arg1, %arg0")
    assert (equiv.canonicalize("p", _BASE).digest
            == equiv.canonicalize("p", swapped).digest)
    resub = _BASE.replace("stablehlo.subtract %0, %arg1",
                          "stablehlo.subtract %arg1, %0")
    assert (equiv.canonicalize("p", _BASE).digest
            != equiv.canonicalize("p", resub).digest)


def test_identity_reshape_and_convert_fold_away():
    padded = _module("""\
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.subtract %0, %arg1 : tensor<8x8xf32>
        %5 = stablehlo.reshape %1 : (tensor<8x8xf32>) -> tensor<8x8xf32>
        %6 = stablehlo.convert %5 : tensor<8x8xf32>
        %2 = stablehlo.multiply %6, %0 : tensor<8x8xf32>
        return %2 : tensor<8x8xf32>
    """)
    a = equiv.canonicalize("p", _BASE)
    b = equiv.canonicalize("p", padded)
    assert a.digest == b.digest and a.n_ops == b.n_ops == 3
    # A reshape that actually changes the type must NOT fold.
    real = padded.replace(
        "stablehlo.reshape %1 : (tensor<8x8xf32>) -> tensor<8x8xf32>",
        "stablehlo.reshape %1 : (tensor<8x8xf32>) -> tensor<64xf32>")
    assert equiv.canonicalize("p", real).digest != a.digest


def test_func_call_inlining_matches_handwritten_inline():
    outlined = textwrap.dedent("""\
        module @jit_f {
          func.func public @main(%arg0: tensor<8x8xf32>, %arg1: tensor<8x8xf32>) -> (tensor<8x8xf32>) {
            %0 = func.call @helper(%arg0, %arg1) : (tensor<8x8xf32>, tensor<8x8xf32>) -> tensor<8x8xf32>
            %1 = stablehlo.multiply %0, %0 : tensor<8x8xf32>
            return %1 : tensor<8x8xf32>
          }
          func.func private @helper(%arg0: tensor<8x8xf32>, %arg1: tensor<8x8xf32>) -> tensor<8x8xf32> {
            %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
            return %0 : tensor<8x8xf32>
          }
        }
    """)
    inline = _module("""\
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.multiply %0, %0 : tensor<8x8xf32>
        return %1 : tensor<8x8xf32>
    """)
    a = equiv.canonicalize("p", outlined)
    b = equiv.canonicalize("p", inline)
    assert a.digest == b.digest


def test_single_op_mutation_moves_digest_and_differ_names_it():
    mutated = _BASE.replace("stablehlo.subtract", "stablehlo.divide", 1)
    a = equiv.canonicalize("p", _BASE)
    b = equiv.canonicalize("p", mutated)
    assert a.digest != b.digest
    diff = equiv.structural_diff(a.lines, b.lines)
    assert diff is not None
    assert "first divergent op" in diff
    assert "subtract" in diff and "divide" in diff
    assert equiv.structural_diff(a.lines, list(a.lines)) is None


def test_duplicate_subcomputations_collapse_and_are_reported():
    dup = _module("""\
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.add %arg1, %arg0 : tensor<8x8xf32>
        %2 = stablehlo.multiply %0, %1 : tensor<8x8xf32>
        return %2 : tensor<8x8xf32>
    """)
    r = equiv.canonicalize("p", dup)
    # Value numbering is Merkle-style: the re-computed (commuted) add
    # collapses onto its first definition in the canonical form...
    assert r.n_ops == 2
    # ...and is reported as a CSE-duplicate group for EQ604.
    (g,) = r.duplicates
    assert g.op == "add" and g.count == 2
    assert g.redundant_flops == 64.0
    assert r.cse_duplicate_flops == 64.0


def test_dead_output_detection():
    dead = _module("""\
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.multiply %arg0, %arg0 : tensor<8x8xf32>
        return %0 : tensor<8x8xf32>
    """)
    r = equiv.canonicalize("p", dead)
    (d,) = r.dead_ops
    assert d.op == "multiply" and d.flops == 64.0
    assert not equiv.canonicalize("p", _BASE).dead_ops


def test_build_semantic_report_is_tolerant():
    r = equiv.build_semantic_report("broken", "not stablehlo at all")
    assert not r.available and r.error
    assert equiv.semantic_summary(r)["available"] is False


# ---------------------------------------------------------------------------
# The EQ rules against manifests (fire AND silent)
# ---------------------------------------------------------------------------


def test_eq601_fire_and_silent_names_divergent_op(tmp_path):
    d = str(tmp_path)
    a = equiv.canonicalize("p", _BASE)
    write_manifest(manifest_path("p", d), manifest_from_report(a))
    assert not _live(check_report_against_dir(a, d))      # silent
    b = equiv.canonicalize(
        "p", _BASE.replace("stablehlo.subtract", "stablehlo.divide", 1))
    (f,) = _live(check_report_against_dir(b, d), "EQ601")
    assert "fingerprint drifted" in f.message
    assert "divide" in f.message          # the divergent op is named
    assert "--update" in f.message


def test_eq601_quiet_when_report_unavailable(tmp_path):
    d = str(tmp_path)
    a = equiv.canonicalize("p", _BASE)
    write_manifest(manifest_path("p", d), manifest_from_report(a))
    ghost = equiv.SemanticReport(name="p", available=False)
    assert not _live(check_report_against_dir(ghost, d))


def test_eq603_and_eq604_fire_and_silent():
    r = equiv.canonicalize("p", _module("""\
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.add %arg1, %arg0 : tensor<8x8xf32>
        %2 = stablehlo.multiply %arg0, %arg0 : tensor<8x8xf32>
        %3 = stablehlo.subtract %0, %1 : tensor<8x8xf32>
        return %3 : tensor<8x8xf32>
    """))
    m = manifest_from_report(r)
    assert not _live(check_report(r, m, "m.json"))        # self-pin: silent
    m.budgets.dead_ops = 0
    m.budgets.duplicate_flops = 0.0
    (f3,) = _live(check_report(r, m, "m.json"), "EQ603")
    assert "dead computation" in f3.message and "multiply" in f3.message
    (f4,) = _live(check_report(r, m, "m.json"), "EQ604")
    assert "duplicate subcomputation" in f4.message
    assert "MC404" in f4.message


def test_suppressions_are_key_scoped_and_reason_mandatory(tmp_path):
    d = str(tmp_path)
    a = equiv.canonicalize("p", _BASE)
    b = equiv.canonicalize(
        "p", _BASE.replace("stablehlo.subtract", "stablehlo.divide", 1))
    path = manifest_path("p", d)

    write_manifest(path, manifest_from_report(
        a, [Suppression("EQ601", "digest", "planned refactor, reviewed")]))
    fs = check_report_against_dir(b, d)
    assert not _live(fs) and any(f.suppressed for f in fs)

    # The wrong key does not cover the digest finding.
    write_manifest(path, manifest_from_report(
        a, [Suppression("EQ601", "dead_ops", "reviewed")]))
    assert _live(check_report_against_dir(b, d), "EQ601")

    # Reasonless suppression: still suppresses, but EQ002 flags it.
    write_manifest(path, manifest_from_report(
        a, [Suppression("EQ601", "digest", None)]))
    fs = check_report_against_dir(b, d)
    assert not _live(fs, "EQ601")
    (w,) = _live(fs, "EQ002")
    assert w.severity == "warning" and "no reason" in w.message


def test_eq605_missing_and_unreadable_manifest(tmp_path):
    r = equiv.canonicalize("ghost", _BASE)
    (f,) = check_report_against_dir(r, str(tmp_path))
    assert f.rule == "EQ605" and "--update" in f.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        fh.write("{not json")
    (f2,) = check_report_against_dir(r, str(tmp_path))
    assert f2.rule == "EQ605" and "unreadable" in f2.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        json.dump({"version": 1, "tool": "memcheck"}, fh)
    (f3,) = check_report_against_dir(r, str(tmp_path))
    assert f3.rule == "EQ605"


# ---------------------------------------------------------------------------
# Manifest round-trip + update-preserves-suppressions
# ---------------------------------------------------------------------------


def test_manifest_round_trip(tmp_path):
    r = equiv.canonicalize("rt_prog", _BASE)
    m = manifest_from_report(
        r, [Suppression("EQ604", "*", "known fanout, reviewed")])
    path = manifest_path("rt_prog", str(tmp_path))
    write_manifest(path, m)
    loaded = load_manifest(path)
    assert loaded.program == "rt_prog"
    assert loaded.budgets == EquivBudget(
        digest=r.digest, n_ops=3, duplicate_flops=0.0, dead_ops=0)
    assert loaded.observed["lines"] == r.lines
    assert loaded.suppressions[0].reason == "known fanout, reviewed"
    assert not _live(check_report_against_dir(r, str(tmp_path)))


def test_update_preserves_suppressions(tmp_path, monkeypatch):
    import dataclasses
    import types

    d = str(tmp_path)
    supp = Suppression("EQ604", "duplicate_flops",
                       "threefry splits duplicate by construction")
    old = equiv.canonicalize("train_step", _BASE)
    write_manifest(manifest_path("train_step", d),
                   manifest_from_report(old, [supp]))
    new = equiv.canonicalize(
        "train_step",
        _BASE.replace("stablehlo.subtract", "stablehlo.divide", 1))
    monkeypatch.setitem(
        sc.REGISTRY, "train_step",
        dataclasses.replace(
            sc.REGISTRY["train_step"],
            build=lambda: types.SimpleNamespace(semantic=new)))
    eqc.update_manifests(["train_step"], d)
    loaded = load_manifest(manifest_path("train_step", d))
    assert loaded.suppressions == [supp]
    assert loaded.budgets.digest == new.digest


def test_semantic_report_for_tolerates_semanticless_builder(monkeypatch):
    import dataclasses
    import types

    monkeypatch.setitem(
        sc.REGISTRY, "train_step",
        dataclasses.replace(sc.REGISTRY["train_step"],
                            build=lambda: types.SimpleNamespace()))
    r = eqc.semantic_report_for("train_step")
    assert r.name == "train_step" and not r.available


# ---------------------------------------------------------------------------
# The scan-hoist verifier: certify the good hoist, refute the broken ones
# ---------------------------------------------------------------------------


def _orig_recomputes(c, xs):
    def body(carry, x):
        w = jnp.tanh(c) - 0.1 * c          # loop-invariant conditioning
        return carry + w * x, None
    out, _ = jax.lax.scan(body, jnp.zeros_like(c), xs)
    return out


def _hoist_good(c, xs):
    w = jnp.tanh(c) - 0.1 * c
    def body(carry, x):
        return carry + w * x, None
    out, _ = jax.lax.scan(body, jnp.zeros_like(c), xs)
    return out


def _hoist_swapped_operands(c, xs):
    w = 0.1 * c - jnp.tanh(c)              # non-commutative order flipped
    def body(carry, x):
        return carry + w * x, None
    out, _ = jax.lax.scan(body, jnp.zeros_like(c), xs)
    return out


def _hoist_dropped_dependency(c, xs):
    w = jnp.tanh(c)                        # the -0.1*c term vanished
    def body(carry, x):
        return carry + w * x, None
    out, _ = jax.lax.scan(body, jnp.zeros_like(c), xs)
    return out


_HOIST_ARGS = (np.linspace(-1.0, 1.0, 8, dtype=np.float32),
               np.ones((5, 8), dtype=np.float32))


def test_verify_hoist_certifies_the_correct_hoist():
    v = equiv.verify_hoist(_orig_recomputes, _hoist_good, _HOIST_ARGS,
                           name="cond_hoist")
    assert v.equivalent, "\n".join(f.render() for f in v.findings)
    assert v.matched >= 2 and not v.unmatched
    assert v.trials == 2 and v.max_abs_diff <= 1e-5


def test_verify_hoist_refutes_swapped_operand_order():
    v = equiv.verify_hoist(_orig_recomputes, _hoist_swapped_operands,
                           _HOIST_ARGS, name="cond_hoist")
    assert not v.equivalent
    assert all(f.rule == "EQ602" for f in v.findings)
    # Structural half: the flipped subtract has no in-loop ancestor.
    assert v.unmatched
    assert any("no ancestor" in f.message for f in v.findings)


def test_verify_hoist_refutes_dropped_dependency():
    v = equiv.verify_hoist(_orig_recomputes, _hoist_dropped_dependency,
                           _HOIST_ARGS, name="cond_hoist")
    assert not v.equivalent
    # The surviving tanh DOES have an ancestor — only the concrete
    # cross-check can catch a dropped term.
    assert not v.unmatched
    assert any(f.rule == "EQ602" and "cross-check diverged" in f.message
               for f in v.findings)


def test_verify_hoist_flags_unanalyzable_program():
    class _Fake:
        def lower(self, *a):
            return self
        def as_text(self):
            return "not stablehlo"
        def __call__(self, *a):
            return jnp.zeros(())
    v = equiv.verify_hoist(_Fake(), _Fake(), (np.float32(0.0),))
    assert not v.equivalent
    assert any("unverifiable" in f.message for f in v.findings)


def test_randomized_args_keep_integer_schedule_values():
    rng = np.random.default_rng(0)
    f, i = equiv._randomized_args(
        (np.ones(4, np.float32), np.arange(3, dtype=np.int32)), rng)
    assert not np.array_equal(f, np.ones(4, np.float32))
    np.testing.assert_array_equal(i, np.arange(3, dtype=np.int32))


# ---------------------------------------------------------------------------
# The semantic_pin marker
# ---------------------------------------------------------------------------


@pytest.mark.semantic_pin
def test_semantic_pin_marker_e2e(equiv_check, tmp_path):
    equiv_check.manifest_dir = str(tmp_path)
    r = equiv_check.analyze(
        "marker_prog",
        jax.jit(lambda x, y: jnp.tanh(x) * y).lower(_sds((4, 4)),
                                                    _sds((4, 4))))
    assert r.available and r.digest      # the pin is non-vacuous
    write_manifest(manifest_path("marker_prog", str(tmp_path)),
                   manifest_from_report(r))


def test_equiv_check_accepts_text_and_reports_findings(tmp_path):
    check = EquivCheck()
    check.manifest_dir = str(tmp_path)
    r = check.analyze("txt_prog", _BASE)
    assert r.digest
    (f,) = check.findings()
    assert f.rule == "EQ605"             # nothing committed yet
    write_manifest(manifest_path("txt_prog", str(tmp_path)),
                   manifest_from_report(r))
    assert not check.findings()


def test_semantic_pin_vacuous_pass_protection(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.semantic_pin
        def test_never_registers(equiv_check):
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*vacuously*"])


def test_semantic_pin_marker_rejects_bad_usage(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.semantic_pin("step_many")
        def test_takes_no_args(equiv_check):
            pass

        @pytest.mark.semantic_pin
        def test_no_fixture():
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*takes no*"])
    result.stdout.fnmatch_lines(["*requires the equiv_check fixture*"])


# ---------------------------------------------------------------------------
# CLI + tools/lint.py six-gate plumbing
# ---------------------------------------------------------------------------


def test_cli_list_and_bad_invocation(capsys):
    assert eqc.main(["--list"]) == 0
    out = capsys.readouterr().out
    for nm in sc.REGISTRY:
        assert nm in out
    assert eqc.main(["--program", "train_step", "--programs-tier1"]) == 2


def _load_lint_script():
    path = os.path.join(_REPO_ROOT, "tools", "lint.py")
    spec = importlib.util.spec_from_file_location("_lint_gate_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_runs_six_gates_equivcheck_last():
    lint_script = _load_lint_script()
    names = [name for name, _, _ in lint_script._GATES]
    assert names == ["graftlint", "lockcheck", "shardcheck", "memcheck",
                     "rngcheck", "equivcheck"]
    assert lint_script._ONLY_TO_GATE["--equiv-only"] == "equivcheck"
    assert set(lint_script._ONLY_FLAGS) == set(lint_script._ONLY_TO_GATE)


def test_lint_equiv_only_passes_arguments_through(monkeypatch):
    lint_script = _load_lint_script()
    calls = []

    def fake_gate_main(module):
        def run(argv):
            calls.append((module, list(argv)))
            return 0
        return run

    monkeypatch.setattr(lint_script, "_gate_main", fake_gate_main)
    monkeypatch.setattr(sys, "argv", ["lint.py", "--equiv-only", "--list"])
    assert lint_script.main() == 0
    assert calls == [("diff3d_tpu.analysis.equivcheck", ["--list"])]


def test_lint_json_summary_aggregates_all_gates(monkeypatch, capsys):
    lint_script = _load_lint_script()
    rcs = {"memcheck": 1}

    def fake_gate_main(module):
        name = module.rsplit(".", 1)[-1]
        name = {"lint": "graftlint"}.get(name, name)

        def run(argv):
            assert argv[-2:] == ["--format", "json"]
            print(json.dumps({"unsuppressed": rcs.get(name, 0),
                              "suppressed": 2}))
            return rcs.get(name, 0)
        return run

    monkeypatch.setattr(lint_script, "_gate_main", fake_gate_main)
    monkeypatch.setattr(sys, "argv", ["lint.py", "--json"])
    assert lint_script.main() == 1       # exit = max over gates
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["gates"]) == {"graftlint", "lockcheck", "shardcheck",
                                 "memcheck", "rngcheck", "equivcheck"}
    assert doc["exit"] == 1
    assert doc["gates"]["memcheck"]["unsuppressed"] == 1
    assert doc["gates"]["equivcheck"] == {
        "exit": 0, "unsuppressed": 0, "suppressed": 2}


def test_lint_json_is_exclusive_with_only_flags(monkeypatch, capsys):
    lint_script = _load_lint_script()
    monkeypatch.setattr(sys, "argv",
                        ["lint.py", "--json", "--equiv-only"])
    assert lint_script.main() == 2
    monkeypatch.setattr(sys, "argv", ["lint.py", "--json", "--list"])
    assert lint_script.main() == 2


# ---------------------------------------------------------------------------
# The tier-1 gate: committed manifests match what the tree lowers
# ---------------------------------------------------------------------------


def test_repo_manifests_clean_tier1():
    """The equivcheck analogue of ``test_repo_lints_clean``: lowering
    the REAL tier-1 programs and diffing their semantic fingerprints
    against the committed ``runs/equivcheck/`` manifests must come back
    clean.  (The builds come from shardcheck's in-process report cache,
    so this shares one lower+compile with the other pillars' gates.)"""
    d = eqc.default_manifest_dir(_REPO_ROOT)
    findings = eqc.check_programs(list(sc.TIER1_PROGRAMS), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)


def test_repo_manifest_pins_exact_tier1():
    """observed == recomputed, not merely within budget: a fingerprint
    that silently moves together with a hand-edited manifest would
    leave the gate green — exact equality makes every drift a visible
    diff that either re-pins via ``equivcheck --update`` or reverts."""
    d = eqc.default_manifest_dir(_REPO_ROOT)
    for nm in sc.TIER1_PROGRAMS:
        committed = load_manifest(manifest_path(nm, d))
        sem = eqc.semantic_report_for(nm)
        assert committed.budgets.digest == sem.digest, (
            f"{nm}: committed fingerprint is stale — run "
            f"'python tools/equivcheck.py --update' and review the diff")
        assert committed.observed.get("lines") == sem.lines


def test_seeded_mutation_of_live_step_many_fires_eq601():
    """The acceptance regression: a single-op mutation of the REAL
    step_many StableHLO must flip the fingerprint and EQ601 must name
    the divergent op against the committed manifest."""
    sampler, _env = sc._sampler()
    txt = sampler.lower_step_many(lanes=sc.MESH_DEVICES,
                                  capacity=4).as_text()
    d = eqc.default_manifest_dir(_REPO_ROOT)
    committed = load_manifest(manifest_path("step_many", d))
    base = equiv.canonicalize("step_many", txt)
    assert base.digest == committed.budgets.digest   # identity guard
    assert "stablehlo.subtract" in txt
    mutated = equiv.canonicalize(
        "step_many",
        txt.replace("stablehlo.subtract", "stablehlo.divide", 1))
    hits = _live(check_report_against_dir(mutated, d), "EQ601")
    assert hits, "mutated step_many did not trip EQ601"
    assert "first divergent op" in hits[0].message
    assert "divide" in hits[0].message


def test_eq604_agrees_with_memchecks_mc404_pin_tier1():
    """Cross-pillar agreement (the issue's satellite 4): equivcheck's
    static loop-invariant estimate for step_many must agree with the
    committed memcheck MC404 pin — two independent walks over the same
    lowering.  The cam-dirs conditioning hoist collapsed both from
    ~154 kFLOP/step to residual index bookkeeping; in that hoist-clean
    regime the two walkers disagree on which <250-FLOP scraps count, so
    agreement means BOTH sit under the noise floor rather than matching
    to 25%.  (The historical ~1.8 GFLOP figure was a shared parser
    artifact, fixed by parsing generic-syntax anonymous regions.)"""
    _NOISE_FLOOR = 1000.0           # residual bookkeeping, not a dup
    sem = eqc.semantic_report_for("step_many")
    md = mc.default_manifest_dir(_REPO_ROOT)
    pin = mb.load_manifest(
        mb.manifest_path("step_many", md)).budgets.hoistable_flops_per_step
    if pin >= _NOISE_FLOOR or sem.hoistable_flops_per_step >= _NOISE_FLOOR:
        assert sem.hoistable_flops_per_step == pytest.approx(pin, rel=0.25)
    else:
        assert 0 <= pin < _NOISE_FLOOR
        assert 0 <= sem.hoistable_flops_per_step < _NOISE_FLOOR
    # The static duplicate ceiling subsumes the per-iteration recompute.
    assert sem.duplicate_flops >= sem.hoistable_flops_per_step


def test_manifests_are_committed_for_all_registered_programs():
    d = eqc.default_manifest_dir(_REPO_ROOT)
    for nm in sc.REGISTRY:
        assert os.path.exists(manifest_path(nm, d)), (
            f"missing committed equivcheck manifest for {nm}; run "
            f"'python tools/equivcheck.py --update --program {nm}'")


@pytest.mark.slow
def test_repo_manifests_clean_full_sweep():
    """All five registered programs (adds distill, DDIM, serving
    warmup) — the full manifest sweep the CLI runs."""
    d = eqc.default_manifest_dir(_REPO_ROOT)
    findings = eqc.check_programs(sorted(sc.REGISTRY), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)
