"""Torch-composed X-UNet for WHOLE-MODEL converted-checkpoint parity tests.

Built from raw torch primitives following the reference's documented
semantics (SURVEY.md §2.1; reference ``xunet.py:355-536``) with two
deliberate differences: ray generation is INJECTED (the reference's visu3d
dependency is not in this image — callers precompute ``(pos, dir)`` with
:func:`diff3d_tpu.geometry.pinhole_rays`, which has its own visu3d golden
tests), and everything is config-driven off
:class:`diff3d_tpu.config.ModelConfig` so tiny test configs exercise the
full structure.  Attribute names are chosen so ``state_dict()`` produces
exactly the reference's checkpoint key scheme (the contract
:mod:`diff3d_tpu.convert.torch_ckpt` documents and consumes).

Test-only code: NOT part of the framework.
"""

from __future__ import annotations

import numpy as np
import torch
import torch.nn.functional as F
from torch import nn


def _gn_groups(C: int, preferred: int = 32) -> int:
    g = min(preferred, C)
    while C % g:
        g -= 1
    return g


def posenc_ddpm(t: torch.Tensor, emb_ch: int,
                max_time: float = 1000.0) -> torch.Tensor:
    t = t * (1000.0 / max_time)
    half = emb_ch // 2
    freq = torch.exp(torch.arange(half, dtype=t.dtype)
                     * -(np.log(10000.0) / (half - 1)))
    emb = t[..., None] * freq
    return torch.cat([torch.sin(emb), torch.cos(emb)], -1)


def posenc_nerf(x: torch.Tensor, min_deg: int, max_deg: int) -> torch.Tensor:
    scales = torch.tensor([2.0 ** i for i in range(min_deg, max_deg)],
                          dtype=x.dtype)
    xb = (x[..., None, :] * scales[:, None]).reshape(*x.shape[:-1], -1)
    emb = torch.sin(torch.cat([xb, xb + np.pi / 2.0], -1))
    return torch.cat([x, emb], -1)


class _GN(nn.Module):
    """Reference wraps nn.GroupNorm as ``.gn`` (xunet.py:66)."""

    def __init__(self, C: int):
        super().__init__()
        self.gn = nn.GroupNorm(_gn_groups(C), C)

    def forward(self, x):                      # [N, C, H, W]
        return self.gn(x)


class _FiLM(nn.Module):
    def __init__(self, emb_ch: int, C: int):
        super().__init__()
        self.dense = nn.Linear(emb_ch, 2 * C)

    def forward(self, h, emb):                 # [N,C,h,w], [N,E,h,w]
        e = F.silu(emb).permute(0, 2, 3, 1)
        scale, shift = self.dense(e).chunk(2, -1)
        return (h * (1 + scale.permute(0, 3, 1, 2))
                + shift.permute(0, 3, 1, 2))


class TResnetBlock(nn.Module):
    def __init__(self, cin: int, cout: int, emb_ch: int, resample=None):
        super().__init__()
        self.groupnorm0 = _GN(cin)
        self.conv1 = nn.Conv2d(cin, cout, 3, padding=1)
        self.groupnorm1 = _GN(cout)
        self.film = _FiLM(emb_ch, cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            # reference names the 1x1 skip projection `dense` (xunet.py:129)
            self.dense = nn.Conv2d(cin, cout, 1)
        self.resample = resample

    def forward(self, x, emb):                 # folded [B*F, C, h, w]
        h = self.conv1(F.silu(self.groupnorm0(x)))
        h = self.film(self.groupnorm1(h), emb)
        h = self.conv2(h)
        skip = self.dense(x) if hasattr(self, "dense") else x
        out = (h + skip) / np.sqrt(2.0)
        if self.resample == "down":
            out = F.avg_pool2d(out, 2)
        elif self.resample == "up":
            out = F.interpolate(out, scale_factor=2, mode="nearest")
        return out


class TAttnLayer(nn.Module):
    def __init__(self, C: int, heads: int):
        super().__init__()
        self.attn = nn.MultiheadAttention(C, heads, batch_first=True)

    def forward(self, q, kv):
        out, _ = self.attn(q, kv, kv, need_weights=False)
        return out


class TAttnBlock(nn.Module):
    def __init__(self, C: int, heads: int, attn_type: str):
        super().__init__()
        self.groupnorm = _GN(C)
        self.attn_layer = TAttnLayer(C, heads)   # shared by both frames
        # zero-init 1x1 out conv is `linear` (xunet.py:190)
        self.linear = nn.Conv2d(C, C, 1)
        self.attn_type = attn_type

    def forward(self, x):                       # [B, F=2, C, H, W]
        B, Fr, C, H, W = x.shape
        h = self.groupnorm(x.reshape(B * Fr, C, H, W))
        tok = h.reshape(B, Fr, C, H * W).permute(0, 1, 3, 2)  # [B,F,HW,C]
        if self.attn_type == "self":
            outs = [self.attn_layer(tok[:, f], tok[:, f])
                    for f in range(Fr)]
        else:                                   # frame0 <-> frame1 swap
            outs = [self.attn_layer(tok[:, f], tok[:, 1 - f])
                    for f in range(Fr)]
        o = torch.stack(outs, 1).permute(0, 1, 3, 2).reshape(
            B * Fr, C, H, W)
        o = self.linear(o).reshape(B, Fr, C, H, W)
        return (o + x) / np.sqrt(2.0)


class TXUNetBlock(nn.Module):
    def __init__(self, cin: int, cout: int, emb_ch: int, heads: int,
                 use_attn: bool):
        super().__init__()
        self.resnetblock = TResnetBlock(cin, cout, emb_ch)
        if use_attn:
            self.attnblock_self = TAttnBlock(cout, heads, "self")
            self.attnblock_cross = TAttnBlock(cout, heads, "cross")

    def forward(self, x, emb):                  # [B,F,C,h,w], [B,F,E,h,w]
        B, Fr = x.shape[:2]
        h = self.resnetblock(x.reshape(B * Fr, *x.shape[2:]),
                             emb.reshape(B * Fr, *emb.shape[2:]))
        h = h.reshape(B, Fr, *h.shape[1:])
        if hasattr(self, "attnblock_self"):
            h = self.attnblock_self(h)
            h = self.attnblock_cross(h)
        return h


class TConditioningProcessor(nn.Module):
    """Reference xunet.py:259-352 with (pos, dir) rays injected."""

    D = 144                                     # 93 + 51 (xunet.py:317-320)

    def __init__(self, emb_ch: int, H: int, W: int, num_resolutions: int):
        super().__init__()
        self.emb_ch = emb_ch
        self.logsnr_emb_emb = nn.Sequential(
            nn.Linear(emb_ch, emb_ch), nn.SiLU(),
            nn.Linear(emb_ch, emb_ch))
        D = self.D
        self.pos_emb = nn.Parameter(torch.randn(D, H, W) / np.sqrt(D))
        self.first_emb = nn.Parameter(
            torch.randn(1, 1, D, 1, 1) / np.sqrt(D))
        self.other_emb = nn.Parameter(
            torch.randn(1, 1, D, 1, 1) / np.sqrt(D))
        self.convs = nn.ModuleList([
            nn.Conv2d(D, emb_ch, 3, stride=2 ** i, padding=1)
            for i in range(num_resolutions)])

    def forward(self, logsnr, rays_pos, rays_dir, cond_mask):
        logsnr = torch.clip(logsnr, -20, 20)
        logsnr_emb = self.logsnr_emb_emb(
            posenc_ddpm(logsnr, emb_ch=self.emb_ch, max_time=1.0))

        pose_emb = torch.cat([posenc_nerf(rays_pos, 0, 15),
                              posenc_nerf(rays_dir, 0, 8)],
                             -1)                # [B, F, H, W, 144]
        pose_emb = torch.where(cond_mask[:, None, None, None, None],
                               pose_emb, torch.zeros_like(pose_emb))
        pose_emb = pose_emb.permute(0, 1, 4, 2, 3)       # b f c h w
        pose_emb = pose_emb + self.pos_emb[None, None]
        pose_emb = torch.cat([self.first_emb, self.other_emb],
                             dim=1) + pose_emb
        B, Fr = pose_emb.shape[:2]
        pose_embs = []
        for conv in self.convs:
            lvl = conv(pose_emb.reshape(B * Fr, *pose_emb.shape[2:]))
            pose_embs.append(lvl.reshape(B, Fr, *lvl.shape[1:]))
        return logsnr_emb, pose_embs


class TXUNet(nn.Module):
    """Full X-UNet from torch primitives, keyed like reference checkpoints."""

    def __init__(self, cfg):                    # diff3d_tpu ModelConfig
        super().__init__()
        self.cfg = cfg
        num_res = cfg.num_resolutions
        dims = [cfg.ch * m for m in cfg.ch_mult]
        E, heads, nrb = cfg.emb_ch, cfg.attn_heads, cfg.num_res_blocks

        self.conditioningprocessor = TConditioningProcessor(
            E, cfg.H, cfg.W, num_res)
        self.conv = nn.Conv2d(3, cfg.ch, 3, padding=1)

        skip_ch = [cfg.ch]
        cur = cfg.ch
        down = []
        for L in range(num_res):
            level = nn.ModuleList()
            for _ in range(nrb):
                level.append(TXUNetBlock(cur, dims[L], E, heads,
                                         L in cfg.attn_levels))
                cur = dims[L]
                skip_ch.append(cur)
            if L != num_res - 1:
                level.append(TResnetBlock(cur, dims[L], E,
                                          resample="down"))
                skip_ch.append(dims[L])
            down.append(level)
        self.xunetblocks = nn.ModuleList(down)

        self.middle = TXUNetBlock(cur, dims[-1], E, heads,
                                  num_res in cfg.attn_levels)
        cur = dims[-1]

        self.upsample = nn.ModuleDict()
        for L in reversed(range(num_res)):
            level = nn.ModuleList()
            for _ in range(nrb + 1):
                level.append(TXUNetBlock(cur + skip_ch.pop(), dims[L], E,
                                         heads, L in cfg.attn_levels))
                cur = dims[L]
            if L != 0:
                level.append(TResnetBlock(cur, dims[L], E, resample="up"))
            self.upsample[str(L)] = level
        assert not skip_ch

        self.lastgn = _GN(dims[0])
        self.lastconv = nn.Conv2d(dims[0], 3, 3, padding=1)

    def forward(self, batch, rays_pos, rays_dir, cond_mask):
        cfg = self.cfg
        num_res = cfg.num_resolutions
        nrb = cfg.num_res_blocks
        logsnr_emb, pose_embs = self.conditioningprocessor(
            batch["logsnr"], rays_pos, rays_dir, cond_mask)

        def level_emb(i):
            return logsnr_emb[:, :, :, None, None] + pose_embs[i]

        h = torch.stack([batch["x"], batch["z"]], 1)     # [B,2,3,H,W]
        B, Fr = h.shape[:2]
        h = self.conv(h.reshape(B * Fr, *h.shape[2:]))
        h = h.reshape(B, Fr, *h.shape[1:])

        def fold_res(mod, h, emb):
            out = mod(h.reshape(B * Fr, *h.shape[2:]),
                      emb.reshape(B * Fr, *emb.shape[2:]))
            return out.reshape(B, Fr, *out.shape[1:])

        hs = [h]
        for L in range(num_res):
            emb = level_emb(L)
            for i, mod in enumerate(self.xunetblocks[L]):
                if i < nrb:
                    h = mod(h, emb)
                else:                            # trailing down-Resnet
                    h = fold_res(mod, h, emb)
                hs.append(h)

        h = self.middle(h, level_emb(num_res - 1))

        for L in reversed(range(num_res)):
            emb = level_emb(L)
            for i, mod in enumerate(self.upsample[str(L)]):
                if i <= nrb:
                    h = mod(torch.cat([h, hs.pop()], dim=2), emb)
                else:                            # trailing up-Resnet
                    h = fold_res(mod, h, emb)
        assert not hs

        h = F.silu(self.lastgn(h.reshape(B * Fr, *h.shape[2:])))
        h = self.lastconv(h).reshape(B, Fr, 3, cfg.H, cfg.W)
        return h[:, 1]
