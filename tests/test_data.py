import os
import pickle
import random

import numpy as np
import pytest

from diff3d_tpu.data import (InfiniteLoader, SRNDataset, SyntheticDataset,
                             build_index, prefetch_to_device, split_ids)


def _write_fake_srn(root, num_objects=4, num_views=3, size=8):
    """Tiny on-disk SRN tree: <obj>/rgb/*.png + pose/*.txt + intrinsics/*.txt."""
    from PIL import Image

    rng = np.random.default_rng(0)
    for o in range(num_objects):
        obj = os.path.join(root, f"obj{o:02d}")
        for sub in ("rgb", "pose", "intrinsics"):
            os.makedirs(os.path.join(obj, sub), exist_ok=True)
        for v in range(num_views):
            name = f"{v:06d}"
            img = Image.fromarray(
                rng.integers(0, 255, (size, size, 4), dtype=np.uint8).astype(
                    np.uint8), "RGBA")
            img.save(os.path.join(obj, "rgb", name + ".png"))
            pose = np.eye(4)
            pose[:3, 3] = rng.normal(size=3)
            np.savetxt(os.path.join(obj, "pose", name + ".txt"),
                       pose.reshape(1, 16))
            K = np.array([[10.0, 0, 4], [0, 10.0, 4], [0, 0, 1]])
            np.savetxt(os.path.join(obj, "intrinsics", name + ".txt"),
                       K.reshape(1, 9))


def test_build_index_glob_and_pickle_roundtrip(tmp_path):
    _write_fake_srn(tmp_path)
    pkl = str(tmp_path / "cars.pickle")
    idx = build_index(str(tmp_path), pkl, save=True)
    assert len(idx) == 4 and all(len(v) == 3 for v in idx.values())
    # second call loads the pickle (reference format: id -> png names)
    with open(pkl, "rb") as f:
        assert pickle.load(f) == idx
    assert build_index(str(tmp_path), pkl) == idx


def test_split_ids_matches_reference_semantics():
    ids = [f"id{i}" for i in range(20)]
    train = split_ids(ids, "train", seed=0)
    val = split_ids(ids, "val", seed=0)
    assert len(train) == 18 and len(val) == 2
    assert set(train) | set(val) == set(ids)
    assert not set(train) & set(val)
    # exact reference algorithm: random.seed(0); shuffle(sorted_ids)
    expect = sorted(ids)
    random.seed(0)
    random.shuffle(expect)
    assert train == expect[:18] and val == expect[18:]


def test_srn_dataset_sample_contract(tmp_path):
    _write_fake_srn(tmp_path)
    ds = SRNDataset("train", str(tmp_path), imgsize=8)
    s = ds.sample(0, np.random.default_rng(0))
    assert s["imgs"].shape == (2, 8, 8, 3)
    assert s["imgs"].dtype == np.float32
    assert s["imgs"].min() >= -1.0 and s["imgs"].max() <= 1.0
    assert s["R"].shape == (2, 3, 3) and s["T"].shape == (2, 3)
    assert s["K"].shape == (3, 3)
    np.testing.assert_allclose(s["K"][0, 0], 10.0)
    # all_views loads every view
    av = ds.all_views(ds.ids[0])
    assert av["imgs"].shape == (3, 8, 8, 3)


def test_srn_dataset_resize(tmp_path):
    _write_fake_srn(tmp_path, size=8)
    ds = SRNDataset("train", str(tmp_path), imgsize=4)
    assert ds.sample(0, np.random.default_rng(0))["imgs"].shape == (2, 4, 4, 3)


def test_synthetic_dataset_contract():
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    s = ds.sample(1, np.random.default_rng(0))
    assert s["imgs"].shape == (2, 8, 8, 3)
    assert s["R"].shape == (2, 3, 3)
    # rotations are orthonormal
    for R in s["R"]:
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-5)
    av = ds.all_views(0)
    assert av["imgs"].shape == (5, 8, 8, 3)


def test_infinite_loader_batches_and_determinism():
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    a = InfiniteLoader(ds, batch_size=4, seed=1, num_workers=2)
    b = InfiniteLoader(ds, batch_size=4, seed=1, num_workers=0)
    ba, bb = next(a), next(b)
    assert ba["imgs"].shape == (4, 2, 8, 8, 3)
    assert ba["K"].shape == (4, 3, 3)
    # same (seed, step, host) -> identical batch regardless of worker count
    np.testing.assert_array_equal(ba["imgs"], bb["imgs"])
    # next step differs
    assert not np.array_equal(next(a)["imgs"], ba["imgs"])


def test_infinite_loader_host_sharding_disjoint_streams():
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    h0 = next(InfiniteLoader(ds, 4, seed=1, host_id=0, num_hosts=2,
                             num_workers=0))
    h1 = next(InfiniteLoader(ds, 4, seed=1, host_id=1, num_hosts=2,
                             num_workers=0))
    assert not np.array_equal(h0["imgs"], h1["imgs"])


def test_infinite_loader_global_stream_invariant_to_host_count():
    """The elasticity determinism rule: for a fixed global batch size,
    the concatenation of all hosts' batches at a step is identical for
    any host count — so a re-mesh (grow or shrink) resumes the same
    global stream without replaying or skipping examples."""
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    G = 8
    for mode in ("iid", "permute"):
        ref = InfiniteLoader(ds, G, seed=3, num_workers=0,
                             sample_mode=mode)
        refs = [next(ref) for _ in range(3)]
        for H in (2, 4):
            loaders = [InfiniteLoader(ds, G // H, seed=3, host_id=h,
                                      num_hosts=H, num_workers=0,
                                      sample_mode=mode)
                       for h in range(H)]
            for step in range(3):
                parts = [next(ld) for ld in loaders]
                for k in ("imgs", "R", "T", "K"):
                    np.testing.assert_array_equal(
                        np.concatenate([p[k] for p in parts]),
                        refs[step][k],
                        err_msg=f"mode={mode} hosts={H} step={step} {k}")


def test_infinite_loader_resume_replays_exact_stream():
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    fresh = InfiniteLoader(ds, 2, seed=7, num_workers=0)
    first, second = next(fresh), next(fresh)
    resumed = InfiniteLoader(ds, 2, seed=7, num_workers=0, start_step=1)
    np.testing.assert_array_equal(next(resumed)["imgs"], second["imgs"])


def test_scenes_dataset_rays_match_model_geometry():
    """The renderer's numpy rays must equal geometry.pinhole_rays — the
    rendered images and the model's pose conditioning share one camera
    convention, or the 3D task is unlearnable."""
    import jax.numpy as jnp

    from diff3d_tpu.data.synthetic import SyntheticScenesDataset, _rays_np
    from diff3d_tpu.geometry import pinhole_rays

    ds = SyntheticScenesDataset(num_objects=1, num_views=4, imgsize=12)
    v = ds.all_views(0)
    R, t, K = v["R"][2], v["T"][2], ds.K
    pos_np, dir_np = _rays_np(R.astype(np.float64), t.astype(np.float64),
                              K.astype(np.float64), 12, 12)
    pos_j, dir_j = pinhole_rays(jnp.asarray(R), jnp.asarray(t),
                                jnp.asarray(K), 12, 12)
    np.testing.assert_allclose(np.asarray(pos_j), pos_np, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dir_j), dir_np, atol=1e-5)


def test_scenes_dataset_renders_consistent_3d():
    from diff3d_tpu.data.synthetic import SyntheticScenesDataset

    ds = SyntheticScenesDataset(num_objects=2, num_views=8, imgsize=24)
    v = ds.all_views(0)
    assert v["imgs"].shape == (8, 24, 24, 3)
    assert v["imgs"].min() >= -1 and v["imgs"].max() <= 1
    # every view shows some foreground (spheres) and isn't constant
    for img in v["imgs"]:
        assert img.std() > 0.05
    # determinism + distinct objects
    v2 = SyntheticScenesDataset(num_objects=2, num_views=8,
                                imgsize=24).all_views(0)
    np.testing.assert_array_equal(v["imgs"], v2["imgs"])
    # object i is invariant to num_objects (eval sets of different sizes
    # must score the SAME scenes)
    v3 = SyntheticScenesDataset(num_objects=5, num_views=8,
                                imgsize=24).all_views(1)
    np.testing.assert_array_equal(ds.all_views(1)["imgs"], v3["imgs"])
    assert not np.array_equal(v["imgs"][0], ds.all_views(1)["imgs"][0])
    # rotations orthonormal, camera on the orbit radius
    for R, t in zip(v["R"], v["T"]):
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-5)
        np.testing.assert_allclose(np.linalg.norm(t), 2.6, atol=1e-5)
    # loader contract
    s = ds.sample(0, np.random.default_rng(0))
    assert s["imgs"].shape == (2, 24, 24, 3) and s["K"].shape == (3, 3)


def test_scenes_dataset_sphere_projects_where_expected():
    """Project a sphere center through K[R|t] and check the rendered
    image is foreground-hit near that pixel (camera-convention end-to-end
    sanity)."""
    from diff3d_tpu.data.synthetic import SyntheticScenesDataset

    ds = SyntheticScenesDataset(num_objects=1, num_views=6, imgsize=48,
                                spheres_per_object=1)
    # put one big sphere dead center so the projection lands in-frame
    ds._centers[0, 0] = [0.0, 0.0, 0.0]
    ds._radii[0, 0] = 0.5
    ds._colors[0, 0] = [1.0, 1.0, 1.0]
    for view in range(6):
        img, R, t = ds._view(0, view)
        p_cam = R.T @ (np.zeros(3) - t)              # cam-from-world
        uvw = ds.K.astype(np.float64) @ p_cam
        u, v = uvw[0] / uvw[2], uvw[1] / uvw[2]
        assert 0 <= u < 48 and 0 <= v < 48
        # pixel at the projected center is lit foreground (bright), and
        # a far corner is background
        assert img[int(v), int(u)].mean() > -0.2
        corner = img[0, 0]
        np.testing.assert_allclose(corner, np.clip(
            [0.15 * 1 - 0.55, 0.15 * 1 - 0.45, 0.25 * 1 - 0.35],
            -1, 1), atol=0.6)


class _IndexRecorder:
    """Dataset wrapper recording which object index each sample drew."""

    def __init__(self, ds):
        self.ds = ds
        self.idxs = []

    def __len__(self):
        return len(self.ds)

    def sample(self, idx, rng):
        self.idxs.append(idx)
        return self.ds.sample(idx, rng)


def test_permute_mode_covers_every_object_once_per_epoch():
    """sample_mode='permute' = the reference's epoch semantics
    (SRNdataset.py:12-40): without-replacement permutations, each object
    exactly once per epoch, still stateless from (seed, step, host)."""
    n = 10
    ds = _IndexRecorder(SyntheticDataset(num_objects=n, num_views=3,
                                         imgsize=8))
    loader = InfiniteLoader(ds, batch_size=5, seed=3, num_workers=0,
                            sample_mode="permute")
    for _ in range(6):   # 6 steps x 5 = 30 draws = 3 epochs
        next(loader)
    for e in range(3):
        epoch_draws = sorted(ds.idxs[e * n:(e + 1) * n])
        assert epoch_draws == list(range(n)), epoch_draws
    # different epochs use different shuffles
    assert ds.idxs[:n] != ds.idxs[n:2 * n]


def test_permute_mode_hosts_partition_the_epoch():
    n = 8
    recs = [_IndexRecorder(SyntheticDataset(num_objects=n, num_views=3,
                                            imgsize=8)) for _ in range(2)]
    for h, rec in enumerate(recs):
        next(InfiniteLoader(rec, 4, seed=3, host_id=h, num_hosts=2,
                            num_workers=0, sample_mode="permute"))
    assert sorted(recs[0].idxs + recs[1].idxs) == list(range(n))


def test_permute_mode_resume_replays_exact_stream():
    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    fresh = InfiniteLoader(ds, 2, seed=7, num_workers=0,
                           sample_mode="permute")
    _, second = next(fresh), next(fresh)
    resumed = InfiniteLoader(ds, 2, seed=7, num_workers=0, start_step=1,
                             sample_mode="permute")
    np.testing.assert_array_equal(next(resumed)["imgs"], second["imgs"])


def test_prefetch_to_device_shards_batch():
    import jax
    from diff3d_tpu.parallel import make_mesh

    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    loader = InfiniteLoader(ds, batch_size=8, seed=0, num_workers=0)
    env = make_mesh()
    it = prefetch_to_device(loader, env.batch(), depth=2)
    batch = next(it)
    assert isinstance(batch["imgs"], jax.Array)
    assert batch["imgs"].shape == (8, 2, 8, 8, 3)
    assert batch["imgs"].sharding.is_equivalent_to(env.batch(), 5)
    it.close()


def test_prefetch_propagates_producer_errors():
    from diff3d_tpu.data.loader import prefetch_to_device

    def bad_iter():
        yield {"x": np.zeros(2)}
        raise RuntimeError("corrupt sample")

    it = prefetch_to_device(bad_iter(), sharding=None, depth=1,
                            to_device=False)
    next(it)
    with pytest.raises(RuntimeError, match="corrupt sample"):
        next(it)


def test_loader_ships_uint8_and_roundtrips():
    """Batches cross the host->device boundary as uint8 (4x less
    transfer); dequantize recovers the float pipeline to within half a
    quantization step."""
    from diff3d_tpu.data.images import dequantize

    ds = SyntheticDataset(num_objects=3, num_views=5, imgsize=8)
    b_u8 = next(InfiniteLoader(ds, 4, seed=0, num_workers=0))
    b_f32 = next(InfiniteLoader(ds, 4, seed=0, num_workers=0,
                                images_uint8=False))
    assert b_u8["imgs"].dtype == np.uint8
    assert b_f32["imgs"].dtype == np.float32
    assert b_u8["R"].dtype == np.float32        # only images quantize
    back = dequantize(b_u8["imgs"])
    assert back.dtype == np.float32
    np.testing.assert_allclose(back, b_f32["imgs"], atol=1.01 / 255)
    # float inputs pass through untouched
    assert dequantize(b_f32["imgs"]) is b_f32["imgs"]
