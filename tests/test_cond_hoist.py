"""EQ-gated conditioning hoist: ``sample_loop(hoist_cond=True)``.

The reverse-diffusion scan used to recompute the intrinsics-only half of
ray generation (``pinhole_rays_cam``: K_inv and the K_inv @ pixel-grid
contraction) at every denoise step even though it is constant along the
trajectory.  ``hoist_cond=True`` lifts it above the scan and feeds the
model ``batch['cam_dirs']``.  Certification here is two-sided:

  * ``equiv.verify_hoist`` (EQ602) — every op the hoisted program runs
    outside the loop hash-matches a loop-invariant ancestor in the
    unhoisted oracle, plus randomized concrete agreement;
  * bit-parity — the full 256-step ancestral sampler produces the SAME
    BYTES with and without the hoist (the hoisted stage is the exact
    composition prefix of ``pinhole_rays``, and the rng key stream never
    touches it — the pinned rngcheck stream manifests are byte-identical
    either way).
"""

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.analysis import equiv
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.diffusion import core
from diff3d_tpu.geometry import (pinhole_rays, pinhole_rays_cam,
                                 pinhole_rays_world)
from diff3d_tpu.models.xunet import XUNet
from diff3d_tpu.train.trainer import init_params


def _setup(size=8):
    # Shallow 2-level model (tier-1 budget): the hoist moves the
    # intrinsics-only ray stage that feeds the model's INPUT
    # conditioning — nothing about it depends on UNet depth.
    cfg = make_tiny_config(imgsize=size, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))

    def denoise_fn(batch, cond_mask):
        return model.apply({"params": params}, batch, cond_mask=cond_mask)

    rs = np.random.RandomState(0)
    N, B = 3, 2
    record_imgs = jnp.asarray(rs.randn(N, B, size, size, 3), jnp.float32)
    record_R = jnp.broadcast_to(jnp.eye(3), (N, 3, 3))
    record_T = jnp.asarray(rs.randn(N, 3), jnp.float32)
    K = jnp.asarray([[float(size), 0, size / 2],
                     [0, float(size), size / 2], [0, 0, 1]], jnp.float32)
    kw = dict(
        record_len=jnp.asarray(N), target_R=jnp.eye(3),
        target_T=jnp.asarray([0.0, 0.0, 1.0]), K=K,
        w=jnp.asarray([1.0, 3.0]), rng=jax.random.PRNGKey(5))
    return denoise_fn, record_imgs, record_R, record_T, kw


def test_rays_split_composes_bit_identically():
    """pinhole_rays == pinhole_rays_world(pinhole_rays_cam(...)) down to
    the bytes — the hoisted stage is exactly the composition prefix."""
    rs = np.random.RandomState(1)
    R = jnp.asarray(rs.randn(2, 2, 3, 3), jnp.float32)
    t = jnp.asarray(rs.randn(2, 2, 3), jnp.float32)
    K = jnp.asarray([[8.0, 0, 4], [0, 8, 4], [0, 0, 1]], jnp.float32)
    K = jnp.broadcast_to(K, (2, 2, 3, 3))
    pos, dirs = pinhole_rays(R, t, K, 8, 8)
    pos2, dirs2 = pinhole_rays_world(R, t, pinhole_rays_cam(K, 8, 8))
    np.testing.assert_array_equal(np.asarray(pos), np.asarray(pos2))
    np.testing.assert_array_equal(np.asarray(dirs), np.asarray(dirs2))


def test_verify_hoist_certifies_cam_dirs_hoist():
    """EQ602 gate: the hoisted sampler is a certified scan-hoist of the
    unhoisted oracle — structurally (outside-loop ops have loop-invariant
    ancestors) and concretely (randomized trials agree)."""
    denoise_fn, record_imgs, record_R, record_T, kw = _setup()

    def run(hoist):
        def f(record_imgs, record_T):
            return core.sample_loop(
                denoise_fn, record_imgs=record_imgs, record_R=record_R,
                record_T=record_T, timesteps=4, hoist_cond=hoist, **kw)
        return f

    verdict = equiv.verify_hoist(
        run(False), run(True), (record_imgs, record_T),
        name="cond_hoist", trials=2)
    assert verdict.equivalent, [f.message for f in verdict.findings]
    assert verdict.findings == []
    assert verdict.unmatched == []
    assert verdict.matched > 0


def test_ancestral_256_bit_parity():
    """The tier-1 parity oracle itself: full 256-step ancestral run,
    hoisted vs unhoisted, byte-for-byte equal."""
    denoise_fn, record_imgs, record_R, record_T, kw = _setup()

    def run(hoist):
        return core.sample_loop(
            denoise_fn, record_imgs=record_imgs, record_R=record_R,
            record_T=record_T, timesteps=256, hoist_cond=hoist, **kw)

    a = np.asarray(run(True))
    b = np.asarray(run(False))
    assert a.shape == b.shape
    assert np.array_equal(a, b)
    assert np.all(np.isfinite(a))
