"""Test harness: force JAX onto CPU with 8 virtual devices, so
multi-device mesh tests run anywhere (the TPU-world equivalent of a fake
distributed backend — the reference has none, SURVEY.md §4).

NOTE: in this image a sitecustomize imports jax at interpreter startup, so
setting JAX_PLATFORMS in os.environ here is too late.  Instead we flip the
already-imported config before any backend is initialised; XLA_FLAGS is
also still honoured at that point because backends are created lazily.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite's cost is XLA CPU compiles of the
# (tiny) X-UNet variants; cached, a full run drops from ~10min to ~1min.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tests")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, got "
    f"{jax.devices()[0].platform}")
assert len(jax.devices()) == 8, len(jax.devices())

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run the slow end-to-end tests (test_cli, test_multiprocess)")


def _env_on(name):
    return os.environ.get(name, "").lower() in ("1", "true", "yes")


def pytest_collection_modifyitems(config, items):
    """Keep the default ``pytest -q`` under ~5 min: the two end-to-end
    files (train->sample CLI roundtrip, 2-process pod) are opt-in, as
    are the ``distill`` soaks (multi-round progressive-distillation
    ladders; the fast 2-round smoke stays in the default run)."""
    run_all = config.getoption("--runslow") or _env_on("RUN_SLOW")
    if not run_all:
        skip = pytest.mark.skip(
            reason="slow end-to-end test; pass --runslow (or RUN_SLOW=1)")
        for item in items:
            if "slow" in item.keywords:
                item.add_marker(skip)
    if not (run_all or _env_on("RUN_DISTILL")):
        skip_d = pytest.mark.skip(
            reason="distillation soak; pass --runslow (or RUN_DISTILL=1)")
        for item in items:
            if "distill" in item.keywords:
                item.add_marker(skip_d)
