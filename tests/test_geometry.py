import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.geometry import pinhole_rays, posenc_ddpm, posenc_nerf
from diff3d_tpu.geometry.posenc import posenc_nerf_channels


def test_posenc_ddpm_shape_and_values():
    t = jnp.array([0.0, 10.0])
    emb = posenc_ddpm(t, emb_ch=64, max_time=1.0)
    assert emb.shape == (2, 64)
    # t=0: sin part 0, cos part 1.
    np.testing.assert_allclose(emb[0, :32], np.zeros(32), atol=1e-6)
    np.testing.assert_allclose(emb[0, 32:], np.ones(32), atol=1e-6)
    # first frequency is 1.0 -> emb[...,0] = sin(1000 * t)
    np.testing.assert_allclose(emb[1, 0], np.sin(10.0 * 1000.0), rtol=1e-3)


def test_posenc_ddpm_max_time_scaling():
    t = jnp.array([500.0])
    a = posenc_ddpm(t, 32, max_time=1000.0)
    b = posenc_ddpm(jnp.array([0.5]), 32, max_time=1.0)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_posenc_nerf_channels():
    x = jnp.zeros((2, 2, 4, 4, 3))
    assert posenc_nerf(x, 0, 15).shape[-1] == 93 == posenc_nerf_channels(0, 15)
    assert posenc_nerf(x, 0, 8).shape[-1] == 51 == posenc_nerf_channels(0, 8)
    assert posenc_nerf(x, 3, 3).shape[-1] == 3


def test_posenc_nerf_values_scale_major():
    # One pixel, x = (0.1, 0.2, 0.3): first 3 sin entries must be
    # sin(2^0 * x) (scale-major flatten, reference einops "(c d)").
    x = jnp.array([0.1, 0.2, 0.3])
    out = np.asarray(posenc_nerf(x[None], 0, 2))[0]
    assert out.shape == (3 + 2 * 3 * 2,)
    np.testing.assert_allclose(out[:3], x, rtol=1e-6)
    np.testing.assert_allclose(out[3:6], np.sin(x), rtol=1e-5)
    np.testing.assert_allclose(out[6:9], np.sin(2 * np.asarray(x)), rtol=1e-5)
    # the +pi/2 half is cosine
    np.testing.assert_allclose(out[9:12], np.cos(x), rtol=1e-5)


@pytest.fixture
def simple_cam():
    K = jnp.array([[100.0, 0.0, 32.0], [0.0, 100.0, 32.0], [0.0, 0.0, 1.0]])
    R = jnp.eye(3)
    t = jnp.array([1.0, 2.0, 3.0])
    return R, t, K


def test_pinhole_rays_identity_cam(simple_cam):
    R, t, K = simple_cam
    pos, dirs = pinhole_rays(R, t, K, 64, 64)
    assert pos.shape == (64, 64, 3) and dirs.shape == (64, 64, 3)
    # origins are the camera position everywhere
    np.testing.assert_allclose(np.asarray(pos), np.broadcast_to(t, (64, 64, 3)))
    # unit directions
    np.testing.assert_allclose(np.linalg.norm(dirs, axis=-1), 1.0, rtol=1e-5)
    # the pixel whose center hits the principal point looks along +z:
    # u = j + 0.5 = cx = 32 -> j = 31.5 — not integral, so check the ray
    # at pixel (31, 31): direction ((31.5-32)/100, (31.5-32)/100, 1)/norm
    expect = np.array([-0.005, -0.005, 1.0])
    expect /= np.linalg.norm(expect)
    np.testing.assert_allclose(np.asarray(dirs[31, 31]), expect, atol=1e-5)


def test_pinhole_rays_rotation(simple_cam):
    R0, t, K = simple_cam
    # 90-degree rotation about y: +z_cam -> +x_world
    Ry = jnp.array([[0.0, 0.0, 1.0], [0.0, 1.0, 0.0], [-1.0, 0.0, 0.0]])
    _, d0 = pinhole_rays(R0, t, K, 8, 8)
    _, d1 = pinhole_rays(Ry, t, K, 8, 8)
    np.testing.assert_allclose(
        np.asarray(d1), np.einsum("ij,hwj->hwi", np.asarray(Ry),
                                  np.asarray(d0)), atol=1e-5)


def test_pinhole_rays_batched(simple_cam):
    R, t, K = simple_cam
    Rb = jnp.broadcast_to(R, (4, 2, 3, 3))
    tb = jnp.broadcast_to(t, (4, 2, 3))
    Kb = jnp.broadcast_to(K, (4, 1, 3, 3))
    pos, dirs = pinhole_rays(Rb, tb, Kb, 16, 16)
    assert pos.shape == (4, 2, 16, 16, 3)
    assert dirs.shape == (4, 2, 16, 16, 3)
    single = pinhole_rays(R, t, K, 16, 16)[1]
    np.testing.assert_allclose(np.asarray(dirs[2, 1]), np.asarray(single),
                               atol=1e-6)
