import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.geometry import pinhole_rays, posenc_ddpm, posenc_nerf
from diff3d_tpu.geometry.posenc import posenc_nerf_channels


def test_posenc_ddpm_shape_and_values():
    t = jnp.array([0.0, 10.0])
    emb = posenc_ddpm(t, emb_ch=64, max_time=1.0)
    assert emb.shape == (2, 64)
    # t=0: sin part 0, cos part 1.
    np.testing.assert_allclose(emb[0, :32], np.zeros(32), atol=1e-6)
    np.testing.assert_allclose(emb[0, 32:], np.ones(32), atol=1e-6)
    # first frequency is 1.0 -> emb[...,0] = sin(1000 * t)
    np.testing.assert_allclose(emb[1, 0], np.sin(10.0 * 1000.0), rtol=1e-3)


def test_posenc_ddpm_max_time_scaling():
    t = jnp.array([500.0])
    a = posenc_ddpm(t, 32, max_time=1000.0)
    b = posenc_ddpm(jnp.array([0.5]), 32, max_time=1.0)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_posenc_nerf_channels():
    x = jnp.zeros((2, 2, 4, 4, 3))
    assert posenc_nerf(x, 0, 15).shape[-1] == 93 == posenc_nerf_channels(0, 15)
    assert posenc_nerf(x, 0, 8).shape[-1] == 51 == posenc_nerf_channels(0, 8)
    assert posenc_nerf(x, 3, 3).shape[-1] == 3


def test_posenc_nerf_values_scale_major():
    # One pixel, x = (0.1, 0.2, 0.3): first 3 sin entries must be
    # sin(2^0 * x) (scale-major flatten, reference einops "(c d)").
    x = jnp.array([0.1, 0.2, 0.3])
    out = np.asarray(posenc_nerf(x[None], 0, 2))[0]
    assert out.shape == (3 + 2 * 3 * 2,)
    np.testing.assert_allclose(out[:3], x, rtol=1e-6)
    np.testing.assert_allclose(out[3:6], np.sin(x), rtol=1e-5)
    np.testing.assert_allclose(out[6:9], np.sin(2 * np.asarray(x)), rtol=1e-5)
    # the +pi/2 half is cosine
    np.testing.assert_allclose(out[9:12], np.cos(x), rtol=1e-5)


# ---------------------------------------------------------------------------
# visu3d oracle: an independent numpy transcription of the EXACT pipeline the
# reference runs at /root/reference/xunet.py:311-318 —
#     v3d.Camera(spec=v3d.PinholeCamera(resolution=(H, W), K=K),
#                world_from_cam=v3d.Transform(R=R, t=t)).rays()
# transcribed step by step from visu3d's public sources (the library is not
# installable in this zero-egress image):
#   * ``PinholeCamera.px_centers``  (visu3d/dc_arrays/camera_spec.py):
#     ``np.meshgrid(arange(w), arange(h), indexing='xy')`` stacked as
#     ``(coord_w, coord_h)`` then ``+ 0.5`` — pixel CENTERS, u along width;
#   * ``PinholeCamera.cam_from_px``: append homogeneous 1, multiply by
#     ``K^-1`` — camera frame is OpenCV ``[right, down, fwd]``, giving
#     un-normalized directions on the z=1 plane;
#   * ``Transform.__matmul__(Ray)``  (visu3d/dc_arrays/transformation.py):
#     ``pos' = R @ pos + t``, ``dir' = R @ dir`` (rotation only on dir);
#     ray origin is the camera center, i.e. pos = 0 -> t;
#   * ``Camera.rays(normalize=True)`` then ``Ray.normalize()``: dir / |dir|.
# Everything runs in float64, uses np.linalg.solve (not inv), and never
# calls into diff3d_tpu — so agreement with pinhole_rays is a genuine
# two-implementation check of the convention, not self-reference.
# ---------------------------------------------------------------------------


def _visu3d_rays_oracle(R, t, K, h, w):
    R, t, K = (np.asarray(a, np.float64) for a in (R, t, K))
    # px_centers: meshgrid indexing='xy', stack (w-coord, h-coord), + 0.5
    coord_w, coord_h = np.meshgrid(np.arange(w), np.arange(h),
                                   indexing="xy")
    points2d = np.stack([coord_w, coord_h], axis=-1) + 0.5      # [h, w, 2]
    # cam_from_px: homogeneous, K^-1 (solve against the stacked points)
    ones = np.ones(points2d.shape[:-1] + (1,))
    points2d_h = np.concatenate([points2d, ones], axis=-1)      # [h, w, 3]
    cam_dir = np.linalg.solve(
        K[None, None], points2d_h[..., None])[..., 0]           # [h, w, 3]
    # Transform @ Ray: pos = R @ 0 + t; dir = R @ cam_dir
    world_dir = np.einsum("ij,hwj->hwi", R, cam_dir)
    # Ray.normalize()
    world_dir = world_dir / np.linalg.norm(world_dir, axis=-1,
                                           keepdims=True)
    pos = np.broadcast_to(t, world_dir.shape)
    return pos, world_dir


def _srn_lookat_pose(position, up=(0.0, 0.0, 1.0)):
    """SRN-style world-from-camera pose: camera at ``position`` on the
    object sphere, optical axis (+z, OpenCV convention) through the
    origin — the geometry of SRN's ``pose/*.txt`` cam2world matrices."""
    p = np.asarray(position, np.float64)
    z = -p / np.linalg.norm(p)                    # forward: toward origin
    x = np.cross(np.asarray(up, np.float64), z)
    x = x / np.linalg.norm(x)
    y = np.cross(z, x)
    return np.stack([x, y, z], axis=-1), p        # columns = cam axes


# SRN-realistic rig: cameras on the r=1.3 view sphere (SRN cars layout),
# intrinsics f=131.25, c=64 at 128^2 (the SRN intrinsics.txt scale).
_SRN_POSITIONS = [
    (1.3, 0.0, 0.0),
    (0.0, -1.3, 0.0),
    (0.919, 0.919, 0.0),
    (0.75, -0.65, 0.86),      # elevated view
    (-0.4, 1.1, -0.55),       # below the equator
]
_SRN_K = np.array([[131.25, 0.0, 64.0],
                   [0.0, 131.25, 64.0],
                   [0.0, 0.0, 1.0]])


@pytest.mark.parametrize("position", _SRN_POSITIONS)
def test_pinhole_rays_match_visu3d_oracle(position):
    """Golden check against the transcribed visu3d pipeline (SURVEY.md §7
    'hard part #1'): a convention slip (pixel corner vs center, K^T,
    row-vs-column camera axes, unnormalized dirs) shifts every ray and
    fails here, independently of diff3d_tpu's own derivation."""
    import jax

    # jax < 0.5 only ships the scoped x64 switch under jax.experimental.
    enable_x64 = getattr(jax, "enable_x64", None)
    if enable_x64 is None:
        from jax.experimental import enable_x64

    R, t = _srn_lookat_pose(position)
    oracle_pos, oracle_dir = _visu3d_rays_oracle(R, t, _SRN_K, 128, 128)

    with enable_x64():
        pos, dirs = pinhole_rays(jnp.asarray(R, jnp.float64),
                                 jnp.asarray(t, jnp.float64),
                                 jnp.asarray(_SRN_K, jnp.float64), 128, 128)
        np.testing.assert_allclose(np.asarray(pos), oracle_pos, atol=1e-9)
        np.testing.assert_allclose(np.asarray(dirs), oracle_dir, atol=1e-9)

    # The production path runs float32 on-device; it must sit on the same
    # convention to float32 accuracy.
    pos32, dirs32 = pinhole_rays(jnp.asarray(R, jnp.float32),
                                 jnp.asarray(t, jnp.float32),
                                 jnp.asarray(_SRN_K, jnp.float32), 128, 128)
    np.testing.assert_allclose(np.asarray(dirs32), oracle_dir, atol=2e-5)


def test_visu3d_oracle_sanity():
    """The oracle itself: center-of-image ray of a look-at camera points
    at the origin (the look-at construction and the +0.5 center offset
    compose correctly)."""
    R, t = _srn_lookat_pose((1.3, 0.0, 0.0))
    _, d = _visu3d_rays_oracle(R, t, _SRN_K, 128, 128)
    # principal point (u=v=64) lies between pixels 63 and 64; the mean of
    # the 4 center pixels' dirs points along -t (toward the origin).
    center = d[63:65, 63:65].mean((0, 1))
    center /= np.linalg.norm(center)
    np.testing.assert_allclose(center, -t / np.linalg.norm(t), atol=1e-4)


@pytest.fixture
def simple_cam():
    K = jnp.array([[100.0, 0.0, 32.0], [0.0, 100.0, 32.0], [0.0, 0.0, 1.0]])
    R = jnp.eye(3)
    t = jnp.array([1.0, 2.0, 3.0])
    return R, t, K


def test_pinhole_rays_identity_cam(simple_cam):
    R, t, K = simple_cam
    pos, dirs = pinhole_rays(R, t, K, 64, 64)
    assert pos.shape == (64, 64, 3) and dirs.shape == (64, 64, 3)
    # origins are the camera position everywhere
    np.testing.assert_allclose(np.asarray(pos), np.broadcast_to(t, (64, 64, 3)))
    # unit directions
    np.testing.assert_allclose(np.linalg.norm(dirs, axis=-1), 1.0, rtol=1e-5)
    # the pixel whose center hits the principal point looks along +z:
    # u = j + 0.5 = cx = 32 -> j = 31.5 — not integral, so check the ray
    # at pixel (31, 31): direction ((31.5-32)/100, (31.5-32)/100, 1)/norm
    expect = np.array([-0.005, -0.005, 1.0])
    expect /= np.linalg.norm(expect)
    np.testing.assert_allclose(np.asarray(dirs[31, 31]), expect, atol=1e-5)


def test_pinhole_rays_rotation(simple_cam):
    R0, t, K = simple_cam
    # 90-degree rotation about y: +z_cam -> +x_world
    Ry = jnp.array([[0.0, 0.0, 1.0], [0.0, 1.0, 0.0], [-1.0, 0.0, 0.0]])
    _, d0 = pinhole_rays(R0, t, K, 8, 8)
    _, d1 = pinhole_rays(Ry, t, K, 8, 8)
    np.testing.assert_allclose(
        np.asarray(d1), np.einsum("ij,hwj->hwi", np.asarray(Ry),
                                  np.asarray(d0)), atol=1e-5)


def test_pinhole_rays_batched(simple_cam):
    R, t, K = simple_cam
    Rb = jnp.broadcast_to(R, (4, 2, 3, 3))
    tb = jnp.broadcast_to(t, (4, 2, 3))
    Kb = jnp.broadcast_to(K, (4, 1, 3, 3))
    pos, dirs = pinhole_rays(Rb, tb, Kb, 16, 16)
    assert pos.shape == (4, 2, 16, 16, 3)
    assert dirs.shape == (4, 2, 16, 16, 3)
    single = pinhole_rays(R, t, K, 16, 16)[1]
    np.testing.assert_allclose(np.asarray(dirs[2, 1]), np.asarray(single),
                               atol=1e-6)
