"""Context-parallel / ring-attention correctness at srn64-REALISTIC
shapes on the 8-virtual-device CPU mesh.

The fast suite (test_train, test_parallel, the driver dryrun) proves
sharded == replicated at toy geometry (imgsize 8-16).  GSPMD conv halo
exchanges and GroupNorm reductions are shape-sensitive: a halo that is
correct at 16x16 with 2-row shards can still be wrong at 64x64 where
downsampling produces 64->32->16->8 feature maps whose shard boundaries
fall differently.  These slow-marked tests run the real srn64 spatial
geometry (H=W=64, the full (1,2,2,4) ch_mult, attention at levels
2/3/4) with reduced channel width — halos and reductions depend on
spatial dims and block structure, not on channel count.

Reference hot spot being re-derived: 4096-token attention at 64^2
(/root/reference/xunet.py:199-208); the reference never shards it
(SURVEY.md §5.7).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from diff3d_tpu.parallel import shard_map  # noqa: F401  (version-compat wrapper)
from diff3d_tpu.config import MeshConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh, ring_sdpa, ulysses_sdpa
from diff3d_tpu.train import TrainState, create_train_state, make_train_step
from diff3d_tpu.train.trainer import init_params


def srn64_geometry_cfg():
    """srn64 spatial structure, narrow channels: H=W=64, 4-level
    (1,2,2,4) ch_mult, attention at levels 2/3/4 — ch=16 instead of 128
    (channel width does not move shard boundaries)."""
    cfg = make_tiny_config(imgsize=64, ch=16)
    model = dataclasses.replace(
        cfg.model, emb_ch=64,
        ch_mult=(1, 2, 2, 4), attn_levels=(2, 3, 4))
    assert model.H == 64 and model.num_resolutions == 4
    return dataclasses.replace(cfg, model=model)


def _batch(cfg, B):
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H,
                          seed=0)
    b = next(InfiniteLoader(ds, B, seed=0, num_workers=0))
    return {"imgs": jnp.asarray(b["imgs"]), "R": jnp.asarray(b["R"]),
            "T": jnp.asarray(b["T"]), "K": jnp.asarray(b["K"])}


@pytest.mark.slow
def test_cp_train_step_matches_replicated_at_srn64_shapes():
    """One GSPMD context-parallel train step at 64x64 over the 8-device
    mesh (data=4, model=2; spatial axis 2-way sharded -> per-level
    feature maps 64/32/16/8 all split mid-image) == the unsharded step,
    loss and updated params."""
    cfg = srn64_geometry_cfg()
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, global_batch=8))
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    batch = _batch(cfg, B=8)

    s1 = create_train_state(params, cfg.train)
    f1 = make_train_step(model, cfg, env=None, donate=False)
    s1, m1 = f1(s1, batch, rng)

    cp = dataclasses.replace(
        cfg, mesh=MeshConfig(model_parallel=2, context_parallel=True))
    env = make_mesh(cp.mesh)
    assert dict(env.mesh.shape) == {"data": 4, "model": 2}
    s2 = create_train_state(params, cfg.train)
    s2 = jax.device_put(
        s2, TrainState(step=env.replicated(), params=env.params(s2.params),
                       opt_state=env.params(s2.opt_state),
                       ema_params=env.params(s2.ema_params)))
    f2 = make_train_step(model, cp, env, donate=False)
    s2, m2 = f2(s2, jax.device_put(batch, env.batch()), rng)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_cp_forward_matches_unsharded_at_srn64_shapes():
    """Plain forward (no optimizer) under context-parallel activation
    constraints at 64x64 == unsharded forward, to fp32 tolerance —
    isolates the halo/reduction question from Adam arithmetic."""
    cfg = srn64_geometry_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    batch = _batch(cfg, B=8)

    B = 8
    inp = {
        "x": batch["imgs"][:, 0], "z": batch["imgs"][:, 1],
        "logsnr": jnp.stack([jnp.full((B,), 20.0),
                             jnp.linspace(-18.0, 18.0, B)], 1),
        "R": batch["R"], "t": batch["T"], "K": batch["K"],
    }
    cond = jnp.ones((B,), bool)
    params = jax.jit(
        lambda r: model.init({"params": r}, inp, cond_mask=cond)
    )(rng)["params"]
    ref = jax.jit(
        lambda p: model.apply({"params": p}, inp, cond_mask=cond))(params)

    cp = MeshConfig(model_parallel=2, context_parallel=True)
    env = make_mesh(cp)
    constrain = env.activation_constraint()

    p_sh = jax.device_put(params, env.params(params))
    i_sh = jax.device_put(inp, env.batch())
    c_sh = jax.device_put(cond, env.batch())
    out = jax.jit(
        lambda p, i, c: model.apply({"params": p}, i, cond_mask=c,
                                    constrain=constrain)
    )(p_sh, i_sh, c_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("core,n_shards", [("ring", 8), ("ulysses", 4)])
def test_seq_parallel_attention_at_srn64_token_count(core, n_shards):
    """Ring / Ulysses attention over the REAL srn64 token count — L=4096
    (= 64^2 spatial tokens, the reference's unsharded hot loop at
    xunet.py:199-208) with the srn64 deep-level head dim D=128 and the
    real head count H=4 (4*ch=512 over 4 heads) — == dense attention.
    Ring shards 8-way; Ulysses needs H % n == 0, so 4-way."""
    B, L, H, D = 1, 64 * 64, 4, 128
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D) * 0.1, jnp.float32)
               for _ in range(3))
    ref = jax.nn.dot_product_attention(q, k, v)

    mesh = Mesh(np.asarray(jax.devices()[:n_shards]), ("seq",))
    spec = P(None, "seq")
    fn = {"ring": ring_sdpa, "ulysses": ulysses_sdpa}[core]
    sharded = shard_map(lambda q, k, v: fn(q, k, v, "seq"),
                        mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    out = jax.jit(sharded)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
