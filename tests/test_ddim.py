"""Few-step sampling: deterministic DDIM, schedule subsets, progressive
distillation, and the serving schedule registry.

The contracts pinned here:

  * SCHEDULE SUBSET — every ``k``-step time grid is the exact stride
    subset of the dense grid, and ``steps=None`` / ``steps=timesteps``
    are BIT-identical (the 256-step ancestral sampler stays usable as a
    parity oracle after the refactor).
  * DDIM DETERMINISM — the eta=0 path is bit-reproducible at a fixed
    seed, chunk-invariant (``scan_chunks`` never changes results), and
    mesh-shardable to float tolerance.
  * SERVING SCHEDULES — an engine serves exactly its compiled
    ``(sampler_kind, steps)`` registry: unknown schedules are rejected
    with a typed retryable error carrying the supported list (never an
    on-demand compile), and a non-default schedule rides the bucket key
    end to end through a sharded service.
  * DISTILLATION — two halving rounds (4 -> 2 -> 1 on the tiny grid)
    run through the async ``full_sliced`` checkpoint path and hand back
    a student whose 1-step DDIM sampler produces finite images.
"""

import dataclasses
import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import MeshConfig, ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.diffusion import (alpha_sigma, ddim_step,
                                  sample_schedule_ts)
from diff3d_tpu.evaluation import PSNR_CAP, matched_seed_parity
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh
from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.serving import (ServingService, UnsupportedSchedule,
                                ViewRequest)
from diff3d_tpu.train.trainer import init_params


@pytest.fixture(scope="module")
def setup():
    cfg = make_tiny_config(imgsize=8, ch=8)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=8)
    return cfg, model, params, ds


def _mesh(data: int):
    return make_mesh(MeshConfig(data_parallel=data, model_parallel=1),
                     devices=jax.devices()[:data])


# ---------------------------------------------------------------------------
# Schedule subsets (pure math)
# ---------------------------------------------------------------------------


def test_sample_schedule_ts_is_exact_dense_grid_subset():
    dense = np.asarray(sample_schedule_ts(None, timesteps=256))
    assert dense.shape == (257,)
    assert dense[0] == 1.0 and dense[-1] == 0.0
    for k in (256, 64, 16, 8):
        ts = np.asarray(sample_schedule_ts(k, timesteps=256))
        assert ts.shape == (k + 1,)
        # Exact index subset, not merely close: the few-step grid must
        # hit logsnr values the dense grid also hits.
        np.testing.assert_array_equal(ts, dense[:: 256 // k])
    np.testing.assert_array_equal(
        np.asarray(sample_schedule_ts(256, timesteps=256)), dense)


def test_sample_schedule_ts_rejects_non_divisors():
    for bad in (0, -1, 3, 5, 7, 17, 512):
        with pytest.raises(ValueError, match="divisor"):
            sample_schedule_ts(bad, timesteps=256)


def test_ddim_step_matches_closed_form():
    """eta=0 update against the formula written out by hand, including
    the post-clip eps re-derivation."""
    r = np.random.RandomState(0)
    B, H = 3, 4
    z = jnp.asarray(r.randn(B, H, H, 3).astype(np.float32)) * 2.0
    eps_c = jnp.asarray(r.randn(B, H, H, 3).astype(np.float32))
    eps_u = jnp.asarray(r.randn(B, H, H, 3).astype(np.float32))
    w = jnp.asarray([0.0, 1.0, 3.0], jnp.float32)
    logsnr, logsnr_next = jnp.asarray(-1.3), jnp.asarray(0.8)

    got = np.asarray(ddim_step(eps_c, eps_u, z, logsnr, logsnr_next, w))

    a, s = (np.sqrt(jax.nn.sigmoid(logsnr)),
            np.sqrt(jax.nn.sigmoid(-logsnr)))
    an, sn = (np.sqrt(jax.nn.sigmoid(logsnr_next)),
              np.sqrt(jax.nn.sigmoid(-logsnr_next)))
    wb = np.asarray(w)[:, None, None, None]
    eps = (1 + wb) * np.asarray(eps_c) - wb * np.asarray(eps_u)
    x0 = np.clip((np.asarray(z) - s * eps) / a, -1.0, 1.0)
    eps2 = (np.asarray(z) - a * x0) / s
    np.testing.assert_allclose(got, an * x0 + sn * eps2, atol=1e-5)

    # Final step: logsnr_next at the schedule max -> sigma_next ~ 4.5e-5,
    # so the update collapses to (clipped) x0 up to that residual noise
    # coefficient, with no special-case guard.
    final = np.asarray(ddim_step(eps_c, eps_u, z, logsnr,
                                 jnp.asarray(20.0), w))
    np.testing.assert_allclose(final, x0, atol=1e-3)


# ---------------------------------------------------------------------------
# Sampler plumbing
# ---------------------------------------------------------------------------


def test_sampler_validates_schedule(setup):
    cfg, model, params, ds = setup
    T = cfg.diffusion.timesteps
    s = Sampler(model, params, cfg)
    assert (s.sampler_kind, s.steps) == ("ancestral", T)
    assert s.model_calls_per_view == T
    s2 = Sampler(model, params, cfg, sampler_kind="ddim", steps=2)
    assert s2.model_calls_per_view == 2
    with pytest.raises(ValueError, match="divisor"):
        Sampler(model, params, cfg, steps=3)
    with pytest.raises(ValueError, match="sampler_kind"):
        Sampler(model, params, cfg, sampler_kind="euler")
    with pytest.raises(ValueError, match="divide"):
        Sampler(model, params, cfg, steps=2, scan_chunks=4)


def test_default_steps_bit_identical_to_explicit(setup):
    """steps=None and steps=timesteps share one prepare path (stride 1):
    the refactor must leave the historical full-grid sampler bit-exact —
    this is what keeps ancestral-256 a parity oracle."""
    cfg, model, params, ds = setup
    v, key = ds.all_views(0), jax.random.PRNGKey(7)
    for kind in ("ancestral", "ddim"):
        ref = Sampler(model, params, cfg,
                      sampler_kind=kind).synthesize(v, key, max_views=3)
        got = Sampler(model, params, cfg, sampler_kind=kind,
                      steps=cfg.diffusion.timesteps).synthesize(
                          v, key, max_views=3)
        np.testing.assert_array_equal(got, ref)


def test_ddim_is_deterministic_and_differs_from_ancestral(setup):
    cfg, model, params, ds = setup
    v, key = ds.all_views(0), jax.random.PRNGKey(11)
    ddim = Sampler(model, params, cfg, sampler_kind="ddim")
    a = ddim.synthesize(v, key, max_views=3)
    b = ddim.synthesize(v, key, max_views=3)
    np.testing.assert_array_equal(a, b)
    assert np.isfinite(a).all()
    anc = Sampler(model, params, cfg).synthesize(v, key, max_views=3)
    assert not np.array_equal(a, anc)


def test_ddim_chunked_scan_bit_parity(setup):
    """scan_chunks only splits device executions; the carried-rng stream
    makes the chunked DDIM run bit-identical to the monolithic scan."""
    cfg, model, params, ds = setup
    v, key = ds.all_views(1), jax.random.PRNGKey(3)
    whole = Sampler(model, params, cfg, sampler_kind="ddim",
                    steps=4).synthesize(v, key, max_views=3)
    chunked = Sampler(model, params, cfg, sampler_kind="ddim", steps=4,
                      scan_chunks=2).synthesize(v, key, max_views=3)
    np.testing.assert_array_equal(chunked, whole)


def test_ddim_sharded_matches_unsharded(setup):
    """Few-step DDIM over a data=2 mesh: per-object results match the
    unsharded runtime to float tolerance (same key stream; XLA may tile
    differently, so not bitwise)."""
    cfg, model, params, ds = setup
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(1), jax.random.PRNGKey(2)]
    ref = Sampler(model, params, cfg, sampler_kind="ddim",
                  steps=2).synthesize_many(views, keys, max_views=3)
    sharded = Sampler(model, params, cfg, sampler_kind="ddim", steps=2,
                      mesh=_mesh(2))
    got = sharded.synthesize_many(views, keys, max_views=3)
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# Matched-seed parity metric
# ---------------------------------------------------------------------------


def test_matched_seed_parity_metric(setup):
    cfg, model, params, ds = setup
    v, key = ds.all_views(0), jax.random.PRNGKey(5)
    oracle = Sampler(model, params, cfg).synthesize(v, key, max_views=3)
    few = Sampler(model, params, cfg, sampler_kind="ddim",
                  steps=2).synthesize(v, key, max_views=3)

    self_par = matched_seed_parity([oracle], [oracle])
    assert self_par["psnr"] == PSNR_CAP          # capped, not inf
    assert self_par["views"] == 2
    assert self_par["ssim"] == pytest.approx(1.0, abs=1e-4)

    par = matched_seed_parity([few], [oracle])
    assert par["views"] == 2
    assert 0.0 < par["psnr"] <= PSNR_CAP
    assert np.isfinite(par["ssim"])

    with pytest.raises(ValueError, match="align"):
        matched_seed_parity([few], [oracle, oracle])
    with pytest.raises(ValueError, match="shape"):
        matched_seed_parity([few[:1]], [oracle])


# ---------------------------------------------------------------------------
# Serving: schedule registry
# ---------------------------------------------------------------------------


def _serving_cfg(cfg, **kw):
    return dataclasses.replace(cfg, serving=ServingConfig(
        port=0, max_batch=4, max_queue=8, max_wait_ms=100.0, max_views=6,
        default_timeout_s=120.0, **kw))


def test_engine_rejects_unsupported_schedule(setup):
    cfg, model, params, ds = setup
    cfg = _serving_cfg(cfg)
    sampler = Sampler(model, params, cfg)
    service = ServingService(sampler, cfg).start(serve_http=False)
    try:
        v = ds.all_views(0)
        req = ViewRequest(
            {k: np.asarray(v[k]) for k in ("imgs", "R", "T", "K")},
            seed=1, n_views=3, sampler_kind="ddim", steps=2)
        with pytest.raises(UnsupportedSchedule) as ei:
            service.engine.submit(req)
        err = ei.value
        assert isinstance(err, RetryableError)   # clients may retry
        assert err.supported == ["ancestral:4"]  # elsewhere, that is
        assert "ddim:2" in str(err)
        snap = service.metrics_snapshot()
        assert snap["counters"][
            "serving_unsupported_schedule_total"] == 1
        assert service.engine.supported_schedules() == ["ancestral:4"]
    finally:
        service.stop()


def test_request_schedule_validation(setup):
    cfg, model, params, ds = setup
    v = {k: np.asarray(ds.all_views(0)[k])
         for k in ("imgs", "R", "T", "K")}
    with pytest.raises(ValueError, match="sampler_kind"):
        ViewRequest(dict(v), seed=0, n_views=3, sampler_kind="euler")
    with pytest.raises(ValueError, match="steps"):
        ViewRequest(dict(v), seed=0, n_views=3, steps=0)
    # Schedule participates in the result-cache content key: the same
    # inputs under different schedules must never collide.
    r_anc = ViewRequest(dict(v), seed=0, n_views=3)
    r_ddim = ViewRequest(dict(v), seed=0, n_views=3,
                         sampler_kind="ddim", steps=2)
    assert r_anc.content_key("v0") != r_ddim.content_key("v0")


def test_ddim_end_to_end_through_sharded_serving(setup):
    """The acceptance pin: a ddim:2 request through scheduler -> engine ->
    program cache on a data=2 mesh completes, matches the offline DDIM
    sampler, and its schedule rides the bucket key (distinct compiled
    program, schedule-suffixed stats name, supported_schedules surfaced
    in health/stats)."""
    cfg, model, params, ds = setup
    cfg = _serving_cfg(cfg)
    env = _mesh(2)
    sampler = Sampler(model, params, cfg, mesh=env)
    ddim2 = Sampler(model, params, cfg, mesh=env, sampler_kind="ddim",
                    steps=2)
    service = ServingService(
        sampler, cfg,
        extra_samplers={("ddim", 2): ddim2}).start(serve_http=False)
    try:
        assert service.engine.supported_schedules() == [
            "ancestral:4", "ddim:2"]
        assert service.health()["supported_schedules"] == [
            "ancestral:4", "ddim:2"]
        v = ds.all_views(1)
        raw = {k: np.asarray(v[k]) for k in ("imgs", "R", "T", "K")}
        req_d = ViewRequest(dict(raw), seed=9, n_views=3,
                            sampler_kind="ddim", steps=2)
        req_a = ViewRequest(dict(raw), seed=9, n_views=3)   # default
        service.engine.submit(req_d)
        service.engine.submit(req_a)
        out_d = req_d.result(timeout=120)
        out_a = req_a.result(timeout=120)

        ref_d = Sampler(model, params, cfg, sampler_kind="ddim",
                        steps=2).synthesize(v, jax.random.PRNGKey(9),
                                            max_views=3)
        ref_a = Sampler(model, params, cfg).synthesize(
            v, jax.random.PRNGKey(9), max_views=3)
        np.testing.assert_allclose(out_d, ref_d, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(out_a, ref_a, atol=1e-5, rtol=1e-5)

        stats = service.engine.programs.stats(include_memory=True)
        names = sorted(stats["programs"])
        assert names == ["H8xW8xcap4xddim2xlanes2", "H8xW8xcap4xlanes2"]
        ddim_entry = stats["programs"]["H8xW8xcap4xddim2xlanes2"]
        assert (ddim_entry["steps"], ddim_entry["sampler"]) == (2, "ddim")
        assert stats["supported_schedules"] == ["ancestral:4", "ddim:2"]
        # memcheck satellite: every program carries its compiled memory
        # footprint (peak-HBM estimate + argument bytes) in /stats.
        for entry in stats["programs"].values():
            assert entry["peak_bytes"] > 0
            assert entry["argument_bytes"] > 0
    finally:
        service.stop()


def test_http_stats_endpoint_and_schedule_rejection(setup):
    """GET /stats serves the structured snapshot (incl. schedules); a
    POST naming an uncompiled schedule gets a typed 503 + Retry-After
    with the supported list in the body."""
    cfg, model, params, ds = setup
    cfg = _serving_cfg(cfg)
    sampler = Sampler(model, params, cfg)
    service = ServingService(sampler, cfg).start(serve_http=True)
    try:
        port = service.port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=30) as r:
            assert r.status == 200
            snap = json.loads(r.read())
        assert snap["engine"]["supported_schedules"] == ["ancestral:4"]
        assert snap["engine"]["default_schedule"] == "ancestral:4"
        assert "serving_unsupported_schedule_total" in snap["counters"]

        v = ds.all_views(0)
        payload = {"views": {k: np.asarray(v[k]).tolist()
                             for k in ("imgs", "R", "T", "K")},
                   "seed": 0, "n_views": 3,
                   "sampler_kind": "ddim", "steps": 2}
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/synthesize", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 503
        err = json.loads(ei.value.read())
        assert "ancestral:4" in err["error"]
    finally:
        service.stop()


# ---------------------------------------------------------------------------
# Progressive distillation
# ---------------------------------------------------------------------------


def _distill_batches(H, B=1, seed=0):
    r = np.random.RandomState(seed)
    while True:
        yield {
            "imgs": r.randint(0, 256, (B, 2, H, H, 3)).astype(np.uint8),
            "R": np.broadcast_to(np.eye(3, dtype=np.float32),
                                 (B, 2, 3, 3)).copy(),
            "T": r.randn(B, 2, 3).astype(np.float32),
            "K": np.broadcast_to(
                np.array([[H * 1.2, 0, H / 2], [0, H * 1.2, H / 2],
                          [0, 0, 1]], np.float32), (B, 3, 3)).copy(),
        }


def test_distill_schedule_validation():
    from diff3d_tpu.train import distill_schedule

    assert distill_schedule(256, 256, 16) == [128, 64, 32, 16]
    assert distill_schedule(4, 4, 1) == [2, 1]
    with pytest.raises(ValueError, match="divide"):
        distill_schedule(4, 3, 1)
    with pytest.raises(ValueError, match="divide"):
        distill_schedule(256, 256, 24)


def test_distill_two_rounds_smoke(tmp_path):
    """4 -> 2 -> 1 on the shallow tiny model: both rounds run, each lands
    an async full_sliced checkpoint, and the final 1-step student drives
    a working DDIM sampler."""
    from diff3d_tpu.train import distill

    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))

    final, history = distill(
        model, cfg, params, _distill_batches(cfg.model.H),
        jax.random.PRNGKey(1), final_steps=1, round_steps=2,
        workdir=str(tmp_path), log_every=0)

    assert [h["student_steps"] for h in history] == [2, 1]
    for h in history:
        assert np.isfinite(h["final_loss"])
        ckpt = tmp_path / f"steps_{h['student_steps']}"
        assert h["checkpoint"] == str(ckpt)
        marker = json.loads((ckpt / "ckpt_format.json").read_text())
        assert marker["mode"] == "full_sliced"
        assert (ckpt / "2").is_dir()             # round_steps saved step

    # The distilled output is a different set of weights...
    assert any(
        not np.array_equal(a, b) for a, b in
        zip(jax.tree.leaves(final), jax.tree.leaves(params)))
    # ...that still runs the few-step sampler it was distilled for.
    ds = SyntheticDataset(num_objects=1, num_views=3, imgsize=8)
    out = Sampler(model, final, cfg, sampler_kind="ddim",
                  steps=1).synthesize(ds.all_views(0),
                                      jax.random.PRNGKey(2), max_views=3)
    assert out.shape[0] == 2 and np.isfinite(out).all()


@pytest.mark.distill
@pytest.mark.slow
def test_distill_full_ladder_long(tmp_path):
    """Longer soak (opt-in): the full 4-round ladder on a 16-step grid
    with more steps per round; every round checkpoints and stays
    finite."""
    from diff3d_tpu.train import distill

    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    cfg = dataclasses.replace(
        cfg, diffusion=dataclasses.replace(cfg.diffusion, timesteps=16))
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    final, history = distill(
        model, cfg, params, _distill_batches(cfg.model.H, B=2),
        jax.random.PRNGKey(1), final_steps=1, round_steps=16,
        workdir=str(tmp_path), log_every=0)
    assert [h["student_steps"] for h in history] == [8, 4, 2, 1]
    assert all(np.isfinite(h["final_loss"]) for h in history)
    assert all((tmp_path / f"steps_{k}").is_dir() for k in (8, 4, 2, 1))
    assert all(np.isfinite(l).all() for l in jax.tree.leaves(final))
