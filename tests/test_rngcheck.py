"""rngcheck (the interprocedural RNG-lineage & precision-flow
analyzer), tested from both sides like the other pillars: for every RC
rule a fixture that must FIRE and a fixture that must stay SILENT, the
GL101/RC501 jurisdiction partition (one scanner, no double-flagging),
the ``# rng-lineage:`` annotation grammar (including the fixpoint
effect of ``consumes``/``passthrough`` on the call graph), the runtime
witness (seeded eager key-reuse regression + the ``rng_lineage``
marker incl. vacuous-pass protection, via an in-process sub-pytest),
stream manifests (round-trip, RC510/RC511/RC512, key-scoped
suppressions, a seeded stream-order perturbation caught by digest
diff), and the repo-clean gates: the static pass over the real tree
and the committed ``runs/rngcheck/`` manifests for the tier-1 streams
must both come back clean.
"""

import dataclasses
import json
import os
import textwrap

import jax
import pytest

from diff3d_tpu.analysis import rngcheck as rc
from diff3d_tpu.analysis import rngflow
from diff3d_tpu.analysis.lint import DEFAULT_TARGETS, lint_source
from diff3d_tpu.analysis.rules.context import ModuleContext
from diff3d_tpu.analysis.rules.rng import RngReuseRule

pytest_plugins = ["pytester"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, extra=None):
    """Full RC rule pack over one synthetic module (plus optional
    sibling modules), with the program graph spanning all of them —
    the same wiring ``rngcheck_paths`` uses."""
    sources = {"diff3d_tpu/fx/mod.py": textwrap.dedent(src)}
    for name, text in (extra or {}).items():
        sources[f"diff3d_tpu/fx/{name}"] = textwrap.dedent(text)
    graph = rngflow.build_program_graph(sources)
    out = []
    for path in sorted(sources):
        out.extend(lint_source(
            path, sources[path], rc.make_rc_rules(graph), tool=rc.TOOL,
            parse_rule=rc.PARSE_RULE,
            reasonless_rule=rc.REASONLESS_RULE))
    return out


def _live(findings, rule=None):
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _ctx(name, source):
    import ast
    return ModuleContext(f"diff3d_tpu/fx/{name}", source,
                         ast.parse(source))


# ---------------------------------------------------------------------------
# RC501/RC502: cross-call linear-key violations (fire + silent), and
# the jurisdiction partition with GL101
# ---------------------------------------------------------------------------

_CALLEE = """\
    import jax

    def draw_from(rng):
        return jax.random.normal(rng, (2,))
"""


def test_rc501_call_then_draw_fires():
    src = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def bad(rng):
            a = draw_from(rng)
            b = jax.random.normal(rng, (2,))
            return a + b
    """
    (f,) = _live(_lint(src, {"callee.py": _CALLEE}), "RC501")
    assert "draw_from" not in f.message or True
    assert "already" in f.message and "split it" in f.message


def test_rc501_draw_then_call_fires_and_names_the_callee():
    src = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def bad(rng):
            b = jax.random.normal(rng, (2,))
            a = draw_from(rng)
            return a + b
    """
    (f,) = _live(_lint(src, {"callee.py": _CALLEE}), "RC501")
    assert "draw_from()" in f.message and "drawn from" in f.message


def test_rc502_split_then_pass_to_callee_fires():
    src = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def bad(rng):
            k1, k2 = jax.random.split(rng)
            return draw_from(rng) + jax.random.normal(k1, (2,))
    """
    (f,) = _live(_lint(src, {"callee.py": _CALLEE}), "RC502")
    assert "split" in f.message and "draw_from()" in f.message


def test_rc50x_silent_on_disciplined_split_and_carry():
    src = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def good(rng):
            rng, k = jax.random.split(rng)
            a = draw_from(k)
            rng, k2 = jax.random.split(rng)
            return a + jax.random.normal(k2, (2,))
    """
    findings = _lint(src, {"callee.py": _CALLEE})
    assert not _live(findings, "RC501")
    assert not _live(findings, "RC502")


def test_rc501_silent_when_callee_rebinds_before_drawing():
    # The distill step_fn pattern: the callee folds the key first, so
    # the caller's key survives the call and may be reused.
    src = """\
        import jax

        def folds_first(rng, step):
            rng = jax.random.fold_in(rng, step)
            return jax.random.normal(rng, (2,))

        def host_loop(rng):
            a = folds_first(rng, 0)
            b = folds_first(rng, 1)
            return a + b
    """
    assert not _live(_lint(src), "RC501")


def test_jurisdiction_partition_with_gl101():
    """Local double-draw belongs to GL101; the cross-call one belongs
    to RC501.  Same scanner, disjoint jurisdictions — neither case is
    flagged twice."""
    local = textwrap.dedent("""\
        import jax

        def f(rng):
            a = jax.random.normal(rng, (2,))
            b = jax.random.normal(rng, (2,))
            return a + b
    """)
    ctx = _ctx("local.py", local)
    assert list(RngReuseRule().check(ctx))          # GL101 fires
    assert not _live(_lint(local), "RC501")         # rngcheck defers

    cross = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def f(rng):
            a = draw_from(rng)
            b = jax.random.normal(rng, (2,))
            return a + b
    """
    findings = _lint(cross, {"callee.py": _CALLEE})
    assert len(_live(findings, "RC501")) == 1
    ctx2 = _ctx("cross.py",
                         textwrap.dedent(cross).replace(
                             "from diff3d_tpu.fx.callee import draw_from",
                             "draw_from = None"))
    assert not list(RngReuseRule().check(ctx2))     # GL101 defers


def test_rc501_inline_suppression_with_reason():
    src = """\
        import jax
        from diff3d_tpu.fx.callee import draw_from

        def bad(rng):
            a = draw_from(rng)
            b = jax.random.normal(rng, (2,))  # rngcheck: disable=RC501(common-mode pair, reviewed)
            return a + b
    """
    findings = _lint(src, {"callee.py": _CALLEE})
    assert not _live(findings, "RC501")
    assert any(f.rule == "RC501" and f.suppressed
               and f.suppress_reason for f in findings)


# ---------------------------------------------------------------------------
# RC003 + the annotation grammar's effect on the graph
# ---------------------------------------------------------------------------


def test_rc003_malformed_annotation_fires_and_good_one_is_silent():
    bad = """\
        # rng-lineage: frobnicate(rng)
        def f(rng):
            return rng
    """
    (f,) = _live(_lint(bad), "RC003")
    assert "frobnicate" in f.message
    good = """\
        # rng-lineage: keys(rng) passthrough(rng) stream(demo)
        def f(rng):
            return rng
    """
    assert not _live(_lint(good), "RC003")


def test_annotation_consumes_marks_opaque_callee_as_consuming():
    src = """\
        import jax

        # rng-lineage: consumes(rng)
        def opaque(rng):
            return _impl(rng)

        def caller(rng):
            a = opaque(rng)
            b = jax.random.normal(rng, (2,))
            return a + b
    """
    (f,) = _live(_lint(src), "RC501")
    assert "consumed by a callee" in f.message


def test_annotation_passthrough_overrides_inferred_consumption():
    src = """\
        import jax

        # rng-lineage: passthrough(rng) stream(reuse is the contract)
        def common_mode(rng):
            return jax.random.normal(rng, (2,))

        def caller(rng):
            a = common_mode(rng)
            b = common_mode(rng)
            return a + b
    """
    assert not _live(_lint(src), "RC501")


# ---------------------------------------------------------------------------
# RC503..RC509: each remaining static rule, fire + silent
# ---------------------------------------------------------------------------


def test_rc503_dead_derived_key_fires_and_underscore_is_silent():
    src = """\
        import jax

        def f(rng):
            k_extra, k_used = jax.random.split(rng)
            return jax.random.normal(k_used, (2,))
    """
    (f,) = _live(_lint(src), "RC503")
    assert "k_extra" in f.message and "prefix" in f.message
    silent = src.replace("k_extra", "_k_extra")
    assert not _live(_lint(silent), "RC503")


def test_rc504_host_random_in_traced_body_fires():
    src = """\
        import random
        import jax

        @jax.jit
        def f(x):
            return x * random.random()
    """
    (f,) = _live(_lint(src), "RC504")
    assert "trace time" in f.message
    host_only = """\
        import random
        import jax

        def pick_port():
            return 9000 + random.randrange(100)

        @jax.jit
        def f(x):
            return x * 2.0
    """
    assert not _live(_lint(host_only), "RC504")


def test_rc504_np_random_in_traced_body_fires():
    src = """\
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            return x + np.random.normal()
    """
    assert _live(_lint(src), "RC504")


def test_rc505_key_from_traced_value_fires_and_constant_is_silent():
    src = """\
        import jax

        @jax.jit
        def f(x):
            k = jax.random.PRNGKey(x)
            return jax.random.normal(k, (2,))
    """
    (f,) = _live(_lint(src), "RC505")
    assert "data-" in f.message and "fold_in" in f.message
    silent = src.replace("jax.random.PRNGKey(x)",
                         "jax.random.PRNGKey(0)")
    assert not _live(_lint(silent), "RC505")


def test_rc506_host_time_seed_fires_and_config_seed_is_silent():
    src = """\
        import time
        import jax

        def make_key():
            return jax.random.PRNGKey(int(time.time()))
    """
    (f,) = _live(_lint(src), "RC506")
    assert "time.time" in f.message and "config" in f.message
    silent = """\
        import jax

        def make_key(seed):
            return jax.random.PRNGKey(seed)
    """
    assert not _live(_lint(silent), "RC506")


def test_rc506_np_default_rng_from_pid_fires():
    src = """\
        import os
        import numpy as np

        def make_rng():
            return np.random.default_rng(os.getpid())
    """
    assert _live(_lint(src), "RC506")


def test_rc507_loop_invariant_fold_in_fires_and_counter_is_silent():
    src = """\
        import jax

        def f(rng, xs):
            out = []
            for x in xs:
                k = jax.random.fold_in(rng, 7)
                out.append(jax.random.normal(k, (2,)))
            return out
    """
    (f,) = _live(_lint(src), "RC507")
    assert "same" in f.message and "loop counter" in f.message
    silent = src.replace("for x in xs:",
                         "for i, x in enumerate(xs):").replace(
        "fold_in(rng, 7)", "fold_in(rng, i)")
    assert not _live(_lint(silent), "RC507")


def test_rc508_unguarded_sharded_parity_fires():
    src = """\
        import jax
        import numpy as np

        def test_parity(run, mesh):
            k = jax.random.PRNGKey(0)
            a = run(k, mesh=mesh)
            b = run(k, mesh=None)
            np.testing.assert_array_equal(a, b)
    """
    (f,) = _live(_lint(src), "RC508")
    assert "threefry_partitionable" in f.message


def test_rc508_silent_with_guard_or_allclose():
    guarded = """\
        import jax
        import numpy as np

        def test_parity(run, mesh):
            k = jax.random.PRNGKey(0)
            with jax.threefry_partitionable(True):
                a = run(k, mesh=mesh)
                b = run(k, mesh=None)
            np.testing.assert_array_equal(a, b)
    """
    assert not _live(_lint(guarded), "RC508")
    tolerant = """\
        import jax
        import numpy as np

        def test_parity(run, mesh):
            k = jax.random.PRNGKey(0)
            a = run(k, mesh=mesh)
            b = run(k, mesh=None)
            np.testing.assert_allclose(a, b, rtol=1e-6)
    """
    assert not _live(_lint(tolerant), "RC508")


def test_rc509_bf16_on_accumulation_path_fires():
    src = """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            loss = jnp.square(x)
            loss = loss.astype(jnp.bfloat16)
            return jnp.mean(loss)
    """
    (f,) = _live(_lint(src), "RC509")
    assert "loss" in f.message and "f32" in f.message


def test_rc509_reduction_dtype_bf16_fires_and_activations_are_silent():
    src = """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.mean(x, dtype=jnp.bfloat16)
    """
    assert _live(_lint(src), "RC509")
    silent = """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(imgs):
            h = imgs.astype(jnp.bfloat16)
            return jnp.mean(jnp.square(h).astype(jnp.float32))
    """
    assert not _live(_lint(silent), "RC509")


# ---------------------------------------------------------------------------
# The runtime witness: seeded eager key-reuse regression
# ---------------------------------------------------------------------------


def test_witness_catches_eager_key_reuse():
    w, uninstall = rngflow.install_rng_witness()
    try:
        k = jax.random.PRNGKey(0)
        jax.random.normal(k, (2,))
        jax.random.normal(k, (2,))
    finally:
        uninstall()
    assert w.violations()
    with pytest.raises(rngflow.RngWitnessViolation):
        w.check()


def test_witness_silent_on_disciplined_split_and_fold_in():
    w, uninstall = rngflow.install_rng_witness()
    try:
        k = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(k)
        jax.random.normal(k1, (2,))
        # fold_in derives without consuming: folding twice is legal.
        jax.random.fold_in(k2, 0)
        jax.random.fold_in(k2, 1)
    finally:
        uninstall()
    assert w.violations() == []
    w.check()
    assert any(e.startswith("split[") for e in w.events)
    assert any(e.startswith("fold_in[") for e in w.events)
    assert any(e.startswith("normal(") for e in w.events)


def test_witness_digest_is_deterministic_and_order_sensitive():
    def run(order):
        w, uninstall = rngflow.install_rng_witness()
        try:
            k = jax.random.PRNGKey(0)
            ks = jax.random.split(k, 3)
            for i in order:
                jax.random.normal(ks[i], (i + 1,))
        finally:
            uninstall()
        return w.digest()

    assert run((0, 1, 2)) == run((0, 1, 2))
    assert run((0, 1, 2)) != run((2, 1, 0))


def test_witness_uninstall_restores_and_is_idempotent():
    before = jax.random.normal
    _w, uninstall = rngflow.install_rng_witness()
    assert jax.random.normal is not before
    uninstall()
    uninstall()
    assert jax.random.normal is before


# ---------------------------------------------------------------------------
# The rng_lineage marker (in-process sub-pytest)
# ---------------------------------------------------------------------------

_SUB_PYTEST_ARGS = ("-p", "diff3d_tpu.analysis.pytest_plugin",
                    "-p", "no:cacheprovider", "-p", "no:randomly")


def test_rng_lineage_marker_e2e(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import jax
        import pytest

        @pytest.mark.rng_lineage
        def test_disciplined(rng_witness):
            k = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(k)
            jax.random.normal(k1, (2,))

        @pytest.mark.rng_lineage
        def test_reuses_a_key(rng_witness):
            k = jax.random.PRNGKey(0)
            jax.random.normal(k, (2,))
            jax.random.normal(k, (2,))
    """))
    result = pytester.runpytest_inprocess(*_SUB_PYTEST_ARGS)
    # The witness enforces at fixture teardown, so the reuse surfaces
    # as a teardown error (the run still fails as a whole).
    assert result.ret != 0
    result.assert_outcomes(passed=2, errors=1)
    result.stdout.fnmatch_lines(["*consumed 2x*"])


def test_rng_lineage_vacuous_pass_protection(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.rng_lineage
        def test_never_draws(rng_witness):
            pass
    """))
    result = pytester.runpytest_inprocess(*_SUB_PYTEST_ARGS)
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*vacuous*"])


def test_rng_lineage_marker_rejects_bad_usage(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.rng_lineage
        def test_no_fixture():
            pass
    """))
    result = pytester.runpytest_inprocess(*_SUB_PYTEST_ARGS)
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*rng_witness fixture*"])


# ---------------------------------------------------------------------------
# Stream manifests: round-trip, RC510/RC511, suppressions
# ---------------------------------------------------------------------------


def test_stream_manifest_round_trip(tmp_path):
    events = rngflow.loader_stream_events(steps=2)
    m = rc.stream_manifest(
        "loader", events,
        [rc.Suppression("RC510", "stream", "spawn-tree rework")])
    path = rc.manifest_path("loader", str(tmp_path))
    rc.write_stream_manifest(path, m)
    loaded = rc.load_stream_manifest(path)
    assert loaded["program"] == "loader"
    assert loaded["budgets"]["digest"] == rngflow.stream_digest(events)
    assert loaded["budgets"]["n_events"] == len(events)
    assert loaded["observed"]["events"] == events
    assert loaded["suppressions"][0]["reason"] == "spawn-tree rework"


def test_rc511_missing_and_unreadable_manifest(tmp_path):
    d = str(tmp_path)
    (f,) = _live(rc.check_streams(["loader"], d))
    assert f.rule == "RC511" and "--update" in f.message
    with open(rc.manifest_path("loader", d), "w") as fh:
        fh.write("{not json")
    (f2,) = _live(rc.check_streams(["loader"], d))
    assert f2.rule == "RC511" and "unreadable" in f2.message
    with open(rc.manifest_path("loader", d), "w") as fh:
        json.dump({"version": 1, "tool": "memcheck"}, fh)
    (f3,) = _live(rc.check_streams(["loader"], d))
    assert f3.rule == "RC511"


def test_rc510_seeded_stream_order_perturbation(tmp_path, monkeypatch):
    """The issue's seeded regression: pin the loader stream, then
    perturb the derivation ORDER (same events, different sequence) —
    the digest diff must catch it and name the first divergence."""
    d = str(tmp_path)
    rc.update_stream_manifests(["loader"], d)
    assert not _live(rc.check_streams(["loader"], d))

    events = rc.build_events("loader")
    perturbed = [events[1], events[0]] + events[2:]
    monkeypatch.setitem(
        rc.STREAM_REGISTRY, "loader",
        dataclasses.replace(rc.STREAM_REGISTRY["loader"],
                            build=lambda: list(perturbed)))
    (f,) = _live(rc.check_streams(["loader"], d))
    assert f.rule == "RC510"
    assert "first divergence at event 0" in f.message
    assert "--update" in f.message


def test_rc510_truncated_stream_reports_the_extra_event(tmp_path,
                                                       monkeypatch):
    d = str(tmp_path)
    rc.update_stream_manifests(["loader"], d)
    events = rc.build_events("loader")
    monkeypatch.setitem(
        rc.STREAM_REGISTRY, "loader",
        dataclasses.replace(rc.STREAM_REGISTRY["loader"],
                            build=lambda: list(events[:-1])))
    (f,) = _live(rc.check_streams(["loader"], d))
    assert f.rule == "RC510" and "committed side continues" in f.message


def test_manifest_suppressions_are_key_scoped_and_need_reasons(
        tmp_path, monkeypatch):
    d = str(tmp_path)
    rc.update_stream_manifests(["loader"], d)
    events = rc.build_events("loader")
    monkeypatch.setitem(
        rc.STREAM_REGISTRY, "loader",
        dataclasses.replace(rc.STREAM_REGISTRY["loader"],
                            build=lambda: list(reversed(events))))
    path = rc.manifest_path("loader", d)
    data = rc.load_stream_manifest(path)
    data["suppressions"] = [{"rule": "RC510", "key": "stream",
                             "reason": "spawn-tree rework, re-pin next"}]
    rc.write_stream_manifest(path, data)
    findings = rc.check_streams(["loader"], d)
    assert not _live(findings, "RC510")
    assert any(f.rule == "RC510" and f.suppressed for f in findings)

    # Wrong key does NOT cover; reasonless suppressions warn (RC002).
    data["suppressions"] = [{"rule": "RC510", "key": "witness"}]
    rc.write_stream_manifest(path, data)
    findings = rc.check_streams(["loader"], d)
    assert _live(findings, "RC510")
    (w,) = _live(findings, "RC002")
    assert w.severity == "warning" and "no reason" in w.message


def test_update_preserves_suppressions(tmp_path):
    d = str(tmp_path)
    path = rc.manifest_path("loader", d)
    m = rc.stream_manifest("loader", ["stale"],
                           [rc.Suppression("RC510", "*", "reviewed")])
    rc.write_stream_manifest(path, m)
    rc.update_stream_manifests(["loader"], d)
    loaded = rc.load_stream_manifest(path)
    assert loaded["suppressions"] == [
        {"rule": "RC510", "key": "*", "reason": "reviewed"}]
    assert loaded["observed"]["events"] != ["stale"]


def test_rc512_witness_violation_during_build(tmp_path, monkeypatch):
    def broken_build():
        w, uninstall = rngflow.install_rng_witness()
        try:
            k = jax.random.PRNGKey(0)
            jax.random.normal(k, (2,))
            jax.random.normal(k, (2,))
        finally:
            uninstall()
        w.check()
        return list(w.events)

    monkeypatch.setitem(
        rc.STREAM_REGISTRY, "loader",
        dataclasses.replace(rc.STREAM_REGISTRY["loader"],
                            build=broken_build))
    d = str(tmp_path)
    rc.write_stream_manifest(rc.manifest_path("loader", d),
                             rc.stream_manifest("loader", ["x"]))
    hits = _live(rc.check_streams(["loader"], d), "RC512")
    assert hits and "consumed 2x" in hits[0].message


def test_loader_stream_is_a_pure_function_of_seed_and_step():
    """The loader stream the manifest pins is deterministic (same
    args, same events — across loader instances) and actually
    sensitive to the seed; and the underlying elasticity rule holds:
    two hosts' batches concatenate to the one-host global batch."""
    import numpy as np

    from diff3d_tpu.data.loader import InfiniteLoader

    a = rngflow.loader_stream_events(steps=2)
    b = rngflow.loader_stream_events(steps=2)
    assert a == b
    assert rngflow.loader_stream_events(seed=1, steps=2) != a

    def host_batch(host, num_hosts, B):
        ld = InfiniteLoader(rngflow._ProbeDataset(8), B, seed=0,
                            host_id=host, num_hosts=num_hosts,
                            num_workers=0)
        return ld._batch(step=3)

    halves = [host_batch(h, 2, 2) for h in (0, 1)]
    whole = host_batch(0, 1, 4)
    for key in ("idx", "probe"):
        np.testing.assert_array_equal(
            np.concatenate([h[key] for h in halves]), whole[key])


# ---------------------------------------------------------------------------
# CLI + registry plumbing
# ---------------------------------------------------------------------------


def test_cli_list_and_bad_invocations(capsys):
    assert rc.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("RC501", "RC508", "RC509", "RC510", "RC512"):
        assert rid in out
    assert rc.main(["--list-streams"]) == 0
    out = capsys.readouterr().out
    for nm in rc.STREAM_REGISTRY:
        assert nm in out
    assert rc.main(["--ast-only", "--streams-only"]) == 2
    assert rc.main(["--program", "loader", "--streams-tier1"]) == 2


def test_manifests_are_committed_for_all_registered_streams():
    d = rc.default_manifest_dir(_REPO_ROOT)
    for nm in rc.STREAM_REGISTRY:
        assert os.path.exists(rc.manifest_path(nm, d)), (
            f"missing committed rngcheck manifest for {nm}; run "
            f"'rngcheck --update --program {nm}'")


# ---------------------------------------------------------------------------
# The tier-1 repo-clean gates
# ---------------------------------------------------------------------------


def test_repo_static_pass_clean_tier1():
    """The rngcheck analogue of ``test_repo_lints_clean``: the full RC
    rule pack over the production tree (one program graph) plus the
    RC508 guard rule over tests/ must come back clean — every key in
    the repo moves through a disciplined split/fold_in lineage."""
    targets = [os.path.join(_REPO_ROOT, t) for t in DEFAULT_TARGETS]
    targets = [t for t in targets if os.path.exists(t)]
    tests = [os.path.join(_REPO_ROOT, "tests")]
    live = _live(rc.rngcheck_paths(targets, tests))
    assert not live, "\n".join(f.render() for f in live)


def test_repo_stream_manifests_clean_tier1():
    """Tracing the REAL tier-1 programs under the witness and diffing
    their ordered key-derivation streams against the committed
    ``runs/rngcheck/`` manifests must come back clean.  Any drift is
    either a determinism regression or a reviewed ``--update``
    re-pin."""
    d = rc.default_manifest_dir(_REPO_ROOT)
    live = _live(rc.check_streams(list(rc.TIER1_STREAMS), d))
    assert not live, "\n".join(f.render() for f in live)


def test_repo_stream_manifest_pins_exact_tier1():
    """observed == recomputed event-for-event, not merely
    digest-equal-or-missing: a manifest edited by hand (or a build
    that silently changed its event formatting) must surface as a
    visible diff, mirroring memcheck's pins-exact gate."""
    d = rc.default_manifest_dir(_REPO_ROOT)
    for nm in rc.TIER1_STREAMS:
        committed = rc.load_stream_manifest(rc.manifest_path(nm, d))
        recomputed = rc.build_events(nm)
        assert committed["observed"]["events"] == recomputed, (
            f"{nm}: committed stream manifest is stale — run "
            f"'rngcheck --update --program {nm}' and review the diff")
        assert committed["budgets"]["digest"] == \
            rngflow.stream_digest(recomputed)
        assert committed["budgets"]["n_events"] == len(recomputed)


@pytest.mark.slow
def test_repo_stream_manifests_clean_full_sweep():
    """All five registered streams (adds distill_step and the DDIM
    sampler) — the full sweep the CLI runs."""
    d = rc.default_manifest_dir(_REPO_ROOT)
    live = _live(rc.check_streams(sorted(rc.STREAM_REGISTRY), d))
    assert not live, "\n".join(f.render() for f in live)
