import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import MeshConfig, TrainConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh
from diff3d_tpu.train import (CheckpointManager, TrainState, Trainer,
                              create_train_state, ema_decay_per_step,
                              make_train_step, warmup_schedule)
from diff3d_tpu.train.trainer import init_params


def tiny_cfg(**train_kw):
    # shallow 2-level UNet: these tests assert train-step PROPERTIES
    # (equality across shardings, NaN guards, accumulation, resume),
    # none of which depend on UNet depth — and it halves the dominant
    # cost of this file, XLA-compiling ~20 block graphs per mesh config.
    # Depth-sensitive coverage lives in test_model / test_torch_parity /
    # the driver dryrun, all on the full 4-level shape.
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    if train_kw:
        import dataclasses
        cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, **train_kw))
    return cfg


def make_batch(cfg, B=8, seed=0):
    ds = SyntheticDataset(num_objects=2, num_views=4,
                          imgsize=cfg.model.H, seed=seed)
    b = next(InfiniteLoader(ds, B, seed=seed, num_workers=0))
    return {"imgs": jnp.asarray(b["imgs"]), "R": jnp.asarray(b["R"]),
            "T": jnp.asarray(b["T"]), "K": jnp.asarray(b["K"])}


@pytest.fixture
def partitionable_rng():
    """Run the test under partitionable threefry.  With the legacy
    lowering, ``jax.random`` produces DIFFERENT bits when its output is
    sharded vs replicated, so a mesh-sharded step can never be
    bit-compared against its single-device oracle — the root cause of
    the long-standing context-parallel trajectory mismatches.
    Partitionable threefry makes the bits a pure function of
    key+position, independent of output sharding.  Scoped to the
    equality tests (not package-global) because the partitionable
    lowering roughly doubles RNG cost on the CPU test backend."""
    with jax.threefry_partitionable(True):
        yield


def test_warmup_schedule_linear_then_flat():
    cfg = TrainConfig(lr=1e-4, warmup_examples=1000, global_batch=100)
    sched = warmup_schedule(cfg)  # 10 warmup steps, (step+1)/10 ramp
    np.testing.assert_allclose(float(sched(0)), 1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(sched(4)), 5e-5, rtol=1e-5)
    np.testing.assert_allclose(float(sched(9)), 1e-4, rtol=1e-5)
    np.testing.assert_allclose(float(sched(1000)), 1e-4, rtol=1e-5)


def test_ema_decay_halflife():
    cfg = TrainConfig(global_batch=128, ema_halflife_examples=500_000)
    d = ema_decay_per_step(cfg)
    halflife_steps = 500_000 / 128
    np.testing.assert_allclose(d ** halflife_steps, 0.5, rtol=1e-6)


def test_train_step_overfits_fixed_batch():
    """Overfit-one-batch integration check (SURVEY.md §7 test plan): with a
    fast lr (tiny-config default warmup spans the whole horizon at ~zero
    lr) the loss trend over repeated steps on one batch must fall clearly.
    Windowed means, not two single draws — the per-step diffusion loss is
    noisy in the sampled logsnr."""
    cfg = tiny_cfg(lr=1e-3, warmup_examples=8)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    state = create_train_state(params, cfg.train)
    step_fn = make_train_step(model, cfg, env=None)
    batch = make_batch(cfg)
    # Host copy of the init: the donated step invalidates the device
    # buffers `params` aliases.
    params0 = jax.device_get(params)

    losses = []
    for _ in range(60):
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert int(state.step) == 60
    head, tail = np.mean(losses[:10]), np.mean(losses[-10:])
    assert tail < head * 0.9, (head, tail)

    # EMA semantics, on the same 60-step run: the shadow moved off its
    # initial copy of the params but trails them (decay < 1), i.e. it
    # is neither frozen nor a live alias.
    ema_vs_params = jax.tree.leaves(jax.tree.map(
        lambda e, p: float(jnp.max(jnp.abs(e - p))),
        state.ema_params, state.params))
    ema_vs_init = jax.tree.leaves(jax.tree.map(
        lambda e, p0: float(np.max(np.abs(np.asarray(e) - p0))),
        state.ema_params, params0))
    assert any(v > 0 for v in ema_vs_params)
    assert any(v > 0 for v in ema_vs_init)


# Tier-1 budget: single-step EMA movement is superseded in tier 1 by
# test_train_step_overfits_fixed_batch's 60-step EMA assertions (moved
# off init, trails params) and the exact EMA trajectory pin in
# test_multi_step_trajectory_equality[fsdp].
@pytest.mark.slow
def test_train_step_updates_ema_toward_params():
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None)
    batch = make_batch(cfg)
    state2, _ = step_fn(state, batch, rng)
    # EMA moved but is not equal to the new params
    diffs = jax.tree.map(
        lambda e, p: float(jnp.max(jnp.abs(e - p))),
        state2.ema_params, state2.params)
    assert any(v > 0 for v in jax.tree.leaves(diffs))


# Tier-1 budget: both parametrizations are smoke-level (finite loss,
# step counter) and superseded in tier 1 — replicated by
# test_replicated_and_sharded_steps_agree's cross-check, fsdp by
# test_multi_step_trajectory_equality[fsdp]'s 25-step equality pin.
@pytest.mark.slow
@pytest.mark.parametrize("policy", ["replicated", "fsdp"])
def test_sharded_train_step_on_mesh(policy):
    cfg = tiny_cfg()
    env = make_mesh(MeshConfig(param_sharding=policy))
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(
        state, TrainState(step=env.replicated(),
                          params=env.params(state.params),
                          opt_state=env.params(state.opt_state),
                          ema_params=env.params(state.ema_params)))
    step_fn = make_train_step(model, cfg, env)
    batch = jax.device_put(make_batch(cfg), env.batch())
    state, metrics = step_fn(state, batch, rng)
    state, metrics = step_fn(state, batch, rng)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 2


def test_replicated_and_sharded_steps_agree():
    """DP over the mesh computes the same update as single-device (the
    correctness property the reference's DDP path loses, SURVEY.md §2.7)."""
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    batch = make_batch(cfg)

    s1 = create_train_state(params, cfg.train)
    f1 = make_train_step(model, cfg, env=None, donate=False)
    s1, m1 = f1(s1, batch, rng)

    env = make_mesh()
    s2 = create_train_state(params, cfg.train)
    s2 = jax.device_put(
        s2, TrainState(step=env.replicated(), params=env.params(s2.params),
                       opt_state=env.params(s2.opt_state),
                       ema_params=env.params(s2.ema_params)))
    f2 = make_train_step(model, cfg, env, donate=False)
    s2, m2 = f2(s2, jax.device_put(batch, env.batch()), rng)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    a = jax.tree.leaves(s1.params)[0]
    b = jax.tree.leaves(s2.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


_TRAJ_REF_CACHE = []


# Tier-1 runs the fsdp trajectory only: the fsdp+tp and
# context-parallel parametrizations re-prove the same 25-step chain
# (~28 s combined) while their single-step mesh equalities stay in
# tier 1 (test_fsdp_tp_train_step_runs,
# test_context_parallel_step_matches_replicated).
@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(param_sharding="fsdp"),
    pytest.param(MeshConfig(model_parallel=2, param_sharding="fsdp+tp"),
                 marks=pytest.mark.slow),
    pytest.param(MeshConfig(model_parallel=2, context_parallel=True),
                 marks=pytest.mark.slow),
], ids=["fsdp", "fsdp+tp", "context-parallel"])
def test_multi_step_trajectory_equality(mesh_cfg, partitionable_rng):
    """25-step TRAJECTORY equality: the sharded step must track the
    single-device step through a long chain of Adam/EMA updates and
    step-folded rng draws, not just agree on one update (r3 VERDICT:
    1-2-step equality can hide slow divergence from e.g. a sharding-
    dependent reduction order or a mis-folded per-step rng)."""
    import dataclasses

    n_steps = 25
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    # A 3-batch cycle gives data variation across steps without paying
    # loader overhead 25 times.
    batches = [make_batch(cfg, seed=s) for s in range(3)]

    def run(env, cfg_run):
        s = create_train_state(params, cfg_run.train)
        if env is not None:
            s = jax.device_put(s, env.state_shardings(s))
        f = make_train_step(model, cfg_run, env, donate=False)
        losses = []
        for i in range(n_steps):
            b = batches[i % len(batches)]
            if env is not None:
                b = jax.device_put(b, env.batch())
            s, m = f(s, b, rng)
            losses.append(float(m["loss"]))
        return (np.asarray(losses), jax.device_get(s.params),
                jax.device_get(s.ema_params))

    # The unsharded reference trajectory is identical for every mesh
    # parametrization (same PRNGKey(0) init, same batch cycle, same
    # partitionable-threefry fixture), so compute it once per module
    # run instead of once per parametrization — recomputing it tripled
    # the reference cost for no extra coverage.
    if not _TRAJ_REF_CACHE:
        _TRAJ_REF_CACHE.append(run(None, cfg))
    ref_losses, ref_params, ref_ema = _TRAJ_REF_CACHE[0]
    cfg_sharded = dataclasses.replace(cfg, mesh=mesh_cfg)
    env = make_mesh(mesh_cfg)
    losses, params_s, ema_s = run(env, cfg_sharded)

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(params_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3)
    for a, b in zip(jax.tree.leaves(ref_ema), jax.tree.leaves(ema_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3)


def test_checkpoint_roundtrip(tmp_path):
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None, donate=False)
    state, _ = step_fn(state, make_batch(cfg), rng)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    assert mgr.save(state, force=True)
    mgr.wait()
    assert mgr.latest_step() == 1

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    restored = mgr.restore(abstract)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_checkpoint_ema_bf16_mode(tmp_path):
    """ema_bf16 saves ~1/16 the bytes (bf16 EMA only), restores via
    restore_ema from a marker-detected directory, and the trainer
    warm-restarts from it (params == ema == restored EMA, step kept)."""
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None, donate=False)
    state, _ = step_fn(state, make_batch(cfg), rng)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2, mode="ema_bf16")
    assert mgr.save(state, force=True)
    mgr.wait()
    mgr.close()

    # A fresh manager with no mode argument detects ema_bf16 via marker.
    mgr2 = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr2.mode == "ema_bf16"
    with pytest.raises(ValueError):
        mgr2.restore(jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state))
    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state.params)
    step, ema = mgr2.restore_ema(abstract_params)
    assert step == 1
    for a, b in zip(jax.tree.leaves(state.ema_params),
                    jax.tree.leaves(ema)):
        assert np.asarray(b).dtype == np.asarray(a).dtype  # upcast back
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0.008, rtol=0.008)  # bf16
    mgr2.close()

    # An unmarked directory that already holds FULL checkpoints must not
    # be relabelable as ema_bf16 (that would wedge restores of the
    # existing steps behind a wrong marker).
    full = CheckpointManager(str(tmp_path / "full"))
    assert full.save(state, force=True)
    full.wait()
    full.close()
    with pytest.raises(ValueError, match="refusing to relabel"):
        CheckpointManager(str(tmp_path / "full"), mode="ema_bf16")


# Tier-1 budget (870s): exact same-mesh roundtrip is subsumed by the
# resharded roundtrip in test_elastic.py (same restore path, stronger
# topology contract) + the guards test's roundtrip assert below.
@pytest.mark.slow
def test_checkpoint_full_sliced_exact_roundtrip(tmp_path):
    """full_sliced streams the state leaf-by-leaf but keeps full-mode
    semantics: EXACT resume (params, EMA, Adam moments, step all
    bit-equal), marker auto-detection, retention, and the trainer's
    ordinary restore path (mode branches on != ema_bf16)."""
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None, donate=False)
    state, _ = step_fn(state, make_batch(cfg), rng)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2,
                            mode="full_sliced")
    assert mgr.save(state)
    mgr.wait()
    assert mgr.latest_step() == 1
    assert not mgr.save(state)          # same step: no duplicate write

    # marker auto-detection + EXACT restore of every leaf incl. opt_state
    mgr2 = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    assert mgr2.mode == "full_sliced"
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    restored = mgr2.restore(abstract)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError):      # no EMA-only view of full data
        mgr2.restore_ema(jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            state.params))

    # retention: keep=2 prunes the oldest of 3 saved steps
    state2, _ = step_fn(state, make_batch(cfg), rng)
    state3, _ = step_fn(state2, make_batch(cfg), rng)
    assert mgr2.save(state2) and mgr2.save(state3)
    assert mgr2._sliced_steps() == [2, 3]

    # the restored state continues the optimizer trajectory exactly:
    # one more step from the restored state == one more step from the
    # original (Adam moments included in the equality)
    cont, _ = step_fn(restored, make_batch(cfg), rng)
    for a, b in zip(jax.tree.leaves(state2), jax.tree.leaves(cont)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a full-mode (Orbax) directory must refuse full_sliced relabeling
    full = CheckpointManager(str(tmp_path / "full"))
    assert full.save(state, force=True)
    full.wait()
    full.close()
    with pytest.raises(ValueError, match="refusing to relabel"):
        CheckpointManager(str(tmp_path / "full"), mode="full_sliced")


def test_checkpoint_full_sliced_guards(tmp_path):
    """full_sliced error surfaces: a missing explicit step names the
    available ones (not a raw FileNotFoundError), a saved-vs-target dtype
    mismatch is a config error (not a silent cast), and
    save_interval_steps/force gate saves like the Orbax modes."""
    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None, donate=False)
    state, _ = step_fn(state, make_batch(cfg), rng)      # step 1

    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3,
                            save_interval_steps=2, mode="full_sliced")
    # interval gating: step 1 % 2 != 0 -> skipped unless forced
    assert not mgr.save(state)
    assert mgr._sliced_steps() == []
    assert mgr.save(state, force=True)
    state2, _ = step_fn(state, make_batch(cfg), rng)     # step 2
    assert mgr.save(state2)                              # 2 % 2 == 0

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    # explicit missing/pruned step: ValueError naming what IS there
    with pytest.raises(ValueError, match=r"available steps: \[1, 2\]"):
        mgr.restore(abstract, step=7)
    # dtype mismatch = config mismatch, loudly (no silent .astype)
    wrong = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, jnp.bfloat16 if x.dtype == jnp.float32 else x.dtype),
        state)
    with pytest.raises(ValueError, match="config mismatch"):
        mgr.restore(wrong, step=1)
    # ...and the matching restore still round-trips exactly
    restored = mgr.restore(abstract, step=1)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# Tier-1 budget: the manager-level ema_bf16 roundtrip stays in tier 1
# (test_checkpoint_ema_bf16_mode); this trainer-level warm-restart
# wiring runs under --runslow / RUN_SLOW=1.
@pytest.mark.slow
def test_trainer_warm_restart_from_ema_bf16(tmp_path):
    cfg = tiny_cfg(max_steps=2, ckpt_every=2, log_every=1,
                   ckpt_mode="ema_bf16")
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)
    loader = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                            num_workers=0)
    tr = Trainer(cfg, loader, workdir=str(tmp_path))
    state = tr.train()
    ema = jax.device_get(state.ema_params)

    loader2 = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                             num_workers=0, start_step=2)
    cfg2 = tiny_cfg(max_steps=3, ckpt_every=10, log_every=1,
                    ckpt_mode="ema_bf16")
    tr2 = Trainer(cfg2, loader2, workdir=str(tmp_path), transfer=True)
    assert int(tr2.state.step) == 2
    for a, b in zip(jax.tree.leaves(ema),
                    jax.tree.leaves(jax.device_get(tr2.state.params))):
        np.testing.assert_allclose(a, b, atol=0.008, rtol=0.008)
    # warm restart: params seeded from EMA
    for a, b in zip(jax.tree.leaves(jax.device_get(tr2.state.params)),
                    jax.tree.leaves(jax.device_get(tr2.state.ema_params))):
        np.testing.assert_array_equal(a, b)
    # ... and training actually CONTINUES: the restored params and ema
    # must be distinct buffers (the step donates the state; aliased
    # leaves fail at execute time), which only running a step proves.
    state2 = tr2.train()
    assert int(state2.step) == 3


def test_trainer_end_to_end(tmp_path):
    import json

    cfg = tiny_cfg(max_steps=3, ckpt_every=3, log_every=1, eval_every=3)
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)
    loader = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                            num_workers=0)
    tr = Trainer(cfg, loader, workdir=str(tmp_path))
    tr.val_loader = InfiniteLoader(
        SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H,
                         seed=1),
        cfg.train.global_batch, num_workers=0)
    state = tr.train()
    assert int(state.step) == 3
    assert os.path.exists(tmp_path / "metrics.jsonl")
    assert tr.ckpt.latest_step() == 3
    # eval_every scored EMA params on the val loader into metrics.jsonl
    # (the reference's unfinished TODO #1, README.md:32).
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    vals = [r for r in recs if "val_loss" in r]
    assert vals and np.isfinite(vals[0]["val_loss"])

    # resume path (--transfer semantics, reference train.py:244-251)
    loader2 = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                             num_workers=0, start_step=3)
    tr2 = Trainer(cfg, loader2, workdir=str(tmp_path), transfer=True)
    assert int(tr2.state.step) == 3


def test_config_validate_rejects_clip_schedule_mismatch():
    import dataclasses
    from diff3d_tpu.config import DiffusionConfig
    cfg = tiny_cfg()
    bad = dataclasses.replace(
        cfg, diffusion=dataclasses.replace(cfg.diffusion, logsnr_max=15.0))
    with pytest.raises(ValueError, match="logsnr_clip"):
        bad.validate()


def test_step_timer_and_profile_window(tmp_path):
    import time

    from diff3d_tpu.utils import StepTimer, profile_window

    t = StepTimer()
    assert t.summary() == {}
    for _ in range(4):
        t.tick()
        time.sleep(0.002)
    s = t.summary()
    assert s["step_ms_mean"] >= 1.0
    assert s["step_ms_p95"] >= s["step_ms_p50"]

    # disabled window is a no-op; enabled window writes a trace dir
    with profile_window(str(tmp_path / "prof_off"), enabled=False):
        pass
    assert not os.path.exists(tmp_path / "prof_off")
    with profile_window(str(tmp_path / "prof")):
        jnp.zeros(8).block_until_ready()
    assert os.path.isdir(tmp_path / "prof")


def test_trainer_halts_on_nonfinite_loss(tmp_path):
    cfg = tiny_cfg(max_steps=2, ckpt_every=10, log_every=1)
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)

    class PoisonLoader:
        def __init__(self):
            self._it = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                                      num_workers=0)

        def __next__(self):
            b = next(self._it)
            b["imgs"] = b["imgs"] * np.nan
            return b

    tr = Trainer(cfg, PoisonLoader(), workdir=str(tmp_path))
    with pytest.raises(FloatingPointError, match="non-finite"):
        tr.train()


def test_trainer_emergency_checkpoint_on_crash(tmp_path):
    cfg = tiny_cfg(max_steps=5, ckpt_every=100, log_every=100)
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)

    class CrashLoader:
        def __init__(self):
            self.n = 0
            self._it = InfiniteLoader(ds, cfg.train.global_batch, seed=0,
                                      num_workers=0)

        def __next__(self):
            self.n += 1
            if self.n > 2:
                raise KeyboardInterrupt  # simulated preemption
            return next(self._it)

    tr = Trainer(cfg, CrashLoader(), workdir=str(tmp_path))
    with pytest.raises(KeyboardInterrupt):
        tr.train()
    tr.ckpt.wait()
    # the 2 completed steps were preserved by the emergency save
    assert tr.ckpt.latest_step() == 2


def test_grad_accumulation_step():
    """accum_steps=2 scans two microbatches per optimizer step: same state
    pytree, one step counter increment, loss decreases while training.
    (warmup shortened: the default tiny-config warmup spans the whole test
    horizon at near-zero lr, hiding any progress.)"""
    cfg = tiny_cfg(accum_steps=2, lr=1e-3, warmup_examples=8)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    step_fn = make_train_step(model, cfg, env=None)
    batch = make_batch(cfg)  # B=8 -> 2 microbatches of 4

    first = None
    for _ in range(25):
        state, metrics = step_fn(state, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    assert int(state.step) == 25
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


def test_grad_accumulation_rejects_indivisible_batch():
    cfg = tiny_cfg(accum_steps=3)  # global_batch=8 not divisible by 3
    with pytest.raises(ValueError, match="accum_steps"):
        cfg.validate()


def test_context_parallel_step_matches_replicated(partitionable_rng):
    """GSPMD context parallelism (spatial axis sharded over the model
    axis via activation constraints) computes the same update as the
    unsharded step — XLA's halo exchange / GN reduction / KV gathers are
    semantics-preserving by construction; this pins it."""
    import dataclasses

    cfg = tiny_cfg()
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    params = init_params(model, cfg, rng)
    batch = make_batch(cfg)

    s1 = create_train_state(params, cfg.train)
    f1 = make_train_step(model, cfg, env=None, donate=False)
    s1, m1 = f1(s1, batch, rng)

    cp = dataclasses.replace(
        cfg, mesh=MeshConfig(model_parallel=2, context_parallel=True))
    env = make_mesh(cp.mesh)
    assert dict(env.mesh.shape) == {"data": 4, "model": 2}
    s2 = create_train_state(params, cfg.train)
    s2 = jax.device_put(
        s2, TrainState(step=env.replicated(), params=env.params(s2.params),
                       opt_state=env.params(s2.opt_state),
                       ema_params=env.params(s2.ema_params)))
    f2 = make_train_step(model, cp, env, donate=False)
    s2, m2 = f2(s2, jax.device_put(batch, env.batch()), rng)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


# Tier-1 budget: superseded in tier 1 by test_trainer_end_to_end,
# which now runs with eval_every + a val loader and asserts the same
# val_loss record — one trainer compile instead of two.
@pytest.mark.slow
def test_val_loss_logged(tmp_path):
    """eval_every scores EMA params on val batches into metrics.jsonl —
    the reference's own unfinished TODO #1 (README.md:32)."""
    import json

    cfg = tiny_cfg(max_steps=2, eval_every=2, ckpt_every=2, log_every=1)
    env = make_mesh()
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)
    tr = Trainer(cfg, InfiniteLoader(ds, cfg.train.global_batch,
                                     num_workers=0),
                 env, workdir=str(tmp_path))
    tr.val_loader = InfiniteLoader(
        SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H,
                         seed=1),
        cfg.train.global_batch, num_workers=0)
    tr.train()
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    vals = [r for r in recs if "val_loss" in r]
    assert vals and np.isfinite(vals[0]["val_loss"])


# Tier-1 budget: graceful preemption (checkpoint current step + return)
# is exercised by a real SIGTERM in test_chaos.py's async exact-resume
# test and three times per run in test_elastic.py's chaos loop.
@pytest.mark.slow
def test_preemption_checkpoints_and_stops(tmp_path):
    """A preemption signal makes the loop checkpoint the current step and
    return (graceful TPU spot/maintenance handling; the reference dies
    mid-step and loses up to 50 steps)."""
    cfg = tiny_cfg(max_steps=50, ckpt_every=100, log_every=100)
    env = make_mesh()
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)

    class PreemptAfter:
        """Loader that raises the flag after a few batches."""

        def __init__(self, inner, trainer_box, after):
            self.inner, self.box, self.n, self.after = inner, trainer_box, 0, after

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == self.after:
                self.box[0]._preempted.set()   # what the signal handler does
            return next(self.inner)

    box = [None]
    loader = PreemptAfter(
        InfiniteLoader(ds, cfg.train.global_batch, num_workers=0), box, 3)
    tr = Trainer(cfg, loader, env, workdir=str(tmp_path))
    box[0] = tr
    state = tr.train()
    assert int(state.step) == 3          # stopped right after the flag
    assert tr.preempt_observed_step == 3  # observed step is recorded
    tr.ckpt.wait()
    assert tr.ckpt.latest_step() == 3    # exact-step checkpoint exists

    # resume picks up at the preempted step
    tr2 = Trainer(cfg, None, env, workdir=str(tmp_path), transfer=True)
    assert int(tr2.state.step) == 3


def test_preemption_handler_sigint_and_uninstall(tmp_path):
    """install_preemption_handler also covers SIGINT (a ^C must behave
    like a preemption: checkpoint + clean stop, not a stack trace), and
    the returned uninstall handle restores the previous handlers without
    clobbering one somebody else installed in the meantime."""
    import signal
    import time

    cfg = tiny_cfg(max_steps=2, ckpt_every=10, log_every=0)
    tr = Trainer(cfg, None, workdir=str(tmp_path))
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)
    uninstall = tr.install_preemption_handler()
    try:
        # a real SIGINT sets the flag instead of raising KeyboardInterrupt
        os.kill(os.getpid(), signal.SIGINT)
        deadline = time.monotonic() + 5
        while not tr._preempted.is_set() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert tr._preempted.is_set()
    finally:
        uninstall()
    assert signal.getsignal(signal.SIGINT) is prev_int
    assert signal.getsignal(signal.SIGTERM) is prev_term
    uninstall()                           # idempotent

    # uninstall must not stomp a handler installed after ours
    tr2 = Trainer(cfg, None, workdir=str(tmp_path), transfer=False)
    uninstall2 = tr2.install_preemption_handler()

    def foreign(signum, frame):           # pragma: no cover - never fired
        pass

    try:
        signal.signal(signal.SIGTERM, foreign)
        uninstall2()
        assert signal.getsignal(signal.SIGTERM) is foreign
        assert signal.getsignal(signal.SIGINT) is prev_int
    finally:
        signal.signal(signal.SIGTERM, prev_term)


def test_preemption_handler_idempotent_install_and_reentrant(tmp_path):
    """The elasticity-loop contract: double-install returns the SAME
    uninstaller (no handler chained onto itself), double-uninstall is a
    no-op, and a signal delivered while the handler is already running
    only sets the stop flag instead of recursing into the chain."""
    import signal

    cfg = tiny_cfg(max_steps=2, ckpt_every=10, log_every=0)
    tr = Trainer(cfg, None, workdir=str(tmp_path))
    prev_term = signal.getsignal(signal.SIGTERM)

    chained = []
    signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    try:
        uninstall = tr.install_preemption_handler()
        assert tr.install_preemption_handler() is uninstall
        handler = signal.getsignal(signal.SIGTERM)

        # Signal-during-signal: a second delivery while the handler is
        # mid-flight must not re-enter the chained previous handler.
        tr._in_handler = True
        try:
            handler(signal.SIGTERM, None)
        finally:
            tr._in_handler = False
        assert tr._preempted.is_set()
        assert chained == []              # chain suppressed while nested

        tr._preempted.clear()
        handler(signal.SIGTERM, None)     # normal delivery chains once
        assert tr._preempted.is_set()
        assert chained == [signal.SIGTERM]
        assert tr._in_handler is False    # guard cleared on the way out

        uninstall()
        assert len(chained) == 1
        uninstall()                       # second uninstall: no-op
        # A fresh install after uninstall works (new chain, new handle).
        uninstall3 = tr.install_preemption_handler()
        assert uninstall3 is not uninstall
        uninstall3()
    finally:
        signal.signal(signal.SIGTERM, prev_term)


# Tier-1 budget: this same-topology contract is pinned (stronger) by
# test_chaos.py::test_trainer_sigterm_async_checkpoint_exact_resume
# (real SIGTERM, async writer, bit-identical next-K) and extended to
# topology changes by test_elastic.py.
@pytest.mark.slow
def test_full_sliced_deterministic_resume(tmp_path):
    """The ISSUE-6 satellite pin: checkpoint at step N (through the
    default ASYNC writer), restore into a fresh trainer with the loader
    sought to N, and the next K steps are bit-identical to a run that was
    never interrupted — params, EMA, Adam moments, step counter, and the
    data-loader position all line up exactly."""
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=8)

    def loader(start=0):
        return InfiniteLoader(ds, 8, seed=0, num_workers=0,
                              start_step=start)

    cfg_a = tiny_cfg(max_steps=3, ckpt_every=3, log_every=0,
                     ckpt_mode="full_sliced")
    tr = Trainer(cfg_a, loader(), workdir=str(tmp_path / "resumed"))
    tr.train()
    tr.ckpt.wait()
    assert tr.ckpt.latest_step() == 3

    cfg_b = tiny_cfg(max_steps=6, ckpt_every=100, log_every=0,
                     ckpt_mode="full_sliced")
    tr2 = Trainer(cfg_b, loader(start=3), workdir=str(tmp_path / "resumed"),
                  transfer=True)
    assert int(tr2.state.step) == 3
    resumed = jax.device_get(tr2.train())

    tr3 = Trainer(cfg_b, loader(), workdir=str(tmp_path / "oracle"))
    oracle = jax.device_get(tr3.train())

    assert int(resumed.step) == 6 and int(oracle.step) == 6
    for a, b in zip(jax.tree.leaves(oracle), jax.tree.leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_context_parallel_requires_model_axis():
    import dataclasses

    cfg = tiny_cfg()
    cfg = dataclasses.replace(cfg, mesh=MeshConfig(context_parallel=True))
    with pytest.raises(ValueError, match="model_parallel"):
        cfg.validate()


# Tier-1 budget: a full trainer run for one config-edge regression pin
# (ckpt_every=0 modulo-by-zero) moves to the slow tier.
@pytest.mark.slow
def test_trainer_ckpt_every_zero_disables_periodic_saves(tmp_path):
    """ckpt_every=0 means 'no periodic saves' (final-step save still
    runs) — it used to crash with a modulo-by-zero inside the loop."""
    cfg = tiny_cfg(max_steps=2, ckpt_every=0, log_every=0)
    ds = SyntheticDataset(num_objects=2, num_views=4,
                          imgsize=cfg.model.H)
    loader = InfiniteLoader(ds, cfg.train.global_batch, num_workers=0)
    tr = Trainer(cfg, loader, workdir=str(tmp_path))
    tr.train()
    assert int(tr.state.step) == 2
    # the end-of-run save still happened
    assert tr.ckpt.latest_step() == 2
