"""Worker for tests/test_multiprocess.py — one JAX process of a
2-process CPU 'pod'.

Argv: process_id num_processes coordinator_address out_dir

Each process owns 2 virtual CPU devices (XLA_FLAGS set by the parent),
so the job forms a 4-device global mesh across 2 processes — the
multi-host topology the framework targets on TPU pods, minus the TPUs.
Exercises: jax.distributed.initialize, cross-process mesh construction,
per-host data assembly (shard_host_local's multi-process branch), the
sharded train step's cross-process gradient all-reduce, and primary-gated
side effects.  Writes the per-step losses to out_dir/loss_<pid>.json.
"""

import json
import os
import sys


def main() -> None:
    pid, nprocs, coord, out_dir = (int(sys.argv[1]), int(sys.argv[2]),
                                   sys.argv[3], sys.argv[4])

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert jax.device_count() == 2 * nprocs, jax.device_count()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import dataclasses

    from diff3d_tpu.config import test_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.parallel.multihost import is_primary, shard_host_local
    from diff3d_tpu.train import create_train_state, make_train_step
    from diff3d_tpu.train.trainer import init_params

    cfg = test_config(imgsize=8, ch=8)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, global_batch=8))

    env = make_mesh(cfg.mesh)   # 4-device data mesh spanning 2 processes
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(state, env.state_shardings(state))

    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=cfg.model.H)
    per_host = cfg.train.global_batch // nprocs
    loader = InfiniteLoader(ds, per_host, seed=0, host_id=pid,
                            num_hosts=nprocs, num_workers=0)

    step_fn = make_train_step(model, cfg, env)
    losses = []
    for _ in range(2):
        raw = next(loader)
        batch = shard_host_local(
            {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"],
             "K": raw["K"]}, env.batch())
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))

    assert is_primary() == (pid == 0)
    with open(os.path.join(out_dir, f"loss_{pid}.json"), "w") as f:
        json.dump(losses, f)


if __name__ == "__main__":
    main()
