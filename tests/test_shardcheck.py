"""shardcheck (the IR-level sharding/comms analyzer), tested from both
sides like graftlint: for every detector a fixture that must FIRE and a
fixture that must stay SILENT — on synthetic HLO/StableHLO text for the
parsers (including the f64 case, which a live CPU trace without
``jax_enable_x64`` cannot produce) and on real lowered pjit programs
over the 8-virtual-device mesh for the end-to-end path.  Then the two
seeded regressions the issue demands (a replicated fsdp param, an
injected resharding site), the manifest round-trip + suppression
grammar, the ``comms_budget`` marker (incl. vacuous-pass protection,
via an in-process sub-pytest), and the repo-clean gate: the committed
manifests for the tier-1 programs must match what the current tree
lowers.
"""

import dataclasses
import json
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from diff3d_tpu.analysis import budgets as budgets_lib
from diff3d_tpu.analysis import ir
from diff3d_tpu.analysis.budgets import (Suppression, check_report,
                                         check_report_against_dir,
                                         load_manifest,
                                         manifest_from_report,
                                         manifest_path, write_manifest)
from diff3d_tpu.analysis.lint import (Finding, apply_baseline,
                                      load_baseline, write_baseline)
from diff3d_tpu.analysis.pytest_plugin import CommsCheck
from diff3d_tpu.analysis import shardcheck as sc

pytest_plugins = ["pytester"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fsdp_env():
    return sc._fsdp_mesh()


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _report(**kw):
    base = dict(name="prog", mesh_shape={"data": 8}, collectives={},
                resharding_sites=[], dtype_upcasts={}, host_callbacks=[],
                param_table=[])
    base.update(kw)
    return ir.ProgramReport(**base)


def _live(findings, rule=None):
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ---------------------------------------------------------------------------
# Text parsers on synthetic HLO / StableHLO
# ---------------------------------------------------------------------------

_HLO = textwrap.dedent("""\
    HloModule fixture

    ENTRY %main (p0: f32[2,8]) -> f32[16,8] {
      %ag = f32[16,8]{1,0} all-gather(f32[2,8]{1,0} %p0), dimensions={0}
      %ars = f32[4,4]{1,0} all-reduce-start(f32[4,4]{1,0} %x), to_apply=%add
      %ard = f32[4,4]{1,0} all-reduce-done(f32[4,4]{1,0} %ars)
      %rs = f32[2,8]{1,0} reduce-scatter(f32[16,8]{1,0} %ag), dimensions={0}
      %cp = f32[4]{0} collective-permute(f32[4]{0} %y)
      %up = f32[4,4]{1,0} convert(bf16[4,4]{1,0} %z)
      %down = bf16[4,4]{1,0} convert(f32[4,4]{1,0} %up)
      %wide = f64[2]{0} convert(f32[2]{0} %v)
      ROOT %cb = f32[1]{0} custom-call(f32[1]{0} %w), custom_call_target="xla_python_cpu_callback"
    }
""")


def test_parse_compiled_collectives_counts_and_bytes():
    stats = ir.parse_compiled_collectives(_HLO)
    assert stats["all-gather"].count == 1
    assert stats["all-gather"].bytes == 16 * 8 * 4
    # async pair: -start counts once, -done is skipped
    assert stats["all-reduce"].count == 1
    assert stats["all-reduce"].bytes == 4 * 4 * 4
    assert stats["reduce-scatter"].count == 1
    assert stats["reduce-scatter"].bytes == 2 * 8 * 4
    assert stats["collective-permute"].count == 1
    assert "all-to-all" not in stats


def test_parse_compiled_collectives_silent_on_local_ops():
    clean = textwrap.dedent("""\
        ENTRY %main {
          %a = f32[8,8]{1,0} add(f32[8,8]{1,0} %x, f32[8,8]{1,0} %y)
          ROOT %d = f32[8,8]{1,0} dot(f32[8,8]{1,0} %a, f32[8,8]{1,0} %y)
        }
    """)
    assert ir.parse_compiled_collectives(clean) == {}


def test_parse_compiled_upcasts_fires_on_widening_only():
    up = ir.parse_compiled_upcasts(_HLO)
    # bf16->f32 widening and the f64 landing fire; f32->bf16 is silent.
    assert up == {"bf16->f32": 1, "f32->f64": 1}


def test_is_upcast_f64_rule_and_same_width():
    assert ir._is_upcast("s32", "f64")       # anything -> f64
    assert ir._is_upcast("bf16", "f32")
    assert not ir._is_upcast("f64", "f64")
    assert not ir._is_upcast("f16", "bf16")  # same width, not wider
    assert not ir._is_upcast("f32", "bf16")


_SHLO = textwrap.dedent("""\
    module @fixture {
      func.func public @main(%arg0: tensor<16x8xbf16>) -> tensor<16x8xf32> {
        %0 = stablehlo.convert %arg0 : (tensor<16x8xbf16>) -> tensor<16x8xf32>
        %1 = stablehlo.custom_call @Sharding(%0) {mhlo.sharding = "{devices=[8,1]<=[8]}"} : (tensor<16x8xf32>) -> tensor<16x8xf32>
        %2 = stablehlo.convert %1 : (tensor<16x8xf32>) -> tensor<16x8xbf16>
        %3 = stablehlo.custom_call @xla_python_cpu_callback(%2) {api_version = 2 : i32} : (tensor<16x8xbf16>) -> tensor<16x8xf32>
        return %3 : tensor<16x8xf32>
      }
    }
""")


def test_parse_stablehlo_extracts_all_three_facts():
    facts = ir.parse_stablehlo(_SHLO)
    assert facts["dtype_upcasts"] == {"bf16->f32": 1}
    (site,) = facts["resharding_sites"]
    assert "devices=[8,1]" in site.sharding
    assert facts["host_callbacks"] == ["xla_python_cpu_callback"]


def test_parse_stablehlo_silent_on_clean_module():
    clean = ("module @m {\n  func.func public @main(%a: tensor<4xf32>)"
             " -> tensor<4xf32> {\n    return %a : tensor<4xf32>\n  }\n}\n")
    facts = ir.parse_stablehlo(clean)
    assert facts == {"dtype_upcasts": {}, "resharding_sites": [],
                     "host_callbacks": []}


# ---------------------------------------------------------------------------
# Live lowered programs on the 8-device mesh: fire + silent per detector
# ---------------------------------------------------------------------------


def test_live_collectives_fire_on_cross_device_reduction():
    env = _fsdp_env()
    xsh = NamedSharding(env.mesh, P("data"))
    rep = NamedSharding(env.mesh, P())
    f = jax.jit(lambda x: x.sum(), in_shardings=(xsh,), out_shardings=rep)
    report = ir.analyze_lowered("sum_fixture", f.lower(_sds((16, 4))))
    assert report.total_collective_count >= 1
    assert report.total_collective_bytes > 0
    assert report.mesh_shape == {"data": 8, "model": 1}


def test_live_collectives_silent_on_elementwise():
    env = _fsdp_env()
    xsh = NamedSharding(env.mesh, P("data"))
    g = jax.jit(lambda x: x * 2.0, in_shardings=(xsh,),
                out_shardings=xsh)
    report = ir.analyze_lowered("elem_fixture", g.lower(_sds((16, 4))))
    assert report.total_collective_count == 0
    assert report.total_collective_bytes == 0


def test_live_resharding_sites_counted():
    env = _fsdp_env()
    xsh = NamedSharding(env.mesh, P("data"))

    def with_constraint(x):
        return jax.lax.with_sharding_constraint(x + 1.0, xsh) * 2.0

    def without(x):
        return (x + 1.0) * 2.0

    fire = ir.analyze_lowered(
        "resh_fire", jax.jit(with_constraint, in_shardings=(xsh,),
                             out_shardings=xsh).lower(_sds((16, 4))))
    silent = ir.analyze_lowered(
        "resh_silent", jax.jit(without, in_shardings=(xsh,),
                               out_shardings=xsh).lower(_sds((16, 4))))
    assert len(fire.resharding_sites) == len(silent.resharding_sites) + 1


def test_live_dtype_upcast_detected():
    fire = ir.analyze_lowered(
        "upcast_fire",
        jax.jit(lambda x: x.astype(jnp.float32) * 2.0).lower(
            _sds((8,), jnp.bfloat16)))
    assert fire.dtype_upcasts.get("bf16->f32", 0) >= 1
    silent = ir.analyze_lowered(
        "upcast_silent",
        jax.jit(lambda x: x * 2.0).lower(_sds((8,), jnp.float32)))
    assert silent.dtype_upcasts == {}


def test_live_host_callback_detected():
    def with_cb(x):
        y = jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    fire = ir.analyze_lowered(
        "cb_fire", jax.jit(with_cb).lower(_sds((4,))))
    assert fire.host_callbacks, "pure_callback not detected"
    assert any("callback" in t for t in fire.host_callbacks)
    silent = ir.analyze_lowered(
        "cb_silent", jax.jit(lambda x: x + 1.0).lower(_sds((4,))))
    assert silent.host_callbacks == []


# ---------------------------------------------------------------------------
# Param-sharding table + seeded regression 1: replicated fsdp param
# ---------------------------------------------------------------------------

#: (32, 32) f32 = 1024 elements — exactly at the fsdp policy's sharding
#: threshold (8 devices x 128), so the policy wants it sharded.
_PARAMS = {"dense": {"kernel": jax.ShapeDtypeStruct((32, 32),
                                                    jnp.float32)}}


def _matmul_program(env, param_shardings):
    rep = NamedSharding(env.mesh, P())
    f = jax.jit(lambda p, x: x @ p["dense"]["kernel"],
                in_shardings=(param_shardings, rep), out_shardings=rep)
    return f.lower(_PARAMS, _sds((8, 32)))


def test_param_table_flags_replicated_policy_param():
    env = _fsdp_env()
    expected = env.params(_PARAMS)
    # Policy sanity: fsdp DOES want this leaf sharded.
    assert not ir._is_replicated(expected["dense"]["kernel"])
    rep = NamedSharding(env.mesh, P())
    bad = ir.analyze_lowered(
        "sc201_fire",
        _matmul_program(env, jax.tree.map(lambda _: rep, _PARAMS)),
        params_template=_PARAMS, params_argnum=0,
        expected_param_shardings=expected)
    (flagged,) = bad.replicated_policy_params
    assert "kernel" in flagged
    good = ir.analyze_lowered(
        "sc201_silent", _matmul_program(env, expected),
        params_template=_PARAMS, params_argnum=0,
        expected_param_shardings=expected)
    assert good.replicated_policy_params == []
    assert len(good.param_table) == 1


def test_sc201_seeded_regression_fires_through_manifest_check():
    """The issue's seeded regression: pin a manifest from the healthy
    fsdp lowering, then force the param replicated — SC201 must fire."""
    env = _fsdp_env()
    expected = env.params(_PARAMS)
    rep = NamedSharding(env.mesh, P())
    good = ir.analyze_lowered(
        "sc201_seed", _matmul_program(env, expected),
        params_template=_PARAMS, params_argnum=0,
        expected_param_shardings=expected)
    manifest = manifest_from_report(good)
    assert not _live(check_report(good, manifest, "m.json"))
    bad = ir.analyze_lowered(
        "sc201_seed", _matmul_program(env, jax.tree.map(lambda _: rep,
                                                        _PARAMS)),
        params_template=_PARAMS, params_argnum=0,
        expected_param_shardings=expected)
    hits = _live(check_report(bad, manifest, "m.json"), "SC201")
    assert hits and "replicated" in hits[0].message


def test_param_table_arity_mismatch_raises():
    with pytest.raises(ValueError, match="arity"):
        ir.param_sharding_table(_PARAMS, [])


def test_mesh_param_spec_table_is_readable():
    env = _fsdp_env()
    table = env.param_spec_table(_PARAMS)
    (path,) = table
    assert "kernel" in path and "data" in table[path]


# ---------------------------------------------------------------------------
# Seeded regression 2: injected resharding site over a pinned manifest
# ---------------------------------------------------------------------------


def test_sc206_injected_resharding_flagged_and_suppressible():
    env = _fsdp_env()
    xsh = NamedSharding(env.mesh, P("data"))

    def base(x):
        return (x + 1.0) * 2.0

    def injected(x):
        return jax.lax.with_sharding_constraint(x + 1.0, xsh) * 2.0

    good = ir.analyze_lowered(
        "resh_seed", jax.jit(base, in_shardings=(xsh,),
                             out_shardings=xsh).lower(_sds((16, 4))))
    manifest = manifest_from_report(good)
    assert not _live(check_report(good, manifest, "m.json"))
    bad = ir.analyze_lowered(
        "resh_seed", jax.jit(injected, in_shardings=(xsh,),
                             out_shardings=xsh).lower(_sds((16, 4))))
    hits = _live(check_report(bad, manifest, "m.json"), "SC206")
    assert hits and "resharding" in hits[0].message
    # A reviewed manifest suppression silences it (reason mandatory).
    manifest.suppressions.append(
        Suppression("SC206", "*", "constraint added intentionally"))
    findings = check_report(bad, manifest, "m.json")
    assert not _live(findings, "SC206")
    assert any(f.rule == "SC206" and f.suppressed
               and f.suppress_reason for f in findings)


# ---------------------------------------------------------------------------
# Budget checking on synthetic reports (each SC rule, fire + silent)
# ---------------------------------------------------------------------------


def test_sc202_unbudgeted_and_over_count():
    r = _report(collectives={"all-gather": ir.CollectiveStat(
        "all-gather", count=3, bytes=512)})
    m = manifest_from_report(_report())          # empty budgets
    (f,) = _live(check_report(r, m, "m.json"), "SC202")
    assert "unbudgeted" in f.message
    m2 = manifest_from_report(r)
    assert not _live(check_report(r, m2, "m.json"))
    worse = _report(collectives={"all-gather": ir.CollectiveStat(
        "all-gather", count=4, bytes=512)})
    (f2,) = _live(check_report(worse, m2, "m.json"), "SC202")
    assert "exceeds budget 3" in f2.message


def test_sc203_bytes_over_budget():
    r = _report(collectives={"all-reduce": ir.CollectiveStat(
        "all-reduce", count=1, bytes=100)})
    m = manifest_from_report(r)
    fatter = _report(collectives={"all-reduce": ir.CollectiveStat(
        "all-reduce", count=1, bytes=200)})
    (f,) = _live(check_report(fatter, m, "m.json"), "SC203")
    assert "exceed budget 100" in f.message


def test_sc204_upcast_unbudgeted_over_and_pinned():
    m = manifest_from_report(_report(dtype_upcasts={"bf16->f32": 2}))
    ok = _report(dtype_upcasts={"bf16->f32": 2})
    assert not _live(check_report(ok, m, "m.json"))
    extra = _report(dtype_upcasts={"bf16->f32": 3})
    (f,) = _live(check_report(extra, m, "m.json"), "SC204")
    assert "exceed budget 2" in f.message
    novel = _report(dtype_upcasts={"f32->f64": 1})
    (f2,) = _live(check_report(novel, m, "m.json"), "SC204")
    assert "unbudgeted" in f2.message and "f32->f64" in f2.message


def test_sc205_callback_allowlist():
    m = manifest_from_report(_report(host_callbacks=["known_callback"]))
    ok = _report(host_callbacks=["known_callback"])
    assert not _live(check_report(ok, m, "m.json"))
    rogue = _report(host_callbacks=["rogue_callback"])
    (f,) = _live(check_report(rogue, m, "m.json"), "SC205")
    assert "rogue_callback" in f.message


def test_sc002_reasonless_manifest_suppression_warns():
    m = manifest_from_report(_report())
    m.suppressions.append(Suppression("SC204", "bf16->f32", reason=None))
    (f,) = _live(check_report(_report(), m, "m.json"), "SC002")
    assert f.severity == "warning" and "no reason" in f.message


def test_suppression_key_scoping():
    supp = Suppression("SC202", "all-gather", "pinned elsewhere")
    assert supp.covers("SC202", "all-gather")
    assert not supp.covers("SC202", "all-reduce")
    assert not supp.covers("SC203", "all-gather")
    assert Suppression("SC202", "*", "r").covers("SC202", "anything")


# ---------------------------------------------------------------------------
# Manifest round-trip, SC207, and the shared fingerprint-baseline format
# ---------------------------------------------------------------------------


def test_manifest_round_trip(tmp_path):
    r = _report(
        name="rt_prog",
        collectives={"all-gather": ir.CollectiveStat("all-gather", 2, 64)},
        dtype_upcasts={"bf16->f32": 1},
        host_callbacks=["cb"],
        resharding_sites=[ir.ReshardingSite("{devices=[8]<=[8]}")])
    m = manifest_from_report(
        r, [Suppression("SC205", "cb", "metrics tap, reviewed")])
    path = manifest_path("rt_prog", str(tmp_path))
    write_manifest(path, m)
    loaded = load_manifest(path)
    assert loaded.program == "rt_prog"
    assert loaded.budgets.collectives == {
        "all-gather": {"count": 2, "bytes": 64}}
    assert loaded.budgets.dtype_upcasts == {"bf16->f32": 1}
    assert loaded.budgets.resharding_sites == 1
    assert loaded.suppressions[0].reason == "metrics tap, reviewed"
    assert not _live(check_report_against_dir(r, str(tmp_path)))


def test_sc207_missing_and_unreadable_manifest(tmp_path):
    r = _report(name="ghost")
    (f,) = check_report_against_dir(r, str(tmp_path))
    assert f.rule == "SC207" and "--update" in f.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        fh.write("{not json")
    (f2,) = check_report_against_dir(r, str(tmp_path))
    assert f2.rule == "SC207" and "unreadable" in f2.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        json.dump({"version": 99, "tool": "other"}, fh)
    (f3,) = check_report_against_dir(r, str(tmp_path))
    assert f3.rule == "SC207"


def test_ir_findings_share_the_baseline_format(tmp_path):
    mf = str(tmp_path / "m.json")
    f = Finding(path=mf, rule="SC202", line=1, col=0, severity="error",
                message="a", fingerprint_data="p\x00SC202\x00all-gather")
    same_key = dataclasses.replace(f, message="different text")
    other_key = dataclasses.replace(
        f, fingerprint_data="p\x00SC202\x00all-reduce")
    root = str(tmp_path)
    # identity is (path, rule, key) — message and line text irrelevant
    assert f.fingerprint(root) == same_key.fingerprint(root)
    assert f.fingerprint(root) != other_key.fingerprint(root)
    bl = str(tmp_path / "baseline.json")
    assert write_baseline(bl, [f], root) == 1
    out = apply_baseline([same_key, other_key], load_baseline(bl), root)
    assert out[0].suppressed and out[0].suppress_reason == "baseline"
    assert not out[1].suppressed


# ---------------------------------------------------------------------------
# The comms_budget marker
# ---------------------------------------------------------------------------


def test_comms_check_violations_aggregate():
    check = CommsCheck()
    check.add(_report(collectives={"all-gather": ir.CollectiveStat(
        "all-gather", count=2, bytes=300)}))
    check.add(_report(
        collectives={"all-gather": ir.CollectiveStat(
            "all-gather", count=1, bytes=100)},
        resharding_sites=[ir.ReshardingSite("s")],
        host_callbacks=["cb"]))
    assert check.violations({"all_gather": 3, "total_bytes": 400,
                             "resharding_sites": 1,
                             "host_callbacks": 1}) == []
    v = check.violations({"all_gather": 2, "total_bytes": 399,
                          "resharding_sites": 0, "host_callbacks": 0})
    assert len(v) == 4
    assert any("all-gather: 3" in s for s in v)
    assert any("total_bytes: 400" in s for s in v)


@pytest.mark.comms_budget(all_reduce=4, total_bytes=1 << 20,
                          resharding_sites=0, dtype_upcasts=0,
                          host_callbacks=0)
def test_comms_budget_marker_e2e(comms_check):
    env = _fsdp_env()
    xsh = NamedSharding(env.mesh, P("data"))
    rep = NamedSharding(env.mesh, P())
    f = jax.jit(lambda x: x.sum(), in_shardings=(xsh,),
                out_shardings=rep)
    r = comms_check.analyze("marker_fixture", f.lower(_sds((16, 4))))
    assert r.total_collective_count >= 1     # the budget is non-vacuous


def test_comms_budget_vacuous_pass_protection(pytester):
    """A marked test that never registers a report must FAIL, not pass
    vacuously — run an in-process sub-pytest to observe the teardown."""
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.comms_budget(all_gather=1)
        def test_never_registers(comms_check):
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*vacuously*"])


def test_comms_budget_marker_rejects_bad_usage(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.comms_budget(warp_drive=1)
        def test_unknown_key(comms_check):
            pass

        @pytest.mark.comms_budget(all_gather=1)
        def test_no_fixture():
            pass

        @pytest.mark.comms_budget()
        def test_no_limits(comms_check):
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*unknown keys warp_drive*"])
    result.stdout.fnmatch_lines(["*requires the comms_check fixture*"])
    result.stdout.fnmatch_lines(["*no limits*"])


# ---------------------------------------------------------------------------
# CLI + registry plumbing
# ---------------------------------------------------------------------------


def test_cli_list_and_bad_invocation(capsys):
    assert sc.main(["--list"]) == 0
    out = capsys.readouterr().out
    for nm in sc.REGISTRY:
        assert nm in out
    assert sc.main(["--program", "train_step", "--programs-tier1"]) == 2


def test_registry_names_and_tier1():
    assert set(sc.TIER1_PROGRAMS) == {"train_step", "step_many",
                                      "step_many_cascade_draft",
                                      "step_many_cascade_refine"}
    assert set(sc.TIER1_PROGRAMS) <= set(sc.REGISTRY)


def test_tier1_manifests_are_committed():
    d = sc.default_manifest_dir(_REPO_ROOT)
    for nm in sc.REGISTRY:
        assert os.path.exists(manifest_path(nm, d)), (
            f"missing committed manifest for {nm}; run "
            f"'python tools/shardcheck.py --update --program {nm}'")


def test_update_preserves_suppressions(tmp_path, monkeypatch):
    """--update re-pins observations but keeps reviewed suppressions."""
    d = str(tmp_path)
    supp = Suppression("SC204", "bf16->f32", "mixed-precision by design")
    r = _report(name="train_step")
    write_manifest(manifest_path("train_step", d),
                   manifest_from_report(r, [supp]))
    monkeypatch.setitem(
        sc.REGISTRY, "train_step",
        dataclasses.replace(sc.REGISTRY["train_step"],
                            build=lambda: _report(name="train_step")))
    sc.update_manifests(["train_step"], d)
    loaded = load_manifest(manifest_path("train_step", d))
    assert loaded.suppressions == [supp]


# ---------------------------------------------------------------------------
# The tier-1 gate: committed manifests match what the tree lowers today
# ---------------------------------------------------------------------------


def test_repo_manifests_clean_tier1():
    """The shardcheck analogue of ``test_repo_lints_clean``: building
    the REAL tier-1 programs (sharded train step, sharded ``step_many``)
    and diffing against the committed manifests must come back clean.
    Any collective/param/upcast drift is either a fix or a reviewed
    ``--update`` re-pin."""
    d = sc.default_manifest_dir(_REPO_ROOT)
    findings = sc.check_programs(list(sc.TIER1_PROGRAMS), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)


@pytest.mark.slow
def test_repo_manifests_clean_full_sweep():
    """All five registered programs (adds distill, DDIM, serving
    warmup) — the full manifest sweep the CLI runs."""
    d = sc.default_manifest_dir(_REPO_ROOT)
    findings = sc.check_programs(sorted(sc.REGISTRY), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)
