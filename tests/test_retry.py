"""Retry-policy and fault-injector units (diff3d_tpu/runtime/retry.py,
diff3d_tpu/testing/faults.py) — no device work, no JAX beyond imports.

These are the contracts every fault-tolerant layer leans on: the trainer
and serving engine wrap dispatches in :class:`RetryPolicy`, the async
checkpoint writer retries commits under it, and the chaos tests drive
all of them through :class:`FaultInjector`.  A behavioral drift here
(e.g. retrying a BackendDialTimeout, or a nondeterministic backoff
sequence) silently changes every one of those layers at once.
"""

import pytest

from diff3d_tpu.runtime.retry import (BackendDialTimeout, RetryPolicy,
                                      RetryableError,
                                      is_transient_backend_error,
                                      is_transient_io_error)
from diff3d_tpu.testing.faults import (FaultInjected, FaultInjector,
                                       wrap_sampler)


def _policy(**kw):
    kw.setdefault("sleep", lambda s: None)       # tests never really sleep
    return RetryPolicy(**kw)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("exc,expected", [
    (RuntimeError("UNAVAILABLE: TPU backend stalled"), True),
    (RuntimeError("DEADLINE_EXCEEDED while dialing"), True),
    (ConnectionResetError("connection reset by peer"), True),
    (RetryableError("typed transient"), True),
    (FaultInjected("injected"), True),           # injected == real transient
    (BackendDialTimeout("dial exceeded 180s"), False),  # a hang, not a blip
    (ValueError("bad shape"), False),
    (RuntimeError("XlaRuntimeError: INVALID_ARGUMENT"), False),
])
def test_transient_backend_classification(exc, expected):
    assert is_transient_backend_error(exc) is expected


def test_transient_io_classification():
    assert is_transient_io_error(OSError("disk quota exceeded"))
    assert is_transient_io_error(FaultInjected("injected"))
    assert not is_transient_io_error(ValueError("bad manifest"))
    assert not is_transient_io_error(KeyboardInterrupt())


# ---------------------------------------------------------------------------
# RetryPolicy.call
# ---------------------------------------------------------------------------


def test_retries_then_succeeds_and_logs_attempts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: blip")
        return "ok"

    log = []
    p = _policy(max_attempts=4, base_delay_s=0.5, jitter=0.0)
    assert p.call(flaky, attempts_log=log) == "ok"
    assert calls["n"] == 3
    assert [e["attempt"] for e in log] == [1, 2]
    assert all("UNAVAILABLE" in e["error"] for e in log)
    # exponential growth: 0.5, then 1.0
    assert [e["backoff_s"] for e in log] == [0.5, 1.0]


def test_nonretryable_raises_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("config error")

    with pytest.raises(ValueError, match="config error"):
        _policy(max_attempts=5).call(bad)
    assert calls["n"] == 1


def test_exhaustion_reraises_last_error_unchanged():
    sentinel = RuntimeError("UNAVAILABLE: still down")

    def always():
        raise sentinel

    with pytest.raises(RuntimeError) as ei:
        _policy(max_attempts=3, base_delay_s=0.0).call(always)
    assert ei.value is sentinel          # typed errors survive the policy


def test_backoff_caps_and_constant_growth():
    import random

    p = _policy(base_delay_s=1.0, max_delay_s=4.0, growth=2.0, jitter=0.0)
    rng = random.Random(0)
    assert [p.delay_for(a, rng) for a in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 4.0, 4.0]
    const = _policy(base_delay_s=7.0, max_delay_s=7.0, growth=1.0,
                    jitter=0.0)
    assert [const.delay_for(a, rng) for a in (1, 4)] == [7.0, 7.0]


def test_jitter_is_deterministic_per_seed():
    slept_a, slept_b, slept_c = [], [], []

    def run(seed, slept):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise RuntimeError("UNAVAILABLE")
            return None

        RetryPolicy(max_attempts=4, base_delay_s=1.0, jitter=0.5,
                    seed=seed, sleep=slept.append).call(flaky)

    run(0, slept_a)
    run(0, slept_b)
    run(1, slept_c)
    assert slept_a == slept_b            # same seed -> same schedule
    assert slept_a != slept_c            # different seed -> different


def test_on_retry_hook_sees_each_failure():
    seen = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE")
        return None

    _policy(max_attempts=3, base_delay_s=0.25, jitter=0.0).call(
        flaky, on_retry=lambda a, e, d: seen.append((a, str(e), d)))
    assert [(a, d) for a, _, d in seen] == [(1, 0.25), (2, 0.5)]


def test_broken_classifier_does_not_mask_the_fault():
    def bad_classify(exc):
        raise RuntimeError("classifier bug")

    with pytest.raises(RuntimeError, match="the real fault"):
        _policy(max_attempts=3, classify=bad_classify).call(
            lambda: (_ for _ in ()).throw(RuntimeError("the real fault")))


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------


def test_injector_first_n_and_at_calls():
    inj = FaultInjector(seed=0)
    inj.add("site", first_n=2)
    inj.add("site", at_calls=(5,))
    fired = []
    for i in range(1, 7):
        try:
            inj.fire("site")
            fired.append(False)
        except FaultInjected:
            fired.append(True)
    assert fired == [True, True, False, False, True, False]
    assert inj.calls["site"] == 6 and inj.fired["site"] == 3


def test_injector_probabilistic_schedule_replays_exactly():
    def schedule(seed):
        inj = FaultInjector(seed=seed)
        inj.add("s", prob=0.5)
        out = []
        for _ in range(20):
            try:
                inj.fire("s")
                out.append(0)
            except FaultInjected:
                out.append(1)
        return out

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)
    assert 0 < sum(schedule(7)) < 20     # actually mixed


def test_injector_max_fires_and_clear():
    inj = FaultInjector()
    inj.add("s", first_n=100, max_fires=1)
    with pytest.raises(FaultInjected):
        inj.fire("s")
    inj.fire("s")                        # capped: second call clean
    inj.add("s", first_n=100)
    with pytest.raises(FaultInjected):
        inj.fire("s")
    inj.clear("s")
    inj.fire("s")                        # specs gone, counters survive
    assert inj.calls["s"] == 4


def test_injector_custom_exception_and_wrap():
    inj = FaultInjector()
    inj.add("s", at_calls=(1,), exc=lambda: OSError("disk gone"))
    wrapped = inj.wrap("s", lambda x: x + 1)
    with pytest.raises(OSError, match="disk gone"):
        wrapped(1)
    assert wrapped(1) == 2


def test_wrap_sampler_proxies_attributes_and_instruments_step_many():
    class FakeSampler:
        lane_multiple = 2

        def step_many(self, *a, **kw):
            return "stepped"

    inj = FaultInjector()
    inj.add("engine.step", at_calls=(1,))
    s = wrap_sampler(FakeSampler(), inj)
    assert s.lane_multiple == 2          # passthrough
    with pytest.raises(FaultInjected):
        s.step_many()
    assert s.step_many() == "stepped"
    assert inj.calls["engine.step"] == 2
