"""bench.py robustness layer: backend acquisition must survive transient
faults (retry) and degrade to a parseable JSON error record, never a bare
crash — round 4's official perf capture was voided by a single transient
``UNAVAILABLE`` raised before any bench code ran."""

import sys

sys.path.insert(0, "/root/repo")

import jax
import pytest

import bench


def test_acquire_backend_retries_transient_fault(monkeypatch):
    calls = {"n": 0}
    real_devices = jax.devices

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: TPU backend stalled")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky)
    devs = bench._acquire_backend(attempts=4, wait_s=0.01)
    assert calls["n"] == 3 and len(devs) >= 1


def test_acquire_backend_exhausts_and_raises(monkeypatch):
    def always_down():
        raise RuntimeError("UNAVAILABLE: still down")

    monkeypatch.setattr(jax, "devices", always_down)
    with pytest.raises(RuntimeError, match="still down"):
        bench._acquire_backend(attempts=2, wait_s=0.01)


def test_main_emits_parseable_json_when_backend_never_comes_up(
        monkeypatch, capsys):
    import json

    def always_down():
        raise RuntimeError("UNAVAILABLE: tunnel outage")

    monkeypatch.setattr(jax, "devices", always_down)
    monkeypatch.setattr(bench, "_acquire_backend",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("UNAVAILABLE: tunnel outage")))
    assert bench.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)          # MUST parse
    assert rec["value"] is None and "UNAVAILABLE" in rec["error"]
    assert rec["metric"].startswith("train_examples_per_sec")
