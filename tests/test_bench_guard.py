"""bench.py robustness layer: backend acquisition must survive transient
faults (retry) and degrade to a parseable JSON error record, never a bare
crash — round 4's official perf capture was voided by a single transient
``UNAVAILABLE`` raised before any bench code ran."""

import sys

sys.path.insert(0, "/root/repo")

import jax
import pytest

import bench


def test_acquire_backend_retries_transient_fault(monkeypatch):
    calls = {"n": 0}
    real_devices = jax.devices

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: TPU backend stalled")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky)
    devs = bench._acquire_backend(attempts=4, wait_s=0.01)
    assert calls["n"] == 3 and len(devs) >= 1


def test_acquire_backend_exhausts_and_raises(monkeypatch):
    def always_down():
        raise RuntimeError("UNAVAILABLE: still down")

    monkeypatch.setattr(jax, "devices", always_down)
    with pytest.raises(RuntimeError, match="still down"):
        bench._acquire_backend(attempts=2, wait_s=0.01)


def test_acquire_backend_fails_fast_on_dial_hang(monkeypatch):
    """A HANGING dial (BackendDialTimeout) must not be retried: each
    attempt burns the full 180s budget and the r01–r05 records show the
    harness rc=124-killing the process mid-backoff, leaving no JSON."""
    calls = {"n": 0}

    def hangs():
        calls["n"] += 1
        raise bench.BackendDialTimeout("backend dial exceeded 180s")

    monkeypatch.setattr(jax, "devices", hangs)
    with pytest.raises(bench.BackendDialTimeout):
        bench._acquire_backend(attempts=6, wait_s=10.0)
    assert calls["n"] == 1          # no retry, no 75s sleeps


def test_acquire_backend_records_dial_telemetry(monkeypatch):
    """Every acquisition resets and refills ``bench._LAST_DIAL`` with the
    attempt count and per-retry backoff records — the telemetry ``main``
    embeds in the structured failure JSON."""
    calls = {"n": 0}
    real_devices = jax.devices

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: TPU backend stalled")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky)
    bench._acquire_backend(attempts=4, wait_s=0.01)
    assert bench._LAST_DIAL["attempts"] == 3
    retries = bench._LAST_DIAL["retries"]
    assert [r["attempt"] for r in retries] == [1, 2]
    assert all("UNAVAILABLE" in r["error"] for r in retries)
    assert all(abs(r["backoff_s"] - 0.01) < 1e-9 for r in retries)


def test_main_failure_json_carries_dial_telemetry(monkeypatch, capsys):
    """The failure record embeds the dial attempts/backoffs, so a voided
    round shows exactly what the retry loop did before conceding."""
    import functools
    import json

    def always_down():
        raise RuntimeError("UNAVAILABLE: tunnel outage")

    monkeypatch.setattr(jax, "devices", always_down)
    # main() calls _acquire_backend() with no args; shrink its budget
    # (the partial binds the original before setattr replaces the name)
    monkeypatch.setattr(
        bench, "_acquire_backend",
        functools.partial(bench._acquire_backend, attempts=2,
                          wait_s=0.01))
    assert bench.main() == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None and "UNAVAILABLE" in rec["error"]
    assert rec["dial"]["attempts"] == 2
    assert len(rec["dial"]["retries"]) == 1
    assert rec["dial"]["retries"][0]["attempt"] == 1


def test_main_emits_backend_dial_timeout_record(monkeypatch, capsys):
    import json

    monkeypatch.setattr(
        bench, "_acquire_backend",
        lambda: (_ for _ in ()).throw(
            bench.BackendDialTimeout("backend dial exceeded 180s")))
    assert bench.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)          # MUST parse
    assert rec["error"] == "backend-dial-timeout"
    assert rec["value"] is None and "180s" in rec["detail"]


def test_sampler_steps_sweep_structure():
    """The few-step sweep record: one DDIM point per schedule, speedups
    relative to the full-grid point, and the 16-step schedule showing at
    least 8x fewer model calls per view than the 256-step one (it is
    exactly 16x; the guard leaves slack only for future schedule
    changes)."""
    calls = []

    def fake_bench(config, n_views, object_batch, use_mesh,
                   sampler_kind, steps, kernels=None):
        calls.append((config, sampler_kind, steps))
        # Per-view time shrinking sub-linearly with the schedule, like
        # real hardware (per-step overhead doesn't vanish).
        return 0.004 * steps + 0.05, 1.0, 3

    rec = bench._sampler_steps_sweep("srn64", bench_fn=fake_bench)
    assert rec["metric"] == "sampler_steps_sweep_srn64"
    assert [c[2] for c in calls] == [256, 64, 16, 8]
    assert all(c[1] == "ddim" for c in calls)

    points = {p["steps"]: p for p in rec["points"]}
    assert set(points) == {256, 64, 16, 8}
    assert points[256]["speedup_vs_256"] == 1.0
    assert points[16]["speedup_vs_256"] > points[64]["speedup_vs_256"] > 1
    # The acceptance pin: 16-step DDIM costs >= 8x fewer model calls.
    assert (points[256]["model_calls_per_view"]
            >= 8 * points[16]["model_calls_per_view"])
    for p in rec["points"]:
        assert p["sampler"] == "ddim"
        assert p["sec_per_view"] > 0 and p["effective_views"] == 3


def test_cascade_sweep_structure():
    """The cascade record: draft/refine/end-to-end s/view against the
    matched single-pass sampler, with the preview speedup (single-pass
    over draft latency) being the progressive-preview win and the plan
    spec pinned next to the numbers."""
    calls = []

    def fake_bench(config, n_views):
        calls.append((config, n_views))
        # draft fast, refine mid, single-pass slowest — the shape a
        # working cascade must have.
        return ("draft=64:ddim:8,refine=128:ancestral:64@t0.5",
                0.2, 1.0, 4.0, n_views - 1)

    rec = bench._cascade_sweep("srn128", n_views=3, bench_fn=fake_bench)
    assert rec["metric"] == "cascade_sweep_srn128"
    assert calls == [("srn128", 3)]
    assert rec["plan"] == "draft=64:ddim:8,refine=128:ancestral:64@t0.5"
    assert rec["effective_views"] == 2
    assert rec["draft_sec_per_view"] == 0.1
    assert rec["refine_sec_per_view"] == 0.5
    assert rec["end_to_end_sec_per_view"] == 0.6
    assert rec["single_pass_sec_per_view"] == 2.0
    # End-to-end still beats single-pass, and the draft preview beats
    # it by much more — the whole point of the cascade.
    assert rec["speedup_vs_single_pass"] > 1
    assert rec["preview_speedup"] > rec["speedup_vs_single_pass"]
    assert rec["unit"] == "s/view" and rec["vs_baseline"] is None


def test_cascade_sweep_in_phase_sequence():
    """Cascade sweep and kernels A/B are real phases: a round dying
    inside either must report it as ``phase_reached`` in the partial
    record, in run order (cascade, then the A/B, then complete)."""
    seq = bench._PHASE_SEQUENCE
    assert "cascade_sweep" in seq
    assert seq.index("kernels_ab") == seq.index("cascade_sweep") + 1
    assert seq.index("kernels_ab") == seq.index("complete") - 1


def test_kernels_ab_structure():
    """The kernel A/B record: one variant per requested backend, timed
    by the SAME train/sampler benches with only ``kernels`` varying,
    speedups relative to variant 0, and per-variant error notes instead
    of a voided record when one backend fails."""
    calls = []

    def fake_train(configs, n_steps, config, kernels=None):
        calls.append(("train", config, kernels, tuple(configs)))
        eps = {"xla": 100.0, "pallas": 125.0}[kernels]
        return eps, configs[0][0], configs[0][1], {"step_ms_median": 9.0}

    def fake_sampler(config, n_views, kernels=None):
        calls.append(("sampler", config, kernels))
        return {"xla": 2.0, "pallas": 1.6}[kernels], 6.0, 3

    rec = bench._kernels_ab(["xla", "pallas"], configs=[(64, 1)],
                            n_steps=5, train_fn=fake_train,
                            sampler_fn=fake_sampler)
    assert rec["metric"] == "kernels_ab_srn64"
    assert rec["dimension"] == "kernels"
    assert [c[2] for c in calls] == ["xla", "xla", "pallas", "pallas"]
    assert all(c[3] == ((64, 1),) for c in calls if c[0] == "train")
    xla, pallas = rec["variants"]
    assert xla["kernels"] == "xla" and pallas["kernels"] == "pallas"
    assert xla["train_examples_per_sec"] == 100.0
    assert pallas["train_speedup_vs_xla"] == 1.25
    assert pallas["sampler_speedup_vs_xla"] == 1.25
    assert "train_speedup_vs_xla" not in xla    # base carries no ratio


def test_kernels_ab_survives_one_variant_failing():
    def fake_train(configs, n_steps, config, kernels=None):
        if kernels == "pallas":
            raise RuntimeError("RESOURCE_EXHAUSTED: vmem")
        return 100.0, 64, 1, {"step_ms_median": 9.0}

    def fake_sampler(config, n_views, kernels=None):
        return 2.0, 6.0, 3

    rec = bench._kernels_ab(["xla", "pallas"], train_fn=fake_train,
                            sampler_fn=fake_sampler)
    xla, pallas = rec["variants"]
    assert xla["train_examples_per_sec"] == 100.0
    assert "RESOURCE_EXHAUSTED" in pallas["train_error"]
    assert "train_speedup_vs_xla" not in pallas
    assert pallas["sampler_speedup_vs_xla"] == 1.0


def test_main_rejects_unknown_kernel_backend(capsys):
    import pytest as _pytest

    with _pytest.raises(SystemExit):
        bench.main(["--kernels", "cuda"])


def test_partial_record_stamps_kernels():
    bench._KERNELS["requested"] = ["xla", "pallas"]
    try:
        rec = bench._partial_record("test")
        assert rec["kernels"] == ["xla", "pallas"]
    finally:
        bench._KERNELS["requested"] = ["xla"]


def test_main_emits_parseable_json_when_backend_never_comes_up(
        monkeypatch, capsys):
    import json

    def always_down():
        raise RuntimeError("UNAVAILABLE: tunnel outage")

    monkeypatch.setattr(jax, "devices", always_down)
    monkeypatch.setattr(bench, "_acquire_backend",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("UNAVAILABLE: tunnel outage")))
    assert bench.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)          # MUST parse
    assert rec["value"] is None and "UNAVAILABLE" in rec["error"]
    assert rec["metric"].startswith("train_examples_per_sec")
