"""Elasticity suite: the re-mesh-and-resume loop end to end (ISSUE
acceptance: a chaos run surviving shrink 8->4, grow 4->8 and a >=3-kill
sigterm loop reaches the target step with typed REMESHING/RESUMED
transitions and zero lost steps; resharded restore is leaf-wise
bit-identical; restore preflight raises a typed mismatch naming the
offending leaf; budget exhaustion raises ElasticityGaveUp).

All topology changes are scripted through the supervisor's seams
(``topology_fn`` device subsets of the 8 virtual CPU devices,
``reinit_fn=lambda: None``) so a single process exercises the real
shrink/grow reshard path.  SIGTERMs are real signals from
:class:`~diff3d_tpu.testing.faults.FaultInjector` — the same preemption
delivery a TPU maintenance event produces.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import MeshConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
from diff3d_tpu.parallel.mesh import make_mesh
from diff3d_tpu.runtime.retry import RetryBudget, RetryPolicy
from diff3d_tpu.testing.faults import FaultInjector, wrap_iter
from diff3d_tpu.train import CheckpointManager, create_train_state
from diff3d_tpu.train.checkpoint import CheckpointMismatchError
from diff3d_tpu.train.trainer import (ELASTIC_GAVE_UP, ELASTIC_REMESHING,
                                      ELASTIC_RESUMED, ELASTIC_RUNNING,
                                      ElasticityGaveUp, ElasticSupervisor)

pytestmark = pytest.mark.chaos


def _elastic_cfg(max_steps):
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    return dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, max_steps=max_steps, ckpt_every=2, log_every=0,
            ckpt_mode="full_sliced", ckpt_async=True))


class _Recorder:
    """Pass-through iterator recording every batch it hands out."""

    def __init__(self, it, out):
        self.it, self.out = it, out

    def __iter__(self):
        return self

    def __next__(self):
        b = next(self.it)
        self.out.append(np.asarray(b["imgs"]).copy())
        return b

    def close(self):
        close = getattr(self.it, "close", None)
        if close is not None:
            close()


# ---- the chaos elasticity loop, end to end --------------------------


@pytest.mark.lock_witness
def test_elastic_loop_survives_kills_shrink_and_grow(tmp_path,
                                                     lock_witness):
    """8 steps, SIGTERM at fetches 3/5/7, topology [8,4,8,4]: every kill
    re-meshes (a real shrink or grow reshard of the sliced checkpoint),
    resumes at exactly the preempted step, and the consumed batch stream
    is identical to an uninterrupted run's — zero replayed, zero
    skipped."""
    cfg = _elastic_cfg(max_steps=8)
    ds = SyntheticDataset(num_objects=4, num_views=4, imgsize=cfg.model.H)
    inj = FaultInjector(seed=0)
    # Per-site call counters span re-mesh cycles, so absolute fetch
    # numbers 3/5/7 land one kill in each of cycles 1-3 (fetch k trains
    # step k; the resumed cycle's first fetch re-derives the next step's
    # batch, never the preempted one).
    inj.add("loader", kind="sigterm", at_calls=(3, 5, 7))

    consumed = []
    schedule = [8, 4, 8, 4]
    cycle_devs = []

    def topology_fn():
        n = schedule[min(len(cycle_devs), len(schedule) - 1)]
        cycle_devs.append(n)
        return jax.devices()[:n]

    def make_loader(step, env):
        inner = InfiniteLoader(ds, cfg.train.global_batch,
                               seed=cfg.train.seed, num_workers=0,
                               start_step=step)
        return wrap_iter(_Recorder(inner, consumed), inj, "loader")

    sup = ElasticSupervisor(cfg, make_loader, workdir=str(tmp_path),
                            topology_fn=topology_fn,
                            reinit_fn=lambda: None)
    state = sup.run(8)

    assert int(state.step) == 8
    assert int(inj.fired["loader"]) == 3
    assert cycle_devs == [8, 4, 8, 4]

    ev = sup.events
    assert [e.state for e in ev] == [
        ELASTIC_RUNNING, ELASTIC_REMESHING,
        ELASTIC_RESUMED, ELASTIC_REMESHING,
        ELASTIC_RESUMED, ELASTIC_REMESHING,
        ELASTIC_RESUMED]
    remesh = [e for e in ev if e.state == ELASTIC_REMESHING]
    resumed = [e for e in ev if e.state == ELASTIC_RESUMED]
    # Zero lost steps: every REMESHING at step S resumes at exactly S.
    assert [e.step for e in remesh] == [3, 5, 7]
    assert [e.step for e in resumed] == [3, 5, 7]
    assert [e.cycle for e in resumed] == [2, 3, 4]
    # Each cycle ran on its scripted topology...
    assert [e.n_devices for e in ev] == [8, 8, 4, 4, 8, 8, 4]
    # ...and every resume was a real reshard (save-time mesh differed).
    for e in resumed:
        assert "resharded step" in e.reason, e

    # Deterministic input pipeline: the batches actually consumed across
    # all four cycles are exactly the uninterrupted stream, in order.
    ref = InfiniteLoader(ds, cfg.train.global_batch, seed=cfg.train.seed,
                         num_workers=0)
    assert len(consumed) == 8
    for got in consumed:
        np.testing.assert_array_equal(got, np.asarray(next(ref)["imgs"]))

    # The typed transitions also landed in metrics.jsonl.
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    elastic = [r for r in recs if "elastic" in r]
    assert [r["elastic"] for r in elastic] == [e.state for e in ev]
    assert all(r["n_devices"] == e.n_devices
               for r, e in zip(elastic, ev))


# ---- resharded restore: bit identity --------------------------------


def test_sliced_restore_reshards_bit_identical(tmp_path):
    """A full_sliced checkpoint saved on an 8-device fsdp mesh restores
    into a 4-device mesh bit-identically, lands on the target mesh's
    shardings, and records the reshard as a first-class event."""
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    mcfg = MeshConfig(param_sharding="fsdp")
    env8 = make_mesh(mcfg, devices=jax.devices())
    env4 = make_mesh(mcfg, devices=jax.devices()[:4])

    # One leaf big and divisible (fsdp-sharded on both meshes), one tiny
    # (replicated) — both placements cross the reshard.
    params = {"w": jnp.arange(8 * 256, dtype=jnp.float32).reshape(8, 256),
              "b": jnp.linspace(-1.0, 1.0, 96, dtype=jnp.float32)}
    state = create_train_state(params, cfg.train)
    state = dataclasses.replace(state, step=jnp.asarray(5, jnp.int32))
    state8 = jax.device_put(state, env8.state_shardings(state))

    d = str(tmp_path / "ckpt")
    writer = CheckpointManager(d, mode="full_sliced")
    writer.mesh_info = env8.topology_summary()
    assert writer.save(state8, force=True)
    manifest = json.load(open(os.path.join(d, "5", "sliced_manifest.json")))
    assert manifest["mesh"]["n_devices"] == 8

    reader = CheckpointManager(d)
    reader.mesh_info = env4.topology_summary()
    sh4 = env4.state_shardings(state)
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s),
        state, sh4)
    restored = reader.restore(abstract)

    assert restored is not None
    assert reader.last_restore_reshard is not None
    assert reader.last_restore_reshard["step"] == 5
    assert reader.last_restore_reshard["from"]["n_devices"] == 8
    assert reader.last_restore_reshard["to"]["n_devices"] == 4

    # Leaf-wise bit identity across the reshard.
    for orig, got in zip(jax.tree.leaves(state8), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(orig)),
                                      np.asarray(jax.device_get(got)))
    # And the restored leaves live on the TARGET mesh: w sharded over the
    # 4-device data axis, b replicated across the same 4 devices.
    w, b = restored.params["w"], restored.params["b"]
    assert w.sharding.mesh.size == 4
    assert len(w.sharding.device_set) == 4
    assert not w.sharding.is_fully_replicated
    assert b.sharding.is_fully_replicated
    writer.close()
    reader.close()


# ---- restore preflight: typed mismatches ----------------------------


def _abstract_like(state):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state)


def test_restore_preflight_names_offending_leaf(tmp_path):
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    params = {"w": jnp.ones((8, 16), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    state = create_train_state(params, cfg.train)
    state = dataclasses.replace(state, step=jnp.asarray(7, jnp.int32))
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, mode="full_sliced")
    assert mgr.save(state, force=True)

    # dtype mismatch: names the leaf, expected vs found, and the step.
    bad = _abstract_like(state)
    bad.params["w"] = jax.ShapeDtypeStruct((8, 16), jnp.float16)
    with pytest.raises(CheckpointMismatchError) as ei:
        mgr.restore(bad)
    e = ei.value
    assert "'w'" in e.leaf
    assert e.expected == "float16" and e.found == "float32"
    assert e.step == 7
    assert "config mismatch" in str(e)

    # shape mismatch.
    bad = _abstract_like(state)
    bad.params["w"] = jax.ShapeDtypeStruct((8, 32), jnp.float32)
    with pytest.raises(CheckpointMismatchError) as ei:
        mgr.restore(bad)
    e = ei.value
    assert "'w'" in e.leaf
    assert e.expected == (8, 32) and e.found == (8, 16)

    # tree-structure mismatch (leaf count): still typed, still stepped.
    widened = create_train_state(
        dict(params, extra=jnp.zeros((2,), jnp.float32)), cfg.train)
    with pytest.raises(CheckpointMismatchError) as ei:
        mgr.restore(_abstract_like(widened))
    assert ei.value.step == 7
    assert "config mismatch" in str(ei.value)

    # A matching target still restores fine after all those refusals.
    ok = mgr.restore(_abstract_like(state))
    assert int(ok.step) == 7
    mgr.close()


# ---- give-up policy -------------------------------------------------


def test_supervisor_gives_up_after_no_progress_budget(tmp_path):
    """Transient faults at every bring-up with zero forward progress
    exhaust the RetryBudget: typed GAVE_UP event, then ElasticityGaveUp
    carrying the full history."""
    cfg = _elastic_cfg(max_steps=4)
    inj = FaultInjector(seed=0)
    inj.add("elastic.cycle", first_n=99)   # every cycle dies at bring-up

    sup = ElasticSupervisor(
        cfg, make_loader=lambda step, env: iter(()),
        workdir=str(tmp_path), reinit_fn=lambda: None,
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                          sleep=lambda s: None),
        fault_hook=inj.fire)
    with pytest.raises(ElasticityGaveUp) as ei:
        sup.run(4)

    ev = sup.events
    assert [e.state for e in ev] == [ELASTIC_REMESHING, ELASTIC_GAVE_UP]
    assert all("FaultInjected" in e.reason for e in ev)
    assert ei.value.events == ev
    assert "budget exhausted" in str(ei.value)
    # The trainer never came up; nothing trained, nothing checkpointed.
    assert sup.trainer is None
    assert not os.path.exists(os.path.join(str(tmp_path), "ckpt"))


def test_retry_budget_semantics():
    b = RetryBudget(2)
    assert b.remaining == 2
    assert b.spend() is True          # 1st no-progress failure: keep going
    assert b.remaining == 1
    assert b.spend() is False         # 2nd: exhausted
    b.reset()                         # forward progress refills in full
    assert b.remaining == 2
    assert b.spend() is True
    with pytest.raises(ValueError):
        RetryBudget(0)
