"""REAL multi-process distributed test: a 2-process CPU 'pod' (2 virtual
devices per process, 4 global) runs the sharded train step end-to-end.

This is the multi-host story the reference never tested anywhere
(README.md:14 'Yet to test'; SURVEY.md §4): here it runs in CI on any
machine.  Covers jax.distributed bring-up, cross-process gradient
all-reduce compiled from shardings, per-host global-batch assembly
(``shard_host_local``'s ``make_array_from_process_local_data`` branch),
and identical loss trajectories on every process.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(port: int, tmp_path) -> tuple[list, list]:
    """Launch both workers against ``port``; returns (procs, log texts)."""
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
        # Fresh per-run cache: if one worker AOT-loads a cached executable
        # while the other compiles, they create different gloo-context
        # sequences and the collective rendezvous times out.  An empty
        # shared dir keeps both workers symmetric (both compile).
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jax_cache"),
    )
    # Workers write straight to files: PIPE capture with sequential
    # communicate() can deadlock (a worker blocking on a full unread pipe
    # stalls the other inside a cross-process collective), and a timeout
    # must still kill BOTH workers or they stay pinned on the rendezvous.
    log_paths = [tmp_path / f"out_{pid}.log" for pid in (0, 1)]
    logs = [open(p, "wb") for p in log_paths]
    procs = []
    try:
        for pid in (0, 1):
            procs.append(subprocess.Popen(
                [sys.executable, _WORKER, str(pid), "2",
                 f"localhost:{port}", str(tmp_path)],
                env=env, stdout=logs[pid], stderr=subprocess.STDOUT))
        for p in procs:
            p.wait(timeout=840)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    return procs, [p.read_text(errors="replace") for p in log_paths]


def test_two_process_distributed_train_step(tmp_path):
    # _free_port closes the probe socket before the coordinator rebinds it
    # (TOCTOU): another process can grab the port in between, so a bind
    # failure retries the whole launch on a fresh port instead of flaking.
    for attempt in range(3):
        procs, outs = _run_workers(_free_port(), tmp_path)
        if all(p.returncode == 0 for p in procs):
            break
        bind_race = any(
            marker in out.lower()
            for out in outs
            for marker in ("address already in use", "failed to bind",
                           "errno 98"))
        if not (bind_race and attempt < 2):
            break
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    losses = [json.load(open(tmp_path / f"loss_{pid}.json"))
              for pid in (0, 1)]
    # Both processes observe the SAME global loss (one global batch, one
    # all-reduced gradient) — the property the reference's DDP path lost.
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    assert all(np.isfinite(l) for l in losses[0]) and len(losses[0]) == 2
