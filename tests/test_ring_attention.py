"""Ring / Ulysses sequence-parallel attention vs unsharded attention,
on the 8-virtual-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from diff3d_tpu.parallel import ring_sdpa, shard_map, ulysses_sdpa


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


def _qkv(B, L, H, D, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_matches_full_attention(n_shards):
    B, L, H, D = 2, 64, 4, 16
    q, k, v = _qkv(B, L, H, D)
    ref = jax.nn.dot_product_attention(q, k, v)

    mesh = _mesh(n_shards)
    spec = P(None, "seq")
    fn = shard_map(lambda q, k, v: ring_sdpa(q, k, v, "seq"),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_grads_match(n_shards=4):
    B, L, H, D = 1, 32, 2, 8
    q, k, v = _qkv(B, L, H, D, seed=1)
    mesh = _mesh(n_shards)
    spec = P(None, "seq")
    ring = shard_map(lambda q, k, v: ring_sdpa(q, k, v, "seq"),
                     mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(jax.nn.dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss(ring), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


# Tier-1 budget: the 2-shard ring is the degenerate rotation (one
# exchange) and is superseded in tier 1 by the 4-shard run, which
# exercises the same values-and-grads equivalence across a longer
# permutation chain.
@pytest.mark.parametrize("n_shards", [
    pytest.param(2, marks=pytest.mark.slow), 4])
def test_ring_pallas_engine_matches_full_attention(n_shards):
    """Ring attention with the Pallas flash kernel as the local block
    engine (interpret mode off-TPU) — values AND grads vs unsharded."""
    B, L, H, D = 1, 64, 2, 16
    q, k, v = _qkv(B, L, H, D, seed=2)
    mesh = _mesh(n_shards)
    spec = P(None, "seq")
    # check_vma=False: the Pallas HLO *interpreter* (CPU test mode) mixes
    # vma'd and constant operands in its internal dynamic_slices; the
    # compiled TPU path carries vma on kernel outputs (_out_struct) and
    # runs under the default check.
    ring = shard_map(
        lambda q, k, v: ring_sdpa(q, k, v, "seq", impl="pallas"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)

    ref = jax.nn.dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(jax.jit(ring)(q, k, v)),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(jax.nn.dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss(ring), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_ulysses_matches_full_attention(n_shards):
    B, L, H, D = 2, 64, 4, 16   # H divisible by n_shards
    q, k, v = _qkv(B, L, H, D, seed=2)
    ref = jax.nn.dot_product_attention(q, k, v)

    mesh = _mesh(n_shards)
    spec = P(None, "seq")
    fn = shard_map(lambda q, k, v: ulysses_sdpa(q, k, v, "seq"),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh(8)
    q, k, v = _qkv(1, 16, 4, 8)  # 4 heads over 8 shards
    spec = P(None, "seq")
    fn = shard_map(lambda q, k, v: ulysses_sdpa(q, k, v, "seq"),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    with pytest.raises(ValueError):
        jax.jit(fn)(q, k, v)


@pytest.mark.parametrize("impl", ["ring:seq", "ulysses:seq"])
def test_attn_layer_sequence_parallel_matches(impl):
    """Model-level integration: the X-UNet's AttnLayer with
    ``attn_impl='ring:<axis>'`` runs token-sharded inside shard_map and
    matches the unsharded layer exactly (same params)."""
    from diff3d_tpu.models.layers import AttnLayer

    B, L, C, n = 2, 64, 32, 4
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(B, L, C), jnp.float32)

    ref_layer = AttnLayer(num_heads=4, attn_impl="xla")
    params = ref_layer.init(jax.random.PRNGKey(0), x, x)
    ref = ref_layer.apply(params, x, x)

    sp_layer = AttnLayer(num_heads=4, attn_impl=impl)
    mesh = _mesh(n)
    spec = P(None, "seq")
    fn = shard_map(lambda p, q, kv: sp_layer.apply(p, q, kv),
                   mesh=mesh, in_specs=(P(), spec, spec), out_specs=spec)
    out = jax.jit(fn)(params, x, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_config_accepts_seq_parallel_attn_impl():
    from diff3d_tpu.config import ModelConfig

    ModelConfig(H=16, W=16, attn_impl="ring:model").validate()
    ModelConfig(H=16, W=16, attn_impl="ulysses:model").validate()
    with pytest.raises(ValueError):
        ModelConfig(H=16, W=16, attn_impl="ring:").validate()
    with pytest.raises(ValueError):
        ModelConfig(H=16, W=16, attn_impl="flash").validate()
