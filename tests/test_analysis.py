"""The analysis subsystem, tested from both sides.

For every lint rule (GL101–GL107) there is a known-BAD fixture that must
fire and a known-GOOD fixture that must stay silent — the silent side
matters as much as the loud one, because each rule's whitelist encodes a
JAX idiom this repo actually uses (re-stored rng carries, static
shape args, ``is None`` checks on traced params).  Then the suppression
grammar, the baseline round-trip, and the runtime harness: sentinel
accuracy under a forced retrace, the compile-budget marker, the transfer
guard, and the donation guards against a real donating jit.

The last test is the tier-1 gate itself: the repo's own lint run must be
clean (zero unsuppressed findings over ``diff3d_tpu/``, ``tools/``,
``bench.py``).
"""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.analysis import lint_source, lint_paths
from diff3d_tpu.analysis.lint import (DEFAULT_TARGETS, apply_baseline,
                                      load_baseline, write_baseline)
from diff3d_tpu.analysis.runtime import (CompileBudgetExceeded,
                                         RecompilationSentinel,
                                         assert_consumed, assert_live,
                                         compile_budget,
                                         no_host_transfers, owned)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _findings(src, rule=None):
    out = lint_source("<fixture>.py", textwrap.dedent(src))
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _live(src, rule=None):
    return [f for f in _findings(src, rule) if not f.suppressed]


# ---------------------------------------------------------------------------
# GL001 / GL002: parse failures and reasonless suppressions
# ---------------------------------------------------------------------------


def test_gl001_syntax_error_is_a_finding():
    (f,) = _live("def f(:\n", "GL001")
    assert f.severity == "error" and "parse" in f.message


def test_gl002_suppression_without_reason():
    src = """
        import jax

        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))  # graftlint: disable=GL101
            return a + b
    """
    assert not _live(src, "GL101")          # the suppression still works
    (f,) = _live(src, "GL002")
    assert "no (reason)" in f.message


# ---------------------------------------------------------------------------
# GL101: rng key reuse
# ---------------------------------------------------------------------------


def test_gl101_fires_on_key_reuse():
    src = """
        import jax

        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
            return a + b
    """
    (f,) = _live(src, "GL101")
    assert "key" in f.message


def test_gl101_silent_on_split_discipline():
    src = """
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (2,))
            b = jax.random.uniform(k2, (2,))
            return a + b
    """
    assert not _live(src, "GL101")


def test_gl101_silent_on_restored_carry():
    # The repo's sampling-loop idiom: `rng, k = split(rng)` re-arms rng.
    src = """
        import jax

        def g(rng):
            for _ in range(3):
                rng, k = jax.random.split(rng)
                x = jax.random.normal(k, (2,))
            return x
    """
    assert not _live(src, "GL101")


def test_gl101_sees_module_alias():
    src = """
        import jax.random as jr

        def f(key):
            a = jr.normal(key, (2,))
            b = jr.normal(key, (2,))
            return a + b
    """
    assert len(_live(src, "GL101")) == 1


# ---------------------------------------------------------------------------
# GL102: Python branch on a traced value
# ---------------------------------------------------------------------------


def test_gl102_fires_on_traced_if():
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """
    (f,) = _live(src, "GL102")
    assert f.severity == "error"


def test_gl102_silent_on_static_argnums():
    src = """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            if n > 2:
                return x * n
            return x
    """
    assert not _live(src, "GL102")


def test_gl102_silent_on_none_and_shape_checks():
    src = """
        import jax

        @jax.jit
        def f(x, y=None):
            if y is None:
                return x
            if x.shape[0] > 2:
                return x + y
            return x - y
    """
    assert not _live(src, "GL102")


def test_gl102_fires_inside_scan_body():
    src = """
        import jax

        def outer(xs):
            def body(c, x):
                if x > 0:
                    c = c + x
                return c, x
            return jax.lax.scan(body, 0.0, xs)
    """
    assert len(_live(src, "GL102")) == 1


# ---------------------------------------------------------------------------
# GL103: host sync inside a traced context
# ---------------------------------------------------------------------------


def test_gl103_fires_on_float_of_traced():
    src = """
        import jax

        @jax.jit
        def f(x):
            return float(x) * 2
    """
    assert len(_live(src, "GL103")) == 1


def test_gl103_fires_on_item_and_asarray_in_jit():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            v = x.item()
            return np.asarray(x) + v
    """
    assert len(_live(src, "GL103")) == 2


def test_gl103_silent_outside_traced_context():
    src = """
        import numpy as np

        def report(x):
            return float(np.asarray(x).mean())
    """
    assert not _live(src, "GL103")


# ---------------------------------------------------------------------------
# GL104: read of a donated buffer
# ---------------------------------------------------------------------------

_DONATING_PRELUDE = """
    import jax

    def g(a, b):
        return a + b, b

    step = jax.jit(g, donate_argnums=(0,))
"""


def test_gl104_fires_on_read_after_donation():
    src = _DONATING_PRELUDE + """
    def run(x, y):
        out, new = step(x, y)
        return out + x
    """
    (f,) = _live(src, "GL104")
    assert "donat" in f.message


def test_gl104_silent_when_reading_returned_buffer():
    src = _DONATING_PRELUDE + """
    def run(x, y):
        out, new = step(x, y)
        return out + new
    """
    assert not _live(src, "GL104")


def test_gl104_loop_carry_rebind_is_clean_but_leak_fires():
    clean = _DONATING_PRELUDE + """
    def loop(x, y):
        for _ in range(3):
            out, x = step(x, y)
        return x
    """
    assert not _live(clean, "GL104")
    leak = _DONATING_PRELUDE + """
    def loop(x, y):
        for _ in range(3):
            out, new = step(x, y)
        return out
    """
    # x is donated on iteration 1 and re-donated (a read) on iteration 2.
    assert _live(leak, "GL104")


# ---------------------------------------------------------------------------
# GL105: shape-like param traced
# ---------------------------------------------------------------------------


def test_gl105_fires_on_traced_shape_param():
    src = """
        import jax
        import jax.numpy as jnp

        def f(x, shape):
            return jnp.zeros(shape) + x

        g = jax.jit(f)
    """
    (f,) = _live(src, "GL105")
    assert f.severity == "warning"


def test_gl105_silent_when_static():
    src = """
        import jax
        import jax.numpy as jnp

        def f(x, shape):
            return jnp.zeros(shape) + x

        g = jax.jit(f, static_argnames=("shape",))
    """
    assert not _live(src, "GL105")


# ---------------------------------------------------------------------------
# GL106: timing device work without a sync
# ---------------------------------------------------------------------------

_TIMING_PRELUDE = """
    import time
    import jax

    f = jax.jit(lambda x: x * 2)
"""


def test_gl106_fires_on_unsynced_timing():
    src = _TIMING_PRELUDE + """
    def bench(x):
        t0 = time.perf_counter()
        y = f(x)
        dt = time.perf_counter() - t0
        return dt, y
    """
    (f,) = _live(src, "GL106")
    assert "dispatch" in f.message


def test_gl106_silent_with_block_until_ready():
    src = _TIMING_PRELUDE + """
    def bench(x):
        t0 = time.perf_counter()
        y = jax.block_until_ready(f(x))
        dt = time.perf_counter() - t0
        return dt, y
    """
    assert not _live(src, "GL106")


def test_gl106_silent_on_host_only_timing():
    src = """
        import time

        def bench(n):
            t0 = time.perf_counter()
            total = sum(range(n))
            dt = time.perf_counter() - t0
            return dt, total
    """
    assert not _live(src, "GL106")


# ---------------------------------------------------------------------------
# GL107: mutable state under trace
# ---------------------------------------------------------------------------


def test_gl107_fires_on_mutable_default_and_traced_global():
    src = """
        import jax

        COUNT = 0

        def h(x, cache={}):
            return cache.setdefault("k", x)

        @jax.jit
        def f(x):
            global COUNT
            COUNT += 1
            return x
    """
    found = _live(src, "GL107")
    assert len(found) == 2
    severities = sorted(f.severity for f in found)
    assert severities == ["error", "warning"]


def test_gl107_silent_on_none_default_and_untraced_global():
    src = """
        CONFIG = None

        def setup(x, cache=None):
            global CONFIG
            CONFIG = x
            return cache
    """
    assert not _live(src, "GL107")


# ---------------------------------------------------------------------------
# Suppression grammar
# ---------------------------------------------------------------------------

_BAD_RNG = """
    import jax

    def f(key):
        a = jax.random.normal(key, (2,))
        b = jax.random.uniform(key, (2,)){supp}
        return a + b
"""


def test_suppression_same_line_with_reason():
    src = _BAD_RNG.format(
        supp="  # graftlint: disable=GL101(fixture: reuse is the point)")
    fs = _findings(src, "GL101")
    assert len(fs) == 1 and fs[0].suppressed
    assert fs[0].suppress_reason == "fixture: reuse is the point"
    assert not _live(src, "GL002")


def test_suppression_next_line():
    src = """
        import jax

        def f(key):
            a = jax.random.normal(key, (2,))
            # graftlint: disable-next-line=GL101(fixture)
            b = jax.random.uniform(key, (2,))
            return a + b
    """
    fs = _findings(src, "GL101")
    assert len(fs) == 1 and fs[0].suppressed


def test_suppression_file_scope_and_all():
    src = """
        # graftlint: disable-file=all(fixture file, every rule off)
        import jax

        @jax.jit
        def f(x, key):
            if x > 0:
                a = jax.random.normal(key, (2,))
                b = jax.random.uniform(key, (2,))
                return float(a + b)
            return 0.0
    """
    fs = _findings(src)
    assert fs and all(f.suppressed for f in fs)


def test_suppression_reason_with_nested_parens():
    src = _BAD_RNG.format(
        supp="  # graftlint: disable=GL101(sync via float(jnp.sum(x)) ok)")
    fs = _findings(src, "GL101")
    assert fs[0].suppress_reason == "sync via float(jnp.sum(x)) ok"
    assert not _live(src, "GL002")


def test_suppression_does_not_cover_other_rules():
    src = """
        import jax

        @jax.jit
        def f(x):  # graftlint: disable=GL101(wrong rule on purpose)
            if x > 0:
                return x
            return -x
    """
    assert len(_live(src, "GL102")) == 1


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    bad = textwrap.dedent("""
        import jax

        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
            return a + b
    """)
    mod = tmp_path / "legacy.py"
    mod.write_text(bad)
    baseline_path = str(tmp_path / "baseline.json")

    findings = lint_paths([str(mod)])
    assert [f.rule for f in findings] == ["GL101"]
    n = write_baseline(baseline_path, findings, str(tmp_path))
    assert n == 1

    baseline = load_baseline(baseline_path)
    masked = apply_baseline(lint_paths([str(mod)]), baseline,
                            str(tmp_path))
    assert masked[0].suppressed and masked[0].suppress_reason == "baseline"

    # Editing the violating line invalidates its fingerprint: the
    # finding comes back live instead of hiding behind a stale entry.
    mod.write_text(bad.replace("jax.random.uniform(key, (2,))",
                               "jax.random.uniform(key, (4,))"))
    fresh = apply_baseline(lint_paths([str(mod)]), baseline,
                           str(tmp_path))
    assert [f.rule for f in fresh] == ["GL101"] and not fresh[0].suppressed


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == set()


# ---------------------------------------------------------------------------
# Recompilation sentinel
# ---------------------------------------------------------------------------


def test_sentinel_counts_retraces_exactly():
    f = jax.jit(lambda x: x * 2.0)
    s = RecompilationSentinel()
    s.track("f", f)
    jax.block_until_ready(f(jnp.ones((4,))))
    assert s.counts() == {"f": 1}
    jax.block_until_ready(f(jnp.ones((4,))))     # same shape: cached
    assert s.counts() == {"f": 1}
    jax.block_until_ready(f(jnp.ones((5,))))     # forced retrace
    assert s.counts() == {"f": 2} and s.total() == 2
    with pytest.raises(CompileBudgetExceeded, match="2 > 1"):
        s.assert_budget(1)
    s.assert_budget(2)
    s.reset()
    assert s.total() == 0


def test_sentinel_zero_point_ignores_warm_cache():
    f = jax.jit(lambda x: x - 1.0)
    jax.block_until_ready(f(jnp.ones((3,))))     # warm before tracking
    s = RecompilationSentinel()
    s.track("f", f)
    jax.block_until_ready(f(jnp.ones((3,))))
    assert s.counts() == {"f": 0}


def test_sentinel_rejects_plain_functions():
    with pytest.raises(TypeError, match="_cache_size"):
        RecompilationSentinel().track("plain", lambda x: x)


def test_compile_budget_context_manager():
    f = jax.jit(lambda x: x + 3.0)
    with compile_budget(1, f=f):
        jax.block_until_ready(f(jnp.ones((4,))))
    with pytest.raises(CompileBudgetExceeded):
        with compile_budget(0, f=f):
            jax.block_until_ready(f(jnp.ones((6,))))


@pytest.mark.compile_budget(1)
def test_compile_budget_marker_enforces(compile_sentinel):
    f = jax.jit(lambda x: x * 0.5)
    compile_sentinel.track("f", f)
    jax.block_until_ready(f(jnp.ones((4,))))
    jax.block_until_ready(f(jnp.ones((4,))))
    assert compile_sentinel.counts() == {"f": 1}


# ---------------------------------------------------------------------------
# Transfer and donation guards
# ---------------------------------------------------------------------------


def test_no_host_transfers_clean_on_device_resident_work():
    f = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((4,))
    jax.block_until_ready(f(x))
    with no_host_transfers():
        jax.block_until_ready(f(x))


def test_no_host_transfers_faults_on_host_staging():
    f = jax.jit(lambda x: x * 2.0)
    jax.block_until_ready(f(jnp.ones((4,))))
    with pytest.raises(Exception, match="[Dd]isallow"):
        with no_host_transfers():
            f(np.ones((4,), np.float32))         # numpy arg: host upload


def test_donation_guards_on_a_donating_jit():
    g = jax.jit(lambda a: a + 1.0, donate_argnums=(0,))
    a = owned(np.ones((8,), np.float32))
    b = g(a)
    jax.block_until_ready(b)
    assert_consumed(a)
    assert_live(b)
    with pytest.raises(AssertionError, match="still live"):
        assert_consumed(b)
    with pytest.raises(AssertionError, match="deleted"):
        assert_live(a)


def test_owned_copies_host_passes_device_through():
    host = np.arange(6, dtype=np.float32)
    dev = owned(host)
    assert isinstance(dev, jax.Array)
    np.testing.assert_array_equal(np.asarray(dev), host)
    # Donating the owned copy must leave the caller's numpy memory alone.
    g = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
    jax.block_until_ready(g(dev))
    np.testing.assert_array_equal(host, np.arange(6, dtype=np.float32))
    already = jnp.ones((3,))
    assert owned(already) is already


# ---------------------------------------------------------------------------
# The tier-1 gate: the repo's own tree lints clean
# ---------------------------------------------------------------------------


def test_tools_import_safely():
    """Every ``tools/*.py`` must import without side effects (no work at
    module scope, no cwd-dependent sys.path mutation) — importing from a
    foreign cwd is exactly what the lint CLI and pytest collection do."""
    import glob
    import importlib.util
    paths = sorted(glob.glob(os.path.join(_REPO_ROOT, "tools", "*.py")))
    assert paths, "no tools found"
    for path in paths:
        name = "_toolcheck_" + os.path.basename(path)[:-3]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(getattr(mod, "main", None)), (
            f"{path}: tools expose their work as main(), "
            "run only under __main__")


def test_repo_lints_clean():
    """Every finding in the shipped tree is fixed or carries an inline
    reason — the same invariant `python -m diff3d_tpu.analysis` gates in
    CI, pinned here so plain `pytest` enforces it too."""
    targets = [os.path.join(_REPO_ROOT, t) for t in DEFAULT_TARGETS]
    targets = [t for t in targets if os.path.exists(t)]
    assert targets, "lint targets missing from the checkout"
    live = [f for f in lint_paths(targets) if not f.suppressed]
    assert not live, "unsuppressed graftlint findings:\n" + "\n".join(
        f.render() for f in live)
