"""Fused GroupNorm->FiLM/SiLU Pallas kernels vs the XLA reference.

Runs the exact TPU tile program in Pallas interpret mode on CPU
(conftest's virtual-device platform), checking forward and backward
against the unfused XLA composition over the channel widths the X-UNet
actually uses — the four srn64/srn128 level widths (128/256/512/1024)
plus lane- and sublane-padding edges (C=96, C=144, row counts off the
tile grid) — in both "fire" (FiLM/SiLU epilogues active) and "silent"
(plain GN) modes, f32 and bf16.  Also pinned here: the dispatch
registry's resolution rules, zero-retrace dispatch, the param-tree
identity between kernel backends, whole-model forward/backward parity,
and sharded step_many end-to-end parity with kernels='pallas'.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import MeshConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.models.layers import FrameGroupNorm
from diff3d_tpu.models.xunet import XUNet
from diff3d_tpu.ops import dispatch
from diff3d_tpu.ops.pallas_film import (fused_groupnorm, supports,
                                        xla_groupnorm)

# (N, L, C, groups): the four real level widths at deep-level token
# counts, plus padding edges.  L=256 is the 16x16 levels' true token
# count; interpret mode makes the 64x64 shallow levels too slow to run
# per-test, and the kernel's tiling is identical there (same C_pad,
# more row tiles — which the L=1000 case exercises harder anyway).
SHAPES = [
    (2, 256, 128, 32),    # srn64 level 0/1 width
    (2, 256, 256, 32),    # srn64 level 2/3 + srn128 level 0/1 width
    (1, 256, 512, 32),    # srn64 deepest / srn128 level 2 width
    (1, 64, 1024, 32),    # srn128 deepest width
    (2, 64, 96, 32),      # channel pad 96 -> 128 (partial lane tile)
    (1, 1000, 144, 24),   # C pad 144 -> 256 + rows off the tile grid
]
MODES = ["gn", "gn_silu", "gn_film", "gn_film_silu"]


def _cross(shapes, core):
    """Full shape x mode cross, with only the ``core`` (shape-index,
    mode) pairs in tier 1 — the rest ride the slow lane.  Core keeps
    every shape and every mode covered, with the all-features-on
    ``gn_film_silu`` variant on each shape (it subsumes the others'
    code paths; the remaining combos guard mode-specific branches and
    run nightly)."""
    out = []
    for si, s in enumerate(shapes):
        for m in MODES:
            if (si, m) in core:
                out.append(pytest.param(s, m, id=f"shape{si}-{m}"))
            else:
                out.append(pytest.param(s, m, id=f"shape{si}-{m}",
                                        marks=pytest.mark.slow))
    return out


def _inputs(shape, dtype, seed=0, film=False):
    rng = np.random.RandomState(seed)
    N, L, C, G = shape
    x = jnp.asarray(rng.randn(N, L, C), dtype)
    gamma = jnp.asarray(rng.randn(C), jnp.float32)
    beta = jnp.asarray(rng.randn(C), jnp.float32)
    kw = dict(num_groups=G)
    if film:
        kw["scale"] = jnp.asarray(0.3 * rng.randn(N, L, C), dtype)
        kw["shift"] = jnp.asarray(0.3 * rng.randn(N, L, C), dtype)
    return x, gamma, beta, kw


def _mode_kw(mode):
    return dict(film="film" in mode, silu="silu" in mode)


@pytest.mark.parametrize(
    "shape,mode",
    _cross(SHAPES, core={(0, "gn_film_silu"), (1, "gn"), (1, "gn_silu"),
                         (1, "gn_film"), (1, "gn_film_silu"),
                         (2, "gn_film_silu"), (3, "gn_film_silu"),
                         (4, "gn_film_silu"), (5, "gn_film_silu")}))
def test_forward_parity_f32(shape, mode):
    m = _mode_kw(mode)
    x, gamma, beta, kw = _inputs(shape, jnp.float32, film=m["film"])
    kw["silu"] = m["silu"]
    ref = xla_groupnorm(x, gamma, beta, **kw)
    out = fused_groupnorm(x, gamma, beta, interpret=True, **kw)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize(
    "shape,mode",
    _cross([SHAPES[1], SHAPES[4], SHAPES[5]],
           core={(0, "gn_silu"), (1, "gn_film_silu"), (2, "gn"),
                 (2, "gn_film")}))
def test_forward_parity_bf16(shape, mode):
    """bf16 inputs, f32 accumulation.  The fused kernel rounds once at
    the end where the reference rounds between GN and the epilogues, so
    agreement is to a couple of bf16 ULP at the output magnitude."""
    m = _mode_kw(mode)
    x, gamma, beta, kw = _inputs(shape, jnp.bfloat16, film=m["film"])
    kw["silu"] = m["silu"]
    ref = xla_groupnorm(x, gamma, beta, **kw).astype(jnp.float32)
    out = fused_groupnorm(x, gamma, beta, interpret=True,
                          **kw).astype(jnp.float32)
    scale = float(jnp.max(jnp.abs(ref))) + 1.0
    np.testing.assert_allclose(out / scale, ref / scale, atol=2e-2)


@pytest.mark.parametrize(
    "shape,mode",
    _cross([SHAPES[0], SHAPES[3], SHAPES[4], SHAPES[5]],
           core={(0, "gn_film_silu"), (1, "gn_film_silu"),
                 (2, "gn"), (2, "gn_silu"), (2, "gn_film"),
                 (2, "gn_film_silu"), (3, "gn_film_silu")}))
def test_backward_parity_f32(shape, mode):
    m = _mode_kw(mode)
    x, gamma, beta, kw = _inputs(shape, jnp.float32, film=m["film"])
    film = m["film"]

    def loss(fn, interpret):
        def f(*args):
            call = dict(num_groups=kw["num_groups"], silu=m["silu"])
            if film:
                call["scale"], call["shift"] = args[3], args[4]
            if interpret is not None:
                call["interpret"] = interpret
            return jnp.mean(fn(args[0], args[1], args[2], **call) ** 2)
        return f

    prim = (x, gamma, beta) + ((kw["scale"], kw["shift"]) if film else ())
    argnums = tuple(range(len(prim)))
    g_ref = jax.grad(loss(xla_groupnorm, None), argnums=argnums)(*prim)
    g_out = jax.grad(loss(fused_groupnorm, True), argnums=argnums)(*prim)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-3)


def test_backward_parity_bf16():
    x, gamma, beta, kw = _inputs(SHAPES[4], jnp.bfloat16, film=True)

    def loss(fn, interpret):
        def f(x, s, t):
            call = dict(num_groups=kw["num_groups"], silu=True,
                        scale=s, shift=t)
            if interpret is not None:
                call["interpret"] = interpret
            return jnp.mean(fn(x, gamma, beta, **call)
                            .astype(jnp.float32) ** 2)
        return f

    prim = (x, kw["scale"], kw["shift"])
    g_ref = jax.grad(loss(xla_groupnorm, None), argnums=(0, 1, 2))(*prim)
    g_out = jax.grad(loss(fused_groupnorm, True), argnums=(0, 1, 2))(*prim)
    for a, b in zip(g_out, g_ref):
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        scale = float(jnp.max(jnp.abs(b))) + 1e-3
        np.testing.assert_allclose(a / scale, b / scale, atol=5e-2)


# ---------------------------------------------------------------------------
# dispatch registry
# ---------------------------------------------------------------------------


def test_supports_predicate():
    x = jnp.zeros((2, 64, 96), jnp.float32)
    assert supports(x, num_groups=32)
    assert not supports(jnp.zeros((2, 64, 96), jnp.float16), num_groups=32)
    assert not supports(jnp.zeros((2, 2, 64, 96)), num_groups=32)   # 4D
    assert not supports(x, num_groups=7)                 # 96 % 7 != 0
    assert not supports(jnp.zeros((1, 8, 8192)), num_groups=32)  # > MAX_C


def test_resolve_rules(monkeypatch):
    x = jax.ShapeDtypeStruct((2, 256, 128), jnp.float32)
    # explicit pallas: honoured when supported...
    assert dispatch.resolve("groupnorm", "pallas", x,
                            num_groups=32).name == "pallas"
    # ...and falls back to xla (never an error) when not.
    bad = jax.ShapeDtypeStruct((2, 256, 128), jnp.float16)
    assert dispatch.resolve("groupnorm", "pallas", bad,
                            num_groups=32).name == "xla"
    assert dispatch.resolve("groupnorm", "xla", x,
                            num_groups=32).name == "xla"
    # 'auto' keys off the process-default backend.
    monkeypatch.setattr(dispatch, "default_backend", lambda: "cpu")
    assert dispatch.resolve("groupnorm", "auto", x,
                            num_groups=32).name == "xla"
    monkeypatch.setattr(dispatch, "default_backend", lambda: "tpu")
    assert dispatch.resolve("groupnorm", "auto", x,
                            num_groups=32).name == "pallas"
    tiny = jax.ShapeDtypeStruct((2, 8, 128), jnp.float32)  # auto-policy no
    assert dispatch.resolve("groupnorm", "auto", tiny,
                            num_groups=32).name == "xla"
    with pytest.raises(ValueError, match="requested"):
        dispatch.resolve("groupnorm", "cuda", x, num_groups=32)
    with pytest.raises(KeyError, match="no implementations"):
        dispatch.resolve("nonesuch", "xla", x)


def test_sdpa_shares_registry():
    """attention.py registers through the same registry: both ops are
    visible and sdpa's auto policy matches the measured rule."""
    import diff3d_tpu.ops.attention  # noqa: F401 - registers 'sdpa'

    assert set(dispatch.implementations("sdpa")) == {"pallas", "xla"}
    assert set(dispatch.implementations("groupnorm")) == {"pallas", "xla"}


@pytest.mark.compile_budget(1)
def test_dispatch_adds_zero_retraces(compile_sentinel):
    """Dispatch resolution is trace-time static: repeated calls through
    the fused path with fresh data never mint a second executable."""
    x, gamma, beta, kw = _inputs(SHAPES[4], jnp.float32, film=True)

    @jax.jit
    def run(x, gamma, beta, scale, shift):
        return dispatch.dispatch("groupnorm", "pallas", x, gamma, beta,
                                 num_groups=kw["num_groups"],
                                 scale=scale, shift=shift, silu=True,
                                 interpret=True)

    compile_sentinel.track("fused_gn", run)
    for seed in range(3):
        x2, _, _, kw2 = _inputs(SHAPES[4], jnp.float32, seed=seed,
                                film=True)
        run(x2, gamma, beta, kw2["scale"], kw2["shift"])
    assert compile_sentinel.counts()["fused_gn"] == 1


# ---------------------------------------------------------------------------
# model wiring: param-tree identity + whole-model parity
# ---------------------------------------------------------------------------


def _tiny_batch(B=2, size=8, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "x": jnp.asarray(rs.randn(B, size, size, 3), jnp.float32),
        "z": jnp.asarray(rs.randn(B, size, size, 3), jnp.float32),
        "logsnr": jnp.asarray(rs.randn(B, 2), jnp.float32),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.asarray(rs.randn(B, 2, 3), jnp.float32),
        "K": jnp.broadcast_to(
            jnp.asarray([[8.0, 0, 4], [0, 8, 4], [0, 0, 1]]), (B, 3, 3)),
    }


def _random_params(model, batch, cond_mask, seed=7):
    """Random NON-ZERO params: the X-UNet's output conv is zero-init, so
    freshly initialised params make every output (and gradient) exactly
    zero — parity would pass vacuously."""
    p0 = model.init(jax.random.PRNGKey(0), batch, cond_mask=cond_mask)
    leaves, treedef = jax.tree_util.tree_flatten(p0)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [0.1 * jax.random.normal(k, l.shape, l.dtype)
                  for k, l in zip(keys, leaves)])


def test_param_tree_identical_across_backends():
    """A checkpoint trained with either backend restores into the other:
    same tree structure, same leaf shapes/dtypes, same inits."""
    h = jnp.zeros((1, 2, 8, 8, 16))
    mx = FrameGroupNorm(kernels="xla", silu=True)
    mp = FrameGroupNorm(kernels="pallas", silu=True)
    px = mx.init(jax.random.PRNGKey(0), h)
    pp = mp.init(jax.random.PRNGKey(0), h)
    assert jax.tree_util.tree_structure(px) == \
        jax.tree_util.tree_structure(pp)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(px),
            jax.tree_util.tree_leaves_with_path(pp)):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)

    # Whole-model tree: eval_shape'd init (free) — leaf VALUES are
    # already proven equal above on FrameGroupNorm, the only module
    # whose parameter emission changed.
    cfg = make_tiny_config(imgsize=8, ch=8)
    batch = _tiny_batch()
    cm = jnp.ones((2,), bool)
    t_x = jax.eval_shape(
        lambda: XUNet(cfg.model).init(
            jax.random.PRNGKey(0), batch, cond_mask=cm))
    t_p = jax.eval_shape(
        lambda: XUNet(dataclasses.replace(
            cfg.model, kernels="pallas")).init(
                jax.random.PRNGKey(0), batch, cond_mask=cm))
    assert jax.tree_util.tree_structure(t_x) == \
        jax.tree_util.tree_structure(t_p)
    for a, b in zip(jax.tree_util.tree_leaves(t_x),
                    jax.tree_util.tree_leaves(t_p)):
        assert a.shape == b.shape and a.dtype == b.dtype


# Tier-1 budget: whole-model forward parity is superseded in tier 1 by
# test_step_many_sharded_pallas_parity, which drives the same kernels
# through every GN/FiLM/SiLU site inside the sharded, scanned sampler
# and compares against the default-kernel runtime end-to-end.
@pytest.mark.slow
def test_xunet_forward_parity():
    """Whole-model check: kernels='pallas' reproduces the default graph's
    outputs through every GN/FiLM/SiLU site (the ResnetBlock entry
    GN->SiLU, the FiLM epilogue, AttnBlock GNs and the head's last_gn).
    Per-parameter gradient parity through the same sites is the
    slow-lane companion below; the per-site custom_vjp itself is pinned
    tier-1 by ``test_backward_parity_f32``."""
    cfg = make_tiny_config(imgsize=8, ch=8)
    m_x = XUNet(cfg.model)
    m_p = XUNet(dataclasses.replace(cfg.model, kernels="pallas"))
    batch = _tiny_batch()
    cm = jnp.ones((2,), bool)
    params = _random_params(m_x, batch, cm)

    out_x = m_x.apply(params, batch, cond_mask=cm)
    out_p = m_p.apply(params, batch, cond_mask=cm)
    assert float(jnp.max(jnp.abs(out_x))) > 1e-3   # not vacuous
    np.testing.assert_allclose(out_p, out_x, atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_xunet_backward_parity():
    """Whole-model gradient sweep (slow lane: differentiating the
    interpret-mode kernels through every site takes minutes of tracing):
    kernels='pallas' reproduces every parameter gradient."""
    cfg = make_tiny_config(imgsize=8, ch=8)
    m_x = XUNet(cfg.model)
    m_p = XUNet(dataclasses.replace(cfg.model, kernels="pallas"))
    batch = _tiny_batch()
    cm = jnp.ones((2,), bool)
    params = _random_params(m_x, batch, cm)

    def loss(m, p):
        return jnp.mean(m.apply(p, batch, cond_mask=cm) ** 2)

    g_x = jax.grad(lambda p: loss(m_x, p))(params)
    g_p = jax.grad(lambda p: loss(m_p, p))(params)
    for (k, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g_x),
                              jax.tree_util.tree_leaves_with_path(g_p)):
        np.testing.assert_allclose(
            b, a, atol=1e-5, rtol=1e-3,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(k)}")


def test_default_kernels_graph_unchanged():
    """kernels='xla' (the default) lowers to a jaxpr with no pallas
    call and no structural drift — pre-kernel-layer checkpoints and the
    pinned analysis manifests stay valid without re-conversion."""
    cfg = make_tiny_config(imgsize=8, ch=8)
    model = XUNet(cfg.model)
    batch = _tiny_batch()
    cm = jnp.ones((2,), bool)
    params = model.init(jax.random.PRNGKey(0), batch, cond_mask=cm)
    text = jax.jit(lambda p: model.apply(p, batch, cond_mask=cm)).lower(
        params).as_text()
    assert "pallas" not in text.lower()


# ---------------------------------------------------------------------------
# sharded end-to-end: step_many with kernels='pallas'
# ---------------------------------------------------------------------------


def test_step_many_sharded_pallas_parity():
    """End-to-end on the CPU mesh (data=2 slice of conftest's 8 virtual
    devices): synthesize_many with kernels='pallas' — interpret-mode
    fused kernels inside the sharded, scanned, donated step_many program
    — matches the unsharded default-kernel sampler per-object."""
    from diff3d_tpu.data import SyntheticDataset
    from diff3d_tpu.parallel import make_mesh
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.train.trainer import init_params

    # Shallow 2-level model (tier-1 budget): the claim — fused kernels
    # inside the sharded/scanned/donated step_many match the default
    # runtime — is depth-independent, and both shallow levels hit every
    # fused-GN site kind (ResnetBlock entry, FiLM epilogue, attention).
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=8)
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(3), jax.random.PRNGKey(4)]

    ref = Sampler(model, params, cfg).synthesize_many(views, keys,
                                                      max_views=3)

    cfg_p = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas"))
    env = make_mesh(MeshConfig(data_parallel=2, model_parallel=1),
                    devices=jax.devices()[:2])
    got = Sampler(XUNet(cfg_p.model), params, cfg_p,
                  mesh=env).synthesize_many(views, keys, max_views=3)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)
