import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from diff3d_tpu.config import MeshConfig
from diff3d_tpu.parallel import make_mesh, param_sharding


def test_make_mesh_all_devices():
    env = make_mesh()
    assert env.mesh.shape == {"data": 8, "model": 1}


def test_make_mesh_model_axis():
    env = make_mesh(MeshConfig(model_parallel=2))
    assert env.mesh.shape == {"data": 4, "model": 2}


def test_make_mesh_too_many_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data_parallel=16))


def test_batch_sharding_splits_leading_axis():
    env = make_mesh()
    x = jax.device_put(jnp.zeros((16, 4)), env.batch())
    assert x.sharding.spec == P("data")
    # each device holds 16/8 = 2 rows
    assert x.addressable_shards[0].data.shape == (2, 4)


def test_param_sharding_policy():
    env = make_mesh()
    # large divisible tensor -> sharded on its largest axis
    s = param_sharding(env.mesh, (3, 3, 256, 512))
    assert s.spec == P(None, None, None, "data")
    # small tensor -> replicated
    assert param_sharding(env.mesh, (32,)).spec == P()
    # indivisible axes -> replicated
    assert param_sharding(env.mesh, (129, 33, 100)).spec in (P(), P(None))


def test_fsdp_state_placement_reduces_per_device_bytes():
    env_r = make_mesh(MeshConfig(param_sharding="replicated"))
    env_f = make_mesh(MeshConfig(param_sharding="fsdp"))
    tree = {"w": jnp.zeros((256, 512)), "b": jnp.zeros((8,))}
    xr = jax.device_put(tree, env_r.params(tree))
    xf = jax.device_put(tree, env_f.params(tree))
    assert xr["w"].addressable_shards[0].data.shape == (256, 512)
    assert xf["w"].addressable_shards[0].data.shape in ((256, 64), (32, 512))
    # tiny bias stays replicated under fsdp
    assert xf["b"].addressable_shards[0].data.shape == (8,)


def test_psum_over_mesh_matches_sum():
    """XLA collectives over the mesh = the DDP all-reduce the reference
    delegates to gloo (train.py:230-233)."""
    from diff3d_tpu.parallel import shard_map

    env = make_mesh()
    x = jnp.arange(8.0)

    @jax.jit
    def allreduce(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "data"), mesh=env.mesh,
            in_specs=P("data"), out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(allreduce(x)), 28.0)


# Tier-1 budget: three tests share this init; the model.init is ~8s on
# CPU and the returned values are immutable (jax arrays; callers that
# perturb params tree.map into fresh trees), so cache the one result.
# Shallow 2-level model: the claims here are TP/fsdp PLACEMENT and
# sharded==replicated equality — depth-independent per test_config's
# shallow contract, and both shallow levels carry attention so every
# TP rule kind (q/k/v column, out_proj row, norm replicated) places.
@functools.lru_cache(maxsize=1)
def _tiny_model_and_batch_cached():
    from diff3d_tpu.config import test_config
    from diff3d_tpu.models import XUNet

    cfg = test_config(imgsize=16, ch=8, shallow=True)
    model = XUNet(cfg.model)
    B = 4
    rng = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rng.randn(B, 16, 16, 3), jnp.float32),
        "z": jnp.asarray(rng.randn(B, 16, 16, 3), jnp.float32),
        "logsnr": jnp.asarray(np.stack([np.full(B, 20.0),
                                        rng.uniform(-20, 20, B)], 1),
                              jnp.float32),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.asarray(rng.randn(B, 2, 3), jnp.float32),
        "K": jnp.broadcast_to(
            jnp.array([[20.0, 0, 8.0], [0, 20.0, 8.0], [0, 0, 1]]),
            (B, 3, 3)),
    }
    cond = jnp.ones((B,), bool)
    params = model.init(jax.random.PRNGKey(0), batch,
                        cond_mask=cond)["params"]
    # nudge zero-init convs so TP-vs-replicated comparison is informative
    params = jax.tree.map(lambda x: x + 0.01, params)
    return model, params, batch, cond


def _tiny_model_and_batch():
    model, params, batch, cond = _tiny_model_and_batch_cached()
    return model, params, dict(batch), cond


def test_tp_param_rules():
    from diff3d_tpu.config import MeshConfig

    env = make_mesh(MeshConfig(model_parallel=4, param_sharding="tp"))
    model, params, _, _ = _tiny_model_and_batch()
    shardings = env.params(params)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]

    def spec_for(substr):
        return [s.spec for path, s in flat
                if substr in "/".join(getattr(p, "key", str(p))
                                      for p in path)]

    # column-parallel q/k/v, row-parallel out_proj
    assert any(sp[-1] == "model" for sp in spec_for("q_proj/kernel") if sp)
    assert any(sp and sp[0] == "model" for sp in spec_for("out_proj/kernel"))


def test_tp_forward_matches_replicated():
    """GSPMD-partitioned (model_parallel=4) forward == single-device."""
    from diff3d_tpu.config import MeshConfig

    model, params, batch, cond = _tiny_model_and_batch()
    ref = model.apply({"params": params}, batch, cond_mask=cond)

    env = make_mesh(MeshConfig(data_parallel=2, model_parallel=4,
                               param_sharding="tp"))
    p_sh = jax.device_put(params, env.params(params))
    b_sh = jax.device_put(batch, env.batch())
    cond_sh = jax.device_put(cond, env.batch())

    @jax.jit
    def fwd(params, batch, cond):
        return model.apply({"params": params}, batch, cond_mask=cond)

    out = fwd(p_sh, b_sh, cond_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_fsdp_tp_train_step_runs():
    # Tier-1 budget: shallow 2-level model (the claim — the combined
    # fsdp+tp placement compiles and steps on the 2x4 mesh — is
    # depth-independent per test_config's shallow contract; both levels
    # keep attention so every TP rule kind still places).  The deep-
    # graph fsdp+tp NUMERICS live in the slow-lane
    # test_multi_step_trajectory_equality[fsdp+tp].
    import dataclasses

    from diff3d_tpu.config import MeshConfig, test_config
    from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train import (TrainState, create_train_state,
                                  make_train_step)
    from diff3d_tpu.train.trainer import init_params

    cfg = test_config(imgsize=16, ch=8, shallow=True)
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, global_batch=4),
        mesh=MeshConfig(data_parallel=2, model_parallel=4,
                        param_sharding="fsdp+tp"))
    env = make_mesh(cfg.mesh)
    model = XUNet(cfg.model)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(init_params(model, cfg, rng), cfg.train)
    state = jax.device_put(
        state, TrainState(step=env.replicated(),
                          params=env.params(state.params),
                          opt_state=env.params(state.opt_state),
                          ema_params=env.params(state.ema_params)))
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=16)
    raw = next(InfiniteLoader(ds, 4, num_workers=0))
    batch = jax.device_put(
        {"imgs": raw["imgs"], "R": raw["R"], "T": raw["T"], "K": raw["K"]},
        env.batch())
    step_fn = make_train_step(model, cfg, env)
    state, metrics = step_fn(state, batch, rng)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1


def test_tp_norm_biases_stay_replicated():
    from diff3d_tpu.config import MeshConfig
    from jax.sharding import PartitionSpec as P

    env = make_mesh(MeshConfig(model_parallel=4, param_sharding="tp"))
    model, params, _, _ = _tiny_model_and_batch()
    shardings = env.params(params)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    gn_bias = [s.spec for path, s in flat
               if "GroupNorm" in "/".join(getattr(p, "key", str(p))
                                          for p in path)]
    # replicated == every spec entry None (P() and P(None) both qualify)
    assert gn_bias and all(all(a is None for a in sp) for sp in gn_bias)
