import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from diff3d_tpu.config import MeshConfig
from diff3d_tpu.parallel import make_mesh, param_sharding


def test_make_mesh_all_devices():
    env = make_mesh()
    assert env.mesh.shape == {"data": 8, "model": 1}


def test_make_mesh_model_axis():
    env = make_mesh(MeshConfig(model_parallel=2))
    assert env.mesh.shape == {"data": 4, "model": 2}


def test_make_mesh_too_many_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data_parallel=16))


def test_batch_sharding_splits_leading_axis():
    env = make_mesh()
    x = jax.device_put(jnp.zeros((16, 4)), env.batch())
    assert x.sharding.spec == P("data")
    # each device holds 16/8 = 2 rows
    assert x.addressable_shards[0].data.shape == (2, 4)


def test_param_sharding_policy():
    env = make_mesh()
    # large divisible tensor -> sharded on its largest axis
    s = param_sharding(env.mesh, (3, 3, 256, 512))
    assert s.spec == P(None, None, None, "data")
    # small tensor -> replicated
    assert param_sharding(env.mesh, (32,)).spec == P()
    # indivisible axes -> replicated
    assert param_sharding(env.mesh, (129, 33, 100)).spec in (P(), P(None))


def test_fsdp_state_placement_reduces_per_device_bytes():
    env_r = make_mesh(MeshConfig(param_sharding="replicated"))
    env_f = make_mesh(MeshConfig(param_sharding="fsdp"))
    tree = {"w": jnp.zeros((256, 512)), "b": jnp.zeros((8,))}
    xr = jax.device_put(tree, env_r.params(tree))
    xf = jax.device_put(tree, env_f.params(tree))
    assert xr["w"].addressable_shards[0].data.shape == (256, 512)
    assert xf["w"].addressable_shards[0].data.shape in ((256, 64), (32, 512))
    # tiny bias stays replicated under fsdp
    assert xf["b"].addressable_shards[0].data.shape == (8,)


def test_psum_over_mesh_matches_sum():
    """XLA collectives over the mesh = the DDP all-reduce the reference
    delegates to gloo (train.py:230-233)."""
    from jax import shard_map

    env = make_mesh()
    x = jnp.arange(8.0)

    @jax.jit
    def allreduce(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "data"), mesh=env.mesh,
            in_specs=P("data"), out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(allreduce(x)), 28.0)
