"""Sharded + device-resident sampling runtime.

Three contracts pinned here, on the 8-virtual-device CPU mesh (conftest):

  * PARITY — ``synthesize_many`` on a mesh (object axis sharded over
    ``data``, params replicated/fsdp) matches the unsharded path
    per-object to float tolerance, including when N must be padded up to
    the data-axis size.
  * ONE PROGRAM — a full ``synthesize_many`` run compiles exactly one
    view-step executable (the autoregressive loop re-enters the same
    jitted function with identical shapes; any per-view recompile is a
    bug that would multiply sampling cost by the compile time).
    Enforced by the ``compile_sentinel`` fixture and the
    ``@pytest.mark.compile_budget`` marker from
    ``diff3d_tpu.analysis.pytest_plugin``.
  * DEVICE RESIDENCE — after the first view step, the record carry never
    crosses the host boundary: a second step under
    ``analysis.runtime.no_host_transfers()`` runs clean, and the donated
    input buffer is actually consumed (``assert_consumed``), i.e. the
    update is in place rather than a device-side copy.

Plus the serving-side divisibility rules (``lane_count`` rounding and the
engine's mesh-quantised ``max_batch``) and an end-to-end sharded engine
run checked against the unsharded offline sampler.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.analysis.runtime import (assert_consumed, assert_live,
                                         no_host_transfers, owned)
from diff3d_tpu.config import MeshConfig, ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh
from diff3d_tpu.sampling import Sampler, record_capacity
from diff3d_tpu.serving import ServingService, ViewRequest
from diff3d_tpu.serving.engine import lane_count
from diff3d_tpu.train.trainer import init_params


@pytest.fixture(scope="module")
def setup():
    # Tier-1 budget: shallow 2-level model — every claim in this file is
    # about the sharded RUNTIME (padding, donation, lane math, compile
    # count, fsdp placement), depth-independent per test_config's
    # shallow contract; all comparisons are in-process.
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    ds = SyntheticDataset(num_objects=3, num_views=4, imgsize=8)
    return cfg, model, params, ds


def _mesh(data: int):
    return make_mesh(MeshConfig(data_parallel=data, model_parallel=1),
                     devices=jax.devices()[:data])


# ---------------------------------------------------------------------------
# Sharded parity
# ---------------------------------------------------------------------------


def test_sharded_synthesize_many_matches_unsharded(setup):
    """Object axis over a data=2 mesh: per-object results must match the
    unsharded runtime to float tolerance (same per-object key stream; XLA
    may tile differently, so not bitwise)."""
    cfg, model, params, ds = setup
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(3), jax.random.PRNGKey(4)]
    plain = Sampler(model, params, cfg)
    ref = plain.synthesize_many(views, keys, max_views=3)

    env = _mesh(2)
    sharded = Sampler(model, params, cfg, mesh=env)
    assert sharded.lane_multiple == 2
    got = sharded.synthesize_many(views, keys, max_views=3)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_sharded_synthesize_many_pads_to_lane_multiple(setup):
    """N=3 objects on a data=2 mesh: the runtime pads the object axis
    3 -> 4 internally and the padding never contaminates the live
    objects' results.  (The full-8-device pad 3 -> 8 is the slow-lane
    variant below — same pad code path, 4x the compile.)"""
    cfg, model, params, ds = setup
    views = [ds.all_views(i) for i in range(3)]
    keys = [jax.random.PRNGKey(10 + i) for i in range(3)]
    plain = Sampler(model, params, cfg)
    ref = plain.synthesize_many(views, keys, max_views=3)

    env = _mesh(2)
    sharded = Sampler(model, params, cfg, mesh=env)
    assert sharded.lane_multiple == 2
    got = sharded.synthesize_many(views, keys, max_views=3)
    assert got.shape[0] == 3               # padding lanes dropped
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


# Tier-1 budget: identical claim to the data=2 pad test above (the pad
# mask / lane-drop path is mesh-size-independent); this variant only
# adds the all-8-device sampler-mesh compile, ~16s of tier-1 wall.
@pytest.mark.slow
def test_sharded_synthesize_many_pads_full_mesh(setup):
    """N=3 objects on the full 8-device data mesh: pad 3 -> 8."""
    cfg, model, params, ds = setup
    views = [ds.all_views(i) for i in range(3)]
    keys = [jax.random.PRNGKey(10 + i) for i in range(3)]
    ref = Sampler(model, params, cfg).synthesize_many(views, keys,
                                                      max_views=3)
    env = make_mesh(MeshConfig())          # all 8 devices on 'data'
    sharded = Sampler(model, params, cfg, mesh=env)
    assert sharded.lane_multiple == 8
    got = sharded.synthesize_many(views, keys, max_views=3)
    assert got.shape[0] == 3               # padding lanes dropped
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_sharded_fsdp_params_match(setup):
    """The fsdp param policy must not change results, only placement."""
    cfg, model, params, ds = setup
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(1), jax.random.PRNGKey(2)]
    ref = Sampler(model, params, cfg).synthesize_many(views, keys,
                                                      max_views=3)
    cfg_fsdp = dataclasses.replace(
        cfg, mesh=dataclasses.replace(cfg.mesh, param_sharding="fsdp"))
    env = _mesh(2)
    got = Sampler(model, params, cfg_fsdp, mesh=env).synthesize_many(
        views, keys, max_views=3)
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_step_many_rejects_non_multiple_batch(setup):
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg, mesh=_mesh(2))
    cap = record_capacity(3)
    B = len(cfg.diffusion.guidance_weights)
    with pytest.raises(ValueError, match="multiple"):
        sampler.step_many(
            np.zeros((3, cap, B, 8, 8, 3), np.float32),
            np.zeros((3, cap, 3, 3), np.float32),
            np.zeros((3, cap, 3), np.float32),
            np.ones((3,), np.int32),
            np.stack([np.eye(3, dtype=np.float32)] * 3),
            np.stack([np.asarray(jax.random.PRNGKey(i))
                      for i in range(3)]))


# ---------------------------------------------------------------------------
# One compiled program per synthesize_many run
# ---------------------------------------------------------------------------


@pytest.mark.compile_budget(1)
def test_synthesize_many_compiles_exactly_once(setup, compile_sentinel):
    """The whole autoregressive run (3 view steps here) re-enters ONE
    compiled executable — record_len is a traced argument, not a shape,
    so no view index triggers its own program.  The marker enforces the
    budget at teardown; the inline check pins that exactly one program
    exists (not zero) and that the second run re-enters it."""
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg, mesh=_mesh(2))
    compile_sentinel.track("view_step", sampler._run_view_many)
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(0), jax.random.PRNGKey(1)]
    sampler.synthesize_many(views, keys, max_views=4)
    assert compile_sentinel.counts()["view_step"] == 1
    # A second run with the same shapes stays on the same program.
    sampler.synthesize_many(views, keys, max_views=4)
    assert compile_sentinel.counts()["view_step"] == 1


# ---------------------------------------------------------------------------
# Device residence: no per-view host re-upload, donated in-place update
# ---------------------------------------------------------------------------


def _device_record(sampler, views, cfg, n_views):
    imgs = np.asarray(views["imgs"], np.float32)
    rec_i, rec_R, rec_T = sampler._record_init(
        imgs[0], np.asarray(views["R"], np.float32),
        np.asarray(views["T"], np.float32), n_views)
    # owned(), not bare jnp.asarray: the record carry is DONATED, and
    # asarray may zero-copy alias the numpy buffer — donating an aliased
    # buffer leaves the carry pointing at freed host memory (the same
    # contract Sampler._owned enforces for the public step API).
    return (owned(rec_i), jnp.asarray(rec_R),
            jnp.asarray(rec_T),
            jnp.asarray(np.asarray(views["K"], np.float32)))


def test_step_loop_runs_under_transfer_guard(setup):
    """Steady-state view steps move NOTHING across the host boundary:
    after one warmup step, further steps on the returned carry run under
    ``no_host_transfers()`` (scoped transfer_guard: faults on any
    implicit host->device or device->host transfer)."""
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg)
    rec_i, rec_R, rec_T, K = _device_record(sampler, ds.all_views(0), cfg,
                                            n_views=4)
    step = jnp.asarray(1, jnp.int32)
    rng = jnp.asarray(jax.random.PRNGKey(0))
    # Warmup: compiles the program and commits every operand to device.
    out, rec_i, step, rng = sampler.step(rec_i, rec_R, rec_T, step, K, rng)
    jax.block_until_ready(out)
    with no_host_transfers():
        out, rec_i, step, rng = sampler.step(rec_i, rec_R, rec_T, step, K,
                                             rng)
        out2, rec_i, step, rng = sampler.step(rec_i, rec_R, rec_T, step,
                                              K, rng)
    np.testing.assert_array_equal(np.asarray(step), 4)
    assert np.isfinite(np.asarray(out2)).all()


def test_step_donates_record_buffer(setup):
    """The record buffer is donated: the input device buffer is consumed
    (in-place dynamic_update_slice), not copied."""
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg)
    rec_i, rec_R, rec_T, K = _device_record(sampler, ds.all_views(0), cfg,
                                            n_views=4)
    _, new_rec, _, _ = sampler.step(rec_i, rec_R, rec_T,
                                    jnp.asarray(1, jnp.int32), K,
                                    jnp.asarray(jax.random.PRNGKey(0)))
    jax.block_until_ready(new_rec)
    assert_consumed(rec_i)
    assert_live(new_rec)


def test_step_loop_bitwise_matches_synthesize(setup):
    """Driving the public step API by hand reproduces ``synthesize``
    BITWISE — same program, same carried rng stream (this is the contract
    the serving engine's bit-parity guarantee stands on)."""
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg)
    views = ds.all_views(1)
    ref = sampler.synthesize(views, jax.random.PRNGKey(9), max_views=4)

    rec_i, rec_R, rec_T, K = _device_record(sampler, views, cfg, n_views=4)
    step = jnp.asarray(1, jnp.int32)
    rng = jnp.asarray(jax.random.PRNGKey(9))
    outs = []
    for _ in range(3):
        out, rec_i, step, rng = sampler.step(rec_i, rec_R, rec_T, step, K,
                                             rng)
        outs.append(np.asarray(out))
    np.testing.assert_array_equal(np.stack(outs), ref)
    # ...and the committed record holds the same views.
    np.testing.assert_array_equal(np.asarray(rec_i[1:4]), ref)


# ---------------------------------------------------------------------------
# Serving: bucket/lane divisibility under a mesh
# ---------------------------------------------------------------------------


def test_lane_count_rounding():
    assert lane_count(0, 8) == 0
    assert lane_count(1, 8) == 1
    assert lane_count(3, 8) == 4
    assert lane_count(5, 8) == 8
    assert lane_count(9, 8) == 8          # clamped at the ceiling
    # Mesh quantum: pow2 first, then up to the multiple.
    assert lane_count(1, 8, 2) == 2
    assert lane_count(3, 8, 2) == 4
    assert lane_count(3, 12, 3) == 6
    assert lane_count(5, 6, 3) == 6


def test_engine_rounds_max_batch_to_lane_multiple(setup):
    cfg, model, params, ds = setup
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        port=0, max_batch=3, max_queue=8, max_views=6))
    sampler = Sampler(model, params, cfg, mesh=_mesh(2))
    service = ServingService(sampler, cfg)
    assert service.engine.lane_multiple == 2
    assert service.engine.max_batch == 4   # 3 rounded up to a multiple
    assert service.health()["lane_multiple"] == 2


def test_sharded_engine_serves_divisible_lanes(setup):
    """End-to-end on a data=2 mesh: a single request launches 2 lanes
    (padded, not a 1-lane recompile), completes, and matches the
    unsharded offline sampler to float tolerance."""
    cfg, model, params, ds = setup
    cfg = dataclasses.replace(cfg, serving=ServingConfig(
        port=0, max_batch=4, max_queue=8, max_wait_ms=100, max_views=6))
    sampler = Sampler(model, params, cfg, mesh=_mesh(2))
    service = ServingService(sampler, cfg).start(serve_http=False)
    try:
        v = ds.all_views(2)
        req = ViewRequest(
            {"imgs": np.asarray(v["imgs"]), "R": np.asarray(v["R"]),
             "T": np.asarray(v["T"]), "K": np.asarray(v["K"])},
            seed=5, n_views=3)
        service.engine.submit(req)
        out = req.result(timeout=120)

        direct = Sampler(model, params, cfg).synthesize(
            v, jax.random.PRNGKey(5), max_views=3)
        np.testing.assert_allclose(out, direct, atol=1e-5, rtol=1e-5)

        stats = service.engine.programs.stats()["programs"]
        assert list(stats) == [f"H8xW8xcap4xlanes2"]
        snap = service.metrics_snapshot()
        assert snap["counters"]["serving_host_upload_bytes_total"] > 0
        assert snap["counters"]["serving_host_fetch_bytes_total"] > 0
    finally:
        service.stop()
