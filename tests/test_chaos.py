"""Chaos suite: injected faults against the real serving engine and the
async checkpoint writer (ISSUE acceptance: zero hung futures, zero lost
requests, the engine returns to ``ok`` once faults stop, and training
resumes from an async checkpoint at the exact preempted step, bit-equal
to the synchronous oracle).

Faults come from :mod:`diff3d_tpu.testing.faults` — deterministic and
seedable, so every schedule here replays exactly.  All device work uses
the tiny shallow config; programs used by timing-sensitive tests are
pre-warmed so a first-use XLA compile can't masquerade as a stuck step.
"""

import dataclasses
import os
import threading
import time

import jax
import numpy as np
import pytest

from diff3d_tpu.config import ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import InfiniteLoader, SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.runtime.retry import RetryPolicy, is_transient_io_error
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.serving import (EngineDraining, EngineStepError,
                                EngineStopTimeout, EngineStopped,
                                ProgramCache, ServingService, ViewRequest)
from diff3d_tpu.testing.faults import (FaultInjected, FaultInjector,
                                       wrap_sampler)
from diff3d_tpu.train import CheckpointManager, Trainer, create_train_state
from diff3d_tpu.train.trainer import init_params

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def chaos_env():
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    ds = SyntheticDataset(num_objects=4, num_views=6, imgsize=8)
    # Pre-compile the programs the watchdog/stop tests launch under tight
    # deadlines (compiles share the sampler's jit cache, so every service
    # built on this sampler reuses them).
    pc = ProgramCache(sampler)
    gb = int(sampler.w.shape[0])
    for bucket, lanes in (((8, 8, 4), 1), ((8, 8, 4), 2), ((8, 8, 8), 1)):
        pc.warmup(bucket, lanes, gb)
    return cfg, sampler, ds


def _views_dict(ds, i):
    v = ds.all_views(i)
    return {"imgs": np.asarray(v["imgs"]), "R": np.asarray(v["R"]),
            "T": np.asarray(v["T"]), "K": np.asarray(v["K"])}


def _mk_request(ds, i, n_views=3, seed=0, timeout_s=None):
    return ViewRequest(_views_dict(ds, i), seed=seed, n_views=n_views,
                       timeout_s=timeout_s)


def _direct(sampler, ds, i, n_views, seed):
    return sampler.synthesize(ds.all_views(i), jax.random.PRNGKey(seed),
                              max_views=n_views)


def make_service(cfg, sampler, injector=None, **over):
    serving = dict(port=0, max_batch=4, max_queue=8, max_wait_ms=20.0,
                   max_views=6, default_timeout_s=60.0,
                   step_retry_backoff_s=0.02, retry_after_s=1.0)
    serving.update(over)
    cfg2 = dataclasses.replace(cfg, serving=ServingConfig(**serving))
    s = (wrap_sampler(sampler, injector) if injector is not None
         else sampler)
    return ServingService(s, cfg2)


def _wait_for(pred, timeout=30.0, poll=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# Serving: step faults, watchdog, drain, stop
# ---------------------------------------------------------------------------


def test_transient_step_fault_retried_transparently(chaos_env):
    """One injected dispatch fault: the engine's internal retry absorbs
    it — the client sees a normal, bit-identical result and health never
    leaves ``ok``."""
    cfg, sampler, ds = chaos_env
    inj = FaultInjector(seed=0)
    inj.add("engine.step", at_calls=(1,))
    svc = make_service(cfg, sampler, inj, step_retry_attempts=2,
                       watchdog_timeout_s=0.0).start(serve_http=False)
    try:
        req = svc.engine.submit(_mk_request(ds, 0, n_views=3, seed=101))
        out = req.result(timeout=120)
        np.testing.assert_array_equal(out, _direct(sampler, ds, 0, 3, 101))
        assert svc.engine.health == "ok"
        snap = svc.metrics_snapshot()
        assert snap["counters"]["serving_engine_step_faults_total"] == 0
        assert snap["counters"]["serving_requests_completed_total"] == 1
        assert inj.fired["engine.step"] == 1
    finally:
        svc.stop()


def test_persistent_faults_degrade_then_recover(chaos_env):
    """Faults outlasting the retry budget: affected requests resolve with
    a typed retryable error (no hung futures), the engine degrades
    (halved batch ceiling, queue soft limit), and once the fault source
    stops it returns to ``ok`` after consecutive clean steps."""
    cfg, sampler, ds = chaos_env
    inj = FaultInjector(seed=0)
    inj.add("engine.step", first_n=4)     # outlasts 2 attempts, twice
    svc = make_service(cfg, sampler, inj, step_retry_attempts=2,
                       watchdog_timeout_s=0.0,
                       degraded_recovery_steps=2).start(serve_http=False)
    try:
        a = svc.engine.submit(_mk_request(ds, 0, n_views=3, seed=201))
        with pytest.raises(EngineStepError) as ei:
            a.result(timeout=30)
        assert ei.value.retry_after_s == 1.0
        assert svc.engine.health == "degraded"
        assert svc.health()["status"] == "degraded"
        assert svc.engine._effective_max_batch() == 2   # halved from 4

        b = svc.engine.submit(_mk_request(ds, 1, n_views=3, seed=202))
        with pytest.raises(EngineStepError):
            b.result(timeout=30)

        # fault budget exhausted: the next request runs clean and its two
        # view steps satisfy degraded_recovery_steps=2
        c = svc.engine.submit(_mk_request(ds, 2, n_views=3, seed=203))
        out = c.result(timeout=120)
        np.testing.assert_array_equal(out, _direct(sampler, ds, 2, 3, 203))
        _wait_for(lambda: svc.engine.health == "ok",
                  what="engine recovery")
        snap = svc.metrics_snapshot()
        assert snap["counters"]["serving_engine_step_faults_total"] == 2
        assert all(r.done() for r in (a, b, c))         # nothing hung
    finally:
        svc.stop()


@pytest.mark.lock_witness
def test_watchdog_rejects_stuck_step(chaos_env, lock_witness):
    """A wedged dispatch (injected 1.5s stall vs a 0.3s watchdog): the
    in-flight requests fail fast with a typed retryable error instead of
    hanging, the trip is counted once, and queued work in other buckets
    still completes after recovery.

    Runs under the lock witness: the whole engine/scheduler/watchdog
    stack is built inside the test body, so every lock it creates is
    order-checked and any held-lock wait on the trip/recovery path
    fails the test."""
    cfg, sampler, ds = chaos_env
    inj = FaultInjector(seed=0)
    inj.add("engine.step", at_calls=(1,), kind="slow", delay_s=1.5)
    svc = make_service(cfg, sampler, inj, watchdog_timeout_s=0.3,
                       step_retry_attempts=1, degraded_recovery_steps=1,
                       max_wait_ms=300.0).start(serve_http=False)
    try:
        # a+b co-batch (same bucket, admitted together inside the 300ms
        # flush window); c waits in a different bucket.
        a = svc.engine.submit(_mk_request(ds, 0, n_views=3, seed=301))
        b = svc.engine.submit(_mk_request(ds, 1, n_views=3, seed=302))
        c = svc.engine.submit(_mk_request(ds, 2, n_views=5, seed=303))

        t0 = time.monotonic()
        with pytest.raises(EngineStepError) as ei:
            a.result(timeout=10)
        # rejected by the watchdog ~0.3s in, NOT after the 1.5s stall
        assert time.monotonic() - t0 < 1.4
        assert ei.value.retry_after_s is not None
        with pytest.raises(EngineStepError):
            b.result(timeout=10)

        out = c.result(timeout=120)
        np.testing.assert_array_equal(out, _direct(sampler, ds, 2, 5, 303))
        _wait_for(lambda: svc.engine.health == "ok",
                  what="post-watchdog recovery")
        snap = svc.metrics_snapshot()
        assert snap["counters"]["serving_engine_watchdog_trips_total"] == 1
        assert all(r.done() for r in (a, b, c))
    finally:
        svc.stop()


def test_drain_mode_blocks_admission_and_finishes_inflight(chaos_env):
    """drain(): health moves to ``draining``, new submissions get a typed
    EngineDraining with Retry-After, and in-flight work runs to
    completion — the clean-rollout contract."""
    cfg, sampler, ds = chaos_env
    svc = make_service(cfg, sampler,
                       watchdog_timeout_s=0.0).start(serve_http=False)
    try:
        a = svc.engine.submit(_mk_request(ds, 3, n_views=6, seed=401))
        _wait_for(lambda: svc.engine._inflight_count() > 0,
                  what="request admission")
        done = {}
        t = threading.Thread(
            target=lambda: done.update(ok=svc.drain(timeout=60)))
        t.start()
        _wait_for(lambda: svc.engine.health == "draining",
                  what="draining state")
        with pytest.raises(EngineDraining) as ei:
            svc.engine.submit(_mk_request(ds, 0, n_views=3, seed=402))
        assert ei.value.retry_after_s == 1.0
        t.join(120)
        assert done.get("ok") is True
        out = a.result(timeout=0)         # already resolved by the drain
        np.testing.assert_array_equal(out, _direct(sampler, ds, 3, 6, 401))
    finally:
        svc.stop()


@pytest.mark.lock_witness
def test_stop_timeout_reports_leaked_worker(chaos_env, lock_witness):
    """stop(timeout) on a wedged worker: raises EngineStopTimeout, bumps
    the leak counter, and resolves in-flight futures with EngineStopped —
    never a silent return with a live thread and hung clients.

    Runs under the lock witness: stop() races the wedged worker's
    drain, exactly where an inverted lock order or a wait under the
    engine lock would deadlock a real shutdown."""
    cfg, sampler, ds = chaos_env
    inj = FaultInjector(seed=0)
    inj.add("engine.step", at_calls=(1,), kind="slow", delay_s=2.5)
    svc = make_service(cfg, sampler, inj, watchdog_timeout_s=0.0,
                       step_retry_attempts=1).start(serve_http=False)
    a = svc.engine.submit(_mk_request(ds, 0, n_views=3, seed=501))
    _wait_for(lambda: inj.calls["engine.step"] >= 1,
              what="dispatch to enter the stall")
    worker = svc.engine._thread
    with pytest.raises(EngineStopTimeout):
        svc.engine.stop(timeout=0.2)
    assert svc.metrics_snapshot()["counters"][
        "serving_engine_stop_timeout_total"] == 1
    with pytest.raises(EngineStopped):
        a.result(timeout=1)
    # the leaked thread does exit once the stall ends (stop flag is set)
    worker.join(60)
    assert not worker.is_alive()


# ---------------------------------------------------------------------------
# Async checkpointing under IO faults
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_state(chaos_env):
    cfg, sampler, _ = chaos_env
    return cfg, create_train_state(sampler.params, cfg.train)


def _abstract(state):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)


def _fast_io_retry(attempts=4):
    return RetryPolicy(max_attempts=attempts, base_delay_s=0.01,
                       max_delay_s=0.02, jitter=0.0,
                       classify=is_transient_io_error,
                       sleep=lambda s: None)


def test_async_checkpoint_bitwise_matches_sync_oracle(tmp_path, tiny_state):
    """The ISSUE pin: the async writer's directory is byte-identical to
    the synchronous path's, and restores bit-equal."""
    cfg, state = tiny_state
    sync = CheckpointManager(str(tmp_path / "sync"), mode="full_sliced")
    asyn = CheckpointManager(str(tmp_path / "async"), mode="full_sliced",
                             async_writes=True)
    assert sync.save(state, force=True)
    assert asyn.save(state, force=True)
    asyn.wait_until_finished()

    sdir, adir = tmp_path / "sync" / "0", tmp_path / "async" / "0"
    assert sorted(os.listdir(sdir)) == sorted(os.listdir(adir))
    for name in sorted(os.listdir(sdir)):
        assert (sdir / name).read_bytes() == (adir / name).read_bytes(), \
            f"{name} differs between sync and async saves"

    ra = asyn.restore(_abstract(state))
    rs = sync.restore(_abstract(state))
    for a, b, orig in zip(jax.tree.leaves(ra), jax.tree.leaves(rs),
                          jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(orig))
    asyn.close()


def test_async_checkpoint_survives_transient_io_faults(tmp_path,
                                                       tiny_state):
    """Injected write + commit faults inside the retry budget: the save
    still lands, durable and bit-equal — the barrier raises nothing."""
    cfg, state = tiny_state
    inj = FaultInjector(seed=0)
    inj.add("write", at_calls=(1,))       # first leaf write fails once
    inj.add("commit", at_calls=(1,))      # first commit attempt fails too
    mgr = CheckpointManager(str(tmp_path / "ckpt"), mode="full_sliced",
                            async_writes=True,
                            write_retry=_fast_io_retry(),
                            fault_hook=inj.fire)
    assert mgr.save(state, force=True)
    mgr.wait_until_finished()             # transient faults: no raise
    assert mgr.latest_step() == 0
    assert inj.fired["write"] == 1 and inj.fired["commit"] == 1
    restored = mgr.restore(_abstract(state))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_async_write_failure_surfaces_at_next_save(tmp_path, tiny_state):
    cfg, state = tiny_state
    inj = FaultInjector(seed=0)
    inj.add("commit", first_n=10 ** 6)    # permanent
    mgr = CheckpointManager(str(tmp_path / "ckpt"), mode="full_sliced",
                            async_writes=True,
                            write_retry=_fast_io_retry(attempts=2),
                            fault_hook=inj.fire)
    assert mgr.save(state, force=True)
    _wait_for(lambda: mgr._async_error is not None,
              what="writer to exhaust its retries")
    with pytest.raises(FaultInjected):
        mgr.save(state, force=True)       # deferred error, not silence
    mgr.close()


def test_async_barrier_surfaces_failure_then_recovers(tmp_path,
                                                      tiny_state):
    """The durability barrier raises a permanent write failure; once the
    fault source clears, re-saving the same step lands normally."""
    cfg, state = tiny_state
    inj = FaultInjector(seed=0)
    inj.add("commit", first_n=10 ** 6)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), mode="full_sliced",
                            async_writes=True,
                            write_retry=_fast_io_retry(attempts=2),
                            fault_hook=inj.fire)
    assert mgr.save(state, force=True)
    with pytest.raises(FaultInjected):
        mgr.wait_until_finished()
    assert mgr.latest_step() is None      # nothing half-published
    inj.clear()
    assert mgr.save(state, force=True)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 0
    mgr.close()


# ---------------------------------------------------------------------------
# Trainer: real SIGTERM -> async checkpoint -> exact resume
# ---------------------------------------------------------------------------


def test_trainer_sigterm_async_checkpoint_exact_resume(tmp_path):
    """End-to-end preemption chaos: a real SIGTERM (injected mid-loop)
    drives the installed handler; the trainer checkpoints the exact
    observed step through the ASYNC writer, waits on the durability
    barrier, and the saved state is bit-equal to a synchronous-oracle
    run preempted at the same step.  Resuming finishes the run."""
    cfg = make_tiny_config(imgsize=8, ch=8, shallow=True)
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, max_steps=6, ckpt_every=100, log_every=0,
        ckpt_mode="full_sliced", ckpt_async=True))
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=8)
    B = cfg.train.global_batch

    inj = FaultInjector(seed=0)
    inj.add("loader", at_calls=(4,), kind="sigterm")

    class SigtermLoader:
        def __init__(self):
            self._it = InfiniteLoader(ds, B, seed=0, num_workers=0)

        def __iter__(self):
            return self

        def __next__(self):
            inj.fire("loader")            # call 4 delivers a real SIGTERM
            return next(self._it)

    tr = Trainer(cfg, SigtermLoader(), workdir=str(tmp_path / "chaos"))
    uninstall = tr.install_preemption_handler()
    try:
        state = tr.train()
    finally:
        uninstall()
    assert tr.preempt_observed_step == 4
    assert int(state.step) == 4
    assert tr.ckpt.latest_step() == 4     # durable before train() returned

    # Synchronous oracle: same run, sync writer, flag raised (not
    # signalled) at the same batch.
    cfg_sync = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, ckpt_async=False))
    box = [None]

    class FlagLoader:
        def __init__(self):
            self.n = 0
            self._it = InfiniteLoader(ds, B, seed=0, num_workers=0)

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == 4:
                box[0]._preempted.set()
            return next(self._it)

    tr2 = Trainer(cfg_sync, FlagLoader(), workdir=str(tmp_path / "oracle"))
    box[0] = tr2
    s2 = tr2.train()
    assert int(s2.step) == 4

    ra = tr.ckpt.restore(tr._abstract_state())
    rs = tr2.ckpt.restore(tr2._abstract_state())
    for a, b in zip(jax.tree.leaves(ra), jax.tree.leaves(rs)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Resume from the async checkpoint at the exact preempted step and
    # finish the run.
    loader3 = InfiniteLoader(ds, B, seed=0, num_workers=0, start_step=4)
    tr3 = Trainer(cfg, loader3, workdir=str(tmp_path / "chaos"),
                  transfer=True)
    assert int(tr3.state.step) == 4
    s3 = tr3.train()
    assert int(s3.step) == 6


# ---------------------------------------------------------------------------
# Soak (opt-in): the chaos_serving tool against a live engine
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_serving_soak_tool(tmp_path):
    """tools/chaos_serving.py survival run: mixed error/slow faults, then
    a clean recovery window — exits 0 only with zero hung/lost requests
    and final health ``ok``."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_serving.py"),
         "--requests", "12", "--fault-rate", "0.3", "--slow-rate", "0.1",
         "--slow-s", "0.4", "--watchdog-s", "2.0", "--seed", "0",
         "--json"],
        capture_output=True, text=True, timeout=1500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["hung"] == 0 and rec["lost"] == 0
    assert rec["final_health"] == "ok"
    assert rec["completed"] + rec["failed_retryable"] == rec["submitted"]
