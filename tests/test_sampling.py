import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.sampling.runtime import to_uint8
from diff3d_tpu.train.trainer import init_params


@pytest.fixture(scope="module")
def setup():
    cfg = make_tiny_config(imgsize=8, ch=8)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    ds = SyntheticDataset(num_objects=2, num_views=4, imgsize=8)
    return cfg, model, params, ds


def test_to_uint8_range():
    img = np.array([[-1.0, 0.0, 1.0]])
    np.testing.assert_array_equal(to_uint8(img), [[0, 127, 255]])
    assert to_uint8(np.array([[-5.0, 5.0]])).tolist() == [[0, 255]]


def test_sampler_synthesize_shapes_and_outputs(setup, tmp_path):
    cfg, model, params, ds = setup
    views = ds.all_views(0)
    sampler = Sampler(model, params, cfg)
    out = sampler.synthesize(views, jax.random.PRNGKey(0),
                             out_dir=str(tmp_path / "sampling"),
                             max_views=3)
    B = len(cfg.diffusion.guidance_weights)
    assert out.shape == (2, B, 8, 8, 3)
    assert np.isfinite(out).all()
    # reference output layout: sampling/{step}/{gt,i}.png
    for step in (1, 2):
        assert os.path.exists(tmp_path / "sampling" / str(step) / "gt.png")
        for i in range(B):
            assert os.path.exists(
                tmp_path / "sampling" / str(step) / f"{i}.png")


def test_sampler_synthesize_many_matches_sequential(setup):
    """The object-batched path must reproduce the sequential path
    per-object when given the same per-object keys (eval_cli relies on
    this to batch objects without changing the scores)."""
    cfg, model, params, ds = setup
    sampler = Sampler(model, params, cfg)
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(3), jax.random.PRNGKey(4)]
    seq = np.stack([sampler.synthesize(v, k, max_views=3)
                    for v, k in zip(views, keys)])
    many = sampler.synthesize_many(views, keys, max_views=3)
    B = len(cfg.diffusion.guidance_weights)
    assert many.shape == (2, 2, B, 8, 8, 3)
    np.testing.assert_allclose(many, seq, atol=1e-5, rtol=1e-5)
    # objects must not leak into each other: object 1 alone == object 1
    # in the batch
    solo = sampler.synthesize_many([views[1]], [keys[1]], max_views=3)
    np.testing.assert_allclose(solo[0], many[1], atol=1e-5, rtol=1e-5)


def test_sampler_autoregressive_record_grows(setup):
    """Later views must condition on generated entries: with 3 views the
    second scan samples cond indices in [0, 2) — exercised by max_views=3
    above; here check determinism given the same rng."""
    cfg, model, params, ds = setup
    views = ds.all_views(1)
    sampler = Sampler(model, params, cfg)
    a = sampler.synthesize(views, jax.random.PRNGKey(7), max_views=2)
    b = sampler.synthesize(views, jax.random.PRNGKey(7), max_views=2)
    np.testing.assert_array_equal(a, b)
    c = sampler.synthesize(views, jax.random.PRNGKey(8), max_views=2)
    assert not np.array_equal(a, c)


def test_sampler_chunked_scan_matches_single(setup):
    """scan_chunks splits the reverse diffusion into several device
    executions; the carried rng makes the result BIT-identical to the
    one-scan path (the property that lets tunnel-deadline-bound setups
    chunk the full-width 128^2 sampler without changing the protocol)."""
    cfg, model, params, ds = setup
    views = ds.all_views(0)
    one = Sampler(model, params, cfg).synthesize(
        views, jax.random.PRNGKey(7), max_views=3)
    # test config has timesteps=4 -> 2 chunks of 2 steps
    chunked = Sampler(model, params, cfg, scan_chunks=2).synthesize(
        views, jax.random.PRNGKey(7), max_views=3)
    np.testing.assert_array_equal(np.asarray(one), np.asarray(chunked))


def test_sampler_chunked_many_matches_single(setup):
    cfg, model, params, ds = setup
    views = [ds.all_views(0), ds.all_views(1)]
    keys = [jax.random.PRNGKey(5), jax.random.PRNGKey(6)]
    one = Sampler(model, params, cfg).synthesize_many(views, keys,
                                                      max_views=3)
    chunked = Sampler(model, params, cfg,
                      scan_chunks=2).synthesize_many(views, keys,
                                                     max_views=3)
    np.testing.assert_array_equal(np.asarray(one), np.asarray(chunked))


def test_sampler_rejects_indivisible_chunks(setup):
    cfg, model, params, _ = setup
    with pytest.raises(ValueError):
        Sampler(model, params, cfg, scan_chunks=3)  # timesteps=4
