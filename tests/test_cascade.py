"""Cascade serving (docs/DESIGN.md §20): truncated schedules, the
draft→refine sampler pair, and the progressive-preview serving e2e.

Four layers, cheapest first:

* **Schedule math** — ``sample_schedule_ts``/``schedule_start_index``
  fire typed :class:`ScheduleError`s naming the valid grid (divisors /
  start points) and stay silent on-grid; plan-grammar round trips.
* **Sampler units** — truncated samplers subtract the skipped steps
  from ``model_calls_per_view``, demand/refuse the draft operand
  symmetrically, and refuse the whole-object ``synthesize`` surface.
* **Bit parity** — the acceptance pin: truncation at stride 1 from
  ``t=1.0`` WITH a draft is bit-identical to the untruncated ancestral
  oracle (the VP prior at t=1 is N(0,1), so the draft is ignored and
  the carried key stream matches draw for draw), witnessed again
  through ``cascade_parity`` as a capped-PSNR refined score.
* **Serving e2e on the CPU mesh** — a 3-view cascade session streams
  every draft event before any refine event, the ``?from=K`` cursor
  walks phase-tagged events gaplessly, refined output is deterministic
  under a pinned seed (and its program carries a committed rngcheck
  stream manifest), and the HBM gate charges cascade phases their own
  pins.
"""

import dataclasses
import json
import os
import time
import urllib.request

import jax
import numpy as np
import pytest

from diff3d_tpu.cascade import (CascadePlan, CascadeRequest, CascadeSampler,
                                PhaseSpec)
from diff3d_tpu.config import MeshConfig, ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.diffusion import (ScheduleError, sample_schedule_ts,
                                  schedule_start_index)
from diff3d_tpu.evaluation import cascade_parity
from diff3d_tpu.evaluation.parity import PSNR_CAP
from diff3d_tpu.models import XUNet
from diff3d_tpu.parallel import make_mesh
from diff3d_tpu.sampling import Sampler
from diff3d_tpu.serving import ServingService
from diff3d_tpu.serving.worker import HbmAdmission, program_for_schedule
from diff3d_tpu.train.trainer import init_params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Schedule math: typed errors fire off-grid, stay silent on-grid
# ---------------------------------------------------------------------------


def test_schedule_divisor_error_names_valid_divisors():
    with pytest.raises(ScheduleError) as ei:
        sample_schedule_ts(3, timesteps=4)
    assert "valid step counts are [1, 2, 4]" in str(ei.value)
    # Silent on a divisor: stride-2 subset of the 4-step dense grid.
    np.testing.assert_allclose(sample_schedule_ts(2, timesteps=4),
                               [1.0, 0.5, 0.0])


def test_start_t_must_be_a_grid_point():
    assert schedule_start_index(4, 1.0, timesteps=4) == 0
    assert schedule_start_index(4, 0.5, timesteps=4) == 2
    for bad in (0.3, 0.0, -0.25, 1.25):
        with pytest.raises(ScheduleError) as ei:
            schedule_start_index(4, bad, timesteps=4)
        assert "[1.0, 0.75, 0.5, 0.25]" in str(ei.value)
    # The truncated grid is the exact tail of the full one.
    np.testing.assert_allclose(
        sample_schedule_ts(2, timesteps=4, start_t=0.5), [0.5, 0.0])
    full = sample_schedule_ts(4, timesteps=4)
    trunc = sample_schedule_ts(4, timesteps=4, start_t=0.5)
    np.testing.assert_array_equal(np.asarray(full)[2:], np.asarray(trunc))


def test_cascade_plan_parse_roundtrip_and_errors():
    spec = "draft=64:ddim:8,refine=128:ancestral:64@t0.4"
    plan = CascadePlan.parse(spec)
    assert plan.spec() == spec
    assert plan.draft == PhaseSpec(64, "ddim", 8)
    assert plan.refine == PhaseSpec(128, "ancestral", 64, start_t=0.4)
    with pytest.raises(ValueError, match="missing"):
        CascadePlan.parse("draft=64:ddim:8")
    with pytest.raises(ValueError, match="must not carry a"):
        CascadePlan.parse("draft=64:ddim:8@t0.5,refine=128:ancestral:64@t0.4")
    with pytest.raises(ValueError, match="needs a start_t"):
        CascadePlan.parse("draft=64:ddim:8,refine=128:ancestral:64")
    with pytest.raises(ValueError, match="must exceed"):
        CascadePlan.parse("draft=128:ddim:8,refine=128:ancestral:64@t0.4")
    with pytest.raises(ValueError, match="expected"):
        CascadePlan.parse("draft=64:ddim,refine=128:ancestral:64@t0.4")


# ---------------------------------------------------------------------------
# Sampler units + the bit-parity acceptance pin
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cascade_env():
    cfg = make_tiny_config(imgsize=16, ch=8, shallow=True)
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    ds = SyntheticDataset(num_objects=2, num_views=3, imgsize=16)
    return cfg, model, params, ds


def test_truncated_sampler_step_count_and_draft_guards(cascade_env):
    cfg, model, params, _ = cascade_env
    trunc = Sampler(model, params, cfg, sampler_kind="ancestral",
                    steps=4, start_t=0.5)
    assert trunc.start_index == 2
    assert trunc.model_calls_per_view == 2       # 4-step grid, tail only
    with pytest.raises(ValueError, match="needs the"):
        trunc.step(np.zeros((3, 8, 16, 16, 3), np.float32),
                   np.zeros((3, 3, 3), np.float32), np.zeros((3, 3)),
                   1, np.eye(3), jax.random.PRNGKey(0))
    plain = Sampler(model, params, cfg, sampler_kind="ancestral", steps=4)
    with pytest.raises(ValueError, match="untruncated"):
        plain.step(np.zeros((3, 8, 16, 16, 3), np.float32),
                   np.zeros((3, 3, 3), np.float32), np.zeros((3, 3)),
                   1, np.eye(3), jax.random.PRNGKey(0),
                   draft=np.zeros((8, 16, 16, 3), np.float32))
    with pytest.raises(ValueError, match="synthesize"):
        trunc.synthesize({"imgs": np.zeros((2, 16, 16, 3), np.float32),
                          "R": np.zeros((2, 3, 3), np.float32),
                          "T": np.zeros((2, 3), np.float32),
                          "K": np.eye(3, dtype=np.float32)},
                         jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="scan_chunks=1"):
        Sampler(model, params, cfg, sampler_kind="ancestral",
                steps=4, start_t=0.5, scan_chunks=2)


def test_truncation_at_t_max_is_bit_identical_to_oracle(cascade_env):
    """The acceptance pin: stride 1 (steps == dense grid) from
    ``start_t=1.0`` WITH a draft reproduces the untruncated ancestral
    oracle bit for bit — the init-noise key is always drawn, and at the
    VP prior the draft term vanishes exactly."""
    cfg, model, params, ds = cascade_env
    views = ds.all_views(0)
    plan = CascadePlan.parse("draft=8:ddim:2,refine=16:ancestral:4@t1")
    cascade = CascadeSampler(model, params, cfg, plan)
    oracle = Sampler(model, params, cfg, sampler_kind="ancestral", steps=4)

    key = jax.random.PRNGKey(7)
    k_draft, k_refine = jax.random.split(key)
    drafts = cascade.synthesize_draft(views, k_draft)
    assert drafts.shape == (2, 8, 8, 8, 3)       # V=2, B=8, 8² draft
    refined = cascade.refine_views(views, drafts, k_refine)
    direct = oracle.synthesize(views, k_refine)
    np.testing.assert_array_equal(refined, np.asarray(direct))

    # The same contract through cascade_parity: refined-vs-oracle PSNR
    # pegs at the cap (bit-identical), draft PSNR is a finite, lower
    # preview score — the side-by-side readout the eval surface reports.
    rec = cascade_parity([drafts], [refined], [np.asarray(direct)])
    assert rec["objects"] == 1
    assert rec["refined"]["psnr"] == PSNR_CAP
    assert 0 < rec["draft"]["psnr"] < rec["refined"]["psnr"]
    assert rec["draft"]["views"] == rec["refined"]["views"] == 2


def test_truncated_refinement_runs_only_the_tail(cascade_env):
    """A genuinely truncated cascade (t=0.5 on a 2-step grid) produces
    full-resolution refined views that depend on the draft."""
    cfg, model, params, ds = cascade_env
    views = ds.all_views(1)
    plan = CascadePlan.parse("draft=8:ddim:2,refine=16:ancestral:2@t0.5")
    cascade = CascadeSampler(model, params, cfg, plan)
    assert cascade.refine.model_calls_per_view == 1
    assert cascade.model_calls_per_view == 3     # 2 draft + 1 refine
    out = cascade.synthesize_cascade(views, jax.random.PRNGKey(3))
    assert out["draft"].shape == (2, 8, 8, 8, 3)
    assert out["refined"].shape == (2, 8, 16, 16, 3)
    # Different drafts (e.g. another draft seed) must change the refined
    # output: the truncated scan is actually consuming its operand.
    other = cascade.refine_views(
        views, np.zeros_like(np.asarray(out["draft"])),
        jax.random.split(jax.random.PRNGKey(3))[1])
    assert not np.array_equal(other, out["refined"])


# ---------------------------------------------------------------------------
# Serving e2e on the CPU mesh: progressive preview, cursor, determinism
# ---------------------------------------------------------------------------


def _serving(cfg, **over):
    serving = dict(port=0, max_batch=4, max_queue=8, max_wait_ms=50.0,
                   max_views=10, default_timeout_s=120.0,
                   result_cache_entries=0)
    serving.update(over)
    return dataclasses.replace(cfg, serving=ServingConfig(**serving))


def _wire_views(views):
    return {k: np.asarray(v).tolist() for k, v in views.items()}


@pytest.mark.lock_witness
def test_cascade_e2e_mesh_preview_cursor_determinism(cascade_env,
                                                     lock_witness):
    """The acceptance run: a 3-view cascade session on a data=2 CPU
    mesh.  Every draft event streams before any refine event, the HTTP
    ``?from=K`` cursor walks phase-tagged events without gaps, refined
    frames replace drafts in place (the terminal result IS the refine
    events), and a second pinned-seed run is bit-identical."""
    cfg, model, params, ds = cascade_env
    env = make_mesh(MeshConfig(data_parallel=2, model_parallel=1),
                    devices=jax.devices()[:2])
    sampler = Sampler(model, params, cfg, mesh=env)
    plan = CascadePlan.parse("draft=8:ddim:2,refine=16:ancestral:2@t0.5")
    cascade = CascadeSampler(model, params, cfg, plan, mesh=env)
    service = ServingService(sampler, _serving(cfg),
                             cascade=cascade).start(serve_http=True)
    try:
        assert service.engine.supports_cascade(plan.spec())
        base = f"http://127.0.0.1:{service.port}"
        views = _wire_views(ds.all_views(0))
        body = json.dumps({"views": views, "seed": 11,
                           "block": False}).encode()
        req = urllib.request.Request(
            f"{base}/cascade", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 202
            head = json.loads(r.read())
        assert head["n_frames"] == 2 and head["n_events"] == 4
        rid = head["id"]

        events, nxt = [], 0
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"{base}/result/{rid}?from={nxt}", timeout=30) as r:
                poll = json.loads(r.read())
            assert poll["from"] == nxt
            assert poll["next"] == nxt + len(poll["events"])
            assert [e["event"] for e in poll["events"]] == list(
                range(nxt, poll["next"]))               # gapless cursor
            events.extend(poll["events"])
            nxt = poll["next"]
            if poll["status"] == "done":
                break
            assert poll["status"] == "running"
            time.sleep(0.05)
        assert nxt == 4 and poll["events_committed"] == 4

        phases = [e["phase"] for e in events]
        # Progressive preview: ALL draft events precede ANY refine event
        # (the refine child only exists once the draft pass resolved).
        assert phases == ["draft", "draft", "refine", "refine"]
        assert [e["frame"] for e in events] == [0, 1, 0, 1]
        for e in events:
            frame = np.asarray(e["view"], np.float32)
            res = 8 if e["phase"] == "draft" else 16
            assert frame.shape == (8, res, res, 3)

        # Refined events replace drafts in place: the terminal result is
        # exactly the refine-phase frames, in frame order.
        with urllib.request.urlopen(f"{base}/result/{rid}",
                                    timeout=30) as r:
            final = json.loads(r.read())
        refined = np.asarray(final["views"], np.float32)
        assert refined.shape == (2, 8, 16, 16, 3)
        for e in events:
            if e["phase"] == "refine":
                np.testing.assert_array_equal(
                    np.asarray(e["view"], np.float32),
                    refined[e["frame"]])

        # Pinned-seed determinism through the direct submit surface:
        # same seed, fresh request -> bit-identical refined output, with
        # first_draft_time stamped before first_refined_time.
        req2 = service.submit_cascade({"views": views, "seed": 11})
        assert isinstance(req2, CascadeRequest)
        sent = 0
        while True:
            got = req2.wait_events(sent, timeout=180)
            if not got:
                break
            sent += len(got)
        np.testing.assert_array_equal(req2.result(timeout=0), refined)
        assert sent == 4
        assert req2.first_draft_time < req2.first_refined_time

        snap = service.metrics_snapshot()
        assert snap["counters"]["serving_cascade_requests_total"] == 2
        assert snap["counters"]["serving_cascade_frames_total"] == 8
        assert service.health()["cascade"] == plan.spec()

        # The determinism witness: the refine program's RNG stream is
        # pinned by a committed rngcheck manifest (tools/lint.py gates
        # on it), so the key lineage the bit-equality above relies on is
        # audited, not incidental.
        manifest = os.path.join(REPO, "runs", "rngcheck",
                                "step_many_cascade_refine.json")
        with open(manifest) as f:
            streams = json.load(f)
        assert streams["program"] == "step_many_cascade_refine"
    finally:
        service.stop()


def test_cascade_rejects_payload_schedules(cascade_env):
    cfg, model, params, ds = cascade_env
    plan = CascadePlan.parse("draft=8:ddim:2,refine=16:ancestral:2@t0.5")
    cascade = CascadeSampler(model, params, cfg, plan)
    sampler = Sampler(model, params, cfg)
    service = ServingService(sampler, _serving(cfg), cascade=cascade)
    views = {k: np.asarray(v) for k, v in ds.all_views(0).items()}
    with pytest.raises(ValueError, match="cascade plan"):
        service.submit_cascade({"views": views, "seed": 0,
                                "sampler_kind": "ddim", "steps": 2})


# ---------------------------------------------------------------------------
# HBM admission: cascade phases charge their own pins
# ---------------------------------------------------------------------------


def test_program_for_schedule_phase_wins_over_kind():
    assert program_for_schedule(None) == "step_many"
    assert program_for_schedule("ancestral") == "step_many"
    assert program_for_schedule("ddim") == "step_many_ddim"
    assert program_for_schedule("ddim", "draft") == "step_many_cascade_draft"
    assert program_for_schedule("ancestral",
                                "refine") == "step_many_cascade_refine"


def test_hbm_admission_loads_committed_cascade_pins():
    adm = HbmAdmission(budget_bytes=1,
                       manifest_dir=os.path.join(REPO, "runs", "memcheck"))
    assert adm.program_peaks["step_many_cascade_draft"] > 0
    assert adm.program_peaks["step_many_cascade_refine"] > 0
    # Pinned phases never take the largest-pin fallback.
    assert (adm.program_peak("ancestral", "refine")
            == adm.program_peaks["step_many_cascade_refine"])


def test_hbm_admission_warns_once_per_unpinned_program(tmp_path, caplog):
    adm = HbmAdmission(budget_bytes=1, manifest_dir=str(tmp_path))
    with caplog.at_level("WARNING", logger="diff3d_tpu.serving.worker"):
        adm.program_peak("ancestral", "draft")
        adm.program_peak("ancestral", "draft")      # second call: silent
        adm.program_peak("ancestral", "refine")
    warnings = [r for r in caplog.records
                if "no committed memcheck manifest pin" in r.getMessage()]
    assert len(warnings) == 2                       # one per program name
    assert "step_many_cascade_draft" in warnings[0].getMessage()
    assert "step_many_cascade_refine" in warnings[1].getMessage()
