import jax.numpy as jnp
import numpy as np

from diff3d_tpu.evaluation import (fid_from_stats, frechet_distance,
                                   gaussian_stats, psnr, ssim)


def test_psnr_identity_and_known_value():
    a = jnp.zeros((2, 8, 8, 3))
    assert float(psnr(a, a)[0]) > 100.0
    # mse = 1, range 2 -> 10 log10(4) ~ 6.02 dB
    b = jnp.ones((2, 8, 8, 3))
    np.testing.assert_allclose(np.asarray(psnr(a, b)), 6.0206, atol=1e-3)


def test_psnr_monotone_in_noise():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-1, 1, (1, 16, 16, 3)), jnp.float32)
    small = float(psnr(a, a + 0.01)[0])
    large = float(psnr(a, a + 0.1)[0])
    assert small > large


def test_ssim_bounds_and_identity():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-1, 1, (2, 16, 16, 3)), jnp.float32)
    s_self = np.asarray(ssim(a, a))
    np.testing.assert_allclose(s_self, 1.0, atol=1e-4)
    noise = jnp.asarray(rng.normal(0, 0.5, a.shape), jnp.float32)
    s_noisy = np.asarray(ssim(a, a + noise))
    assert (s_noisy < s_self).all()
    assert (s_noisy > -1.0 - 1e-6).all()


def test_fid_zero_for_identical_and_positive_for_shifted():
    rng = np.random.default_rng(0)
    imgs = rng.uniform(-1, 1, (64, 8, 8, 3)).astype(np.float32)
    s1 = gaussian_stats([imgs[:32], imgs[32:]])
    s2 = gaussian_stats([imgs[:32], imgs[32:]])
    assert abs(fid_from_stats(s1, s2)) < 1e-6
    shifted = np.clip(imgs + 0.5, -1, 1)
    s3 = gaussian_stats([shifted])
    assert fid_from_stats(s1, s3) > 0.01


def test_frechet_distance_closed_form_1d_like():
    """Two Gaussians with equal cov: FID = |mu1 - mu2|^2."""
    from diff3d_tpu.evaluation.fid import FIDStats

    d = 4
    cov = np.eye(d)
    a = FIDStats(mu=np.zeros(d), cov=cov, n=100)
    b = FIDStats(mu=np.full(d, 2.0), cov=cov, n=100)
    np.testing.assert_allclose(frechet_distance(a, b), d * 4.0, atol=1e-4)
