import jax.numpy as jnp
import numpy as np

from diff3d_tpu.evaluation import (fid_from_stats, frechet_distance,
                                   gaussian_stats, psnr, ssim)


def test_psnr_identity_and_known_value():
    a = jnp.zeros((2, 8, 8, 3))
    assert float(psnr(a, a)[0]) > 100.0
    # mse = 1, range 2 -> 10 log10(4) ~ 6.02 dB
    b = jnp.ones((2, 8, 8, 3))
    np.testing.assert_allclose(np.asarray(psnr(a, b)), 6.0206, atol=1e-3)


def test_psnr_monotone_in_noise():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-1, 1, (1, 16, 16, 3)), jnp.float32)
    small = float(psnr(a, a + 0.01)[0])
    large = float(psnr(a, a + 0.1)[0])
    assert small > large


def test_ssim_bounds_and_identity():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-1, 1, (2, 16, 16, 3)), jnp.float32)
    s_self = np.asarray(ssim(a, a))
    np.testing.assert_allclose(s_self, 1.0, atol=1e-4)
    noise = jnp.asarray(rng.normal(0, 0.5, a.shape), jnp.float32)
    s_noisy = np.asarray(ssim(a, a + noise))
    assert (s_noisy < s_self).all()
    assert (s_noisy > -1.0 - 1e-6).all()


def test_fid_zero_for_identical_and_positive_for_shifted():
    rng = np.random.default_rng(0)
    imgs = rng.uniform(-1, 1, (64, 8, 8, 3)).astype(np.float32)
    s1 = gaussian_stats([imgs[:32], imgs[32:]])
    s2 = gaussian_stats([imgs[:32], imgs[32:]])
    assert abs(fid_from_stats(s1, s2)) < 1e-6
    shifted = np.clip(imgs + 0.5, -1, 1)
    s3 = gaussian_stats([shifted])
    assert fid_from_stats(s1, s3) > 0.01


def test_frechet_distance_closed_form_1d_like():
    """Two Gaussians with equal cov: FID = |mu1 - mu2|^2."""
    from diff3d_tpu.evaluation.fid import FIDStats

    d = 4
    cov = np.eye(d)
    a = FIDStats(mu=np.zeros(d), cov=cov, n=100)
    b = FIDStats(mu=np.full(d, 2.0), cov=cov, n=100)
    np.testing.assert_allclose(frechet_distance(a, b), d * 4.0, atol=1e-4)


def _tiny_vgg_state_dict(rng):
    """Torchvision-shaped VGG with 2 convs (pool after each): input 8x8.

    Index pattern mirrors torchvision ``vgg16``: conv indices gap 3 across
    a pool, trailing pool implicit; ``classifier.0`` fan-in 6*2*2 fixes
    the inferred input at 2 * 2^2 = 8.
    """
    return {
        "features.0.weight": rng.normal(0, 0.2, (4, 3, 3, 3)).astype(
            np.float32),
        "features.0.bias": rng.normal(0, 0.1, (4,)).astype(np.float32),
        "features.3.weight": rng.normal(0, 0.2, (6, 4, 3, 3)).astype(
            np.float32),
        "features.3.bias": rng.normal(0, 0.1, (6,)).astype(np.float32),
        "classifier.0.weight": rng.normal(0, 0.2, (10, 24)).astype(
            np.float32),
        "classifier.0.bias": rng.normal(0, 0.1, (10,)).astype(np.float32),
        "classifier.3.weight": rng.normal(0, 0.2, (7, 10)).astype(
            np.float32),
        "classifier.3.bias": rng.normal(0, 0.1, (7,)).astype(np.float32),
    }


def test_vgg_feature_fn_matches_torch_composed_forward(tmp_path):
    """The jnp VGG extractor == the same net composed from torch
    primitives (conv2d/max_pool2d/linear), weights loaded from .pth."""
    import torch
    import torch.nn.functional as F

    from diff3d_tpu.evaluation.features import (_IMAGENET_MEAN,
                                                _IMAGENET_STD,
                                                vgg16_feature_fn)

    rng = np.random.default_rng(0)
    sd = _tiny_vgg_state_dict(rng)
    path = tmp_path / "vgg_tiny.pth"
    torch.save({k: torch.from_numpy(v) for k, v in sd.items()}, path)

    # Input already at the inferred 8x8 so resize semantics drop out.
    imgs = rng.uniform(-1, 1, (5, 8, 8, 3)).astype(np.float32)
    ours = np.asarray(vgg16_feature_fn(str(path))(jnp.asarray(imgs)))

    x = torch.from_numpy(imgs).permute(0, 3, 1, 2)
    x = (x + 1.0) / 2.0
    x = (x - torch.from_numpy(_IMAGENET_MEAN).view(1, 3, 1, 1)) \
        / torch.from_numpy(_IMAGENET_STD).view(1, 3, 1, 1)
    for i in (0, 3):
        x = F.relu(F.conv2d(x, torch.from_numpy(sd[f"features.{i}.weight"]),
                            torch.from_numpy(sd[f"features.{i}.bias"]),
                            padding=1))
        x = F.max_pool2d(x, 2)
    x = torch.flatten(x, 1)
    for i in (0, 3):
        x = F.relu(F.linear(x,
                            torch.from_numpy(sd[f"classifier.{i}.weight"]),
                            torch.from_numpy(sd[f"classifier.{i}.bias"])))
    theirs = x.numpy()

    assert ours.shape == theirs.shape == (5, 7)
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_vgg_spec_inference_on_real_vgg16_layout():
    """_vgg_spec must recover torchvision vgg16's exact structure: conv
    indices 0,2,5,7,10,12,14,17,19,21,24,26,28; a pool follows convs
    2,7,14,21,28 (each block's last conv); classifier.0 fan-in 512*7*7
    -> input 224."""
    from diff3d_tpu.evaluation.features import _vgg_spec

    widths = [64, 64, 128, 128, 256, 256, 256, 512, 512, 512, 512, 512,
              512]
    idxs = [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28]
    sd = {}
    cin = 3
    for i, w in zip(idxs, widths):
        sd[f"features.{i}.weight"] = np.zeros((w, cin, 3, 3), np.float32)
        sd[f"features.{i}.bias"] = np.zeros((w,), np.float32)
        cin = w
    sd["classifier.0.weight"] = np.zeros((4096, 512 * 7 * 7), np.float32)
    sd["classifier.0.bias"] = np.zeros((4096,), np.float32)
    sd["classifier.3.weight"] = np.zeros((4096, 4096), np.float32)
    sd["classifier.3.bias"] = np.zeros((4096,), np.float32)

    convs, input_hw = _vgg_spec(sd)
    assert input_hw == 224
    pools_after = [i for i, p in convs if p]
    assert pools_after == [2, 7, 14, 21, 28]   # last conv of each block
    assert [i for i, _ in convs] == idxs


def test_resolve_feature_fn_labels_and_npz_roundtrip(tmp_path):
    from diff3d_tpu.evaluation.features import resolve_feature_fn

    # no weights -> random fallback, labeled fid_randfeat
    fn, label = resolve_feature_fn(None)
    assert label == "fid_randfeat"

    sd = _tiny_vgg_state_dict(np.random.default_rng(1))
    path = tmp_path / "vgg_tiny.npz"
    np.savez(path, **sd)
    fn, label = resolve_feature_fn(str(path))
    assert label == "fid"

    # real-feature FID end to end: identical sets -> ~0, shifted -> > 0
    rng = np.random.default_rng(2)
    imgs = rng.uniform(-1, 1, (16, 8, 8, 3)).astype(np.float32)
    s1 = gaussian_stats([imgs], fn)
    s2 = gaussian_stats([np.clip(imgs + 0.5, -1, 1)], fn)
    assert abs(fid_from_stats(s1, s1)) < 1e-6
    assert fid_from_stats(s1, s2) > 0.0

    import pytest

    with pytest.raises(FileNotFoundError):
        resolve_feature_fn(str(tmp_path / "missing.pth"))
