"""Torch-checkpoint converter: reference state dict -> Flax params.

Without the reference's runtime deps (visu3d is absent from this image)
the reference model can't be instantiated here, so the test constructs a
state dict with the reference's exact key scheme and shapes (derived from
``/root/reference/xunet.py`` constructors, documented in
``diff3d_tpu/convert/torch_ckpt.py``) by INVERTING the converter's layout
rules, then checks that conversion reproduces the Flax init tree exactly —
structure, shapes, and values."""

import dataclasses

import jax
import numpy as np
import pytest

from diff3d_tpu.config import ModelConfig
from diff3d_tpu.convert import convert_state_dict
from diff3d_tpu.models import XUNet


def tiny_cfg():
    return ModelConfig(H=16, W=16, ch=8, ch_mult=(1, 2, 2, 4), emb_ch=32,
                       num_res_blocks=2, attn_levels=(2, 3, 4),
                       attn_heads=2, dropout=0.0, dtype="float32")


def _init_params(cfg):
    import jax.numpy as jnp

    model = XUNet(cfg)
    B = 1
    batch = {
        "x": jnp.zeros((B, cfg.H, cfg.W, 3)),
        "z": jnp.zeros((B, cfg.H, cfg.W, 3)),
        "logsnr": jnp.zeros((B, 2)),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.zeros((B, 2, 3)),
        "K": jnp.broadcast_to(jnp.eye(3), (B, 3, 3)),
    }
    return model.init(jax.random.PRNGKey(0), batch,
                      cond_mask=jnp.ones((B,), bool))["params"]


def _randomize(tree, rng):
    return jax.tree.map(
        lambda x: np.asarray(rng.standard_normal(x.shape), np.float32), tree)


def _invert(flax_tree, cfg):
    """Flax params -> reference-style torch state dict (inverse layouts)."""
    sd = {}

    def linear(tkey, p):
        sd[f"{tkey}.weight"] = np.ascontiguousarray(p["kernel"].T)
        sd[f"{tkey}.bias"] = p["bias"]

    def conv(tkey, p):
        sd[f"{tkey}.weight"] = np.ascontiguousarray(
            p["kernel"].transpose(3, 2, 0, 1))
        sd[f"{tkey}.bias"] = p["bias"]

    def gn(tkey, p):
        sd[f"{tkey}.gn.weight"] = p["GroupNorm_0"]["scale"]
        sd[f"{tkey}.gn.bias"] = p["GroupNorm_0"]["bias"]

    def attn_layer(tkey, p):
        w = np.concatenate([p[n]["kernel"].T
                            for n in ("q_proj", "k_proj", "v_proj")], 0)
        b = np.concatenate([p[n]["bias"]
                            for n in ("q_proj", "k_proj", "v_proj")], 0)
        sd[f"{tkey}.attn.in_proj_weight"] = np.ascontiguousarray(w)
        sd[f"{tkey}.attn.in_proj_bias"] = b
        linear(f"{tkey}.attn.out_proj", p["out_proj"])

    def resnet(tkey, p):
        gn(f"{tkey}.groupnorm0", p["FrameGroupNorm_0"])
        gn(f"{tkey}.groupnorm1", p["FrameGroupNorm_1"])
        conv(f"{tkey}.conv1", p["conv1"])
        conv(f"{tkey}.conv2", p["conv2"])
        linear(f"{tkey}.film.dense", p["FiLM_0"]["Dense_0"])
        if "skip_proj" in p:
            conv(f"{tkey}.dense", p["skip_proj"])

    def attn_block(tkey, p):
        gn(f"{tkey}.groupnorm", p["FrameGroupNorm_0"])
        attn_layer(f"{tkey}.attn_layer", p["attn"])
        conv(f"{tkey}.linear", p["out_conv"])

    def xblock(tkey, p):
        resnet(f"{tkey}.resnetblock", p["resnetblock"])
        if "attnblock_self" in p:
            attn_block(f"{tkey}.attnblock_self", p["attnblock_self"])
            attn_block(f"{tkey}.attnblock_cross", p["attnblock_cross"])

    cp = flax_tree["conditioningprocessor"]
    linear("conditioningprocessor.logsnr_emb_emb.0", cp["Dense_0"])
    linear("conditioningprocessor.logsnr_emb_emb.2", cp["Dense_1"])
    sd["conditioningprocessor.pos_emb"] = np.ascontiguousarray(
        cp["pos_emb"].transpose(2, 0, 1))
    for k in ("first_emb", "other_emb"):
        sd[f"conditioningprocessor.{k}"] = np.ascontiguousarray(
            cp[k].transpose(0, 1, 4, 2, 3))
    for i in range(cfg.num_resolutions):
        conv(f"conditioningprocessor.convs.{i}", cp[f"level_conv_{i}"])

    conv("conv", flax_tree["stem_conv"])
    for lvl in range(cfg.num_resolutions):
        for blk in range(cfg.num_res_blocks):
            xblock(f"xunetblocks.{lvl}.{blk}",
                   flax_tree[f"down_{lvl}_{blk}"])
        if lvl != cfg.num_resolutions - 1:
            resnet(f"xunetblocks.{lvl}.{cfg.num_res_blocks}",
                   flax_tree[f"down_{lvl}_downsample"])
    xblock("middle", flax_tree["middle"])
    for lvl in range(cfg.num_resolutions):
        for blk in range(cfg.num_res_blocks + 1):
            xblock(f"upsample.{lvl}.{blk}", flax_tree[f"up_{lvl}_{blk}"])
        if lvl != 0:
            resnet(f"upsample.{lvl}.{cfg.num_res_blocks + 1}",
                   flax_tree[f"up_{lvl}_upsample"])
    gn("lastgn", flax_tree["last_gn"])
    conv("lastconv", flax_tree["last_conv"])
    return sd


@pytest.fixture(scope="module")
def cfg_and_params():
    cfg = tiny_cfg()
    params = _randomize(_init_params(cfg), np.random.default_rng(0))
    return cfg, params


def test_roundtrip_exact(cfg_and_params):
    cfg, params = cfg_and_params
    sd = _invert(jax.tree.map(np.asarray, params), cfg)
    converted = convert_state_dict(sd, cfg)

    flat_a = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    flat_b = dict(jax.tree_util.tree_flatten_with_path(converted)[0])
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(np.asarray(flat_a[k]),
                                      np.asarray(flat_b[k]), err_msg=str(k))


def test_converted_params_run_forward(cfg_and_params):
    import jax.numpy as jnp

    cfg, params = cfg_and_params
    sd = _invert(jax.tree.map(np.asarray, params), cfg)
    sd = {f"module.{k}": v for k, v in sd.items()}   # DataParallel prefix
    converted = convert_state_dict(sd, cfg)

    model = XUNet(cfg)
    B = 2
    rng = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rng.randn(B, 16, 16, 3), jnp.float32),
        "z": jnp.asarray(rng.randn(B, 16, 16, 3), jnp.float32),
        "logsnr": jnp.asarray(np.stack([np.full(B, 20.0),
                                        rng.uniform(-20, 20, B)], 1)),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.asarray(rng.randn(B, 2, 3), jnp.float32),
        "K": jnp.broadcast_to(jnp.eye(3), (B, 3, 3)),
    }
    out = model.apply({"params": converted}, batch,
                      cond_mask=jnp.ones((B,), bool))
    assert out.shape == (B, 16, 16, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_torch_tensor_inputs(cfg_and_params):
    torch = pytest.importorskip("torch")
    cfg, params = cfg_and_params
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in _invert(jax.tree.map(np.asarray, params), cfg).items()}
    converted = convert_state_dict(sd, cfg)
    np.testing.assert_array_equal(
        np.asarray(converted["stem_conv"]["bias"]),
        np.asarray(params["stem_conv"]["bias"]))


# Tier-1 budget: CLI integration wrapper; the weight-mapping
# invertibility it depends on is pinned by test_roundtrip_exact, and
# manager-level orbax save/restore by test_checkpoint_roundtrip.
@pytest.mark.slow
def test_convert_cli_roundtrip_to_orbax(tmp_path, cfg_and_params):
    """.pt -> convert_cli -> Orbax -> sample-able params."""
    torch = pytest.importorskip("torch")
    cfg, params = cfg_and_params
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in _invert(jax.tree.map(np.asarray, params), cfg).items()}
    pt = tmp_path / "latest.pt"
    torch.save({"model": sd, "step": 123}, pt)

    import dataclasses

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.cli import convert_cli
    from diff3d_tpu.train import CheckpointManager, create_train_state

    # route the CLI's 'test' preset onto this test's model config
    test_cfg = dataclasses.replace(config_lib.test_config(), model=tiny_cfg())
    orig = config_lib.test_config
    config_lib.test_config = lambda *a, **k: test_cfg
    try:
        convert_cli.main(["--torch_ckpt", str(pt),
                          "--out", str(tmp_path / "ckpt"),
                          "--config", "test"])
    finally:
        config_lib.test_config = orig

    state = create_train_state(_init_params(cfg), test_cfg.train)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    restored = mgr.restore(abstract)
    assert int(restored.step) == 123
    np.testing.assert_allclose(
        np.asarray(restored.params["stem_conv"]["bias"]),
        np.asarray(params["stem_conv"]["bias"]), atol=1e-7)
    mgr.close()


def test_advance_schedule_skips_warmup():
    """A converted late-step checkpoint must not re-run lr warmup: the
    schedule position lives in optax's count, not TrainState.step."""
    import jax.numpy as jnp
    import optax

    from diff3d_tpu.config import TrainConfig
    from diff3d_tpu.train.state import (advance_schedule, make_optimizer,
                                        warmup_schedule)

    cfg = TrainConfig(lr=1e-4, warmup_examples=1000, global_batch=10)
    tx = make_optimizer(cfg)
    params = {"w": jnp.ones((4,))}
    opt_state = advance_schedule(tx.init(params), step=1000)  # past warmup
    grads = {"w": jnp.ones((4,))}
    _, new_state = tx.update(grads, opt_state, params)
    # the schedule count advanced from 1000, not 0
    sched_states = [s for s in new_state
                    if isinstance(s, optax.ScaleByScheduleState)]
    assert sched_states and int(sched_states[0].count) == 1001
    # and a fresh (unadvanced) state would have applied warmup lr instead
    np.testing.assert_allclose(float(warmup_schedule(cfg)(1000)), cfg.lr,
                               rtol=1e-5)
    assert float(warmup_schedule(cfg)(0)) < cfg.lr / 10


def test_convert_cli_rejects_config_mismatch(tmp_path, cfg_and_params):
    """A .pt converted under the wrong --config must fail fast at convert
    time, not at restore time."""
    torch = pytest.importorskip("torch")
    cfg, params = cfg_and_params
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in _invert(jax.tree.map(np.asarray, params), cfg).items()}
    pt = tmp_path / "latest.pt"
    torch.save({"model": sd, "step": 1}, pt)

    import dataclasses

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.cli import convert_cli

    # 'test' preset with a DIFFERENT model shape than the .pt was built for
    wrong = dataclasses.replace(
        config_lib.test_config(),
        model=dataclasses.replace(tiny_cfg(), ch=16))
    orig = config_lib.test_config
    config_lib.test_config = lambda *a, **k: wrong
    try:
        with pytest.raises(SystemExit, match="does not match"):
            convert_cli.main(["--torch_ckpt", str(pt),
                              "--out", str(tmp_path / "ckpt"),
                              "--config", "test"])
    finally:
        config_lib.test_config = orig


def test_expected_torch_state_matches_torch_oracle():
    """expected_torch_state's reconstructed key set must equal the REAL
    state_dict of the torch-composed reference model (tests/_torch_xunet),
    keys and shapes both — so convert_cli --verify is checking published
    checkpoints against the same scheme the parity oracle implements."""
    torch = pytest.importorskip("torch")
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from _torch_xunet import TXUNet

    from diff3d_tpu.config import test_config
    from diff3d_tpu.convert import expected_torch_state

    cfg = test_config(imgsize=16, ch=8).model
    sd = {k: tuple(v.shape) for k, v in TXUNet(cfg).state_dict().items()}
    want = expected_torch_state(cfg)
    assert sd.keys() == want.keys(), (
        sorted(sd.keys() - want.keys()), sorted(want.keys() - sd.keys()))
    bad = {k: (sd[k], want[k]) for k in sd if sd[k] != want[k]}
    assert not bad, bad


def test_verify_state_dict_reports_corruption(cfg_and_params):
    """A deliberately-corrupted checkpoint yields a complete report:
    every missing, extra, and shape-mismatched key is named."""
    from diff3d_tpu.convert import verify_state_dict

    cfg, params = cfg_and_params
    sd = _invert(jax.tree.map(np.asarray, params), cfg)

    clean = verify_state_dict(sd, cfg)
    assert clean == {"missing": [], "extra": [], "shape_mismatch": []}
    # module. prefix (DataParallel) is stripped before comparison
    assert verify_state_dict(
        {f"module.{k}": v for k, v in sd.items()}, cfg) == clean

    bad = dict(sd)
    del bad["lastconv.bias"]                              # missing
    bad["totally.bogus.weight"] = np.zeros((3, 3))        # extra
    bad["conv.weight"] = bad["conv.weight"][..., :1]      # shape mismatch
    report = verify_state_dict(bad, cfg)
    assert report["missing"] == ["lastconv.bias"]
    assert report["extra"] == ["totally.bogus.weight"]
    assert [k for k, *_ in report["shape_mismatch"]] == ["conv.weight"]


def test_convert_cli_verify_dry_run(tmp_path, cfg_and_params):
    """--verify on a corrupted .pt exits non-zero with the report and
    writes nothing; on a clean .pt it exits cleanly and writes nothing."""
    torch = pytest.importorskip("torch")
    cfg, params = cfg_and_params
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in _invert(jax.tree.map(np.asarray, params), cfg).items()}

    import dataclasses

    from diff3d_tpu import config as config_lib
    from diff3d_tpu.cli import convert_cli

    patched = dataclasses.replace(config_lib.test_config(), model=cfg)
    orig = config_lib.test_config
    config_lib.test_config = lambda *a, **k: patched
    try:
        pt = tmp_path / "clean.pt"
        torch.save({"model": sd, "step": 1}, pt)
        out = tmp_path / "ckpt"
        convert_cli.main(["--torch_ckpt", str(pt), "--out", str(out),
                          "--config", "test", "--verify"])
        assert not out.exists()

        bad = dict(sd)
        del bad["lastconv.bias"]
        pt2 = tmp_path / "bad.pt"
        torch.save({"model": bad, "step": 1}, pt2)
        with pytest.raises(SystemExit, match="1 missing"):
            convert_cli.main(["--torch_ckpt", str(pt2), "--out", str(out),
                              "--config", "test", "--verify"])
        assert not out.exists()
    finally:
        config_lib.test_config = orig


def test_progressive_resolution_transfer():
    """64->128-style transfer at toy scale: every param copies except
    pos_emb (bilinearly upsampled); the adapted tree initializes the
    higher-resolution model and its forward runs."""
    import jax.numpy as jnp

    from diff3d_tpu.convert.progressive import (adapt_params_resolution,
                                                check_resolution_compatible)

    cfg_lo = tiny_cfg()                                   # 16x16
    hi = dataclasses.replace(cfg_lo, H=32, W=32)
    params_lo = _randomize(_init_params(cfg_lo), np.random.default_rng(0))

    adapted = adapt_params_resolution(params_lo, (32, 32))
    params_hi = _init_params(hi)
    check_resolution_compatible(adapted, params_hi)       # no raise

    pe_lo = params_lo["conditioningprocessor"]["pos_emb"]
    pe_hi = adapted["conditioningprocessor"]["pos_emb"]
    assert pe_hi.shape == (32, 32, pe_lo.shape[2])
    # bilinear: corners track the source corners, mean is preserved-ish
    np.testing.assert_allclose(np.asarray(pe_hi).mean(),
                               np.asarray(pe_lo).mean(), atol=0.02)
    # non-pos_emb leaves are copied verbatim
    np.testing.assert_array_equal(
        np.asarray(adapted["stem_conv"]["kernel"]),
        np.asarray(params_lo["stem_conv"]["kernel"]))

    model = XUNet(hi)
    B = 1
    batch = {
        "x": jnp.zeros((B, 32, 32, 3)), "z": jnp.zeros((B, 32, 32, 3)),
        "logsnr": jnp.zeros((B, 2)),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.zeros((B, 2, 3)),
        "K": jnp.broadcast_to(jnp.eye(3) * 16.0, (B, 3, 3)),
    }
    out = model.apply({"params": adapted}, batch,
                      cond_mask=jnp.ones((B,), bool))
    assert out.shape == (B, 32, 32, 3)
    assert bool(jnp.isfinite(out).all())

    # width mismatch is refused with a named leaf
    wrong = dataclasses.replace(hi, ch=16)
    with pytest.raises(ValueError, match="shape mismatch|tree mismatch"):
        check_resolution_compatible(adapted, _init_params(wrong))
