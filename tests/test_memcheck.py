"""memcheck (the HLO-level memory/recompute analyzer), tested from both
sides like the other pillars: for every detector a fixture that must
FIRE and a fixture that must stay SILENT — on synthetic StableHLO/HLO
text for the parsers and the while-loop invariance pass, and on real
lowered programs for the end-to-end path.  Then the two seeded
regressions the issue demands (a requested donation that silently
copies, an injected loop-invariant matmul in a scan body), the manifest
round-trip + MC405 + suppression grammar, the ``memory_budget`` marker
(incl. vacuous-pass protection, via an in-process sub-pytest), and the
repo-clean gate: the committed manifests under ``runs/memcheck/`` for
the tier-1 programs must match what the current tree compiles.
"""

import dataclasses
import json
import os
import textwrap
import types
import warnings

import jax
import jax.numpy as jnp
import pytest

from diff3d_tpu.analysis import mem
from diff3d_tpu.analysis import membudgets as mb
from diff3d_tpu.analysis import memcheck as mc
from diff3d_tpu.analysis import shardcheck as sc
from diff3d_tpu.analysis.membudgets import (MemBudget, Suppression,
                                            check_report,
                                            check_report_against_dir,
                                            load_manifest,
                                            manifest_from_report,
                                            manifest_path, write_manifest)
from diff3d_tpu.analysis.pytest_plugin import MemCheck

pytest_plugins = ["pytester"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _mem_report(**kw):
    base = dict(name="prog")
    base.update(kw)
    return mem.MemoryReport(**base)


def _donation(idx, requested=True, lowered=True, effective=True, **kw):
    base = dict(arg_index=idx, type="8x8xf32", bytes=256,
                requested=requested, lowered=lowered, effective=effective,
                output_index=0 if effective else None)
    base.update(kw)
    return mem.DonationEntry(**base)


def _live(findings, rule=None):
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ---------------------------------------------------------------------------
# Parsers on synthetic StableHLO / HLO text
# ---------------------------------------------------------------------------


def test_tensor_numel_dtype_and_bytes():
    assert mem._tensor_numel_dtype("8x4x8xf32") == (256, "f32")
    assert mem._tensor_numel_dtype("i32") == (1, "i32")
    assert mem._tensor_bytes("4x4xbf16") == 32
    assert mem._tensor_bytes("f64") == 8


_SHLO_DONATE = textwrap.dedent("""\
    module @jit_f attributes {mhlo.num_partitions = 1 : i32} {
      func.func public @main(%arg0: tensor<8x8xf32> {tf.aliasing_output = 0 : i32}, %arg1: tensor<8x8xf32>, %arg2: tensor<4xf32> {jax.buffer_donor = true}) -> (tensor<8x8xf32>, tensor<8x8xf32>) {
        %0 = stablehlo.add %arg0, %arg1 : tensor<8x8xf32>
        %1 = stablehlo.multiply %arg1, %arg1 : tensor<8x8xf32>
        return %0, %1 : tensor<8x8xf32>, tensor<8x8xf32>
      }
    }
""")

_HLO_ALIASED = ("HloModule jit_f, is_scheduled=true, "
                "input_output_alias={ {0}: (0, {}, may-alias) }, "
                "entry_computation_layout={(f32[8,8]{1,0})->f32[8,8]{1,0}}"
                "\n\nENTRY %main { ROOT %x = f32[] parameter(0) }\n")


def test_parse_arg_donations_attrs():
    attrs = mem.parse_arg_donations(_SHLO_DONATE)
    assert attrs[0]["aliasing_output"] == 0
    assert not attrs[0]["buffer_donor"]
    assert attrs[1]["aliasing_output"] is None
    assert attrs[2]["buffer_donor"]
    assert attrs[0]["type"] == "8x8xf32"


def test_parse_input_output_aliases_fire_and_silent():
    (a,) = mem.parse_input_output_aliases(_HLO_ALIASED)
    assert a == {"output_index": 0, "param": 0, "kind": "may-alias"}
    clean = _HLO_ALIASED.replace(
        "input_output_alias={ {0}: (0, {}, may-alias) }, ", "")
    assert mem.parse_input_output_aliases(clean) == []


def test_donation_table_joins_three_sources():
    attrs = mem.parse_arg_donations(_SHLO_DONATE)
    aliases = mem.parse_input_output_aliases(_HLO_ALIASED)
    table = mem.donation_table([True, False, True], attrs, aliases)
    by_idx = {d.arg_index: d for d in table}
    # arg0: requested, lowered, XLA committed the alias.
    assert by_idx[0].requested and by_idx[0].lowered
    assert by_idx[0].effective and by_idx[0].output_index == 0
    # arg2: requested + donor-marked, but XLA never aliased it.
    assert by_idx[2].requested and by_idx[2].lowered
    assert not by_idx[2].effective
    # arg1: never part of the donation story.
    assert 1 not in by_idx
    # No mhlo.sharding annotations: global == per-device bytes.
    assert by_idx[0].shard_count == 1 and by_idx[0].bytes == 256


def test_shard_count_parses_hlo_sharding_annotations():
    # Fire: tiled shardings divide.
    assert mem._shard_count("{devices=[8,1,1,1,1,1]<=[8]}") == 8
    assert mem._shard_count("{devices=[2,2,2]0,1,2,3,4,5,6,7}") == 8
    # Trailing replicate / subgroup dims do not tile.
    assert mem._shard_count(
        "{devices=[2,1,4]<=[8] last_tile_dim_replicate}") == 2
    assert mem._shard_count(
        "{devices=[2,2,2]<=[8] last_tile_dims={manual, replicated}}") == 2
    # Silent: replicated / maximal / absent keep the full tensor.
    assert mem._shard_count(None) == 1
    assert mem._shard_count("{replicated}") == 1
    assert mem._shard_count("{maximal device=3}") == 1


def test_donation_bytes_are_per_device_on_sharded_args():
    """The StableHLO @main type is the GLOBAL shape while
    memory_analysis() accounts per-device bytes; the donation table
    must divide by the mhlo.sharding shard count or the alias discount
    (and the pinned peak) is off by the mesh size on sharded programs —
    the unit-mixing regression this PR's review caught."""
    sharded = _SHLO_DONATE.replace(
        '%arg0: tensor<8x8xf32> {tf.aliasing_output = 0 : i32}',
        '%arg0: tensor<8x8xf32> {mhlo.sharding = "{devices=[8,1]<=[8]}",'
        ' tf.aliasing_output = 0 : i32}')
    attrs = mem.parse_arg_donations(sharded)
    assert attrs[0]["sharding"] == "{devices=[8,1]<=[8]}"
    table = mem.donation_table(
        [True, False, False], attrs,
        mem.parse_input_output_aliases(_HLO_ALIASED))
    by_idx = {d.arg_index: d for d in table}
    assert by_idx[0].shard_count == 8
    assert by_idx[0].bytes == 256 // 8          # per-device, not global
    # Silent: a replicated arg keeps its full size.
    replicated = _SHLO_DONATE.replace(
        '%arg0: tensor<8x8xf32> {tf.aliasing_output = 0 : i32}',
        '%arg0: tensor<8x8xf32> {mhlo.sharding = "{replicated}",'
        ' tf.aliasing_output = 0 : i32}')
    table = mem.donation_table(
        [True, False, False], mem.parse_arg_donations(replicated),
        mem.parse_input_output_aliases(_HLO_ALIASED))
    (d0,) = [d for d in table if d.arg_index == 0]
    assert d0.shard_count == 1 and d0.bytes == 256


# ---------------------------------------------------------------------------
# The while-loop invariance pass on synthetic StableHLO (the exact
# pretty-printed shape jax 0.4.x emits for a lax.scan whose body is
# outlined into a private callee)
# ---------------------------------------------------------------------------

_SHLO_SCAN = textwrap.dedent("""\
    module @jit_h attributes {mhlo.num_partitions = 1 : i32} {
      func.func public @main(%arg0: tensor<4x4xf32>, %arg1: tensor<10x4x4xf32>) -> (tensor<f32> {jax.result_info = ""}) {
        %cst = stablehlo.constant dense<0.000000e+00> : tensor<f32>
        %c = stablehlo.constant dense<0> : tensor<i32>
        %0:4 = stablehlo.while(%iterArg = %arg1, %iterArg_0 = %arg0, %iterArg_1 = %c, %iterArg_2 = %cst) : tensor<10x4x4xf32>, tensor<4x4xf32>, tensor<i32>, tensor<f32>
         cond {
          %c_3 = stablehlo.constant dense<10> : tensor<i32>
          %1 = stablehlo.compare  LT, %iterArg_1, %c_3,  SIGNED : (tensor<i32>, tensor<i32>) -> tensor<i1>
          stablehlo.return %1 : tensor<i1>
        } do {
          %c_5 = stablehlo.constant dense<0> : tensor<i32>
          %5 = stablehlo.dynamic_slice %iterArg, %iterArg_1, %c_5, %c_5, sizes = [1, 4, 4] : (tensor<10x4x4xf32>, tensor<i32>, tensor<i32>, tensor<i32>) -> tensor<1x4x4xf32>
          %6 = stablehlo.reshape %5 : (tensor<1x4x4xf32>) -> tensor<4x4xf32>
          %7 = func.call @None(%iterArg_0, %iterArg_2, %6) : (tensor<4x4xf32>, tensor<f32>, tensor<4x4xf32>) -> tensor<f32>
          %c_6 = stablehlo.constant dense<1> : tensor<i32>
          %8 = stablehlo.add %iterArg_1, %c_6 : tensor<i32>
          stablehlo.return %iterArg, %iterArg_0, %8, %7 : tensor<10x4x4xf32>, tensor<4x4xf32>, tensor<i32>, tensor<f32>
        }
        return %0#3 : tensor<f32>
      }
      func.func private @None(%arg0: tensor<4x4xf32>, %arg1: tensor<f32>, %arg2: tensor<4x4xf32>) -> tensor<f32> {
        %0 = stablehlo.dot_general %arg0, %arg0, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<4x4xf32>, tensor<4x4xf32>) -> tensor<4x4xf32>
        %1 = stablehlo.tanh %0 : tensor<4x4xf32>
        %2 = stablehlo.multiply %arg2, %1 : tensor<4x4xf32>
        %cst = stablehlo.constant dense<0.000000e+00> : tensor<f32>
        %3 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<4x4xf32>, tensor<f32>) -> tensor<f32>
        %4 = stablehlo.convert %arg1 : tensor<f32>
        %5 = stablehlo.add %4, %3 : tensor<f32>
        return %5 : tensor<f32>
      }
    }
""")


def test_scan_invariance_fires_on_invariant_matmul():
    (loop,) = mem.analyze_scan_invariants(_SHLO_SCAN)
    assert loop.trip_count == 10
    # The dot_general contracts the invariant %arg0 with itself:
    # 2 * 16 * 4 = 128 FLOPs, plus tanh's 16 — both hoistable.
    assert loop.invariant_flops == 128 + 16
    assert loop.hoistable_flops_total == (128 + 16) * 10
    # The tanh result (64 bytes) is the invariant frontier consumed by
    # the variant multiply (plus a few scalar loop constants).
    assert 64 <= loop.invariant_bytes < 128
    assert loop.total_flops > loop.invariant_flops
    tops = [t["op"] for t in loop.top_invariant]
    assert tops[0] == "dot_general"


def test_scan_invariance_silent_when_body_is_all_variant():
    # Same loop, but the callee contracts the VARIANT %arg2 instead of
    # the invariant %arg0 — nothing in the body is hoistable.
    variant = _SHLO_SCAN.replace(
        "stablehlo.dot_general %arg0, %arg0,",
        "stablehlo.dot_general %arg2, %arg2,").replace(
        "%2 = stablehlo.multiply %arg2, %1",
        "%2 = stablehlo.multiply %1, %1")
    (loop,) = mem.analyze_scan_invariants(variant)
    assert loop.invariant_flops == 0
    # Only scalar loop constants remain on the invariant frontier.
    assert loop.invariant_bytes < 64


def test_scan_invariance_no_loops_in_plain_module():
    assert mem.analyze_scan_invariants(_SHLO_DONATE) == []


# ---------------------------------------------------------------------------
# Live lowered programs: donation + scan analysis end to end
# ---------------------------------------------------------------------------


def test_live_donation_effective():
    def f(x, y):
        return x + y, y * 2.0

    lowered = jax.jit(f, donate_argnums=(0,)).lower(
        _sds((8, 8)), _sds((8, 8)))
    rep = mem.analyze_lowered_memory("donate_ok", lowered)
    (d,) = rep.donations
    assert d.requested and d.lowered and d.effective
    assert rep.ineffective_donations == []
    assert rep.available and rep.peak_bytes > 0


def test_live_donation_ineffective_fires():
    # No output matches the donated (16,16) buffer: jax warns and drops
    # the pairing — exactly the silent copy MC402 exists for.
    def g(x, y):
        return jnp.sum(x) + jnp.sum(y)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        lowered = jax.jit(g, donate_argnums=(0,)).lower(
            _sds((16, 16)), _sds((4,)))
    rep = mem.analyze_lowered_memory("donate_bad", lowered)
    assert rep.ineffective_donations == [0]
    (d,) = rep.donations
    assert d.requested and not d.lowered and not d.effective


def test_live_scan_invariant_branch_quantified():
    def h(c, xs):
        def body(carry, x):
            inv = jnp.tanh(c @ c)        # loop-invariant conditioning
            return carry + jnp.sum(x * inv), jnp.sum(x)
        s, ys = jax.lax.scan(body, 0.0, xs)
        return s, ys

    rep = mem.analyze_lowered_memory(
        "scan_live", jax.jit(h).lower(_sds((32, 32)), _sds((10, 32, 32))))
    (loop,) = rep.scan_loops
    assert loop.trip_count == 10
    # The invariant matmul alone is 2*32^3 = 65536 FLOPs/step.
    assert loop.invariant_flops >= 2 * 32 ** 3
    assert rep.hoistable_flops_total >= 10 * 2 * 32 ** 3
    assert loop.total_flops > loop.invariant_flops


# ---------------------------------------------------------------------------
# Budget checking on synthetic reports (each MC rule, fire + silent)
# ---------------------------------------------------------------------------


def test_mc401_peak_over_budget():
    good = _mem_report(argument_bytes=100, temp_bytes=50)
    m = manifest_from_report(good)
    assert not _live(check_report(good, m, "m.json"))
    fat = _mem_report(argument_bytes=100, temp_bytes=51)
    (f,) = _live(check_report(fat, m, "m.json"), "MC401")
    assert "peak HBM" in f.message and "+1" in f.message


def test_mc402_requested_but_ineffective_names_the_stage():
    ok = _mem_report(donations=[_donation(0)])
    m = manifest_from_report(ok)
    assert not _live(check_report(ok, m, "m.json"))
    assert m.budgets.effective_donations == [0]
    dropped_at_lowering = _mem_report(
        donations=[_donation(0, lowered=False, effective=False)])
    (f,) = _live(check_report(dropped_at_lowering, m, "m.json"), "MC402")
    assert "lowering time" in f.message
    dropped_by_xla = _mem_report(
        donations=[_donation(0, lowered=True, effective=False)])
    (f2,) = _live(check_report(dropped_by_xla, m, "m.json"), "MC402")
    assert "XLA declined" in f2.message
    # An unrequested, un-aliased arg is nobody's bug.
    bystander = _mem_report(
        donations=[_donation(0, requested=False, lowered=False,
                             effective=False)])
    assert not _live(check_report(bystander, m, "m.json"), "MC402")


def test_mc403_temp_bytes_over_budget():
    m = manifest_from_report(_mem_report(temp_bytes=1000))
    ok = _mem_report(temp_bytes=1000)
    assert not _live(check_report(ok, m, "m.json"), "MC403")
    fat = _mem_report(temp_bytes=1200)
    hits = _live(check_report(fat, m, "m.json"), "MC403")
    assert hits and "temp bytes 1200" in hits[0].message


def test_mc404_hoistable_flops_over_budget():
    def scan_rep(flops):
        return _mem_report(scan_loops=[mem.ScanLoopReport(
            index=0, trip_count=8, body_ops=10, invariant_ops=2,
            invariant_flops=flops, invariant_bytes=64,
            total_flops=flops * 2)])

    m = manifest_from_report(scan_rep(1000.0))
    assert not _live(check_report(scan_rep(1000.0), m, "m.json"))
    (f,) = _live(check_report(scan_rep(2000.0), m, "m.json"), "MC404")
    assert "scan-invariant" in f.message and "every denoise step" \
        in f.message


def test_mc002_reasonless_manifest_suppression_warns():
    m = manifest_from_report(_mem_report())
    m.suppressions.append(Suppression("MC402", "3", reason=None))
    (f,) = _live(check_report(_mem_report(), m, "m.json"), "MC002")
    assert f.severity == "warning" and "no reason" in f.message


def test_suppression_key_scoping_and_silencing():
    supp = Suppression("MC402", "3", "layout blocks the alias, reviewed")
    assert supp.covers("MC402", "3")
    assert not supp.covers("MC402", "4")
    assert not supp.covers("MC401", "3")
    assert Suppression("MC402", "*", "r").covers("MC402", "9")
    bad = _mem_report(donations=[_donation(3, effective=False)])
    m = manifest_from_report(_mem_report(), [supp])
    findings = check_report(bad, m, "m.json")
    assert not _live(findings, "MC402")
    assert any(f.rule == "MC402" and f.suppressed and f.suppress_reason
               for f in findings)


# ---------------------------------------------------------------------------
# Seeded regression 1: a donation that silently copies, over a pinned
# manifest (the issue's copy-instead-of-alias case)
# ---------------------------------------------------------------------------


def test_mc402_seeded_donation_regression_through_manifest():
    def healthy(x, y):                     # donated x aliases output 0
        return x + y, jnp.sum(y)

    def regressed(x, y):                   # output reshaped: no alias
        return (x + y).reshape(-1), jnp.sum(y)

    lowered = jax.jit(healthy, donate_argnums=(0,)).lower(
        _sds((8, 8)), _sds((8, 8)))
    good = mem.analyze_lowered_memory("donation_seed", lowered)
    manifest = manifest_from_report(good)
    assert manifest.budgets.effective_donations == [0]
    assert not _live(check_report(good, manifest, "m.json"))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        lowered_bad = jax.jit(regressed, donate_argnums=(0,)).lower(
            _sds((8, 8)), _sds((8, 8)))
    bad = mem.analyze_lowered_memory("donation_seed", lowered_bad)
    assert bad.ineffective_donations == [0]
    hits = _live(check_report(bad, manifest, "m.json"), "MC402")
    assert hits and "silently copied" in hits[0].message


# ---------------------------------------------------------------------------
# Seeded regression 2: an injected loop-invariant recompute in a scan
# body, over a pinned manifest
# ---------------------------------------------------------------------------


def test_mc404_injected_scan_recompute_through_manifest():
    def lean(c, xs):
        def body(carry, x):
            return carry + jnp.sum(x * 2.0), ()
        s, _ = jax.lax.scan(body, 0.0, xs)
        return s

    def recomputing(c, xs):
        def body(carry, x):
            inv = jnp.tanh(c @ c)          # re-run every step, same value
            return carry + jnp.sum(x * inv), ()
        s, _ = jax.lax.scan(body, 0.0, xs)
        return s

    args = (_sds((32, 32)), _sds((10, 32, 32)))
    good = mem.analyze_lowered_memory(
        "recompute_seed", jax.jit(lean).lower(*args))
    manifest = manifest_from_report(good)
    assert not _live(check_report(good, manifest, "m.json"))

    bad = mem.analyze_lowered_memory(
        "recompute_seed", jax.jit(recomputing).lower(*args))
    assert bad.hoistable_flops_per_step >= 2 * 32 ** 3
    hits = _live(check_report(bad, manifest, "m.json"), "MC404")
    assert hits and "scan-invariant" in hits[0].message


# ---------------------------------------------------------------------------
# Manifest round-trip, MC405, update-preserves-suppressions
# ---------------------------------------------------------------------------


def test_manifest_round_trip(tmp_path):
    r = _mem_report(
        name="rt_prog", argument_bytes=512, output_bytes=128,
        temp_bytes=256, generated_code_bytes=64, alias_bytes=32,
        donations=[_donation(2)],
        scan_loops=[mem.ScanLoopReport(
            index=0, trip_count=4, body_ops=6, invariant_ops=1,
            invariant_flops=100.0, invariant_bytes=16,
            total_flops=300.0)])
    m = manifest_from_report(
        r, [Suppression("MC403", "*", "chunked path, reviewed")])
    path = manifest_path("rt_prog", str(tmp_path))
    write_manifest(path, m)
    loaded = load_manifest(path)
    assert loaded.program == "rt_prog"
    assert loaded.budgets.peak_bytes == r.peak_bytes == 928
    assert loaded.budgets.temp_bytes == 256
    assert loaded.budgets.hoistable_flops_per_step == 100.0
    assert loaded.budgets.effective_donations == [2]
    assert loaded.suppressions[0].reason == "chunked path, reviewed"
    assert loaded.observed["hoistable_flops_total"] == 400.0
    assert not _live(check_report_against_dir(r, str(tmp_path)))


def test_mc405_missing_and_unreadable_manifest(tmp_path):
    r = _mem_report(name="ghost")
    (f,) = check_report_against_dir(r, str(tmp_path))
    assert f.rule == "MC405" and "--update" in f.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        fh.write("{not json")
    (f2,) = check_report_against_dir(r, str(tmp_path))
    assert f2.rule == "MC405" and "unreadable" in f2.message
    with open(manifest_path("ghost", str(tmp_path)), "w") as fh:
        json.dump({"version": 1, "tool": "shardcheck"}, fh)
    (f3,) = check_report_against_dir(r, str(tmp_path))
    assert f3.rule == "MC405"


def test_update_preserves_suppressions(tmp_path, monkeypatch):
    d = str(tmp_path)
    supp = Suppression("MC402", "1", "psum layout blocks it, reviewed")
    write_manifest(manifest_path("train_step", d),
                   manifest_from_report(_mem_report(name="train_step"),
                                        [supp]))
    monkeypatch.setitem(
        sc.REGISTRY, "train_step",
        dataclasses.replace(
            sc.REGISTRY["train_step"],
            build=lambda: types.SimpleNamespace(
                memory=_mem_report(name="train_step", temp_bytes=7))))
    mc.update_manifests(["train_step"], d)
    loaded = load_manifest(manifest_path("train_step", d))
    assert loaded.suppressions == [supp]
    assert loaded.budgets.temp_bytes == 7


# ---------------------------------------------------------------------------
# The memory_budget marker
# ---------------------------------------------------------------------------


def test_mem_check_violations_aggregate_and_default_forbid():
    check = MemCheck()
    check.add(_mem_report(argument_bytes=300, temp_bytes=100))
    check.add(_mem_report(
        temp_bytes=50,
        donations=[_donation(4, effective=False)],
        scan_loops=[mem.ScanLoopReport(
            index=0, trip_count=2, body_ops=3, invariant_ops=1,
            invariant_flops=500.0, invariant_bytes=8,
            total_flops=600.0)]))
    # Within budget (ineffective donation explicitly allowed).
    assert check.violations({"peak_bytes": 450, "temp_bytes": 150,
                             "hoistable_flops_per_step": 500,
                             "ineffective_donations": 1}) == []
    v = check.violations({"peak_bytes": 449, "temp_bytes": 149,
                          "hoistable_flops_per_step": 499})
    assert len(v) == 4          # 3 ceilings + default-forbidden donation
    assert any("ineffective_donations: 1 > budget 0" in s for s in v)
    assert any("arg 4" in s for s in v)


@pytest.mark.memory_budget(peak_bytes=1 << 30,
                           hoistable_flops_per_step=1 << 40)
def test_memory_budget_marker_e2e(mem_check):
    r = mem_check.analyze(
        "marker_fixture",
        jax.jit(lambda x, y: (x + y, y * 2.0),
                donate_argnums=(0,)).lower(_sds((8, 8)), _sds((8, 8))))
    assert r.peak_bytes > 0          # the budget is non-vacuous


def test_memory_budget_vacuous_pass_protection(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.memory_budget(peak_bytes=1)
        def test_never_registers(mem_check):
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*vacuously*"])


def test_memory_budget_marker_rejects_bad_usage(pytester):
    pytester.makepyfile(textwrap.dedent("""\
        import pytest

        @pytest.mark.memory_budget(flux_capacitor=1)
        def test_unknown_key(mem_check):
            pass

        @pytest.mark.memory_budget(peak_bytes=1)
        def test_no_fixture():
            pass

        @pytest.mark.memory_budget()
        def test_no_limits(mem_check):
            pass
    """))
    result = pytester.runpytest_inprocess(
        "-p", "diff3d_tpu.analysis.pytest_plugin",
        "-p", "no:cacheprovider", "-p", "no:randomly")
    assert result.ret != 0
    result.stdout.fnmatch_lines(["*unknown keys flux_capacitor*"])
    result.stdout.fnmatch_lines(["*requires the mem_check fixture*"])
    result.stdout.fnmatch_lines(["*no limits*"])


# ---------------------------------------------------------------------------
# CLI + registry plumbing
# ---------------------------------------------------------------------------


def test_cli_list_and_bad_invocation(capsys):
    assert mc.main(["--list"]) == 0
    out = capsys.readouterr().out
    for nm in sc.REGISTRY:
        assert nm in out
    assert mc.main(["--program", "train_step", "--programs-tier1"]) == 2


def test_manifests_are_committed_for_all_registered_programs():
    d = mc.default_manifest_dir(_REPO_ROOT)
    for nm in sc.REGISTRY:
        assert os.path.exists(manifest_path(nm, d)), (
            f"missing committed memcheck manifest for {nm}; run "
            f"'python tools/memcheck.py --update --program {nm}'")


# ---------------------------------------------------------------------------
# The tier-1 gate: committed manifests match what the tree compiles
# ---------------------------------------------------------------------------


def test_repo_manifests_clean_tier1():
    """The memcheck analogue of ``test_repo_lints_clean``: compiling the
    REAL tier-1 programs and diffing their memory reports against the
    committed ``runs/memcheck/`` manifests must come back clean.  Any
    peak/temp/donation/recompute drift is either a fix or a reviewed
    ``--update`` re-pin.  (The builds come from shardcheck's in-process
    report cache, so this shares one lower+compile with the shardcheck
    gate.)"""
    d = mc.default_manifest_dir(_REPO_ROOT)
    findings = mc.check_programs(list(sc.TIER1_PROGRAMS), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)


def test_repo_manifest_pins_exact_tier1():
    """observed == recomputed, not merely observed <= budget: the MC4xx
    ceilings only catch drift UP, so a footprint that silently shrinks
    (or an accounting change like the per-device donation fix) would
    leave committed manifests stale while the gate stays green.  Exact
    equality makes every drift a visible diff that either re-pins via
    ``memcheck --update`` or reverts."""
    d = mc.default_manifest_dir(_REPO_ROOT)
    for nm in sc.TIER1_PROGRAMS:
        committed = load_manifest(manifest_path(nm, d)).observed
        recomputed = mc.memory_report_for(nm).to_json()
        stale = {k for k in set(committed) | set(recomputed)
                 if committed.get(k) != recomputed.get(k)}
        assert not stale, (
            f"{nm}: committed manifest is stale on {sorted(stale)} — "
            f"run 'python tools/memcheck.py --update' and review the "
            f"diff")


def test_tier1_step_many_pins_nonzero_hoistable_conditioning():
    """ROADMAP item 2a as a pinned number: the committed step_many
    manifest must carry a NONZERO hoistable-FLOPs ceiling — the sampler
    still recomputes loop-invariant conditioning work every denoise
    step, and the manifest is the machine-checked record.  When
    conditioning reuse lands, this ceiling is tightened, not deleted.

    (The earlier ~1.8 GFLOP/step figure was a parser artifact: the
    quoted generic-syntax ops in the denoiser callee truncated the
    callee parse, making the whole denoiser look like an invariant
    passthrough.  With anonymous regions parsed correctly the true
    invariant portion is ~154 kFLOP/step — equivcheck pins the same
    number independently, see test_equivcheck's cross-pillar gate.)"""
    d = mc.default_manifest_dir(_REPO_ROOT)
    m = load_manifest(manifest_path("step_many", d))
    assert m.budgets.hoistable_flops_per_step > 0
    obs = m.observed
    assert obs["hoistable_flops_per_step"] > 0
    (loop,) = [l for l in obs["scan_loops"]]
    assert loop["invariant_flops"] > 0
    assert loop["invariant_flops"] <= loop["total_flops"]
    # The record_imgs donation must stay effective — pinned by index.
    assert m.budgets.effective_donations


@pytest.mark.slow
def test_repo_manifests_clean_full_sweep():
    """All five registered programs (adds distill, DDIM, serving
    warmup) — the full manifest sweep the CLI runs."""
    d = mc.default_manifest_dir(_REPO_ROOT)
    findings = mc.check_programs(sorted(sc.REGISTRY), d)
    live = _live(findings)
    assert not live, "\n".join(f.render() for f in live)
