import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from diff3d_tpu.config import ModelConfig
from diff3d_tpu.models import XUNet
from diff3d_tpu.models.layers import (AttnBlock, FiLM, FrameGroupNorm,
                                      ResnetBlock, XUNetBlock,
                                      avgpool_downsample,
                                      nearest_neighbor_upsample)


def tiny_cfg(**kw):
    base = dict(H=16, W=16, ch=8, ch_mult=(1, 2, 2, 4), emb_ch=32,
                num_res_blocks=1, attn_levels=(2, 3, 4), attn_heads=2,
                dropout=0.0, dtype="float32")
    base.update(kw)
    return ModelConfig(**base)


def make_batch(B, H, W, key=0):
    rng = np.random.RandomState(key)
    return {
        "x": jnp.asarray(rng.randn(B, H, W, 3), jnp.float32),
        "z": jnp.asarray(rng.randn(B, H, W, 3), jnp.float32),
        "logsnr": jnp.asarray(np.stack([np.full(B, 20.0),
                                        rng.uniform(-20, 20, B)], 1),
                              jnp.float32),
        "R": jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3)),
        "t": jnp.asarray(rng.randn(B, 2, 3), jnp.float32),
        "K": jnp.broadcast_to(
            jnp.array([[20.0, 0, H / 2], [0, 20.0, H / 2], [0, 0, 1]]),
            (B, 3, 3)),
    }


def test_resample_helpers():
    h = jnp.arange(2 * 2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 2, 4, 4, 3)
    up = nearest_neighbor_upsample(h)
    assert up.shape == (2, 2, 8, 8, 3)
    np.testing.assert_allclose(np.asarray(up[:, :, ::2, ::2]), np.asarray(h))
    down = avgpool_downsample(h)
    assert down.shape == (2, 2, 2, 2, 3)
    np.testing.assert_allclose(float(down[0, 0, 0, 0, 0]),
                               np.asarray(h[0, 0, :2, :2, 0]).mean())


def test_groupnorm_normalizes_per_frame():
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 2, 8, 8, 16)) * 5 + 3
    gn = FrameGroupNorm()
    out, _ = gn.init_with_output(rng, h)
    # per (batch, frame) the output is ~standardised at init
    m = np.asarray(out).reshape(4, -1)
    np.testing.assert_allclose(m.mean(1), 0.0, atol=1e-4)
    np.testing.assert_allclose(m.std(1), 1.0, atol=1e-2)


def test_film_zero_emb_is_identity_at_init():
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 2, 4, 4, 8))
    emb = jnp.zeros((2, 2, 4, 4, 16))
    film = FiLM(features=8)
    out, _ = film.init_with_output(rng, h, emb)
    # Dense bias is zero-init -> scale=shift=0 -> identity
    np.testing.assert_allclose(np.asarray(out), np.asarray(h), atol=1e-6)


@pytest.mark.parametrize("resample,expect_hw", [(None, 8), ("down", 4),
                                                ("up", 16)])
def test_resnet_block_shapes(resample, expect_hw):
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 2, 8, 8, 8))
    emb = jax.random.normal(rng, (2, 2, 8, 8, 16))
    blk = ResnetBlock(features=12, resample=resample)
    out, _ = blk.init_with_output(rng, h, emb)
    assert out.shape == (2, 2, expect_hw, expect_hw, 12)
    assert np.isfinite(np.asarray(out)).all()


def test_resnet_block_zero_init_residual():
    # At init conv2 is zero, so (pre-resample) output = (film_path + skip)/√2
    # with identity channels -> for same-width block with zero emb the block
    # output equals h_in/√2 exactly IF the first conv path contributed 0 to
    # conv2's output (it does: conv2 weights are zero).
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (1, 2, 4, 4, 8))
    emb = jnp.zeros((1, 2, 4, 4, 16))
    blk = ResnetBlock(features=8)
    out, _ = blk.init_with_output(rng, h, emb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(h) / np.sqrt(2),
                               atol=1e-5)


@pytest.mark.parametrize("attn_type", ["self", "cross"])
def test_attn_block_residual_at_init(attn_type):
    # zero-init out conv -> block is h/√2 at init
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 2, 4, 4, 8))
    blk = AttnBlock(attn_type, num_heads=2, attn_impl="xla")
    out, _ = blk.init_with_output(rng, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(h) / np.sqrt(2),
                               atol=1e-5)


def test_attn_cross_differs_from_self():
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 2, 4, 4, 8))
    out_s, vs = AttnBlock("self", 2, "xla").init_with_output(rng, h)
    out_c, vc = AttnBlock("cross", 2, "xla").init_with_output(rng, h)
    # same params (same rng/shape); different wiring must change activations
    # of the attention layer itself (check pre-out-conv by perturbing):
    # instead, simply run apply with a non-zero out conv.
    params_s = jax.tree.map(lambda x: x + 0.1, vs["params"])
    a = AttnBlock("self", 2, "xla").apply({"params": params_s}, h)
    b = AttnBlock("cross", 2, "xla").apply({"params": params_s}, h)
    assert np.abs(np.asarray(a) - np.asarray(b)).max() > 1e-6


# Tier-1 budget: the canonical B=2 tiny-XUNet init (~6s on CPU) is
# identical across three tests below (same cfg, batch seed, rng key);
# cache the one result — everything returned is immutable.
@functools.lru_cache(maxsize=1)
def _canonical_init():
    cfg = tiny_cfg()
    model = XUNet(cfg)
    B = 2
    batch = make_batch(B, cfg.H, cfg.W)
    variables = model.init(jax.random.PRNGKey(0), batch,
                           cond_mask=jnp.ones(B, bool))
    return cfg, model, batch, variables


def test_xunet_forward_shape_and_param_structure():
    cfg, model, batch, variables = _canonical_init()
    B = 2
    out = model.apply(variables, batch, cond_mask=jnp.ones(B, bool))
    assert out.shape == (B, cfg.H, cfg.W, 3)
    assert np.isfinite(np.asarray(out)).all()
    # zero-init head -> output is exactly zero at init
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_xunet_cond_mask_changes_output():
    cfg, model, batch, variables = _canonical_init()
    B = 2
    # nudge head conv away from zero so outputs are informative
    params = jax.tree.map(lambda x: x + 0.01, variables["params"])
    on = model.apply({"params": params}, batch,
                     cond_mask=jnp.ones(B, bool))
    off = model.apply({"params": params}, batch,
                      cond_mask=jnp.zeros(B, bool))
    assert np.abs(np.asarray(on) - np.asarray(off)).max() > 1e-6


# Tier-1 budget: jitted forward+grad finiteness through the same tiny
# XUNet is superseded in tier 1 by test_train_step_overfits_fixed_batch
# (60 jitted grad steps with a loss-decrease assertion) and the exact
# 25-step pin in test_multi_step_trajectory_equality[fsdp].
@pytest.mark.slow
def test_xunet_jit_and_grad():
    cfg = tiny_cfg()
    model = XUNet(cfg)
    B = 2
    batch = make_batch(B, cfg.H, cfg.W)
    variables = model.init(jax.random.PRNGKey(0), batch,
                           cond_mask=jnp.ones(B, bool))

    @jax.jit
    def loss_fn(params):
        out = model.apply({"params": params}, batch,
                          cond_mask=jnp.ones(B, bool))
        return jnp.mean(out ** 2)

    # Nudge the zero-init head so the loss has a live gradient path.
    params = variables["params"]
    params = jax.tree.map(lambda x: x + 0.01, params)
    g = jax.grad(loss_fn)(params)
    leaves = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    total = sum(float(jnp.sum(jnp.abs(l))) for l in leaves)
    assert total > 0


def test_xunet_dropout_rng_path():
    cfg = tiny_cfg(dropout=0.5)
    model = XUNet(cfg)
    B = 2
    batch = make_batch(B, cfg.H, cfg.W)
    variables = model.init(jax.random.PRNGKey(0), batch,
                           cond_mask=jnp.ones(B, bool))
    out = model.apply(variables, batch, cond_mask=jnp.ones(B, bool),
                      deterministic=False,
                      rngs={"dropout": jax.random.PRNGKey(1)})
    assert out.shape == (B, cfg.H, cfg.W, 3)


# Tier-1 keeps one remat policy; "nothing" (checkpoint-everything) is
# the slowest parametrization (full recompute in the backward) and
# guards the same forward/grad equivalence as "dots".  The applies and
# the grad are jitted: eagerly, remat dispatches every checkpointed
# block op-by-op (~60 s for the SAME assertions); under jit the
# programs land in the persistent compile cache.
@pytest.mark.parametrize("policy", [
    pytest.param("nothing", marks=pytest.mark.slow), "dots"])
def test_xunet_remat_matches(policy):
    cfg, _, batch, v = _canonical_init()
    cfg_r = tiny_cfg(remat=True, remat_policy=policy)
    B = 2

    @jax.jit
    def fwd_plain(v):
        return XUNet(cfg).apply(v, batch, cond_mask=jnp.ones(B, bool))

    @jax.jit
    def fwd_remat(v):
        return XUNet(cfg_r).apply(v, batch, cond_mask=jnp.ones(B, bool))

    a = fwd_plain(v)
    b = fwd_remat(v)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # The policy must also hold up under differentiation (the whole point
    # of remat is the backward pass).
    def loss(params):
        return jnp.mean(XUNet(cfg_r).apply(
            {"params": params}, batch, cond_mask=jnp.ones(B, bool)) ** 2)

    g = jax.jit(jax.grad(loss))(
        jax.tree.map(lambda x: x + 0.01, v["params"]))
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_xunet_rejects_bad_size():
    with pytest.raises(ValueError):
        XUNet(tiny_cfg(H=10)).init(
            jax.random.PRNGKey(0), make_batch(1, 10, 16),
            cond_mask=jnp.ones(1, bool))


# Tier-1 budget (870s): the remat numeric-equality pin stays in tier 1
# (test_xunet_remat_matches[dots]); this dropout-under-remat regression
# smoke runs under --runslow / RUN_SLOW=1.
@pytest.mark.slow
def test_xunet_remat_with_dropout_trains():
    # regression: remat static_argnums must mark `deterministic` (argnum 3
    # counting self) static, or dropout>0 under remat raises
    # TracerBoolConversionError.
    cfg = tiny_cfg(dropout=0.1, remat=True)
    model = XUNet(cfg)
    B = 1
    batch = make_batch(B, cfg.H, cfg.W)
    variables = model.init(jax.random.PRNGKey(0), batch,
                           cond_mask=jnp.ones(B, bool))
    out = model.apply(variables, batch, cond_mask=jnp.ones(B, bool),
                      deterministic=False,
                      rngs={"dropout": jax.random.PRNGKey(1)})
    assert out.shape == (B, cfg.H, cfg.W, 3)


def test_conditioning_encodings_stay_float32_in_bf16():
    # regression: posenc sinusoid args reach ~2e4; computed in bf16 they
    # lose all phase info (logsnr 4.0 vs 4.01 become identical).
    from diff3d_tpu.models.conditioning import ConditioningProcessor
    cp = ConditioningProcessor(emb_ch=32, H=8, W=8, num_resolutions=2,
                               dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)

    def batch_with_logsnr(v):
        return {
            "x": jnp.zeros((1, 8, 8, 3)),
            "logsnr": jnp.array([[20.0, v]]),
            "R": jnp.broadcast_to(jnp.eye(3), (1, 2, 3, 3)),
            "t": jnp.asarray(rng.randn(1, 2, 3), jnp.float32),
            "K": jnp.broadcast_to(jnp.eye(3), (1, 3, 3)),
        }

    b1 = batch_with_logsnr(4.0)
    variables = cp.init(jax.random.PRNGKey(0), b1, jnp.ones(1, bool))
    e1, _ = cp.apply(variables, b1, jnp.ones(1, bool))
    e2, _ = cp.apply(variables, batch_with_logsnr(4.01), jnp.ones(1, bool))
    assert np.abs(np.asarray(e1, np.float32)
                  - np.asarray(e2, np.float32)).max() > 1e-3


def test_attn_impl_levels_override():
    """Per-level attention-engine override: all-'xla' levels match the
    global attn_impl='xla' bitwise (same params, same math, different
    plumbing), and validation rejects bad shapes/entries."""
    cfg_global = tiny_cfg(attn_impl="xla")
    cfg_levels = tiny_cfg(attn_impl="auto",
                          attn_impl_levels=("xla", "xla", "xla", "xla"))
    batch = make_batch(2, 16, 16)
    cond = jnp.ones((2,), bool)
    params = XUNet(cfg_global).init({"params": jax.random.PRNGKey(0)},
                                    batch, cond_mask=cond)["params"]
    out_g = XUNet(cfg_global).apply({"params": params}, batch,
                                    cond_mask=cond)
    out_l = XUNet(cfg_levels).apply({"params": params}, batch,
                                    cond_mask=cond)
    np.testing.assert_array_equal(np.asarray(out_g), np.asarray(out_l))
    assert cfg_levels.attn_impl_at(1) == "xla"
    assert cfg_levels.attn_impl_at(99) == "xla"   # middle clamps to last

    with pytest.raises(ValueError, match="entries"):
        tiny_cfg(attn_impl_levels=("xla",)).validate()
    with pytest.raises(ValueError, match="invalid"):
        tiny_cfg(attn_impl_levels=("xla", "bogus", "xla",
                                   "xla")).validate()
